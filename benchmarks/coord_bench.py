"""Control-plane negotiation benchmark: flat vs hierarchical coordination.

Drives the REAL ``CoordState`` barrier with simulated ranks and measures
negotiation rounds per second and p99 round latency as the rank count
grows. ``flat`` mode models the pre-hierarchy control plane: one
``exchange()`` call (= one control frame at rank 0) per rank per round.
``hier`` mode models per-host sub-coordinators: one ``exchange_batch()``
call (= ONE frame) per host per round, each carrying that host's ranks.

The interesting output is the scaling curve — flat does O(ranks) frame
work and O(ranks) thread wakeups under the coordinator lock per round,
hierarchical does O(hosts). The ISSUE acceptance bar is >= 5x rounds/s
for hier over flat at 1024 simulated ranks (64 ranks/host).

Usage::

    python benchmarks/coord_bench.py --ranks 64,256,1024 --mode both
    python benchmarks/coord_bench.py --history perf.jsonl --check-regression

With ``--history`` the headline metric (hier rounds/s at the largest rank
count) is appended to the JSONL perf history; ``--check-regression`` exits
3 when it falls below the recorded trajectory (benchmarks/history.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from horovod_tpu.runtime import wire  # noqa: E402
from horovod_tpu.runtime.coordinator import CoordState  # noqa: E402


def _make_state(world):
    return CoordState(world, 0, cache_capacity=4096,
                      stall_warning_s=600.0, stall_shutdown_s=0.0)


def _payload():
    return wire.encode_request_list(
        0, [], [wire.ReqMeta("bench", 0, "float32", (1024,))], epoch=-1)


def bench_mode(mode, ranks, ranks_per_host, rounds, warmup):
    """One (mode, ranks) cell: persistent worker threads drive ``rounds``
    negotiation rounds through a fresh CoordState; returns rounds/s, p99
    round latency, and the frames-per-round the coordinator observed."""
    if mode == "hier":
        hosts = max(1, ranks // ranks_per_host)
        units = hosts
    else:
        units = ranks
    st = _make_state(ranks)
    payload = _payload()
    total = warmup + rounds
    start = threading.Barrier(units + 1)
    done = threading.Barrier(units + 1)
    errors = []

    def flat_worker(r):
        try:
            for seq in range(total):
                start.wait()
                st.exchange(r, seq, payload)
                done.wait()
        except Exception as exc:  # pragma: no cover - surfaced in main
            errors.append(exc)
            start.abort()
            done.abort()

    def host_worker(h):
        lo = h * ranks_per_host
        hi = min(lo + ranks_per_host, ranks)
        try:
            for seq in range(total):
                start.wait()
                st.exchange_batch(
                    [(r, seq, payload) for r in range(lo, hi)])
                done.wait()
        except Exception as exc:  # pragma: no cover
            errors.append(exc)
            start.abort()
            done.abort()

    target = host_worker if mode == "hier" else flat_worker
    threads = [threading.Thread(target=target, args=(u,), daemon=True)
               for u in range(units)]
    for t in threads:
        t.start()

    latencies = []
    frames0 = None
    for seq in range(total):
        t0 = time.perf_counter()
        start.wait()
        done.wait()
        dt = time.perf_counter() - t0
        if seq == warmup - 1:
            frames0 = st.frames_in
        if seq >= warmup:
            latencies.append(dt)
    frames_per_round = (st.frames_in - frames0) / rounds if rounds else 0
    for t in threads:
        t.join(timeout=30)
    if errors:
        raise errors[0]

    latencies.sort()
    p99 = latencies[min(len(latencies) - 1,
                        int(round(0.99 * (len(latencies) - 1))))]
    wall = sum(latencies)
    return {
        "mode": mode,
        "ranks": ranks,
        "units": units,
        "rounds": rounds,
        "rounds_per_sec": round(rounds / wall, 2) if wall else 0.0,
        "p99_round_ms": round(p99 * 1e3, 3),
        "frames_per_round": round(frames_per_round, 1),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ranks", default="64,256,1024",
                    help="comma-separated simulated rank counts")
    ap.add_argument("--ranks-per-host", type=int, default=64,
                    help="batch size per simulated host in hier mode")
    ap.add_argument("--rounds", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--mode", choices=["flat", "hier", "both"],
                    default="both")
    ap.add_argument("--history", default=None,
                    help="JSONL perf-history file (benchmarks/history.py)")
    ap.add_argument("--check-regression", action="store_true",
                    help="exit 3 when the headline metric regresses "
                         "against --history")
    ap.add_argument("--regression-window", type=int, default=None)
    ap.add_argument("--regression-tolerance", type=float, default=None)
    args = ap.parse_args(argv)

    rank_counts = [int(r) for r in args.ranks.split(",")]
    modes = ["flat", "hier"] if args.mode == "both" else [args.mode]
    results = []
    for ranks in rank_counts:
        for mode in modes:
            r = bench_mode(mode, ranks, args.ranks_per_host,
                           args.rounds, args.warmup)
            results.append(r)
            print(json.dumps(r))
        if args.mode == "both":
            flat = next(r for r in results
                        if r["ranks"] == ranks and r["mode"] == "flat")
            hier = next(r for r in results
                        if r["ranks"] == ranks and r["mode"] == "hier")
            if flat["rounds_per_sec"]:
                print(json.dumps({
                    "metric": "coord_hier_speedup",
                    "ranks": ranks,
                    "value": round(hier["rounds_per_sec"]
                                   / flat["rounds_per_sec"], 2)}))

    biggest = max(rank_counts)
    headline = next((r for r in results
                     if r["ranks"] == biggest and r["mode"] == "hier"),
                    results[-1])
    result = {
        "metric": "coord_hier_rounds_per_sec",
        "value": headline["rounds_per_sec"],
        "unit": "rounds/s",
        "ranks": headline["ranks"],
    }
    print(json.dumps(result))

    rc = 0
    if args.history:
        from benchmarks.history import (append_record, check_regression,
                                        load_history)

        # compare against the trajectory BEFORE appending: today's run
        # must not be allowed to vote in its own baseline
        if args.check_regression:
            verdict = check_regression(
                load_history(args.history, metric=result["metric"]),
                result["value"],
                **{k: v for k, v in (
                    ("window", args.regression_window),
                    ("tolerance", args.regression_tolerance))
                   if v is not None})
            print("# regression check: %s" % json.dumps(verdict),
                  file=sys.stderr)
            if verdict["regression"]:
                print(f"# REGRESSION: {result['metric']} = "
                      f"{result['value']} fell below the floor "
                      f"{verdict['floor']} (baseline {verdict['baseline']} "
                      f"over {verdict['samples']} runs)", file=sys.stderr)
                rc = 3
        append_record(args.history, {
            "metric": result["metric"], "value": result["value"],
            "unit": result["unit"], "ranks": result["ranks"],
            "ranks_per_host": args.ranks_per_host,
            "rounds": args.rounds,
        })
        print(f"# perf history appended to {args.history}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
