"""Control-plane negotiation benchmark: flat vs hierarchical coordination.

Drives the REAL ``CoordState`` barrier with simulated ranks and measures
negotiation rounds per second and p99 round latency as the rank count
grows. ``flat`` mode models the pre-hierarchy control plane: one
``exchange()`` call (= one control frame at rank 0) per rank per round.
``hier`` mode models per-host sub-coordinators: one ``exchange_batch()``
call (= ONE frame) per host per round, each carrying that host's ranks.

``tier`` mode models the N-tier tree (HOROVOD_HIERARCHY_TIERS >= 2): one
``exchange_tier()`` call per TOP-TIER subtree per round, each carrying the
steady-state single GROUP (seq, payload, rank runs) its whole subtree
coalesces into — rank 0's work is O(groups), independent of rank count.

The interesting output is the scaling curve — flat does O(ranks) frame
work and O(ranks) thread wakeups under the coordinator lock per round,
hierarchical does O(hosts), tiered does O(top-tier subtrees). The PR-9
acceptance bar is >= 5x rounds/s for hier over flat at 1024 simulated
ranks (64 ranks/host); the PR-15 bar is tier-mode p99 round latency at
100k ranks <= 5x the 1024-rank point (``--p99-gate``), where the flat
wire degrades linearly.

Usage::

    python benchmarks/coord_bench.py --ranks 64,256,1024 --mode both
    python benchmarks/coord_bench.py --mode tier \
        --ranks 1024,10240,102400 --p99-gate 5.0
    python benchmarks/coord_bench.py --history perf.jsonl --check-regression

With ``--history`` the headline metric (hier/tier rounds/s at the largest
rank count) is appended to the JSONL perf history, plus one
``coord_round_p99_ms`` row per (mode, ranks) sweep point gated with
``direction="lower"``; ``--check-regression`` exits 3 when either the
headline falls below — or any sweep point's p99 rises above — the
recorded trajectory (benchmarks/history.py). Flat mode is capped at
``--flat-cap`` simulated ranks (one OS thread per rank).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from horovod_tpu.runtime import wire  # noqa: E402
from horovod_tpu.runtime.coordinator import CoordState  # noqa: E402


def _make_state(world):
    return CoordState(world, 0, cache_capacity=4096,
                      stall_warning_s=600.0, stall_shutdown_s=0.0)


def _payload():
    return wire.encode_request_list(
        0, [], [wire.ReqMeta("bench", 0, "float32", (1024,))], epoch=-1)


def bench_mode(mode, ranks, ranks_per_host, rounds, warmup,
               tiers=2, fanout=32):
    """One (mode, ranks) cell: persistent worker threads drive ``rounds``
    negotiation rounds through a fresh CoordState; returns rounds/s, p99
    round latency, and the frames-per-round the coordinator observed."""
    if mode == "hier":
        hosts = max(1, ranks // ranks_per_host)
        units = hosts
    elif mode == "tier":
        # one worker per TOP-TIER subtree: the tree below it (hosts
        # coalescing local ranks, mid tiers merging run lists) happens on
        # other machines in reality, so here its steady-state output — one
        # group covering the subtree's whole rank span — is precomputed
        # and only rank 0's per-round work is measured
        hosts = -(-ranks // ranks_per_host)
        if tiers <= 0:
            # auto depth (the docs/control-plane.md deployment rule): add
            # a tier whenever rank 0 would otherwise face more than
            # ``fanout`` direct children — this is what keeps its
            # per-round work bounded as ranks grow two orders
            tiers = 2
            while -(-hosts // fanout ** (tiers - 1)) > fanout:
                tiers += 1
        span = fanout ** (tiers - 1)          # hosts per top-tier subtree
        units = -(-hosts // span)
        unit_ranks = span * ranks_per_host
    else:
        units = ranks
    st = _make_state(ranks)
    payload = _payload()
    total = warmup + rounds
    start = threading.Barrier(units + 1)
    done = threading.Barrier(units + 1)
    errors = []

    def flat_worker(r):
        try:
            for seq in range(total):
                start.wait()
                st.exchange(r, seq, payload)
                done.wait()
        except Exception as exc:  # pragma: no cover - surfaced in main
            errors.append(exc)
            start.abort()
            done.abort()

    def host_worker(h):
        lo = h * ranks_per_host
        hi = min(lo + ranks_per_host, ranks)
        try:
            for seq in range(total):
                start.wait()
                st.exchange_batch(
                    [(r, seq, payload) for r in range(lo, hi)])
                done.wait()
        except Exception as exc:  # pragma: no cover
            errors.append(exc)
            start.abort()
            done.abort()

    def tier_worker(u):
        lo = u * unit_ranks
        hi = min(lo + unit_ranks, ranks)
        subtree = "t%d.%d" % (tiers, u)
        runs = [(lo, hi - lo)]
        try:
            for seq in range(total):
                start.wait()
                st.exchange_tier(tiers, subtree,
                                 [(seq, payload, runs)])
                done.wait()
        except Exception as exc:  # pragma: no cover
            errors.append(exc)
            start.abort()
            done.abort()

    target = {"hier": host_worker, "tier": tier_worker}.get(mode,
                                                            flat_worker)
    threads = [threading.Thread(target=target, args=(u,), daemon=True)
               for u in range(units)]
    for t in threads:
        t.start()

    latencies = []
    frames0 = None
    for seq in range(total):
        t0 = time.perf_counter()
        start.wait()
        done.wait()
        dt = time.perf_counter() - t0
        if seq == warmup - 1:
            frames0 = st.frames_in
        if seq >= warmup:
            latencies.append(dt)
    frames_per_round = (st.frames_in - frames0) / rounds if rounds else 0
    for t in threads:
        t.join(timeout=30)
    if errors:
        raise errors[0]

    latencies.sort()
    p99 = latencies[min(len(latencies) - 1,
                        int(round(0.99 * (len(latencies) - 1))))]
    wall = sum(latencies)
    return {
        "mode": mode,
        "ranks": ranks,
        "tiers": tiers if mode == "tier" else 1,
        "units": units,
        "rounds": rounds,
        "rounds_per_sec": round(rounds / wall, 2) if wall else 0.0,
        "p99_round_ms": round(p99 * 1e3, 3),
        "frames_per_round": round(frames_per_round, 1),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ranks", default="64,256,1024",
                    help="comma-separated simulated rank counts")
    ap.add_argument("--ranks-per-host", type=int, default=64,
                    help="batch size per simulated host in hier mode")
    ap.add_argument("--rounds", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--mode", choices=["flat", "hier", "tier", "both"],
                    default="both")
    ap.add_argument("--tiers", type=int, default=0,
                    help="aggregation-tree depth modeled in tier mode "
                         "(0 = auto: deepen until rank 0 has at most "
                         "--fanout direct children)")
    ap.add_argument("--fanout", type=int, default=8,
                    help="children per aggregator above the host tier")
    ap.add_argument("--flat-cap", type=int, default=4096,
                    help="skip flat cells above this rank count (flat "
                         "mode spawns one OS thread per rank)")
    ap.add_argument("--p99-gate", type=float, default=None,
                    help="exit 3 when p99 round latency at the LARGEST "
                         "rank count exceeds this multiple of the "
                         "smallest point's p99 (the 100k-rank scaling "
                         "acceptance gate)")
    ap.add_argument("--history", default=None,
                    help="JSONL perf-history file (benchmarks/history.py)")
    ap.add_argument("--check-regression", action="store_true",
                    help="exit 3 when the headline metric regresses "
                         "against --history")
    ap.add_argument("--regression-window", type=int, default=None)
    ap.add_argument("--regression-tolerance", type=float, default=None)
    args = ap.parse_args(argv)

    rank_counts = [int(r) for r in args.ranks.split(",")]
    modes = ["flat", "hier"] if args.mode == "both" else [args.mode]
    results = []
    for ranks in rank_counts:
        for mode in modes:
            if mode == "flat" and ranks > args.flat_cap:
                print(json.dumps({
                    "mode": "flat", "ranks": ranks, "skipped":
                    "above --flat-cap %d (one thread per rank)"
                    % args.flat_cap}))
                continue
            r = bench_mode(mode, ranks, args.ranks_per_host,
                           args.rounds, args.warmup,
                           tiers=args.tiers, fanout=args.fanout)
            results.append(r)
            print(json.dumps(r))
        if args.mode == "both":
            flat = next((r for r in results
                         if r["ranks"] == ranks and r["mode"] == "flat"),
                        None)
            hier = next((r for r in results
                         if r["ranks"] == ranks and r["mode"] == "hier"),
                        None)
            if flat and hier and flat["rounds_per_sec"]:
                print(json.dumps({
                    "metric": "coord_hier_speedup",
                    "ranks": ranks,
                    "value": round(hier["rounds_per_sec"]
                                   / flat["rounds_per_sec"], 2)}))

    biggest = max(rank_counts)
    best_mode = "tier" if args.mode == "tier" else "hier"
    headline = next((r for r in results
                     if r["ranks"] == biggest and r["mode"] == best_mode),
                    results[-1])
    result = {
        "metric": "coord_%s_rounds_per_sec" % best_mode,
        "value": headline["rounds_per_sec"],
        "unit": "rounds/s",
        "ranks": headline["ranks"],
    }
    print(json.dumps(result))

    rc = 0
    # the 100k scaling gate (ISSUE 15 acceptance): p99 round latency at
    # the largest sweep point must stay within --p99-gate times the
    # smallest point's — flat degrades ~linearly, the tree must not
    if args.p99_gate and len(rank_counts) >= 2:
        per_ranks = {r["ranks"]: r for r in results
                     if r["mode"] == best_mode}
        if len(per_ranks) >= 2:
            small = per_ranks[min(per_ranks)]
            big = per_ranks[max(per_ranks)]
            scale = (big["p99_round_ms"] / small["p99_round_ms"]
                     if small["p99_round_ms"] else 0.0)
            verdict = {
                "metric": "coord_p99_scaling",
                "mode": best_mode,
                "ranks_small": small["ranks"], "ranks_big": big["ranks"],
                "p99_small_ms": small["p99_round_ms"],
                "p99_big_ms": big["p99_round_ms"],
                "scale": round(scale, 2), "gate": args.p99_gate,
                "pass": scale <= args.p99_gate,
            }
            print(json.dumps(verdict))
            if not verdict["pass"]:
                print("# P99 GATE FAILED: %dx ranks cost %.2fx p99 "
                      "(gate %.1fx)" % (big["ranks"] // small["ranks"],
                                        scale, args.p99_gate),
                      file=sys.stderr)
                rc = 3
    if args.history:
        from benchmarks.history import (append_record, check_regression,
                                        load_history)

        # compare against the trajectory BEFORE appending: today's run
        # must not be allowed to vote in its own baseline
        if args.check_regression:
            verdict = check_regression(
                load_history(args.history, metric=result["metric"]),
                result["value"],
                **{k: v for k, v in (
                    ("window", args.regression_window),
                    ("tolerance", args.regression_tolerance))
                   if v is not None})
            print("# regression check: %s" % json.dumps(verdict),
                  file=sys.stderr)
            if verdict["regression"]:
                print(f"# REGRESSION: {result['metric']} = "
                      f"{result['value']} fell below the floor "
                      f"{verdict['floor']} (baseline {verdict['baseline']} "
                      f"over {verdict['samples']} runs)", file=sys.stderr)
                rc = 3
        # one p99 row per sweep point, gated direction="lower": a latency
        # regression at ANY scale (not just the headline's throughput)
        # fails CI. Trajectories are per (mode, ranks) — history rows for
        # other sweep points must not vote in this point's baseline.
        p99_history = load_history(args.history,
                                   metric="coord_round_p99_ms")
        for r in results:
            if args.check_regression:
                verdict = check_regression(
                    [h for h in p99_history
                     if h.get("ranks") == r["ranks"]
                     and h.get("mode") == r["mode"]],
                    r["p99_round_ms"], direction="lower",
                    **{k: v for k, v in (
                        ("window", args.regression_window),
                        ("tolerance", args.regression_tolerance))
                       if v is not None})
                if verdict["regression"]:
                    print("# REGRESSION: coord_round_p99_ms[%s,%d] = %s "
                          "rose above the gate %s (baseline %s over %d "
                          "runs)" % (r["mode"], r["ranks"],
                                     r["p99_round_ms"], verdict["floor"],
                                     verdict["baseline"],
                                     verdict["samples"]), file=sys.stderr)
                    rc = 3
            append_record(args.history, {
                "metric": "coord_round_p99_ms",
                "value": r["p99_round_ms"], "unit": "ms",
                "direction": "lower", "mode": r["mode"],
                "ranks": r["ranks"],
                "ranks_per_host": args.ranks_per_host,
                "rounds": args.rounds,
            })
        append_record(args.history, {
            "metric": result["metric"], "value": result["value"],
            "unit": result["unit"], "ranks": result["ranks"],
            "ranks_per_host": args.ranks_per_host,
            "rounds": args.rounds,
        })
        print(f"# perf history appended to {args.history}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
