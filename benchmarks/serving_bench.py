#!/usr/bin/env python
"""Serving-mode load benchmark: sustained QPS, p50/p99 latency, tokens/s.

A Poisson load generator over the inference serving subsystem
(horovod_tpu/serving/, docs/inference.md). Requests arrive with
exponential inter-arrival times at ``--qps``, each a random prompt of
``--prompt-len`` tokens decoding ``--max-new`` tokens; the bench waits for
every completion and reports the sustained rate and the latency tail.

Two modes:

* **in-process** (default): one ``ServingEngine`` replica, submits go
  straight to the engine. This is the deterministic perf-gate mode.
* **pod** (``--workers N``): spawns a ``ServingFrontend`` plus N worker
  replica subprocesses (``python -m horovod_tpu.serving.worker``) and
  drives them through a ``ServingClient`` over the hardened control
  plane. ``--kill-one`` SIGKILLs a worker mid-run and asserts ZERO lost
  requests — the killed replica's in-flight work must re-admit onto the
  survivors (exit 4 if anything is lost), which is the ISSUE-11
  acceptance demonstration.

With ``--history PATH`` the run's p99 appends to the schema-versioned
JSONL store (benchmarks/history.py); ``--check-regression`` compares
against the trajectory BEFORE appending with ``direction="lower"``
(latency: smaller is better) and exits 3 when the fresh p99 rises above
the tolerance bound.

    JAX_PLATFORMS=cpu python benchmarks/serving_bench.py            # smoke
    python benchmarks/serving_bench.py --workers 2 --kill-one       # pod
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="Poisson load generator for the serving subsystem")
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--qps", type=float, default=16.0,
                   help="Poisson arrival rate (requests/second)")
    p.add_argument("--prompt-len", type=int, default=8)
    p.add_argument("--max-new", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout", type=float, default=300.0,
                   help="per-request completion deadline")
    p.add_argument("--workers", type=int, default=0,
                   help="pod mode: spawn a frontend + N worker replica "
                        "subprocesses (0 = in-process engine)")
    p.add_argument("--kill-one", action="store_true",
                   help="pod mode: SIGKILL one worker mid-run and require "
                        "zero lost requests (exit 4 on loss)")
    p.add_argument("--chaos", default=None,
                   choices=["kill-frontend", "slow-replica", "overload",
                            "rolling-restart"],
                   help="run one survivable-serving chaos drill instead of "
                        "the load benchmark (exit 4 on any lost or "
                        "duplicated request, or a jepsen violation)")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--blocks", type=int, default=256)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--history", metavar="PATH", default=None,
                   help="append this run's p99 to a schema-versioned JSONL "
                        "perf history (benchmarks/history.py)")
    p.add_argument("--check-regression", action="store_true",
                   help="with --history: compare this run's p99 against "
                        "the recorded trajectory BEFORE appending "
                        "(direction=lower); exit 3 above the tolerance "
                        "bound")
    p.add_argument("--regression-window", type=int, default=None)
    p.add_argument("--regression-tolerance", type=float, default=None)
    return p.parse_args(argv)


def poisson_load(submit, args, vocab=251):
    """Drive ``submit(prompt, max_new) -> future`` at Poisson arrivals;
    returns (futures, submit_wall_seconds)."""
    rng = np.random.RandomState(args.seed)
    futs = []
    t0 = time.monotonic()
    next_t = t0
    for _ in range(args.requests):
        next_t += rng.exponential(1.0 / max(args.qps, 1e-6))
        while True:
            now = time.monotonic()
            if now >= next_t:
                break
            time.sleep(min(0.002, next_t - now))
        prompt = rng.randint(1, vocab, size=args.prompt_len).tolist()
        futs.append(submit(prompt, args.max_new))
    return futs, time.monotonic() - t0


def run_inprocess(args):
    from horovod_tpu.serving import ServingConfig
    from horovod_tpu.serving.worker import build_replica_engine

    cfg = ServingConfig(block_size=args.block_size, num_blocks=args.blocks,
                        max_batch=args.max_batch, max_context=128)
    engine = build_replica_engine(max_seq_len=128, config=cfg).start()
    # one throwaway request compiles prefill+decode outside the timed window
    engine.submit([1] * args.prompt_len, 2).wait(timeout=args.timeout)

    t0 = time.monotonic()
    futs, _ = poisson_load(engine.submit, args)
    for f in futs:
        f.wait(timeout=args.timeout)
    wall = time.monotonic() - t0
    engine.stop()
    lost = [f for f in futs if not f.done() or f.state != "done"]
    lats = [f.latency() for f in futs if f.latency() is not None]
    toks = sum(len(f.output) for f in futs)
    return lats, toks, wall, len(lost)


def run_pod(args):
    from horovod_tpu.serving import ServingClient, ServingFrontend

    fe = ServingFrontend().start()
    host, port = fe.addr[0], fe.addr[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = []
    for i in range(args.workers):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.serving.worker",
             "--addr", f"{host}:{port}", "--rank", str(i + 1),
             "--max-batch", str(args.max_batch),
             "--blocks", str(args.blocks),
             "--block-size", str(args.block_size)],
            env=env, cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
    try:
        fe.wait_for_workers(args.workers, timeout=120)
        cli = ServingClient(host, port, name="bench")
        # warm every replica's compile cache before the timed window
        warm = [cli.submit([1] * args.prompt_len, 2)
                for _ in range(args.workers * args.max_batch)]
        for f in warm:
            f.result(timeout=args.timeout)

        t0 = time.monotonic()
        kill_at = args.requests // 3 if args.kill_one else None
        futs = []
        rng = np.random.RandomState(args.seed)
        next_t = time.monotonic()
        for i in range(args.requests):
            next_t += rng.exponential(1.0 / max(args.qps, 1e-6))
            while time.monotonic() < next_t:
                time.sleep(0.002)
            prompt = rng.randint(1, 251, size=args.prompt_len).tolist()
            futs.append(cli.submit(prompt, args.max_new))
            if kill_at is not None and i == kill_at:
                victim = procs[0]
                print(f"# SIGKILL worker pid {victim.pid} mid-run",
                      file=sys.stderr)
                victim.kill()
        lost = 0
        lats, toks = [], 0
        for f in futs:
            try:
                tokens = f.result(timeout=args.timeout)
            except (RuntimeError, TimeoutError) as exc:
                print(f"# LOST {f.id}: {exc}", file=sys.stderr)
                lost += 1
                continue
            toks += len(tokens)
            lats.append(f.client_latency())
        wall = time.monotonic() - t0
        print("# frontend: %s" % json.dumps(fe.stats()), file=sys.stderr)
        cli.close()
        return lats, toks, wall, lost
    finally:
        for pr in procs:
            if pr.poll() is None:
                pr.terminate()
        for pr in procs:
            try:
                pr.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pr.kill()
        fe.stop()


# --------------------------------------------------------- chaos drills
#
# Each drill exercises one row of the docs/inference.md failure matrix
# end to end with REAL processes/sockets and gates on the exactly-once
# ledger: every submitted request answered terminally exactly once (a
# delivery ledger recorded below the client's dedupe, so a duplicate
# RESULT from a confused frontend would be caught, not hidden).


class _LedgerClient:
    """Wraps a ServingClient to record every terminal RESULT frame as it
    arrives — BEFORE the client's pending-pop dedupe — so duplicated
    deliveries are observable evidence, not silently absorbed."""

    def __init__(self, cli, wire):
        self.cli = cli
        self.delivered = []  # (request_id, status) per terminal frame
        self._wire = wire
        inner = cli._on_result

        def spy(payload):
            rid, status, _, _, _ = wire.decode_serve_result(payload)
            if status != wire.SERVE_REJECTED:
                self.delivered.append((rid, status))
            inner(payload)

        cli._on_result = spy


def _drain_futures(futs, timeout):
    """Wait every future out; returns (lost_ids, statuses by id)."""
    lost = []
    for f in futs:
        if not f.wait(timeout=timeout):
            lost.append(f.id)
    return lost


def chaos_kill_frontend(args):
    """SIGKILL the active frontend under Poisson load with a warm standby
    attached: the standby must win the serving lease, workers and the
    client must follow the failover key, and every request must complete
    exactly once (jepsen-checked over the merged blackbox bundles)."""
    import shutil
    import tempfile

    from horovod_tpu import blackbox as _blackbox
    from horovod_tpu.blackbox.doctor import load_bundle
    from horovod_tpu.faultinject.jepsen import check_serving_history
    from horovod_tpu.run.rendezvous import KVStoreServer
    from horovod_tpu.runtime import wire
    from horovod_tpu.serving import ServingClient, ServingStandby
    from horovod_tpu.serving.worker import build_replica_engine
    from horovod_tpu.serving.worker import ServingWorker
    from horovod_tpu.serving import ServingConfig

    # honor a caller-supplied blackbox dir (pod_smoke runs the doctor
    # over the bundle after the drill); otherwise use a throwaway
    keep_bb = os.environ.get("HOROVOD_BLACKBOX_DIR")
    bb_dir = keep_bb or tempfile.mkdtemp(prefix="hvd_serving_chaos_")
    kv = KVStoreServer("", host="127.0.0.1").start()
    os.environ["HVD_KV_ADDR"] = f"127.0.0.1:{kv.port}"
    os.environ["HOROVOD_LEASE_TTL"] = "1.0"
    os.environ["HOROVOD_SERVING_STANDBY"] = "1"
    os.environ["HOROVOD_BLACKBOX"] = "1"
    os.environ["HOROVOD_BLACKBOX_DIR"] = bb_dir
    os.environ["HOROVOD_RECONNECT_JITTER"] = "0.3"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    # the active frontend is a subprocess — the thing we SIGKILL
    fe_proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.serving.server",
         "--rank", "0", "--gen", "0"],
        env=env, cwd=repo, stdout=subprocess.PIPE, text=True)
    line = fe_proc.stdout.readline().strip()
    assert line.startswith("SERVING_FRONTEND "), line
    host, port = line.split()[1].rsplit(":", 1)
    port = int(port)

    _blackbox.maybe_activate()
    _blackbox.set_identity(1, 4)
    standby = ServingStandby((host, port), "", rank=1, gen=0).start()
    time.sleep(0.3)  # let the replication snapshot land

    # two replica engines in-process (ranks 2 and 3 for the blackbox)
    cfg = lambda: ServingConfig(  # noqa: E731 - tiny local factory
        block_size=args.block_size, num_blocks=args.blocks,
        max_batch=args.max_batch, max_context=128)
    workers = [
        ServingWorker(host, port, build_replica_engine(
            max_seq_len=128, config=cfg()), name=f"worker-{i}",
            rank=2 + i, gen=0).start()
        for i in range(2)]

    rc = 0
    try:
        cli = ServingClient(host, port, name="chaos", gen=0,
                            connect_timeout=30.0)
        ledger = _LedgerClient(cli, wire)
        warm = [cli.submit([1] * args.prompt_len, 2) for _ in range(4)]
        for f in warm:
            f.result(timeout=args.timeout)

        rng = np.random.RandomState(args.seed)
        futs, kill_at = [], args.requests // 3
        for i in range(args.requests):
            time.sleep(rng.exponential(1.0 / max(args.qps, 1e-6)))
            prompt = rng.randint(1, 251, size=args.prompt_len).tolist()
            futs.append(cli.submit(prompt, args.max_new))
            if i == kill_at:
                print(f"# SIGKILL frontend pid {fe_proc.pid} mid-load",
                      file=sys.stderr)
                fe_proc.kill()
        lost = _drain_futures(futs, args.timeout)
        ok = sum(1 for f in futs if f.done() and not f._failed)
        assert standby.promoted, "standby never promoted"
        cli.close()

        submitted = [f.id for f in warm + futs]
        delivered = [rid for rid, _ in ledger.delivered]
        _blackbox.dump("chaos drill complete", force=True)
        verdict = check_serving_history(load_bundle(bb_dir),
                                        submitted, delivered)
        print("# jepsen: %s" % json.dumps(
            {k: verdict[k] for k in ("single_writer", "exactly_once",
                                     "lost", "duplicates",
                                     "fenced_frames", "violations")}),
            file=sys.stderr)
        print(f"# kill-frontend: {ok}/{len(futs)} ok, "
              f"{len(lost)} unresolved, standby promoted epoch "
              f"{standby.frontend.fence_epoch}", file=sys.stderr)
        if lost or verdict["violations"]:
            print("# FAIL: lost=%s violations=%s"
                  % (lost, verdict["violations"]), file=sys.stderr)
            rc = 4
    finally:
        for w in workers:
            w.stop()
        standby.stop()
        if fe_proc.poll() is None:
            fe_proc.kill()
        fe_proc.wait(timeout=10)
        kv.stop()
        if not keep_bb:
            shutil.rmtree(bb_dir, ignore_errors=True)
    return rc


def chaos_slow_replica(args):
    """One replica stalls every engine step: hedged decode must fire
    after the p95-derived delay and keep the run loss-free — the fast
    replica's first-winner answer cancels the laggard's copy."""
    os.environ["HOROVOD_SERVING_HEDGE"] = "2.0"
    from horovod_tpu.runtime import wire
    from horovod_tpu.serving import (ServingClient, ServingConfig,
                                     ServingFrontend)
    from horovod_tpu.serving.worker import ServingWorker, \
        build_replica_engine

    fe = ServingFrontend().start()
    fe.hedge_delay_override = 0.3  # deterministic drill, no warmup ring
    host, port = fe.addr[0], fe.addr[1]

    def mk(i, slow):
        cfg = ServingConfig(block_size=args.block_size,
                            num_blocks=args.blocks,
                            max_batch=args.max_batch, max_context=128)
        eng = build_replica_engine(max_seq_len=128, config=cfg)
        if slow:
            eng.step_delay = 0.5
        return ServingWorker(host, port, eng, name=f"worker-{i}",
                             rank=i).start()

    workers = [mk(0, slow=True), mk(1, slow=False)]
    rc = 0
    try:
        fe.wait_for_workers(2, timeout=60)
        cli = ServingClient(host, port, name="chaos")
        ledger = _LedgerClient(cli, wire)
        futs = [cli.submit(
            [1 + i] * args.prompt_len, args.max_new)
            for i in range(args.requests)]
        lost = _drain_futures(futs, args.timeout)
        cli.close()
        dup = len(ledger.delivered) - len({r for r, _ in ledger.delivered})
        stats = fe.stats()
        print(f"# slow-replica: hedged={stats['hedged']} lost={len(lost)} "
              f"duplicate_deliveries={dup}", file=sys.stderr)
        if lost or dup:
            print(f"# FAIL: lost={lost} dup={dup}", file=sys.stderr)
            rc = 4
        elif stats["hedged"] == 0:
            print("# FAIL: the slow replica never triggered a hedge",
                  file=sys.stderr)
            rc = 1
    finally:
        for w in workers:
            w.stop()
        fe.stop()
        os.environ.pop("HOROVOD_SERVING_HEDGE", None)
    return rc


def chaos_overload(args):
    """Burst at ~4x the sustainable rate with a 50/50 priority mix and
    shedding enabled: the brownout/shed path must confine degradation to
    the best-effort class while high-priority p99 stays within 1.5x of
    its uncontended baseline."""
    os.environ["HOROVOD_SERVING_SHED"] = "0.5"
    from horovod_tpu.runtime import wire
    from horovod_tpu.serving import (ServingClient, ServingConfig,
                                     ServingFrontend)
    from horovod_tpu.serving.worker import ServingWorker, \
        build_replica_engine

    fe = ServingFrontend(max_backlog=2 * args.max_batch).start()
    host, port = fe.addr[0], fe.addr[1]
    cfg = ServingConfig(block_size=args.block_size, num_blocks=args.blocks,
                        max_batch=args.max_batch, max_context=128)
    worker = ServingWorker(host, port, build_replica_engine(
        max_seq_len=128, config=cfg), name="worker-0", rank=0).start()
    rc = 0
    try:
        fe.wait_for_workers(1, timeout=60)
        cli = ServingClient(host, port, name="chaos", max_retries=8)
        # warmup — pay the compile cost outside every measurement window
        for i in range(2):
            cli.submit([1 + i] * args.prompt_len, args.max_new,
                       priority=wire.SERVE_PRIO_HIGH).result(
                           timeout=args.timeout)
        # phase 1a — uncontended baseline: sequential high-priority load
        base_lats = []
        for i in range(max(8, args.requests // 4)):
            f = cli.submit([1 + i % 64] * args.prompt_len, args.max_new,
                           priority=wire.SERVE_PRIO_HIGH)
            f.result(timeout=args.timeout)
            base_lats.append(f.client_latency())
        base_p99 = float(np.percentile(base_lats, 99))
        # phase 1b — sustainable throughput at full batch occupancy (the
        # rate the burst must beat; a sequential probe would undercount
        # capacity by roughly the batch width)
        probe = max(2 * args.max_batch, args.requests // 2)
        t0 = time.monotonic()
        _drain_futures(
            [cli.submit([1 + i % 64] * args.prompt_len, args.max_new,
                        priority=wire.SERVE_PRIO_HIGH)
             for i in range(probe)], args.timeout)
        sustainable = probe / (time.monotonic() - t0)

        # phase 2 — 4x sustainable burst. The high class stays inside
        # capacity (1 in 8 submits ≈ 0.5x sustainable): the contract
        # under test is that best-effort overload cannot starve it, not
        # that an over-capacity high class magically stays fast.
        rng = np.random.RandomState(args.seed)
        futs = {wire.SERVE_PRIO_HIGH: [], wire.SERVE_PRIO_BEST_EFFORT: []}
        for i in range(args.requests):
            time.sleep(rng.exponential(1.0 / (4.0 * sustainable)))
            prio = (wire.SERVE_PRIO_HIGH if i % 8 == 0
                    else wire.SERVE_PRIO_BEST_EFFORT)
            futs[prio].append(cli.submit(
                rng.randint(1, 251, size=args.prompt_len).tolist(),
                args.max_new, priority=prio))
        all_futs = futs[0] + futs[1]
        lost = _drain_futures(all_futs, args.timeout)
        stats = fe.stats()
        cli.close()

        shed_wrong_class = [f.id for f in futs[wire.SERVE_PRIO_HIGH]
                            if f.status == wire.SERVE_SHED]
        hi_lats = [f.client_latency()
                   for f in futs[wire.SERVE_PRIO_HIGH]
                   if f.done() and not f._failed]
        hi_p99 = (float(np.percentile(hi_lats, 99))
                  if hi_lats else float("inf"))
        ratio = hi_p99 / max(base_p99, 1e-9)
        print(f"# overload: sustainable={sustainable:.1f}/s "
              f"base_p99={base_p99 * 1e3:.0f}ms hi_p99={hi_p99 * 1e3:.0f}ms "
              f"ratio={ratio:.2f} shed={stats['shed']} lost={len(lost)}",
              file=sys.stderr)
        if lost or shed_wrong_class:
            print(f"# FAIL: lost={lost} "
                  f"high-priority sheds={shed_wrong_class}",
                  file=sys.stderr)
            rc = 4
        elif stats["shed"] == 0:
            print("# FAIL: the burst never tripped the shed path",
                  file=sys.stderr)
            rc = 1
        # the ratio gate rides the perf-history machinery so drift is
        # caught across runs, not just against the in-run baseline
        if args.history and rc == 0:
            from benchmarks.history import (append_record,
                                            check_regression, load_history)

            metric = "serving_overload_high_p99_ratio"
            if args.check_regression:
                verdict = check_regression(
                    load_history(args.history, metric=metric),
                    ratio, direction="lower")
                print("# regression check: %s" % json.dumps(verdict),
                      file=sys.stderr)
                if verdict["regression"]:
                    rc = 3
            append_record(args.history, {
                "metric": metric, "value": round(ratio, 3), "unit": "x",
                "shed": stats["shed"], "requests": args.requests})
        if rc == 0 and ratio > 1.5 and hi_p99 > 0.25:
            # absolute guard rail from the acceptance criterion (the
            # 0.25s floor keeps millisecond-scale noise from flaking CI)
            print(f"# FAIL: high-priority p99 degraded {ratio:.2f}x under "
                  "overload (budget 1.5x)", file=sys.stderr)
            rc = 1
    finally:
        worker.stop()
        fe.stop()
        os.environ.pop("HOROVOD_SERVING_SHED", None)
    return rc


def chaos_rolling_restart(args):
    """Drain → kill → replace each replica in turn under load: the drain
    hands queued work back for re-dispatch and lets in-flight work
    finish, so the rolling restart loses and duplicates nothing."""
    from horovod_tpu.runtime import wire
    from horovod_tpu.serving import (ServingClient, ServingConfig,
                                     ServingFrontend)
    from horovod_tpu.serving.worker import ServingWorker, \
        build_replica_engine

    fe = ServingFrontend().start()
    host, port = fe.addr[0], fe.addr[1]

    def mk(name, rank):
        cfg = ServingConfig(block_size=args.block_size,
                            num_blocks=args.blocks,
                            max_batch=args.max_batch, max_context=128)
        return ServingWorker(host, port, build_replica_engine(
            max_seq_len=128, config=cfg), name=name, rank=rank).start()

    workers = {"worker-0": mk("worker-0", 0), "worker-1": mk("worker-1", 1)}
    rc = 0
    try:
        fe.wait_for_workers(2, timeout=60)
        cli = ServingClient(host, port, name="chaos")
        ledger = _LedgerClient(cli, wire)
        rng = np.random.RandomState(args.seed)
        futs = []
        restarts = ["worker-0", "worker-1"]
        restart_at = {args.requests // 3: "worker-0",
                      2 * args.requests // 3: "worker-1"}
        gen = 0
        for i in range(args.requests):
            time.sleep(rng.exponential(1.0 / max(args.qps, 1e-6)))
            futs.append(cli.submit(
                rng.randint(1, 251, size=args.prompt_len).tolist(),
                args.max_new))
            name = restart_at.get(i)
            if name:
                print(f"# rolling restart: draining {name}",
                      file=sys.stderr)
                assert fe.drain_worker(name)
                assert fe.wait_worker_drained(name, timeout=args.timeout)
                workers[name].stop()
                gen += 1
                workers[name] = mk(name, gen + 1)
        lost = _drain_futures(futs, args.timeout)
        cli.close()
        dup = len(ledger.delivered) - len({r for r, _ in ledger.delivered})
        print(f"# rolling-restart: {len(futs) - len(lost)}/{len(futs)} ok, "
              f"restarted {restarts}, dup={dup}", file=sys.stderr)
        if lost or dup:
            print(f"# FAIL: lost={lost} dup={dup}", file=sys.stderr)
            rc = 4
    finally:
        for w in workers.values():
            w.stop()
        fe.stop()
    return rc


_CHAOS = {
    "kill-frontend": chaos_kill_frontend,
    "slow-replica": chaos_slow_replica,
    "overload": chaos_overload,
    "rolling-restart": chaos_rolling_restart,
}


def main(argv=None):
    args = parse_args(argv)
    if args.chaos:
        return _CHAOS[args.chaos](args)
    if args.kill_one and args.workers < 2:
        sys.exit("--kill-one needs --workers >= 2 (someone must survive)")
    lats, toks, wall, lost = (run_pod(args) if args.workers
                              else run_inprocess(args))
    if not lats:
        sys.exit("no requests completed")
    p50 = float(np.percentile(lats, 50))
    p99 = float(np.percentile(lats, 99))
    qps = len(lats) / wall
    tok_s = toks / wall
    # bucketed p99 alongside the exact one: the same estimate Prometheus
    # consumers (anomaly watch, SLO engine) compute from the histogram
    # family, so the bench shows the quantization error operators will see
    from horovod_tpu.metrics import LATENCY_BUCKETS, quantile_from_buckets

    counts = [0] * (len(LATENCY_BUCKETS) + 1)
    for lat in lats:
        for i, b in enumerate(LATENCY_BUCKETS):
            if lat <= b:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
    p99_bucketed = quantile_from_buckets(LATENCY_BUCKETS, counts, 0.99)
    print(f"# {len(lats)}/{args.requests} requests in {wall:.2f}s "
          f"({'pod, %d workers' % args.workers if args.workers else 'in-process'})",
          file=sys.stderr)
    print(f"# sustained QPS: {qps:.1f}; tokens/s: {tok_s:.0f}; "
          f"p50: {p50 * 1e3:.1f}ms; p99: {p99 * 1e3:.1f}ms "
          f"(bucketed: {p99_bucketed * 1e3:.1f}ms); lost: {lost}",
          file=sys.stderr)
    result = {
        "metric": "serving_p99_seconds",
        "value": round(p99, 4),
        "unit": "s",
        "qps": round(qps, 2),
        "tokens_per_sec": round(tok_s, 1),
        "p50_seconds": round(p50, 4),
        "lost": lost,
    }
    print(json.dumps(result))

    rc = 0
    if lost:
        print(f"# FAIL: {lost} request(s) lost — elastic re-admission must "
              "leave zero behind", file=sys.stderr)
        rc = 4
    if args.history:
        from benchmarks.history import (append_record, check_regression,
                                        load_history)

        # compare against the trajectory BEFORE appending: today's run
        # must not be allowed to vote in its own baseline
        if args.check_regression:
            verdict = check_regression(
                load_history(args.history, metric=result["metric"]),
                result["value"], direction="lower",
                **{k: v for k, v in (
                    ("window", args.regression_window),
                    ("tolerance", args.regression_tolerance))
                   if v is not None})
            print("# regression check: %s" % json.dumps(verdict),
                  file=sys.stderr)
            if verdict["regression"]:
                print(f"# REGRESSION: p99 {result['value']}s rose above "
                      f"the bound {verdict['floor']}s (baseline "
                      f"{verdict['baseline']}s over {verdict['samples']} "
                      "runs)", file=sys.stderr)
                rc = rc or 3
        append_record(args.history, {
            "metric": result["metric"], "value": result["value"],
            "unit": result["unit"], "qps": result["qps"],
            "tokens_per_sec": result["tokens_per_sec"],
            "p50_seconds": result["p50_seconds"],
            "workers": args.workers, "requests": args.requests,
            "prompt_len": args.prompt_len, "max_new": args.max_new,
        })
        print(f"# perf history appended to {args.history}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
