#!/usr/bin/env python
"""Serving-mode load benchmark: sustained QPS, p50/p99 latency, tokens/s.

A Poisson load generator over the inference serving subsystem
(horovod_tpu/serving/, docs/inference.md). Requests arrive with
exponential inter-arrival times at ``--qps``, each a random prompt of
``--prompt-len`` tokens decoding ``--max-new`` tokens; the bench waits for
every completion and reports the sustained rate and the latency tail.

Two modes:

* **in-process** (default): one ``ServingEngine`` replica, submits go
  straight to the engine. This is the deterministic perf-gate mode.
* **pod** (``--workers N``): spawns a ``ServingFrontend`` plus N worker
  replica subprocesses (``python -m horovod_tpu.serving.worker``) and
  drives them through a ``ServingClient`` over the hardened control
  plane. ``--kill-one`` SIGKILLs a worker mid-run and asserts ZERO lost
  requests — the killed replica's in-flight work must re-admit onto the
  survivors (exit 4 if anything is lost), which is the ISSUE-11
  acceptance demonstration.

With ``--history PATH`` the run's p99 appends to the schema-versioned
JSONL store (benchmarks/history.py); ``--check-regression`` compares
against the trajectory BEFORE appending with ``direction="lower"``
(latency: smaller is better) and exits 3 when the fresh p99 rises above
the tolerance bound.

    JAX_PLATFORMS=cpu python benchmarks/serving_bench.py            # smoke
    python benchmarks/serving_bench.py --workers 2 --kill-one       # pod
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="Poisson load generator for the serving subsystem")
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--qps", type=float, default=16.0,
                   help="Poisson arrival rate (requests/second)")
    p.add_argument("--prompt-len", type=int, default=8)
    p.add_argument("--max-new", type=int, default=8)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout", type=float, default=300.0,
                   help="per-request completion deadline")
    p.add_argument("--workers", type=int, default=0,
                   help="pod mode: spawn a frontend + N worker replica "
                        "subprocesses (0 = in-process engine)")
    p.add_argument("--kill-one", action="store_true",
                   help="pod mode: SIGKILL one worker mid-run and require "
                        "zero lost requests (exit 4 on loss)")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--blocks", type=int, default=256)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--history", metavar="PATH", default=None,
                   help="append this run's p99 to a schema-versioned JSONL "
                        "perf history (benchmarks/history.py)")
    p.add_argument("--check-regression", action="store_true",
                   help="with --history: compare this run's p99 against "
                        "the recorded trajectory BEFORE appending "
                        "(direction=lower); exit 3 above the tolerance "
                        "bound")
    p.add_argument("--regression-window", type=int, default=None)
    p.add_argument("--regression-tolerance", type=float, default=None)
    return p.parse_args(argv)


def poisson_load(submit, args, vocab=251):
    """Drive ``submit(prompt, max_new) -> future`` at Poisson arrivals;
    returns (futures, submit_wall_seconds)."""
    rng = np.random.RandomState(args.seed)
    futs = []
    t0 = time.monotonic()
    next_t = t0
    for _ in range(args.requests):
        next_t += rng.exponential(1.0 / max(args.qps, 1e-6))
        while True:
            now = time.monotonic()
            if now >= next_t:
                break
            time.sleep(min(0.002, next_t - now))
        prompt = rng.randint(1, vocab, size=args.prompt_len).tolist()
        futs.append(submit(prompt, args.max_new))
    return futs, time.monotonic() - t0


def run_inprocess(args):
    from horovod_tpu.serving import ServingConfig
    from horovod_tpu.serving.worker import build_replica_engine

    cfg = ServingConfig(block_size=args.block_size, num_blocks=args.blocks,
                        max_batch=args.max_batch, max_context=128)
    engine = build_replica_engine(max_seq_len=128, config=cfg).start()
    # one throwaway request compiles prefill+decode outside the timed window
    engine.submit([1] * args.prompt_len, 2).wait(timeout=args.timeout)

    t0 = time.monotonic()
    futs, _ = poisson_load(engine.submit, args)
    for f in futs:
        f.wait(timeout=args.timeout)
    wall = time.monotonic() - t0
    engine.stop()
    lost = [f for f in futs if not f.done() or f.state != "done"]
    lats = [f.latency() for f in futs if f.latency() is not None]
    toks = sum(len(f.output) for f in futs)
    return lats, toks, wall, len(lost)


def run_pod(args):
    from horovod_tpu.serving import ServingClient, ServingFrontend

    fe = ServingFrontend().start()
    host, port = fe.addr[0], fe.addr[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = []
    for i in range(args.workers):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.serving.worker",
             "--addr", f"{host}:{port}", "--rank", str(i + 1),
             "--max-batch", str(args.max_batch),
             "--blocks", str(args.blocks),
             "--block-size", str(args.block_size)],
            env=env, cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
    try:
        fe.wait_for_workers(args.workers, timeout=120)
        cli = ServingClient(host, port, name="bench")
        # warm every replica's compile cache before the timed window
        warm = [cli.submit([1] * args.prompt_len, 2)
                for _ in range(args.workers * args.max_batch)]
        for f in warm:
            f.result(timeout=args.timeout)

        t0 = time.monotonic()
        kill_at = args.requests // 3 if args.kill_one else None
        futs = []
        rng = np.random.RandomState(args.seed)
        next_t = time.monotonic()
        for i in range(args.requests):
            next_t += rng.exponential(1.0 / max(args.qps, 1e-6))
            while time.monotonic() < next_t:
                time.sleep(0.002)
            prompt = rng.randint(1, 251, size=args.prompt_len).tolist()
            futs.append(cli.submit(prompt, args.max_new))
            if kill_at is not None and i == kill_at:
                victim = procs[0]
                print(f"# SIGKILL worker pid {victim.pid} mid-run",
                      file=sys.stderr)
                victim.kill()
        lost = 0
        lats, toks = [], 0
        for f in futs:
            try:
                tokens = f.result(timeout=args.timeout)
            except (RuntimeError, TimeoutError) as exc:
                print(f"# LOST {f.id}: {exc}", file=sys.stderr)
                lost += 1
                continue
            toks += len(tokens)
            lats.append(f.client_latency())
        wall = time.monotonic() - t0
        print("# frontend: %s" % json.dumps(fe.stats()), file=sys.stderr)
        cli.close()
        return lats, toks, wall, lost
    finally:
        for pr in procs:
            if pr.poll() is None:
                pr.terminate()
        for pr in procs:
            try:
                pr.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pr.kill()
        fe.stop()


def main(argv=None):
    args = parse_args(argv)
    if args.kill_one and args.workers < 2:
        sys.exit("--kill-one needs --workers >= 2 (someone must survive)")
    lats, toks, wall, lost = (run_pod(args) if args.workers
                              else run_inprocess(args))
    if not lats:
        sys.exit("no requests completed")
    p50 = float(np.percentile(lats, 50))
    p99 = float(np.percentile(lats, 99))
    qps = len(lats) / wall
    tok_s = toks / wall
    # bucketed p99 alongside the exact one: the same estimate Prometheus
    # consumers (anomaly watch, SLO engine) compute from the histogram
    # family, so the bench shows the quantization error operators will see
    from horovod_tpu.metrics import LATENCY_BUCKETS, quantile_from_buckets

    counts = [0] * (len(LATENCY_BUCKETS) + 1)
    for lat in lats:
        for i, b in enumerate(LATENCY_BUCKETS):
            if lat <= b:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
    p99_bucketed = quantile_from_buckets(LATENCY_BUCKETS, counts, 0.99)
    print(f"# {len(lats)}/{args.requests} requests in {wall:.2f}s "
          f"({'pod, %d workers' % args.workers if args.workers else 'in-process'})",
          file=sys.stderr)
    print(f"# sustained QPS: {qps:.1f}; tokens/s: {tok_s:.0f}; "
          f"p50: {p50 * 1e3:.1f}ms; p99: {p99 * 1e3:.1f}ms "
          f"(bucketed: {p99_bucketed * 1e3:.1f}ms); lost: {lost}",
          file=sys.stderr)
    result = {
        "metric": "serving_p99_seconds",
        "value": round(p99, 4),
        "unit": "s",
        "qps": round(qps, 2),
        "tokens_per_sec": round(tok_s, 1),
        "p50_seconds": round(p50, 4),
        "lost": lost,
    }
    print(json.dumps(result))

    rc = 0
    if lost:
        print(f"# FAIL: {lost} request(s) lost — elastic re-admission must "
              "leave zero behind", file=sys.stderr)
        rc = 4
    if args.history:
        from benchmarks.history import (append_record, check_regression,
                                        load_history)

        # compare against the trajectory BEFORE appending: today's run
        # must not be allowed to vote in its own baseline
        if args.check_regression:
            verdict = check_regression(
                load_history(args.history, metric=result["metric"]),
                result["value"], direction="lower",
                **{k: v for k, v in (
                    ("window", args.regression_window),
                    ("tolerance", args.regression_tolerance))
                   if v is not None})
            print("# regression check: %s" % json.dumps(verdict),
                  file=sys.stderr)
            if verdict["regression"]:
                print(f"# REGRESSION: p99 {result['value']}s rose above "
                      f"the bound {verdict['floor']}s (baseline "
                      f"{verdict['baseline']}s over {verdict['samples']} "
                      "runs)", file=sys.stderr)
                rc = rc or 3
        append_record(args.history, {
            "metric": result["metric"], "value": result["value"],
            "unit": result["unit"], "qps": result["qps"],
            "tokens_per_sec": result["tokens_per_sec"],
            "p50_seconds": result["p50_seconds"],
            "workers": args.workers, "requests": args.requests,
            "prompt_len": args.prompt_len, "max_new": args.max_new,
        })
        print(f"# perf history appended to {args.history}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
