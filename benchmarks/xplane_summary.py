#!/usr/bin/env python
"""Summarize a jax.profiler trace (xplane.pb) into a per-op time table.

The image has no tensorboard profile plugin; this reads the XSpace proto
directly (tensorflow.tsl ships the schema) and aggregates event durations
on the device planes — the "xplane op breakdown" the perf docs cite.

    LM_PROFILE=/tmp/lmprof python benchmarks/lm_bench.py
    python benchmarks/xplane_summary.py /tmp/lmprof [top_n]

``--host-trace PATH`` additionally (or instead) ingests the merged
host-side Chrome trace written by the distributed tracer
(``HOROVOD_TRACE``, docs/tracing.md) and prints the same exposed-comm %
breakdown that ``bin/hvdprof report`` gives — so one command covers both
the device-op view and the cross-rank critical-path view:

    python benchmarks/xplane_summary.py /tmp/lmprof --host-trace hvd_trace.json
    python benchmarks/xplane_summary.py --host-trace hvd_trace.json
"""

import argparse
import glob
import os
import sys
from collections import defaultdict


def load_xspaces(root):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(os.path.join(root, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise SystemExit(f"no .xplane.pb under {root}")
    spaces = []
    for p in paths:
        xs = xplane_pb2.XSpace()
        with open(p, "rb") as f:
            xs.ParseFromString(f.read())
        spaces.append((p, xs))
    return spaces


def summarize(root, top_n=25):
    agg = defaultdict(float)          # op name -> total ms
    plane_totals = defaultdict(float)
    for _, xs in load_xspaces(root):
        for plane in xs.planes:
            # device planes carry the op timeline; host/python planes are
            # trace noise for this purpose
            if not ("tpu" in plane.name.lower()
                    or "device" in plane.name.lower()):
                continue
            emeta = {k: v.name for k, v in plane.event_metadata.items()}
            for line in plane.lines:
                # derived lines (module/step containers) span whole
                # executions and would double-count every op under them
                if any(s in line.name.lower() for s in ("module", "step")):
                    continue
                for ev in line.events:
                    nm = emeta.get(ev.metadata_id, f"#{ev.metadata_id}")
                    if nm.startswith("jit_"):  # whole-program container
                        continue
                    ms = ev.duration_ps / 1e9
                    agg[nm] += ms
                    plane_totals[plane.name] += ms
    total = sum(agg.values())
    print(f"planes: {dict(plane_totals)}")
    print(f"{'op':<72} {'ms':>10} {'%':>6}")
    for nm, ms in sorted(agg.items(), key=lambda kv: -kv[1])[:top_n]:
        print(f"{nm[:72]:<72} {ms:>10.2f} {100 * ms / total:>5.1f}%")
    print(f"{'TOTAL (sum of events; includes nesting overlap)':<72} "
          f"{total:>10.2f}")


def summarize_host_trace(path, top_n=10):
    """Exposed-comm breakdown of a merged ``HOROVOD_TRACE`` Chrome trace —
    the same report ``bin/hvdprof report`` prints, inlined here so the
    device-op table and the host critical path come out of one command."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from horovod_tpu.tracing import analyzer

    report = analyzer.analyze(path, top=top_n)
    print(analyzer.format_report(report, path=path))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("root", nargs="?", default=None,
                   help="directory holding .xplane.pb files from a "
                        "jax.profiler trace")
    p.add_argument("top_n", nargs="?", type=int, default=25,
                   help="rows in the per-op table (default 25)")
    p.add_argument("--host-trace", metavar="PATH", default=None,
                   help="merged Chrome trace from HOROVOD_TRACE; prints the "
                        "hvdprof exposed-comm %% breakdown after (or instead "
                        "of) the device-op table")
    args = p.parse_args(argv)
    if args.root is None and args.host_trace is None:
        p.error("need an xplane root, --host-trace, or both")
    return args


def main(argv=None):
    args = parse_args(argv)
    if args.root is not None:
        summarize(args.root, args.top_n)
    if args.host_trace is not None:
        if args.root is not None:
            print()
        summarize_host_trace(args.host_trace)


if __name__ == "__main__":
    main()
