#!/usr/bin/env python
"""Allreduce bus-bandwidth microbench — SPMD data plane and eager engine.

The reference's perf story is collective bandwidth (NCCL ring allreduce,
`nccl_operations.cc:55-105`; timeline makes per-op cost visible). This
measures the TPU-native equivalents:

  * ``spmd``  — `psum` inside a jitted `shard_map` over the device mesh: the
    hot path XLA compiles onto ICI. Per-device buffers are distinct, so the
    collective cannot be constant-folded.
  * ``eager`` — `hvd.allreduce` through the background engine (tensor queue →
    negotiation → fused XLA program → host round-trip). The delta vs ``spmd``
    is the engine + host-boundary overhead the reference's timeline exposes
    as QUEUE/MEMCPY/NEGOTIATE spans.

Reports, per message size: algorithm bandwidth (bytes/s of one rank's buffer)
and bus bandwidth (algbw x 2(n-1)/n — the ring-transfer normalization NCCL
uses, so numbers are comparable to `nccl-tests`).

Run on a virtual pod:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python benchmarks/allreduce_bench.py

Prints one JSON line per (path, size); final line is a summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Honor an explicit CPU request even under the axon sitecustomize, which
# pre-imports jax pointed at the TPU relay (same dance as tests/conftest.py).
if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np


def bench_spmd(sizes_mb, iters, warmup):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu import spmd
    from horovod_tpu.basics import MESH_AXIS

    mesh = hvd.mesh()
    n = hvd.num_replicas()
    results = []
    for mb in sizes_mb:
        nelem = max(1, int(mb * (1 << 20)) // 4)
        # distinct per-device shards: [n, nelem] split on dim 0, psum inside
        # shard_map -> a real cross-device reduce, not a replication no-op.
        x = jnp.arange(n * nelem, dtype=jnp.float32).reshape(n, nelem)
        x = jax.device_put(x, NamedSharding(mesh, P(MESH_AXIS)))

        @jax.jit
        def reduce(x):
            return jax.shard_map(
                lambda s: jax.lax.psum(s, MESH_AXIS), mesh=mesh,
                in_specs=P(MESH_AXIS), out_specs=P(MESH_AXIS))(x)

        out = reduce(x)
        for _ in range(warmup - 1):
            out = reduce(x)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = reduce(x)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        algbw = nelem * 4 / dt
        busbw = algbw * (2 * (n - 1) / n) if n > 1 else algbw
        results.append({"path": "spmd", "size_mb": mb, "n": n,
                        "time_us": round(dt * 1e6, 1),
                        "algbw_gbps": round(algbw / 1e9, 3),
                        "busbw_gbps": round(busbw / 1e9, 3)})
        print(json.dumps(results[-1]))
    return results


def bench_eager(sizes_mb, iters, warmup):
    import horovod_tpu as hvd

    n = hvd.size()
    results = []
    for mb in sizes_mb:
        nelem = max(1, int(mb * (1 << 20)) // 4)
        x = np.arange(nelem, dtype=np.float32)
        for _ in range(warmup):
            hvd.allreduce(x, name=f"bench_{mb}")
        t0 = time.perf_counter()
        for i in range(iters):
            hvd.allreduce(x, name=f"bench_{mb}")
        dt = (time.perf_counter() - t0) / iters
        algbw = nelem * 4 / dt
        busbw = algbw * (2 * (n - 1) / n) if n > 1 else algbw
        results.append({"path": "eager", "size_mb": mb, "n": n,
                        "time_us": round(dt * 1e6, 1),
                        "algbw_gbps": round(algbw / 1e9, 3),
                        "busbw_gbps": round(busbw / 1e9, 3)})
        print(json.dumps(results[-1]))
    return results


def bench_allgather(sizes_mb, iters, warmup):
    """Eager allgather across cluster sizes at FIXED total output size.

    The result of each gather is one compiled program whose outputs stay
    replicated on the rank devices (`executor._allgather_assemble_fn`) —
    nothing moves through the host per destination. Evidence: time per op
    stays ~flat as the rank count grows (the round-2 per-destination
    ``device_put`` loop grew linearly in world size x output bytes).
    """
    import jax

    import horovod_tpu as hvd
    from horovod_tpu import testing

    results = []
    world_sizes = [n for n in (2, 4, 8) if n <= len(jax.devices())]
    for mb in sizes_mb:
        for n in world_sizes:
            total_elems = max(n, int(mb * (1 << 20)) // 4)
            rows = total_elems // n  # per-rank contribution; output constant

            def worker():
                import time as _t

                x = np.full((rows,), float(hvd.rank()), np.float32)
                for i in range(warmup):
                    hvd.allgather(x, name="agb")
                t0 = _t.perf_counter()
                for i in range(iters):
                    out = hvd.allgather(x, name="agb")
                return (_t.perf_counter() - t0) / iters

            if hvd.is_initialized():
                hvd.shutdown()
            dts = testing.run_cluster(worker, np=n)
            hvd.shutdown()
            dt = max(dts)
            results.append({"path": "eager-allgather", "size_mb": mb, "n": n,
                            "time_us": round(dt * 1e6, 1),
                            "gather_gbps": round(total_elems * 4 / dt / 1e9,
                                                 3)})
            print(json.dumps(results[-1]))
    return results


_COMPRESSION_MODES = ("none", "bf16", "int8", "int8-dcn", "int4", "adaptive")


def bench_compression(sizes_mb, iters, warmup, modes):
    """Wire-mode sweep through the eager engine: same fp32 payload, each
    wire format. Reports the bytes each mode actually moves (the
    executor's per-rank reduce+gather accounting — int8 pays 1 byte/elem +
    one f32 scale per block, int4 packs two values per byte, on both hops)
    and the resulting wire GB/s. ``int8-dcn`` runs on a synthetic 2-host
    topology (HVD_LOCAL_SIZE=2) so the mixed bf16-ICI/int8-DCN program
    actually compiles. ``adaptive`` feeds the bitwidth selector during
    warmup (extended past the decision interval) so the timed iterations
    ride the converged per-bucket grid.
    """
    import horovod_tpu as hvd
    from horovod_tpu import testing
    from horovod_tpu.ops import compression as comp

    results = []
    for mode in modes:
        two_level = mode == "int8-dcn"
        for mb in sizes_mb:
            nelem = max(1, int(mb * (1 << 20)) // 4)

            def worker():
                import time as _t

                from horovod_tpu import basics
                from horovod_tpu.ops import adaptive as _ad

                c = comp.by_name(mode)
                observe = getattr(c, "observe", None)
                if observe is not None:
                    comp.AdaptiveCompressor.reset()
                # the selector re-decides every interval() observations —
                # warm up past the first boundary so timing sees the
                # converged grid
                warm = (max(warmup, _ad.interval() + 2)
                        if observe is not None else warmup)
                x = np.arange(nelem, dtype=np.float32) / nelem - 0.5
                for _ in range(warm):
                    out = hvd.allreduce(x, name="cb", op=hvd.Sum,
                                        compression=c)
                    if observe is not None:
                        observe("cb", np.asarray(out))
                t0 = _t.perf_counter()
                for _ in range(iters):
                    out = hvd.allreduce(x, name="cb", op=hvd.Sum,
                                        compression=c)
                    if observe is not None:
                        observe("cb", np.asarray(out))
                dt = (_t.perf_counter() - t0) / iters
                ex = basics._engine()._executor
                return dt, ex.last_wire_mode, ex.last_wire_bytes

            if hvd.is_initialized():
                hvd.shutdown()
            if two_level:
                os.environ["HVD_LOCAL_SIZE"] = "2"
            try:
                outs = testing.run_cluster(worker, np=4)
            finally:
                hvd.shutdown()
                if two_level:
                    os.environ.pop("HVD_LOCAL_SIZE", None)
            dt = max(o[0] for o in outs)
            wire_bytes = max(o[2] for o in outs)
            fp32_bytes = comp.wire_footprint(nelem, "none")
            results.append({
                "path": "compression", "mode": mode, "size_mb": mb, "n": 4,
                "wire_mode": outs[0][1],  # the grid that actually compiled
                "time_us": round(dt * 1e6, 1),
                "wire_bytes": wire_bytes,
                "wire_ratio_vs_fp32": round(wire_bytes / fp32_bytes, 4),
                "wire_gbps": round(wire_bytes / dt / 1e9, 3),
                "effective_algbw_gbps": round(nelem * 4 / dt / 1e9, 3),
            })
            print(json.dumps(results[-1]))
    return results


_ALGO_WIRES = ("off", "int8", "int4")


def bench_algo_sweep(sizes_mb, iters, warmup, wires=_ALGO_WIRES):
    """Algorithm-zoo sweep on the compiled fast path: one jitted shard_map
    program per (payload size, algorithm, bitwidth) cell — the flat
    bidirectional ring, the recursive-halving/doubling tree, and the
    two-level hierarchical schedule, each over the exact and quantized
    wires. One JSON row per cell (step time, algbw, catalog wire bytes);
    the driver derives the per-size "tuned" row as the step-time argmin,
    which is what the joint tuner converges to online (docs/autotune.md).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu import spmd
    from horovod_tpu.basics import MESH_AXIS, Average
    from horovod_tpu.ops import compression as comp

    mesh = hvd.mesh()
    n = hvd.num_replicas()
    block = comp.block_size()
    hosts = spmd.mesh_hosts(n)
    zoo = (("ring", spmd.quantized_allreduce),
           ("tree", spmd.quantized_allreduce_tree),
           ("hier", spmd.quantized_allreduce_hier))
    results = []
    for mb in sizes_mb:
        nelem = max(1, int(mb * (1 << 20)) // 4)
        x = jnp.arange(n * nelem, dtype=jnp.float32).reshape(n, nelem)
        x = jax.device_put(x, NamedSharding(mesh, P(MESH_AXIS)))
        for algo, fn in zoo:
            for wire in wires:
                def body(row, fn=fn, wire=wire):
                    return fn(row[0], Average, MESH_AXIS, wire)[None]

                reduce = jax.jit(spmd._shard_map(
                    body, mesh, in_specs=P(MESH_AXIS),
                    out_specs=P(MESH_AXIS)))
                out = reduce(x)
                for _ in range(warmup - 1):
                    out = reduce(x)
                jax.block_until_ready(out)
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = reduce(x)
                jax.block_until_ready(out)
                dt = (time.perf_counter() - t0) / iters
                mode = "none" if wire == "off" else wire
                wire_bytes = comp.gspmd_wire_footprint(
                    nelem, mode, n, block, algorithm=algo,
                    hosts=hosts if algo == "hier" else None)
                results.append({
                    "path": "algo", "algorithm": algo, "mode": wire,
                    "size_mb": mb, "n": n,
                    "time_us": round(dt * 1e6, 1),
                    "algbw_gbps": round(nelem * 4 / dt / 1e9, 3),
                    "wire_bytes": wire_bytes,
                })
                print(json.dumps(results[-1]))
    return results


def bench_bucket_overlap(bucket_mbs, iters, warmup, layers=16, np_=8):
    """Backward-pass bucket-overlap sweep (HOROVOD_BUCKET_MB,
    docs/overlap.md): a synthetic gradient pytree (``layers`` x
    [256, 1024] weight + [1024] bias, fp32) rides ``allreduce_gradients``
    with the bucket knob swept; 0 is the per-leaf baseline. Reports the
    drain wall time AND the per-step exposed-communication seconds
    (hvd_exposed_comm_seconds delta — time blocked in synchronize, the
    quantity bucket overlap exists to shrink)."""
    import horovod_tpu as hvd
    from horovod_tpu import testing

    shapes = [(256, 1024), (1024,)] * layers
    total_mb = sum(int(np.prod(s)) for s in shapes) * 4 / (1 << 20)
    results = []
    for bmb in bucket_mbs:

        def worker():
            import time as _t

            from horovod_tpu.metrics import instruments
            from horovod_tpu.optim import distributed as dist

            rng = np.random.RandomState(1234)
            grads = [rng.randn(*s).astype(np.float32) for s in shapes]
            for _ in range(warmup):
                dist.allreduce_gradients(grads, op=hvd.Sum, prefix="ob")
            e0 = instruments.exposed_comm_seconds().value
            t0 = _t.perf_counter()
            for _ in range(iters):
                dist.allreduce_gradients(grads, op=hvd.Sum, prefix="ob")
            dt = (_t.perf_counter() - t0) / iters
            exposed = (instruments.exposed_comm_seconds().value - e0) / iters
            return dt, exposed

        if hvd.is_initialized():
            hvd.shutdown()
        if bmb > 0:
            os.environ["HOROVOD_BUCKET_MB"] = str(bmb)
        else:
            os.environ.pop("HOROVOD_BUCKET_MB", None)
        try:
            outs = testing.run_cluster(worker, np=np_)
        finally:
            hvd.shutdown()
            os.environ.pop("HOROVOD_BUCKET_MB", None)
        dt = max(o[0] for o in outs)
        exposed = max(o[1] for o in outs)
        results.append({
            "path": "bucket-overlap", "bucket_mb": bmb, "n": np_,
            "layers": layers, "total_mb": round(total_mb, 2),
            "time_us": round(dt * 1e6, 1),
            "exposed_comm_us": round(exposed * 1e6, 1),
            "exposed_comm_pct": round(100.0 * exposed / dt, 1) if dt else 0.0,
            "algbw_gbps": round(total_mb * (1 << 20) / dt / 1e9, 3),
        })
        print(json.dumps(results[-1]))
    return results


def bench_straggler_chaos(chaos, iters, warmup, np_=4, victim=1,
                          deadline="3x"):
    """Straggler-chaos acceptance bench (docs/fault-tolerance.md): the same
    eager allreduce loop run twice — clean, then with ``chaos`` (e.g.
    ``slow@rank:500``) injected on ``victim`` — with the straggler policy
    armed (HOROVOD_STRAGGLER_DEADLINE). The claim under test: once the
    policy excludes the slow rank, the SURVIVORS' step time tracks the
    group median, not the victim's injected delay.

    Point ``rank`` is the per-process engine-tick hook (elastic mode); the
    in-process cluster shares one engine across rank threads, so it is
    mapped to ``collective`` — the per-rank enqueue hook — which models
    the same thing: one rank chronically late into every round. Runs with
    HVD_TPU_NATIVE=0 in both phases so the Python controller (the one
    that implements exclusion in-process) negotiates both sides of the
    comparison."""
    import horovod_tpu as hvd
    from horovod_tpu import faultinject, testing

    kind, _, rest = chaos.partition("@")
    point, _, chaos_args = rest.partition(":")
    if point == "rank":
        point = "collective"
    spec = f"{kind}@{point}" + (f":{chaos_args}" if chaos_args else "")
    spec += f"#{victim}"
    faultinject.parse_spec(spec)  # fail fast on a bad --chaos value

    nelem = 1 << 16

    def worker():
        import time as _t

        from horovod_tpu.metrics import instruments

        x = np.arange(nelem, dtype=np.float32) + hvd.rank()
        # in the chaos phase, extend the warmup past the policy's patience
        # window so the exclusion has engaged before the timed iterations
        # begin (same fixed count on every rank — the loop must stay in
        # lockstep). patience late rounds + the exclusion-effective round
        # + slack for arrival jitter around the relative floor.
        extra = ((int(os.environ.get("HOROVOD_STRAGGLER_PATIENCE", "2")) + 5)
                 if os.environ.get("HOROVOD_FAULT_SPEC") else 0)
        for i in range(warmup + extra):
            hvd.allreduce(x, name="chaos_g")
        steps = []
        for i in range(iters):
            t0 = _t.perf_counter()
            hvd.allreduce(x, name="chaos_g")
            steps.append(_t.perf_counter() - t0)
        return (sum(steps) / len(steps),
                instruments.partial_collectives().value)

    def run_phase(env):
        old = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            if hvd.is_initialized():
                hvd.shutdown()
            faultinject.reset_shared()
            return testing.run_cluster(worker, np=np_)
        finally:
            hvd.shutdown()
            faultinject.reset_shared()
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    base_env = {"HVD_TPU_NATIVE": "0"}
    outs = run_phase(base_env)
    baseline = sorted(o[0] for i, o in enumerate(outs)
                      if i != victim)[(np_ - 1) // 2]
    chaos_env = dict(base_env)
    chaos_env.update({
        "HOROVOD_FAULT_SPEC": spec,
        "HOROVOD_STRAGGLER_DEADLINE": deadline,
        "HOROVOD_STRAGGLER_PATIENCE": os.environ.get(
            "HOROVOD_STRAGGLER_PATIENCE", "2"),
    })
    outs = run_phase(chaos_env)
    chaos_step = sorted(o[0] for i, o in enumerate(outs)
                        if i != victim)[(np_ - 1) // 2]
    partial_rounds = max(o[1] for o in outs)
    result = {
        "path": "straggler-chaos", "n": np_, "victim": victim,
        "chaos": spec, "deadline": deadline,
        "baseline_step_us": round(baseline * 1e6, 1),
        "chaos_step_us": round(chaos_step * 1e6, 1),
        "partial_rounds": int(partial_rounds),
        "step_ratio": round(chaos_step / baseline, 3) if baseline else 0.0,
    }
    print(json.dumps(result))
    return result


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sizes-mb", default="0.0625,0.25,1,4,16,64",
                    help="comma-separated message sizes in MB (may be "
                         "empty when --sizes-kb carries the sweep)")
    ap.add_argument("--sizes-kb", default=None,
                    help="extra sub-MB message sizes in KB, merged into "
                         "the sweep (e.g. '4,16' for the latency-bound "
                         "payloads the tree algorithm targets)")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--path", choices=["spmd", "eager", "allgather",
                                       "compression", "both"],
                    default="both")
    ap.add_argument("--compression", default=None,
                    help="comma-separated wire modes to sweep "
                         f"({','.join(_COMPRESSION_MODES)}); implies "
                         "--path compression")
    ap.add_argument("--bucket-mb", default=None,
                    help="comma-separated HOROVOD_BUCKET_MB values to sweep "
                         "(0 = per-leaf baseline), e.g. '0,0.5,1,4'; runs "
                         "the bucket-overlap bench instead of --path")
    ap.add_argument("--layers", type=int, default=16,
                    help="synthetic model depth for --bucket-mb")
    ap.add_argument("--np", type=int, default=8, dest="np_",
                    help="cluster size for --bucket-mb")
    ap.add_argument("--chaos", default=None,
                    help="straggler-chaos acceptance run: a fault rule "
                         "like 'slow@rank:500' or 'flaky_slow@rank:500:0.5' "
                         "injected on --chaos-victim while the straggler "
                         "policy is armed; reports survivors' step-time "
                         "ratio vs a clean run and exits 3 past "
                         "--chaos-budget")
    ap.add_argument("--chaos-victim", type=int, default=1,
                    help="rank the --chaos rule applies to (default 1)")
    ap.add_argument("--chaos-budget", type=float, default=1.5,
                    help="max allowed chaos/clean step-time ratio "
                         "(default 1.5, the ISSUE acceptance bound)")
    ap.add_argument("--straggler-deadline", default="3x",
                    help="HOROVOD_STRAGGLER_DEADLINE for the chaos phase "
                         "(default 3x = 3x the median arrival spread)")
    ap.add_argument("--history", default=None,
                    help="JSONL perf-history file (benchmarks/history.py); "
                         "with --path compression the headline "
                         "allreduce_compressed_algbw_gbps appends to it")
    ap.add_argument("--check-regression", action="store_true",
                    help="exit 3 when the headline metric regresses "
                         "against --history")
    ap.add_argument("--regression-window", type=int, default=None)
    ap.add_argument("--regression-tolerance", type=float, default=None)
    ap.add_argument("--algo-sweep", action="store_true",
                    help="sweep the collective-algorithm zoo (ring/tree/"
                         "hier x off/int8/int4) on the compiled fast path; "
                         "one JSON row per cell plus the per-size tuned "
                         "argmin; headline allreduce_algo_tuned_algbw_gbps "
                         "feeds --history/--check-regression")
    args = ap.parse_args(argv)
    sizes = [float(s) for s in args.sizes_mb.split(",") if s.strip()]
    if args.sizes_kb:
        sizes = sorted(set(sizes) | {
            float(k) / 1024.0 for k in args.sizes_kb.split(",") if k.strip()})
    if not sizes:
        ap.error("no message sizes: give --sizes-mb and/or --sizes-kb")

    import horovod_tpu as hvd

    if args.chaos is not None:
        r = bench_straggler_chaos(args.chaos, args.iters, args.warmup,
                                  np_=args.np_, victim=args.chaos_victim,
                                  deadline=args.straggler_deadline)
        result = {"metric": "straggler_chaos_step_ratio",
                  "value": r["step_ratio"], "unit": "x",
                  "config": {k: r[k] for k in ("chaos", "n", "victim",
                                               "deadline")}}
        print(json.dumps(result))
        rc = 0
        if r["step_ratio"] > args.chaos_budget:
            print(f"# REGRESSION: straggler_chaos_step_ratio = "
                  f"{r['step_ratio']} exceeds the --chaos-budget "
                  f"{args.chaos_budget} (survivors' step time did not "
                  f"track the median rank)", file=sys.stderr)
            rc = 3
        if args.history:
            from benchmarks.history import (append_record, check_regression,
                                            load_history)

            # ratio: LOWER is better; compare before appending, same as
            # the compression headline below
            if args.check_regression:
                verdict = check_regression(
                    load_history(args.history, metric=result["metric"]),
                    result["value"], direction="lower",
                    **{k: v for k, v in (
                        ("window", args.regression_window),
                        ("tolerance", args.regression_tolerance))
                       if v is not None})
                print("# regression check: %s" % json.dumps(verdict),
                      file=sys.stderr)
                if verdict["regression"]:
                    print(f"# REGRESSION: {result['metric']} = "
                          f"{result['value']} rose above the ceiling "
                          f"{verdict['floor']} (baseline "
                          f"{verdict['baseline']} over "
                          f"{verdict['samples']} runs)", file=sys.stderr)
                    rc = 3
            append_record(args.history, result)
        if rc:
            sys.exit(rc)
        return [r]

    if args.bucket_mb is not None:
        bucket_mbs = [float(b) for b in args.bucket_mb.split(",")]
        results = bench_bucket_overlap(bucket_mbs, args.iters, args.warmup,
                                       layers=args.layers, np_=args.np_)
        off = next((r for r in results if r["bucket_mb"] == 0), None)
        on = [r for r in results if r["bucket_mb"] > 0]
        if off and on:
            best = min(on, key=lambda r: r["exposed_comm_pct"])
            print(json.dumps({
                "metric": "bucket_overlap_exposed_comm_pct",
                "off_pct": off["exposed_comm_pct"],
                "on_pct": best["exposed_comm_pct"],
                "best_bucket_mb": best["bucket_mb"],
                "time_us_off": off["time_us"],
                "time_us_on": best["time_us"]}))
        return results

    if args.path == "compression" or args.compression is not None:
        modes = ([m.strip() for m in args.compression.split(",")]
                 if args.compression else list(_COMPRESSION_MODES))
        bad = [m for m in modes if m not in _COMPRESSION_MODES]
        if bad:
            ap.error(f"unknown compression mode(s) {bad}; choose from "
                     f"{_COMPRESSION_MODES}")
        results = bench_compression(sizes, args.iters, args.warmup, modes)
        by_mode = {}
        for r in results:
            by_mode.setdefault(r["mode"], []).append(r)
        if "int8" in by_mode:
            biggest = max(by_mode["int8"], key=lambda r: r["size_mb"])
            print(json.dumps({"metric": "allreduce_int8_wire_ratio",
                              "value": biggest["wire_ratio_vs_fp32"],
                              "size_mb": biggest["size_mb"]}))
        if "int8" in by_mode and "adaptive" in by_mode:
            # the ISSUE acceptance: the adaptive wire moves <= 60% of
            # int8's bytes on at least one bucket-size config
            i8 = {r["size_mb"]: r["wire_bytes"] for r in by_mode["int8"]}
            ratios = {r["size_mb"]: r["wire_bytes"] / i8[r["size_mb"]]
                      for r in by_mode["adaptive"] if r["size_mb"] in i8}
            if ratios:
                mb, ratio = min(ratios.items(), key=lambda kv: kv[1])
                print(json.dumps({"metric": "allreduce_adaptive_vs_int8_bytes",
                                  "value": round(ratio, 4), "size_mb": mb,
                                  "meets_60pct_target": ratio <= 0.6}))
        best = max(results, key=lambda r: r["effective_algbw_gbps"])
        result = {"metric": "allreduce_compressed_algbw_gbps",
                  "value": best["effective_algbw_gbps"],
                  "unit": "GB/s",
                  "config": {k: best[k] for k in ("mode", "size_mb", "n")}}
        print(json.dumps(result))
        rc = 0
        if args.history:
            from benchmarks.history import (append_record, check_regression,
                                            load_history)

            # compare against the trajectory BEFORE appending: today's run
            # must not vote in its own baseline
            if args.check_regression:
                verdict = check_regression(
                    load_history(args.history, metric=result["metric"]),
                    result["value"],
                    **{k: v for k, v in (
                        ("window", args.regression_window),
                        ("tolerance", args.regression_tolerance))
                       if v is not None})
                print("# regression check: %s" % json.dumps(verdict),
                      file=sys.stderr)
                if verdict["regression"]:
                    print(f"# REGRESSION: {result['metric']} = "
                          f"{result['value']} fell below the floor "
                          f"{verdict['floor']} (baseline "
                          f"{verdict['baseline']} over "
                          f"{verdict['samples']} runs)", file=sys.stderr)
                    rc = 3
            append_record(args.history, result)
        if rc:
            sys.exit(rc)
        return results

    if args.algo_sweep:
        hvd.init()
        results = bench_algo_sweep(sizes, args.iters, args.warmup)
        by_size = {}
        for r in results:
            by_size.setdefault(r["size_mb"], []).append(r)
        tuned = []
        for mb in sorted(by_size):
            # the per-size winner: what the joint tuner's argmin settles on,
            # >= every fixed (algorithm, bitwidth) at this size by
            # construction (the ISSUE acceptance)
            best = min(by_size[mb], key=lambda r: r["time_us"])
            tuned.append(best)
            print(json.dumps({"metric": "allreduce_algo_tuned",
                              "size_mb": mb,
                              "algorithm": best["algorithm"],
                              "mode": best["mode"],
                              "time_us": best["time_us"],
                              "algbw_gbps": best["algbw_gbps"]}))
        peak = max(tuned, key=lambda r: r["algbw_gbps"])
        result = {"metric": "allreduce_algo_tuned_algbw_gbps",
                  "value": peak["algbw_gbps"], "unit": "GB/s",
                  "config": {k: peak[k] for k in ("algorithm", "mode",
                                                  "size_mb", "n")}}
        print(json.dumps(result))
        rc = 0
        if args.history:
            from benchmarks.history import (append_record, check_regression,
                                            load_history)

            # compare against the trajectory BEFORE appending, same as the
            # compression headline below
            if args.check_regression:
                verdict = check_regression(
                    load_history(args.history, metric=result["metric"]),
                    result["value"],
                    **{k: v for k, v in (
                        ("window", args.regression_window),
                        ("tolerance", args.regression_tolerance))
                       if v is not None})
                print("# regression check: %s" % json.dumps(verdict),
                      file=sys.stderr)
                if verdict["regression"]:
                    print(f"# REGRESSION: {result['metric']} = "
                          f"{result['value']} fell below the floor "
                          f"{verdict['floor']} (baseline "
                          f"{verdict['baseline']} over "
                          f"{verdict['samples']} runs)", file=sys.stderr)
                    rc = 3
            append_record(args.history, result)
        hvd.shutdown()
        if rc:
            sys.exit(rc)
        return results

    if args.path == "allgather":
        results = bench_allgather(sizes, args.iters, args.warmup)
        by_size = {}
        for r in results:
            by_size.setdefault(r["size_mb"], []).append(r)
        for mb, rs in by_size.items():
            times = [r["time_us"] for r in sorted(rs, key=lambda r: r["n"])]
            print(json.dumps({"metric": "allgather_time_vs_world_us",
                              "size_mb": mb, "times_us": times,
                              "flat_ratio": round(times[-1] / times[0], 2)}))
        return results

    hvd.init()

    results = []
    if args.path in ("spmd", "both"):
        results += bench_spmd(sizes, args.iters, args.warmup)
    if args.path in ("eager", "both"):
        results += bench_eager(sizes, args.iters, args.warmup)

    best = max((r for r in results if r["path"] == "spmd"),
               key=lambda r: r["busbw_gbps"], default=None)
    if best is None:
        best = max(results, key=lambda r: r["busbw_gbps"])
    print(json.dumps({"metric": "allreduce_busbw_gbps",
                      "value": best["busbw_gbps"], "unit": "GB/s",
                      "config": {k: best[k] for k in ("path", "size_mb", "n")}}))
    hvd.shutdown()
    return results


if __name__ == "__main__":
    main()
