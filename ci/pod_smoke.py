#!/usr/bin/env python
"""Pod-day readiness smoke: the exact multi-host command lines documented
in docs/running.md ("Pod day" section) must stay valid with zero edits.

For every ``hvdrun ...`` line in that section this checks, without
launching anything:

  * the hvdrun flags parse against the REAL launcher parser;
  * the target script exists and its own argparser accepts the
    documented arguments (--help-level validation in a subprocess with a
    stubbed-out run, for scripts with argparse; compile-check otherwise).

Run by ci/run_tests.sh; also runnable directly: python ci/pod_smoke.py
"""

import os
import re
import shlex
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DOC = os.path.join(REPO, "docs", "running.md")
ELASTIC_DOC = os.path.join(REPO, "docs", "elastic.md")


def pod_day_commands():
    text = open(DOC).read()
    m = re.search(r"## Pod day.*?```bash\n(.*?)```", text, re.S)
    assert m, "docs/running.md lost its Pod day section"
    cmds = [ln.strip() for ln in m.group(1).splitlines()
            if ln.strip().startswith("hvdrun ")]
    assert len(cmds) >= 4, f"expected >=4 pod-day commands, found {cmds}"
    return cmds


def elastic_commands():
    """The documented elastic launch lines (docs/elastic.md) get the same
    no-rot guarantee: --min-np/--max-np/--host-discovery-script/
    --blacklist-cooldown must keep parsing against the real launcher."""
    text = open(ELASTIC_DOC).read()
    cmds = [ln.strip()
            for m in re.finditer(r"```bash\n(.*?)```", text, re.S)
            for ln in m.group(1).splitlines()
            if ln.strip().startswith("hvdrun ")]
    assert len(cmds) >= 2, f"expected >=2 elastic commands, found {cmds}"
    return cmds


def check_command(cmd: str) -> None:
    from horovod_tpu.run.launcher import build_parser

    argv = shlex.split(cmd)[1:]
    args = build_parser().parse_args(argv)  # SystemExit on a rotten flag
    rest = args.command
    assert rest and rest[0] == "python", f"{cmd!r}: remainder {rest}"
    script = rest[1]
    script_path = os.path.join(REPO, script)
    assert os.path.exists(script_path), f"{cmd!r}: {script} missing"
    script_args = rest[2:]
    if script_args:
        # the script's own argparser must accept the documented args:
        # append --help AFTER them — argparse validates the names/choices/
        # types of everything it consumed before the help action fires, so
        # an unknown or ill-typed documented flag exits 2 while a valid
        # line exits 0. (Known limit: --help short-circuits required-arg
        # presence checks; none of the documented scripts have required
        # args today.)
        code = (
            "import sys, runpy\n"
            f"sys.argv = [{script!r}] + {script_args!r} + ['--help']\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "try:\n"
            f"    runpy.run_path({script_path!r}, run_name='__main__')\n"
            "except SystemExit as e:\n"
            "    raise SystemExit(0 if e.code in (0, None) else e.code)\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, (
            f"{cmd!r}: script argparse rejected the documented args:\n"
            f"{r.stderr[-2000:]}")
    else:
        # no args: a syntax/compile check is the zero-cost validation
        import py_compile

        py_compile.compile(script_path, doraise=True)


def check_metrics_endpoint() -> None:
    """Live /metrics smoke (docs/metrics.md): a 2-thread local cluster with
    HOROVOD_METRICS_PORT=0 scrapes its own endpoint via urllib and prints the
    text; this parent fails on empty or Prometheus-unparsable output."""
    code = (
        "import os, sys, urllib.request\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "os.environ['HOROVOD_METRICS_PORT'] = '0'\n"
        "import numpy as np\n"
        "import horovod_tpu as hvd\n"
        "from horovod_tpu import testing\n"
        "from horovod_tpu.metrics import server_port\n"
        "def fn():\n"
        "    for i in range(3):\n"
        "        hvd.allreduce(np.ones((8,), np.float32), name='g',"
        " op=hvd.Sum)\n"
        "    return True\n"
        "assert all(testing.run_cluster(fn, np=2))\n"
        "port = server_port()\n"
        "assert port, 'metrics endpoint did not start'\n"
        "body = urllib.request.urlopen(\n"
        "    f'http://127.0.0.1:{port}/metrics', timeout=10).read()\n"
        "hvd.shutdown()\n"
        "sys.stdout.write(body.decode())\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (
        f"metrics smoke job failed:\n{r.stderr[-2000:]}")
    from horovod_tpu.metrics import parse_prometheus

    assert r.stdout.strip(), "metrics endpoint served empty output"
    samples = parse_prometheus(r.stdout)  # ValueError on unparsable text
    for want in ("hvd_allreduce_latency_seconds_count",
                 "hvd_wire_bytes_total",
                 "hvd_response_cache_hits_total",
                 "hvd_elastic_epoch"):
        assert want in samples, f"/metrics output missing {want}"
    print(f"ok: /metrics endpoint served {len(samples)} sample families")


def check_chaos_reconnect() -> None:
    """Fault-tolerance smoke (docs/fault-tolerance.md): a real 2-process job
    with a connection drop injected mid-step (HOROVOD_FAULT_SPEC) must
    complete normally AND its /metrics endpoint must show a nonzero
    ``hvd_control_reconnects_total`` — proof the drop was recovered by
    reconnect+replay, not by luck."""
    code = (
        "import sys, time, urllib.request\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "from horovod_tpu.run.api import run\n"
        "def fn():\n"
        "    import time, urllib.request\n"
        "    import numpy as np\n"
        "    import horovod_tpu as hvd\n"
        "    from horovod_tpu.metrics import server_port\n"
        "    hvd.init()\n"
        "    r = hvd.rank()\n"
        "    for i in range(6):\n"
        "        out = hvd.allreduce(np.ones((8,), np.float32),"
        " name=f'c{i}', op=hvd.Sum)\n"
        "        assert np.allclose(np.asarray(out), 2.0)\n"
        "    time.sleep(1.0)  # a few metrics-ship intervals: rank 1's\n"
        "    # reconnect count must reach the rank-0 aggregator\n"
        "    body = ''\n"
        "    if r == 0:\n"
        "        port = server_port()\n"
        "        assert port, 'metrics endpoint did not start'\n"
        "        body = urllib.request.urlopen(\n"
        "            f'http://127.0.0.1:{port}/metrics',"
        " timeout=10).read().decode()\n"
        "    hvd.shutdown()\n"
        "    return (r, body)\n"
        "env = {\n"
        "    'JAX_PLATFORMS': 'cpu',\n"
        "    'PALLAS_AXON_POOL_IPS': '',\n"
        "    'HVD_ELASTIC': '1',\n"
        "    'HOROVOD_FAULT_SPEC': 'conn_drop@tick:3#1',\n"
        "    'HOROVOD_METRICS_PORT': '0',\n"
        "    'HOROVOD_METRICS_INTERVAL': '0.2',\n"
        f"    'PYTHONPATH': {REPO!r},\n"
        "}\n"
        "out = dict(run(fn, np=2, env=env, start_timeout=120))\n"
        "sys.stdout.write('===METRICS===\\n' + out[0] + '===END===\\n')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (
        f"chaos smoke job failed:\n{r.stderr[-2000:]}")
    from horovod_tpu.metrics import parse_prometheus

    m = re.search(r"===METRICS===\n(.*?)===END===", r.stdout, re.S)
    assert m, (
        "chaos smoke produced no metrics body; stdout tail:\n"
        f"{r.stdout[-2000:]}")
    samples = parse_prometheus(m.group(1))
    assert "hvd_control_reconnects_total" in samples, \
        "/metrics output missing hvd_control_reconnects_total"
    total = sum(samples["hvd_control_reconnects_total"].values())
    assert total > 0, (
        "injected connection drop produced no reconnect: "
        f"hvd_control_reconnects_total == {total}")
    print(f"ok: chaos smoke recovered {int(total)} injected connection "
          "drop(s) via reconnect+replay")


def check_nan_skip() -> None:
    """Data-plane integrity smoke (docs/fault-tolerance.md): training with
    `nan@grad` injected under HOROVOD_GRAD_GUARD=skip must still converge,
    with a nonzero ``hvd_steps_skipped_total`` — proof the poisoned step
    was dropped in lockstep on every rank rather than reduced into the
    weights."""
    code = (
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "os.environ['HOROVOD_GRAD_GUARD'] = 'skip'\n"
        "os.environ['HOROVOD_FAULT_SPEC'] = 'nan@grad:2#1'\n"
        "import numpy as np\n"
        "import jax, optax\n"
        "import jax.numpy as jnp\n"
        "import horovod_tpu as hvd\n"
        "from horovod_tpu import testing\n"
        "from horovod_tpu.metrics import instruments\n"
        "def fn():\n"
        "    params = {'w': jnp.zeros((4,))}\n"
        "    target = jnp.asarray([1.0, -2.0, 3.0, 0.5])\n"
        "    tx = hvd.DistributedOptimizer(optax.sgd(0.3))\n"
        "    opt = tx.init(params)\n"
        "    loss_fn = lambda p: jnp.mean((p['w'] - target) ** 2)\n"
        "    grad_fn = jax.jit(jax.value_and_grad(loss_fn))\n"
        "    first = None\n"
        "    for _ in range(25):\n"
        "        loss, grads = grad_fn(params)\n"
        "        first = loss if first is None else first\n"
        "        updates, opt = tx.update(grads, opt, params)\n"
        "        params = optax.apply_updates(params, updates)\n"
        "    return float(first), float(loss_fn(params)),"
        " np.asarray(params['w'])\n"
        "res = testing.run_cluster(fn, np=2)\n"
        "skipped = instruments.steps_skipped().value\n"
        "assert skipped > 0, 'injected NaN produced no skipped step'\n"
        "np.testing.assert_array_equal(res[0][2], res[1][2])\n"
        "for first, final, _ in res:\n"
        "    assert final < first * 0.05, (first, final)\n"
        "print(f'skipped={int(skipped)} loss {res[0][0]:.3f} ->"
        " {res[0][1]:.5f}')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (
        f"nan-injection smoke job failed:\n{r.stderr[-2000:]}")
    print(f"ok: nan-injection smoke converged through a skipped step "
          f"({r.stdout.strip().splitlines()[-1]})")


def check_trace_capture() -> None:
    """Distributed-tracing smoke (docs/tracing.md): a real 2-process
    training job with HOROVOD_TRACE set must leave ONE merged strictly-valid
    Chrome trace on rank 0, and ``bin/hvdprof`` must parse it with a nonzero
    wire span count — proof both ranks' spans crossed the control plane and
    survived the merge."""
    import json
    import tempfile

    trace = os.path.join(tempfile.mkdtemp(prefix="hvd_trace_smoke_"),
                         "trace.json")
    code = (
        "import sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from horovod_tpu.run.api import run\n"
        "def fn():\n"
        "    import jax, optax\n"
        "    import jax.numpy as jnp\n"
        "    import horovod_tpu as hvd\n"
        "    hvd.init()\n"
        "    params = {'w': jnp.zeros((64,))}\n"
        "    tx = hvd.DistributedOptimizer(optax.sgd(0.1))\n"
        "    opt = tx.init(params)\n"
        "    loss_fn = lambda p: jnp.mean(p['w'] ** 2)\n"
        "    grad_fn = jax.jit(jax.grad(loss_fn))\n"
        "    for _ in range(4):\n"
        "        grads = grad_fn(params)\n"
        "        updates, opt = tx.update(grads, opt, params)\n"
        "        params = optax.apply_updates(params, updates)\n"
        "    hvd.shutdown()\n"
        "    return True\n"
        "env = {\n"
        "    'JAX_PLATFORMS': 'cpu',\n"
        "    'PALLAS_AXON_POOL_IPS': '',\n"
        # host-wire data plane: the only cross-process eager path on CPU
        "    'HVD_ELASTIC': '1',\n"
        f"    'HOROVOD_TRACE': {trace!r},\n"
        "    'HOROVOD_TRACE_INTERVAL': '0.2',\n"
        f"    'PYTHONPATH': {REPO!r},\n"
        "}\n"
        "assert all(run(fn, np=2, env=env, start_timeout=120))\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (
        f"trace-capture smoke job failed:\n{r.stderr[-2000:]}")
    assert os.path.exists(trace), f"no merged trace at {trace}"
    hvdprof = os.path.join(REPO, "bin", "hvdprof")
    v = subprocess.run([sys.executable, hvdprof, "validate", trace],
                       capture_output=True, text=True, timeout=60)
    assert v.returncode == 0, (
        f"hvdprof validate rejected the merged trace:\n{v.stderr[-2000:]}"
        f"\n{v.stdout[-2000:]}")
    p = subprocess.run([sys.executable, hvdprof, "report", trace, "--json"],
                       capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, (
        f"hvdprof report failed:\n{p.stderr[-2000:]}")
    report = json.loads(p.stdout)
    wire = report["counts"]["wire_spans"]
    assert wire > 0, f"merged trace has no wire spans: {report['counts']}"
    ranks = sorted(int(k) for k in report["ranks"])
    assert ranks == [0, 1], f"expected spans from both ranks, got {ranks}"
    print(f"ok: trace capture merged {report['counts']['events']} events "
          f"({wire} wire spans) from ranks {ranks}; hvdprof parses it")


def check_bucket_overlap() -> None:
    """Bucket-overlap smoke (docs/overlap.md): a real 2-process training
    job with HOROVOD_BUCKET_MB set must put client-built ``grad.bucket.*``
    tensors on the wire as SEPARATE responses (several distinct bucket
    names in the trace — the controller did not re-merge them), with WIRE
    spans running concurrently with the GRAD launch/drain phase spans,
    and ``bin/hvdprof`` must report the overlap %% line off the merged
    trace."""
    import json
    import tempfile

    trace = os.path.join(tempfile.mkdtemp(prefix="hvd_overlap_smoke_"),
                         "trace.json")
    code = (
        "import sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from horovod_tpu.run.api import run\n"
        "def fn():\n"
        "    import jax, optax\n"
        "    import jax.numpy as jnp\n"
        "    import horovod_tpu as hvd\n"
        "    hvd.init()\n"
        # 8 dense leaves of 16 KiB against a 20 KiB budget: every leaf
        # closes its own bucket -> 8 concurrent non-fusable allreduces
        "    params = {f'w{i}': jnp.zeros((4096,)) for i in range(8)}\n"
        "    tx = hvd.DistributedOptimizer(optax.sgd(0.1))\n"
        "    opt = tx.init(params)\n"
        "    loss_fn = lambda p: sum(jnp.mean(v ** 2) for v in"
        " p.values())\n"
        "    grad_fn = jax.jit(jax.grad(loss_fn))\n"
        "    for _ in range(4):\n"
        "        grads = grad_fn(params)\n"
        "        updates, opt = tx.update(grads, opt, params)\n"
        "        params = optax.apply_updates(params, updates)\n"
        "    hvd.shutdown()\n"
        "    return True\n"
        "env = {\n"
        "    'JAX_PLATFORMS': 'cpu',\n"
        "    'PALLAS_AXON_POOL_IPS': '',\n"
        # host-wire data plane: the only cross-process eager path on CPU
        "    'HVD_ELASTIC': '1',\n"
        "    'HOROVOD_BUCKET_MB': '0.02',\n"
        f"    'HOROVOD_TRACE': {trace!r},\n"
        "    'HOROVOD_TRACE_INTERVAL': '0.2',\n"
        f"    'PYTHONPATH': {REPO!r},\n"
        "}\n"
        "assert all(run(fn, np=2, env=env, start_timeout=120))\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (
        f"bucket-overlap smoke job failed:\n{r.stderr[-2000:]}")
    assert os.path.exists(trace), f"no merged trace at {trace}"
    from horovod_tpu.tracing.analyzer import intersect_us, load_events

    events = [e for e in load_events(trace) if e.get("ph") == "X"]
    buckets = {e["args"]["tensor"] for e in events
               if (e.get("args") or {}).get("tensor", "").startswith(
                   "grad.bucket.")}
    assert len(buckets) >= 2, (
        f"expected several client-built buckets on the wire, saw {buckets}")
    overlap = 0
    for rank in (0, 1):
        wire = [(e["ts"], e["dur"]) for e in events
                if e.get("pid") == rank and e.get("name") == "WIRE"]
        grad = [(e["ts"], e["dur"]) for e in events
                if e.get("pid") == rank
                and e.get("name") in ("GRAD_LAUNCH", "GRAD_DRAIN")]
        assert wire, f"rank {rank} left no WIRE spans"
        assert grad, f"rank {rank} left no GRAD phase spans"
        overlap += intersect_us(wire, grad)
    assert overlap > 0, (
        "no WIRE span ran concurrently with a GRAD phase span — bucket "
        "overlap produced zero wire/backward concurrency")
    hvdprof = os.path.join(REPO, "bin", "hvdprof")
    p = subprocess.run([sys.executable, hvdprof, "report", trace, "--json"],
                       capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, f"hvdprof report failed:\n{p.stderr[-2000:]}"
    report = json.loads(p.stdout)
    assert "overlap_pct" in report["overall"], (
        f"hvdprof report lost the overlap %: {report['overall']}")
    print(f"ok: bucket overlap — {len(buckets)} buckets on the wire, "
          f"{overlap} us of WIRE concurrent with GRAD phases, hvdprof "
          f"overall overlap {report['overall']['overlap_pct']:.1f}%")


def check_blackbox_doctor() -> None:
    """Postmortem smoke (docs/observability.md): a real 2-process job with
    rank 1 wedged at its first collective (``hang@collective``) under an
    enforced 3 s HOROVOD_COLLECTIVE_TIMEOUT must die leaving a blackbox
    dump from BOTH ranks, and ``bin/hvddoctor`` on the bundle must name
    the collective deadlock, the stalled tensor, and the missing rank."""
    import tempfile

    bbdir = tempfile.mkdtemp(prefix="hvd_blackbox_smoke_")
    code = (
        "import sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from horovod_tpu.run.api import run\n"
        "def fn():\n"
        "    import numpy as np\n"
        "    import horovod_tpu as hvd\n"
        "    hvd.init()\n"
        "    hvd.allreduce(np.ones((8,), np.float32), name='bb_probe',"
        " op=hvd.Sum)\n"
        "    hvd.shutdown()\n"
        "    return True\n"
        "env = {\n"
        "    'JAX_PLATFORMS': 'cpu',\n"
        "    'PALLAS_AXON_POOL_IPS': '',\n"
        # wedge rank 1 for 30s at its 1st enqueued collective; the 3s
        # watchdog fails rank 0 long before, and the launcher's
        # first-failure SIGTERM triggers rank 1's signal-path dump
        "    'HOROVOD_FAULT_SPEC': 'hang@collective:30:1#1',\n"
        "    'HOROVOD_COLLECTIVE_TIMEOUT': '3',\n"
        "    'HOROVOD_BLACKBOX': '1',\n"
        f"    'HOROVOD_BLACKBOX_DIR': {bbdir!r},\n"
        f"    'PYTHONPATH': {REPO!r},\n"
        "}\n"
        "try:\n"
        "    run(fn, np=2, env=env, start_timeout=120)\n"
        "except RuntimeError as exc:\n"
        "    print('===DIED===', str(exc).splitlines()[-1])\n"
        "else:\n"
        "    raise SystemExit('job survived a wedged rank + 3s watchdog')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (
        f"blackbox smoke job failed:\n{r.stderr[-2000:]}\n{r.stdout[-2000:]}")
    assert "===DIED===" in r.stdout, (
        f"wedged job did not die as expected:\n{r.stdout[-2000:]}")
    for rank in (0, 1):
        path = os.path.join(bbdir, f"rank_{rank}.json")
        assert os.path.exists(path), (
            f"no blackbox dump from rank {rank}; dir has "
            f"{sorted(os.listdir(bbdir))}")
    hvddoctor = os.path.join(REPO, "bin", "hvddoctor")
    d = subprocess.run([sys.executable, hvddoctor, bbdir],
                       capture_output=True, text=True, timeout=60)
    assert d.returncode == 0, (
        f"hvddoctor rejected the bundle:\n{d.stderr[-2000:]}")
    out = d.stdout
    assert "collective deadlock" in out, f"no deadlock diagnosis:\n{out}"
    assert "bb_probe" in out, f"diagnosis does not name the tensor:\n{out}"
    assert "[1]" in out, f"diagnosis does not name the missing rank:\n{out}"
    print("ok: blackbox smoke — both ranks dumped; hvddoctor named the "
          "deadlock, tensor 'bb_probe', missing rank [1]")


def _failover_smoke_fn():
    """3-rank elastic job with the warm standby on; rank 0 — the
    coordinator — dies abruptly mid-training. Survivors must finish all 10
    steps on the promoted standby and return a parameter digest."""
    import hashlib
    import os

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import blackbox

    hvd.init()
    state = hvd.elastic.ElasticState(w=np.array([4.0], np.float32), step=0)

    @hvd.elastic.run_fn
    def train(state):
        while state.step < 10:
            if hvd.rank() == 0 and state.step == 4:
                os._exit(29)  # no BYE, no cleanup: the coordinator is gone
            g = np.float32(hvd.rank() + 1) * (np.asarray(state.w) - 1.0)
            avg = hvd.allreduce(g, name=f"grad{state.step}",
                                op=hvd.Average)
            state.w = np.asarray(state.w) - np.float32(0.1) * \
                np.asarray(avg, np.float32)
            state.step += 1
            state.commit()
        return hashlib.sha256(
            np.asarray(state.w, np.float32).tobytes()).hexdigest()

    digest = train(state)
    # the blackbox normally only speaks on abnormal exit; force the dump
    # so hvddoctor can diagnose the failover this survivor lived through
    blackbox.dump("failover smoke postmortem", force=True)
    return digest


def check_coordinator_failover() -> None:
    """Survivable-control-plane smoke (docs/control-plane.md): SIGKILL the
    rank-0 coordinator mid-step with HOROVOD_STANDBY_COORD on. Training
    must resume on the promoted standby, the survivors' parameter digests
    must be bit-identical, and ``bin/hvddoctor`` over the blackbox bundle
    must name the coordinator failover."""
    import pickle
    import tempfile
    import time

    import cloudpickle

    from horovod_tpu.run import rendezvous

    bbdir = tempfile.mkdtemp(prefix="hvd_failover_smoke_")
    secret = rendezvous.make_secret()
    kv = rendezvous.KVStoreServer(secret).start()
    addr = f"127.0.0.1:{kv.port}"
    client = rendezvous.KVStoreClient(addr, secret)
    client.put("runfunc", "fn",
               cloudpickle.dumps((_failover_smoke_fn, (), {})))

    procs = []
    try:
        for r in range(3):
            env = dict(os.environ)
            env.update({
                "HVD_NUM_PROCS": "3",
                "HVD_PROCESS_ID": str(r),
                "HVD_KV_ADDR": addr,
                "HVD_SECRET": secret,
                "HVD_ELASTIC": "1",
                "HOROVOD_STANDBY_COORD": "1",
                # failover doesn't wait on the grace (promotion declares
                # rank 0 lost explicitly); a tight value only risks a
                # loaded host spuriously losing a live survivor
                "HOROVOD_RECONNECT_GRACE": "15",
                "HOROVOD_BLACKBOX": "1",
                "HOROVOD_BLACKBOX_DIR": bbdir,
                "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
                # the smoke fn unpickles by reference to this module
                "PYTHONPATH": os.pathsep.join(
                    [REPO, os.path.dirname(os.path.abspath(__file__))]),
            })
            env.pop("XLA_FLAGS", None)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "horovod_tpu.run.task"], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))

        deadline = time.time() + 180
        blobs = {}
        while time.time() < deadline and len(blobs) < 2:
            for r in (1, 2):
                if r not in blobs:
                    blob = client.get("result", str(r))
                    if blob is not None:
                        blobs[r] = blob
            if len(blobs) < 2 and all(p.poll() is not None for p in procs):
                time.sleep(1.0)
                for r in (1, 2):
                    blob = client.get("result", str(r))
                    if blob is not None:
                        blobs[r] = blob
                break
            time.sleep(0.25)
        assert len(blobs) == 2, (
            "survivors produced no result after the coordinator kill; "
            f"got ranks {sorted(blobs)}, exit codes "
            f"{[p.poll() for p in procs]}")
        digests = {}
        for r, blob in blobs.items():
            ok, payload = pickle.loads(blob)
            assert ok, f"rank {r} raised:\n{payload}"
            digests[r] = payload
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        kv.stop()

    assert procs[0].wait(timeout=10) == 29, \
        "rank 0 did not die with its marker code"
    assert digests[1] == digests[2], (
        "survivors' parameters diverged across the failover: "
        f"{digests}")

    for rank in (1, 2):
        path = os.path.join(bbdir, f"rank_{rank}.json")
        assert os.path.exists(path), (
            f"no blackbox dump from survivor rank {rank}; dir has "
            f"{sorted(os.listdir(bbdir))}")
    hvddoctor = os.path.join(REPO, "bin", "hvddoctor")
    d = subprocess.run([sys.executable, hvddoctor, bbdir],
                       capture_output=True, text=True, timeout=60)
    assert d.returncode == 0, (
        f"hvddoctor rejected the bundle:\n{d.stderr[-2000:]}")
    assert "coordinator failover" in d.stdout, (
        f"hvddoctor did not diagnose the failover:\n{d.stdout[-3000:]}")
    print("ok: coordinator failover smoke — rank 0 killed mid-step, "
          "survivors resumed on the promoted standby with bit-identical "
          f"parameters (sha256 {digests[1][:12]}…); hvddoctor named the "
          "coordinator failover")


def _split_brain_smoke_fn():
    """2-rank elastic job for the split-brain drill (docs/fault-tolerance.md):
    the lease plane is on and a ``partition@net`` cut isolates rank 0 (with
    the coordinator) from rank 1 (with the standby) mid-training. Rank 0
    must self-fence before the TTL expires, rank 1's standby must take over
    by acquiring the lease, and after the heal the deposed primary's FENCED
    answer must be rejected by the promoted side's fence guard. The
    gradient is identical on every rank, so averaging over any member set
    is bit-exact and the survivor's final parameters are closed-form."""
    import os
    import time

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import blackbox
    from horovod_tpu.metrics import instruments

    hvd.init()
    rank = hvd.rank()
    state = hvd.elastic.ElasticState(w=np.array([4.0], np.float32), step=0)

    @hvd.elastic.run_fn
    def train(state):
        while state.step < 12:
            time.sleep(0.7)  # pace the run so the cut lands mid-training
            w = np.asarray(state.w, np.float32)
            g = (w - np.float32(1.0)).astype(np.float32)
            avg = hvd.allreduce(g, name=f"grad{state.step}", op=hvd.Average)
            state.w = (w - np.float32(0.1)
                       * np.asarray(avg, np.float32)).astype(np.float32)
            state.step += 1
            state.commit()
        return np.asarray(state.w, np.float32)

    try:
        w = train(state)
        fenced_seen = 0
        deadline = time.monotonic() + 25
        while time.monotonic() < deadline:
            fenced_seen = int(instruments.frames_fenced().value)
            if fenced_seen:
                break
            time.sleep(0.25)
        blackbox.dump("split-brain smoke postmortem", force=True)
        return ("done", int(state.step), w.tobytes().hex(), fenced_seen)
    except Exception as exc:  # the fenced side of the cut lands here
        if rank == 0:
            # stay alive past the heal so the fenced server can answer the
            # promoted standby's redial with its FENCED frame
            time.sleep(12.0)
        blackbox.dump("split-brain smoke postmortem", force=True)
        return ("fenced", repr(exc), int(state.step))


def check_split_brain() -> None:
    """Partition-tolerance smoke (docs/fault-tolerance.md): cut a 2-process
    lease-enabled job in half mid-training. The old coordinator must
    self-fence before the lease TTL, the standby must promote by acquiring
    the lease, the survivor must finish with the closed-form parameters,
    and the merged blackbox history must satisfy the jepsen-lite checker:
    single-writer leadership, exactly-once step application, and at least
    one fenced-frame rejection — while ``bin/hvddoctor`` stays clean of
    the split_brain signature."""
    import json
    import pickle
    import tempfile
    import time

    import cloudpickle
    import numpy as np

    from horovod_tpu.faultinject import jepsen
    from horovod_tpu.run import rendezvous

    bbdir = tempfile.mkdtemp(prefix="hvd_splitbrain_smoke_")
    secret = rendezvous.make_secret()
    kv = rendezvous.KVStoreServer(secret).start()
    addr = f"127.0.0.1:{kv.port}"
    client = rendezvous.KVStoreClient(addr, secret)
    client.put("runfunc", "fn",
               cloudpickle.dumps((_split_brain_smoke_fn, (), {})))

    procs = []
    results = {}
    try:
        for r in range(2):
            env = dict(os.environ)
            env.update({
                "HVD_NUM_PROCS": "2",
                "HVD_PROCESS_ID": str(r),
                "HVD_KV_ADDR": addr,
                "HVD_SECRET": secret,
                "HVD_ELASTIC": "1",
                "HOROVOD_STANDBY_COORD": "1",
                "HOROVOD_LEASE_TTL": "1.2",
                "HOROVOD_LEASE_RENEW": "0.25",
                "HOROVOD_RECONNECT_GRACE": "20",
                "HOROVOD_BLACKBOX": "1",
                "HOROVOD_BLACKBOX_DIR": bbdir,
                # cut ranks {0} | {1} 8s in, heal 6s later
                "HOROVOD_FAULT_SPEC": "partition@net:0|1:6:8",
                "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
                # the smoke fn unpickles by reference to this module
                "PYTHONPATH": os.pathsep.join(
                    [REPO, os.path.dirname(os.path.abspath(__file__))]),
            })
            env.pop("XLA_FLAGS", None)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "horovod_tpu.run.task"], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))

        deadline = time.time() + 180
        while time.time() < deadline and len(results) < 2:
            for r in range(2):
                if r not in results:
                    blob = client.get("result", str(r))
                    if blob is not None:
                        ok, payload = pickle.loads(blob)
                        assert ok, f"rank {r} harness raised:\n{payload}"
                        results[r] = payload
            time.sleep(0.25)
        assert len(results) == 2, (
            "the partitioned job did not finish; got ranks "
            f"{sorted(results)}, exit codes {[p.poll() for p in procs]}")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        kv.stop()

    assert results[0][0] == "fenced", (
        f"rank 0 was cut from the KV and must self-fence: {results[0]}")
    outcome, steps, w_hex, fenced_seen = results[1]
    assert outcome == "done" and steps == 12, (
        f"the survivor did not finish all 12 steps: {results[1]}")
    assert fenced_seen > 0, (
        "no fenced-frame rejection observed on the promoted side "
        "(hvd_frames_fenced_total stayed 0)")
    # identical gradients make the survivor's parameters closed-form:
    # replay the same float32 recurrence locally
    w = np.array([4.0], np.float32)
    for _ in range(12):
        g = (w - np.float32(1.0)).astype(np.float32)
        w = (w - np.float32(0.1) * g).astype(np.float32)
    assert w_hex == w.tobytes().hex(), (
        f"survivor parameters diverged: {w_hex} != {w.tobytes().hex()}")

    bundle = {}
    for rank in (0, 1):
        path = os.path.join(bbdir, f"rank_{rank}.json")
        assert os.path.exists(path), (
            f"no blackbox dump from rank {rank}; dir has "
            f"{sorted(os.listdir(bbdir))}")
        with open(path) as f:
            bundle[rank] = json.load(f)
    verdict = jepsen.check_history(bundle)
    assert verdict["single_writer"], (
        f"leadership overlapped: {verdict['violations']}")
    assert verdict["exactly_once"], (
        f"steps were double-applied: {verdict['violations']}")
    assert verdict["fenced_frames"] > 0, (
        "the merged history records no fenced-frame rejection")

    hvddoctor = os.path.join(REPO, "bin", "hvddoctor")
    d = subprocess.run([sys.executable, hvddoctor, bbdir],
                       capture_output=True, text=True, timeout=60)
    assert d.returncode == 0, (
        f"hvddoctor rejected the bundle:\n{d.stderr[-2000:]}")
    assert "split_brain" not in d.stdout, (
        "hvddoctor diagnosed a split brain on a fenced (clean) history:\n"
        f"{d.stdout[-3000:]}")
    print("ok: split-brain smoke — partition isolated the coordinator, it "
          "self-fenced before the lease TTL, the standby promoted by "
          "acquiring the lease, the deposed primary's post-heal frame was "
          f"rejected ({fenced_seen} fenced), and the jepsen-lite checker "
          "proved single-writer leadership with exactly-once steps")


def _straggler_smoke_fn():
    """2-rank elastic job for the straggler smoke: every rank times its
    steps past a warmup window (long enough for the policy to exclude the
    injected straggler), so rank 0's timed mean reflects the adapted
    steady state. Returns (rank, mean_timed_step_s, partial_rounds)."""
    import os
    import time

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.metrics import instruments
    from horovod_tpu.run import rendezvous

    hvd.init()
    r = hvd.rank()
    warmup, timed = 8, 12
    x = np.ones((1 << 14,), np.float32) * (r + 1)
    times = []
    for step in range(warmup + timed):
        t0 = time.monotonic()
        try:
            hvd.allreduce(x, name="s%d" % step, op=hvd.Average)
        except hvd.WorkerLostError:
            # escalation variant: the victim was promoted away and this
            # round absorbed the epoch bump (elastic.run_fn's job in a
            # real training loop). The events we came for are recorded.
            if not os.environ.get("HVD_SMOKE_DUMP"):
                raise
            break
        if step >= warmup:
            times.append(time.monotonic() - t0)
    partial = float(instruments.partial_collectives().value)
    if os.environ.get("HVD_SMOKE_DUMP"):
        # escalation variant: the victim was promoted away mid-run; force
        # the dump so hvddoctor can read the exclusion/escalation events
        from horovod_tpu import blackbox

        blackbox.dump("straggler smoke postmortem", force=True)
    else:
        # rank 0 hosts the coordinator: hold it until the (possibly
        # excluded, trailing) peer drains its solo rounds, or its last
        # steps die with ShutdownError
        kv = rendezvous.KVStoreClient(os.environ["HVD_KV_ADDR"],
                                      os.environ["HVD_SECRET"])
        kv.put("sdone", str(r), b"1")
        if r == 0:
            deadline = time.time() + 60
            while time.time() < deadline and \
                    kv.get("sdone", "1") is None:
                time.sleep(0.2)
    hvd.shutdown()
    return (r, sum(times) / len(times) if times else 0.0, partial)


def _run_straggler_smoke_job(extra_env, want_ranks):
    """Launch _straggler_smoke_fn on 2 task.py processes; return
    {rank: payload} for the ranks in want_ranks (others may die —
    the escalation variant removes the victim on purpose)."""
    import pickle
    import time

    import cloudpickle

    from horovod_tpu.run import rendezvous

    secret = rendezvous.make_secret()
    kv = rendezvous.KVStoreServer(secret).start()
    addr = f"127.0.0.1:{kv.port}"
    client = rendezvous.KVStoreClient(addr, secret)
    client.put("runfunc", "fn",
               cloudpickle.dumps((_straggler_smoke_fn, (), {})))
    procs = []
    try:
        for r in range(2):
            env = dict(os.environ)
            env.update({
                "HVD_NUM_PROCS": "2",
                "HVD_PROCESS_ID": str(r),
                "HVD_KV_ADDR": addr,
                "HVD_SECRET": secret,
                "HVD_ELASTIC": "1",
                "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
                "PYTHONPATH": os.pathsep.join(
                    [REPO, os.path.dirname(os.path.abspath(__file__))]),
            })
            env.update(extra_env)
            env.pop("XLA_FLAGS", None)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "horovod_tpu.run.task"], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        deadline = time.time() + 180
        blobs = {}
        while time.time() < deadline and len(blobs) < len(want_ranks):
            for r in want_ranks:
                if r not in blobs:
                    blob = client.get("result", str(r))
                    if blob is not None:
                        blobs[r] = blob
            time.sleep(0.25)
        assert len(blobs) == len(want_ranks), (
            f"straggler smoke ranks {sorted(want_ranks)} produced no "
            f"result (got {sorted(blobs)}); exit codes "
            f"{[p.poll() for p in procs]}")
        out = {}
        for r, blob in blobs.items():
            ok, payload = pickle.loads(blob)
            assert ok, f"rank {r} raised:\n{payload}"
            out[r] = payload
        return out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        kv.stop()


def check_straggler_adaptive() -> None:
    """Straggler-adaptive smoke (docs/fault-tolerance.md): a 2-process run
    with rank 1 injected 300 ms slow per step must (a) keep rank 0's
    steady-state step time within 1.5x the fault-free baseline — the
    policy excluded the victim instead of waiting on it — with partial
    rounds actually counted, and (b) under a tight MAX_SKIP, escalate the
    victim to rank_lost and leave a blackbox bundle from which
    ``bin/hvddoctor`` names the chronic straggler."""
    import tempfile

    base = _run_straggler_smoke_job({}, want_ranks=(0, 1))
    chaos = _run_straggler_smoke_job({
        "HOROVOD_FAULT_SPEC": "slow@rank:300#1",
        "HOROVOD_STRAGGLER_DEADLINE": "3x",
        "HOROVOD_STRAGGLER_PATIENCE": "2",
        "HOROVOD_STRAGGLER_MAX_SKIP": "10000",
    }, want_ranks=(0, 1))
    base_step = base[0][1]
    chaos_step = chaos[0][1]
    # 1.5x the acceptance budget, plus a 50 ms absolute floor so two
    # near-zero means on a loaded CI host can't produce a spurious ratio;
    # an un-excluded victim costs >=300 ms/step, far past either bound
    assert chaos_step <= max(1.5 * base_step, base_step + 0.05), (
        f"step time did not track the healthy rank: baseline "
        f"{base_step * 1e3:.1f} ms vs chaos {chaos_step * 1e3:.1f} ms")
    assert chaos[0][2] > 0, (
        "no partial rounds counted — the straggler was never excluded")

    bbdir = tempfile.mkdtemp(prefix="hvd_straggler_smoke_")
    _run_straggler_smoke_job({
        "HOROVOD_FAULT_SPEC": "slow@rank:300#1",
        "HOROVOD_STRAGGLER_DEADLINE": "3x",
        "HOROVOD_STRAGGLER_PATIENCE": "1",
        "HOROVOD_STRAGGLER_MAX_SKIP": "2",
        "HVD_SMOKE_DUMP": "1",
        "HOROVOD_BLACKBOX": "1",
        "HOROVOD_BLACKBOX_DIR": bbdir,
    }, want_ranks=(0,))
    hvddoctor = os.path.join(REPO, "bin", "hvddoctor")
    d = subprocess.run([sys.executable, hvddoctor, bbdir],
                       capture_output=True, text=True, timeout=60)
    assert d.returncode == 0, (
        f"hvddoctor rejected the bundle:\n{d.stderr[-2000:]}")
    assert "chronic straggler" in d.stdout, (
        f"hvddoctor did not name the chronic straggler:\n"
        f"{d.stdout[-3000:]}")
    assert "rank 1" in d.stdout, (
        f"diagnosis does not name the victim rank:\n{d.stdout[-3000:]}")
    print(f"ok: straggler smoke — victim excluded (baseline "
          f"{base_step * 1e3:.1f} ms, chaos {chaos_step * 1e3:.1f} ms, "
          f"{chaos[0][2]:.0f} partial rounds); escalation variant left a "
          "bundle and hvddoctor named the chronic straggler")


def check_adaptive_wire() -> None:
    """Adaptive mixed-bitwidth wire smoke (docs/compression.md): a 2-process
    job under HOROVOD_COMPRESSION=adaptive must (a) converge the bitwidth
    selector to the same decision on both ranks, (b) drop wire bytes below
    int8's once the 4-bit grid engages, and (c) keep parameters bit-identical
    across ranks under the ConsistencyAuditor — proof the negotiated
    per-bucket grid compiled the same program everywhere."""
    code = (
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import jax, optax\n"
        "import jax.numpy as jnp\n"
        "import horovod_tpu as hvd\n"
        "from horovod_tpu import testing\n"
        "from horovod_tpu.ops import adaptive as ad\n"
        "from horovod_tpu.ops import compression as comp\n"
        "from horovod_tpu.runtime.executor import Executor\n"
        "def fn():\n"
        "    from horovod_tpu import basics\n"
        "    comp.AdaptiveCompressor.reset(); ad.reset()\n"
        "    n = 4096\n"
        "    params = {'w': jnp.zeros((n,))}\n"
        "    target = jnp.asarray(np.random.RandomState(0).randn(n)"
        ".astype(np.float32))\n"
        "    tx = hvd.DistributedOptimizer(optax.sgd(0.3),\n"
        "        compression=comp.AdaptiveCompressor, error_feedback=True)\n"
        "    opt = tx.init(params)\n"
        "    loss_fn = lambda p: jnp.sum((p['w'] - target) ** 2)\n"
        "    grad_fn = jax.jit(jax.value_and_grad(loss_fn))\n"
        "    modes, wire_bytes, first = [], [], None\n"
        "    for _ in range(2 * ad.interval() + 2):\n"
        "        loss, grads = grad_fn(params)\n"
        "        first = loss if first is None else first\n"
        "        updates, opt = tx.update(grads, opt, params)\n"
        "        params = optax.apply_updates(params, updates)\n"
        "        ex = basics._engine()._executor\n"
        "        modes.append(ex.last_wire_mode)\n"
        "        wire_bytes.append(ex.last_wire_bytes)\n"
        "    aud = hvd.ConsistencyAuditor(interval=1, policy='abort')\n"
        "    params = aud.audit(params)\n"
        "    return (modes, wire_bytes, float(first),"
        " float(loss_fn(params)), np.asarray(params['w']))\n"
        "res = testing.run_cluster(fn, np=2)\n"
        "(ma, ba, fa, la, wa), (mb, bb, fb, lb, wb) = res\n"
        "assert ma == mb, ('selector diverged across ranks', ma, mb)\n"
        "assert ma[0] == 'int8' and ma[-1] == 'int4', ma\n"
        "i8 = Executor.quantized_wire_layout(4096, 2, bits=8)['wire_bytes']\n"
        "assert min(ba) <= 0.6 * i8, (min(ba), i8)\n"
        "np.testing.assert_array_equal(wa, wb)\n"
        "assert la < fa * 0.2, (fa, la)\n"
        "print(f'modes {ma[0]}->{ma[-1]} bytes {max(ba)}->{min(ba)}"
        " loss {fa:.1f}->{la:.4f}')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (
        f"adaptive-wire smoke job failed:\n{r.stderr[-2000:]}")
    print(f"ok: adaptive-wire smoke — selector converged, bytes dropped "
          f"vs int8, parameters rank-consistent "
          f"({r.stdout.strip().splitlines()[-1]})")


def check_gspmd_quantized() -> None:
    """Quantized GSPMD-wire smoke (docs/gspmd.md): training on the 8-device
    virtual mesh with HOROVOD_GSPMD_WIRE=int8 in the ENVIRONMENT (the knob,
    not the API argument) must engage the quantized ring inside the
    compiled step, converge the loss, and put <=60% of the bf16 run's
    bytes on the wire per the hvd_wire_bytes_total instrument — the
    EQuARX-style acceptance from ROADMAP item 1."""
    code = (
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import jax, optax\n"
        "import jax.numpy as jnp\n"
        "from jax.sharding import Mesh\n"
        "import horovod_tpu as hvd\n"
        "from horovod_tpu import spmd\n"
        "from horovod_tpu.basics import MESH_AXIS\n"
        "from horovod_tpu.metrics import instruments\n"
        "from horovod_tpu.ops import compression as comp\n"
        "hvd.init()\n"
        "n = len(jax.devices())\n"
        "assert n == 8, n\n"
        "mesh = Mesh(np.asarray(jax.devices()), (MESH_AXIS,))\n"
        "d = 16384  # per-rank chunk 2048 = 8 whole blocks: no pad skew\n"
        "rng = np.random.RandomState(0)\n"
        "x = rng.randn(2 * n, d).astype(np.float32) / np.sqrt(d)\n"
        "y = x @ rng.randn(d).astype(np.float32)\n"
        "params = {'w': jnp.zeros((d,), jnp.float32)}\n"
        "loss_fn = lambda p, b: jnp.mean((b[0] @ p['w'] - b[1]) ** 2)\n"
        "tx = optax.adam(0.1)\n"
        "step = spmd.make_train_step(loss_fn, tx, mesh=mesh, donate=False)\n"
        "assert hasattr(step, 'jitted'), \\\n"
        "    'HOROVOD_GSPMD_WIRE=int8 did not engage the quantized step'\n"
        "p = spmd.replicate(params, mesh)\n"
        "o = spmd.quantized_opt_state(tx, params, mesh)\n"
        "data = spmd.shard_batch((jnp.asarray(x), jnp.asarray(y)), mesh)\n"
        "c = instruments.wire_bytes().labels(compression='gspmd-int8')\n"
        "b0, steps, losses = c.value, 40, []\n"
        "for _ in range(steps):\n"
        "    p, o, loss = step(p, o, data)\n"
        "    losses.append(float(loss))\n"
        "assert np.isfinite(losses).all(), losses\n"
        "assert losses[-1] < 0.2 * losses[0], losses\n"
        "wire = (c.value - b0) / steps\n"
        "bf16 = comp.gspmd_wire_footprint(d, 'bf16', n)\n"
        "assert wire > 0, 'quantized ring put no bytes on the instrument'\n"
        "assert wire <= 0.6 * bf16, (wire, bf16)\n"
        "print(f'loss {losses[0]:.3f}->{losses[-1]:.4f}; wire "
        "{int(wire)} B/step <= 60% of bf16 {int(bf16)} B')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               HOROVOD_GSPMD_WIRE="int8",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (
        f"quantized GSPMD smoke job failed:\n{r.stderr[-2000:]}")
    print(f"ok: quantized GSPMD smoke — env knob engaged the int8 ring, "
          f"converged, bytes under the bf16 bar "
          f"({r.stdout.strip().splitlines()[-1]})")


def check_algo_hierarchical() -> None:
    """Hierarchical collective smoke (docs/gspmd.md algorithm zoo): on a
    simulated 2-host x 4-chip factorization (HOROVOD_MESH_HOSTS=2 over the
    8-device virtual mesh) the two-level schedule must agree with the flat
    ring — bit-identical across ranks, within float tolerance of the
    ring's result (the schedules reduce in different orders, so last-ulp
    equality is the per-rank invariant, not the cross-algorithm one) —
    while crossing host boundaries with strictly fewer bytes per the
    gspmd_cross_host_footprint catalog."""
    code = (
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import jax\n"
        "from jax.sharding import PartitionSpec as P\n"
        "from horovod_tpu import spmd\n"
        "from horovod_tpu.basics import Average, MESH_AXIS\n"
        "from horovod_tpu.ops import compression as comp\n"
        "n = len(jax.devices())\n"
        "assert n == 8, n\n"
        "assert spmd.mesh_hosts(n) == 2  # the env factorization: 2x4\n"
        "mesh = jax.make_mesh((n,), (MESH_AXIS,))\n"
        "d = 16384\n"
        "rng = np.random.RandomState(0)\n"
        "data = rng.randn(n, d).astype(np.float32)\n"
        "def run(fn, wire):\n"
        "    body = lambda r: fn(r[0], Average, MESH_AXIS, wire)[None]\n"
        "    sm = spmd._shard_map(body, mesh, in_specs=P(MESH_AXIS),\n"
        "                         out_specs=P(MESH_AXIS))\n"
        "    return np.asarray(jax.jit(sm)(data))\n"
        "for wire, tol in (('off', 1e-5), ('int8', 0.05)):\n"
        "    ring = run(spmd.quantized_allreduce, wire)\n"
        "    hier = run(spmd.quantized_allreduce_hier, wire)\n"
        "    for p in range(1, n):  # replicated params rest on this\n"
        "        assert (hier[p] == hier[0]).all(), (wire, p)\n"
        "    assert np.abs(hier[0] - ring[0]).max() < tol, wire\n"
        "block = comp.block_size()\n"
        "xring = comp.gspmd_cross_host_footprint(d, 'int8', n, 2, block,\n"
        "                                        'ring')\n"
        "xhier = comp.gspmd_cross_host_footprint(d, 'int8', n, 2, block,\n"
        "                                        'hier')\n"
        "assert 0 < xhier < xring, (xhier, xring)\n"
        "print(f'hier == ring on 2x4, cross-host {xhier} B < ring "
        "{xring} B ({100.0 * xhier / xring:.0f}%)')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               HOROVOD_MESH_HOSTS="2",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (
        f"hierarchical-algorithm smoke job failed:\n{r.stderr[-2000:]}")
    print(f"ok: hierarchical collective smoke — 2x4 factorization matched "
          f"the flat ring with fewer cross-host bytes "
          f"({r.stdout.strip().splitlines()[-1]})")


def check_moe_quantized() -> None:
    """Quantized MoE dispatch smoke (docs/moe.md): capacity-factor Switch
    dispatch on a dp=2 x ep=4 virtual mesh with HOROVOD_MOE_WIRE=int8 in
    the ENVIRONMENT (the knob, not the API argument) must route the
    token exchange through the quantized all_to_all, converge the loss,
    keep the per-step dispatch bytes <=60% of a bf16 exchange per the
    hvd_wire_bytes_total{compression="moe-int8"} instrument, and keep
    the drop rate bounded at the stock CF=1.25."""
    code = (
        "import os, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import numpy as np\n"
        "import jax, optax\n"
        "import jax.numpy as jnp\n"
        "from jax.sharding import NamedSharding, PartitionSpec as P\n"
        "import horovod_tpu as hvd\n"
        "from horovod_tpu.metrics import instruments\n"
        "from horovod_tpu.ops import compression as comp\n"
        "from horovod_tpu.parallel import expert as epar\n"
        "hvd.init()\n"
        "assert len(jax.devices()) == 8\n"
        "E, D, N, CF = 8, 64, 1024, 1.25\n"
        "mesh = epar.make_dp_ep_mesh(2, 4)\n"
        "params = epar.init_moe_params(jax.random.PRNGKey(0), D, E,"
        " hidden_mult=2)\n"
        "rng = np.random.RandomState(0)\n"
        "xb = jnp.asarray(rng.randn(N, D).astype(np.float32))\n"
        "yb = xb @ jnp.asarray(0.1 * rng.randn(D, D).astype(np.float32))\n"
        "def loss_fn(p, batch, moe):\n"
        "    x, y = batch\n"
        "    out, aux = moe(p, x)\n"
        "    return jnp.mean((out - y) ** 2) + 0.01 * aux\n"
        "tx = optax.adam(1e-2)\n"
        "step = epar.make_ep_train_step(loss_fn, tx, mesh,"
        " dispatch='capacity', capacity_factor=CF)\n"
        "assert hasattr(step, 'jitted'), 'capacity step not instrumented'\n"
        "p = epar.shard_params_ep(params, mesh)\n"
        "opt = epar.moe_opt_state(tx, params, mesh, N, CF)\n"
        "sh = NamedSharding(mesh, P(('dp', 'ep')))\n"
        "batch = (jax.device_put(xb, sh), jax.device_put(yb, sh))\n"
        "c = instruments.wire_bytes().labels(compression='moe-int8')\n"
        "b0, steps, losses = c.value, 30, []\n"
        "for _ in range(steps):\n"
        "    p, opt, loss, stats = step(p, opt, batch)\n"
        "    losses.append(float(loss))\n"
        "assert np.isfinite(losses).all(), losses\n"
        "assert losses[-1] < 0.5 * losses[0], losses\n"
        "wire = (c.value - b0) / steps\n"
        "cap = epar.expert_capacity(N // 8, E, CF)\n"
        "per_peer = E * cap * D // 4\n"
        "bf16 = comp.moe_wire_footprint(per_peer, 'bf16', 4)\n"
        "assert wire > 0, 'HOROVOD_MOE_WIRE=int8 put no dispatch bytes "
        "on the instrument'\n"
        "assert wire <= 0.6 * bf16, (wire, bf16)\n"
        "drop_rate = float(stats['dropped']) / N\n"
        "assert 0 <= drop_rate < 0.5, drop_rate\n"
        "assert float(stats['capacity']) == cap\n"
        "assert float(instruments.moe_capacity_factor().value) == CF\n"
        "print(f'loss {losses[0]:.3f}->{losses[-1]:.4f}; dispatch "
        "{int(wire)} B/step <= 60% of bf16 {int(bf16)} B; drop rate "
        "{drop_rate:.3f} at CF={CF}')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               HOROVOD_MOE_WIRE="int8",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (
        f"quantized MoE smoke job failed:\n{r.stderr[-2000:]}")
    print(f"ok: quantized MoE smoke — env knob engaged the int8 dispatch, "
          f"converged, bytes under the bf16 bar, drops bounded "
          f"({r.stdout.strip().splitlines()[-1]})")


def check_serving_kill() -> None:
    """Elastic serving smoke (docs/inference.md): a frontend + 2 worker
    replicas under sustained load must survive a SIGKILL of one replica —
    the dead worker's in-flight requests re-admit onto the survivor, ZERO
    requests are lost, and the frontend's /metrics endpoint keeps serving
    the hvd_serving_* catalog (including the readmitted counter) after
    the kill."""
    code = (
        "import json, os, signal, subprocess, sys, time, urllib.request\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "os.environ['HOROVOD_METRICS_PORT'] = '0'\n"
        "import numpy as np\n"
        "from horovod_tpu.metrics import server_port\n"
        "from horovod_tpu.serving import ServingClient, ServingFrontend\n"
        "fe = ServingFrontend().start()\n"
        "host, port = fe.addr\n"
        "env = dict(os.environ, JAX_PLATFORMS='cpu',"
        " PALLAS_AXON_POOL_IPS='')\n"
        "procs = [subprocess.Popen(\n"
        "    [sys.executable, '-m', 'horovod_tpu.serving.worker',\n"
        "     '--addr', f'{host}:{port}', '--rank', str(i + 1),\n"
        "     '--max-batch', '4'],\n"
        f"    env=env, cwd={REPO!r}) for i in range(2)]\n"
        "try:\n"
        "    fe.wait_for_workers(2, timeout=120)\n"
        "    cli = ServingClient(host, port, name='smoke')\n"
        "    # warm both replicas' compile caches before the timed window\n"
        "    for f in [cli.submit([1, 2, 3], 2) for _ in range(8)]:\n"
        "        f.result(timeout=120)\n"
        "    rng = np.random.RandomState(0)\n"
        "    futs = []\n"
        "    for i in range(18):\n"
        "        futs.append(cli.submit(\n"
        "            rng.randint(1, 251, size=6).tolist(), 6))\n"
        "        if i == 6:\n"
        "            procs[0].kill()  # SIGKILL a replica mid-flight\n"
        "        time.sleep(0.02)\n"
        "    lost = 0\n"
        "    for f in futs:\n"
        "        try:\n"
        "            f.result(timeout=120)\n"
        "        except Exception as exc:\n"
        "            print(f'LOST {f.id}: {exc}', file=sys.stderr)\n"
        "            lost += 1\n"
        "    stats = fe.stats()\n"
        "    assert lost == 0, f'{lost} request(s) lost after worker kill'\n"
        "    assert stats['readmitted'] >= 1, stats\n"
        "    assert stats['completed'] >= 18, stats\n"
        "    assert len(stats['workers']) == 1, stats\n"
        "    mport = server_port()\n"
        "    assert mport, 'frontend metrics endpoint did not start'\n"
        "    body = urllib.request.urlopen(\n"
        "        f'http://127.0.0.1:{mport}/metrics', timeout=10)"
        ".read().decode()\n"
        "    print(json.dumps(stats), file=sys.stderr)\n"
        "    sys.stdout.write(body)\n"
        "finally:\n"
        "    for pr in procs:\n"
        "        if pr.poll() is None:\n"
        "            pr.terminate()\n"
        "    for pr in procs:\n"
        "        try:\n"
        "            pr.wait(timeout=10)\n"
        "        except subprocess.TimeoutExpired:\n"
        "            pr.kill()\n"
        "    fe.stop()\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, (
        f"serving worker-kill smoke failed:\n{r.stderr[-3000:]}")
    from horovod_tpu.metrics import parse_prometheus

    samples = parse_prometheus(r.stdout)
    for want in ("hvd_serving_requests_total",
                 "hvd_serving_request_latency_seconds_count"):
        assert any(k.startswith(want) for k in samples), (
            f"/metrics output missing {want} after the kill:\n"
            f"{sorted(samples)[:40]}")
    print("ok: serving smoke — SIGKILLed a replica under load, in-flight "
          "requests re-admitted onto the survivor, zero lost, /metrics "
          "still serving the hvd_serving_* catalog")


def check_serving_frontend_kill() -> None:
    """Survivable-serving smoke (docs/inference.md failure matrix): run
    the kill-frontend chaos drill — SIGKILL the active frontend under
    Poisson load with a warm standby attached — and then point
    ``bin/hvddoctor`` at the blackbox bundle: the doctor must NAME the
    failover via the ``serving_failover`` signature (promotion recorded,
    not misdiagnosed as a coordinator event), and must not raise
    ``split_brain`` on the fenced handover."""
    import shutil
    import tempfile

    bbdir = tempfile.mkdtemp(prefix="hvd_serving_fkill_smoke_")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               HOROVOD_BLACKBOX_DIR=bbdir)
    try:
        r = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "benchmarks", "serving_bench.py"),
             "--chaos", "kill-frontend", "--requests", "24",
             "--qps", "12", "--max-new", "4"],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=600)
        assert r.returncode == 0, (
            f"kill-frontend drill failed (rc={r.returncode}):\n"
            f"{r.stderr[-3000:]}")
        assert "exactly_once\": true" in r.stderr.replace("'", '"'), (
            f"drill output missing a clean jepsen verdict:\n"
            f"{r.stderr[-2000:]}")

        hvddoctor = os.path.join(REPO, "bin", "hvddoctor")
        d = subprocess.run([sys.executable, hvddoctor, bbdir],
                           capture_output=True, text=True, timeout=60)
        assert d.returncode == 0, (
            f"hvddoctor rejected the bundle:\n{d.stderr[-2000:]}")
        assert "serving frontend failover" in d.stdout, (
            "hvddoctor did not name the frontend failover "
            f"(serving_failover signature):\n{d.stdout[:3000]}")
        assert "split_brain" not in d.stdout, (
            "hvddoctor misdiagnosed the fenced serving handover as a "
            f"split brain:\n{d.stdout[-3000:]}")
    finally:
        shutil.rmtree(bbdir, ignore_errors=True)
    print("ok: serving frontend-kill smoke — SIGKILLed the frontend "
          "under load, standby promoted behind the lease, jepsen verdict "
          "clean, and hvddoctor named the serving_failover")


def _ckpt_smoke_fn():
    """2-rank elastic job with async sharded checkpointing on; the
    HVD_CKPT_VICTIM process hard-kills itself at step 5 and its same-rank
    replacement must restore its rank-local shard from the buddy journal
    (O(shard), no disk) and finish the bit-identical trajectory."""
    import hashlib
    import os
    import time

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import blackbox, ckpt

    hvd.init()
    state = hvd.elastic.ElasticState(
        w=np.array([4.0], np.float32),
        opt_shard=np.array([hvd.rank() + 1.0], np.float32),
        step=0)
    state.mark_sharded("opt_shard")
    target = np.float32(1.0)

    @hvd.elastic.run_fn
    def train(state):
        ctrl = hvd.basics._engine().controller
        while state.step < 12:
            if (os.environ.get("HVD_CKPT_VICTIM") == "1"
                    and state.step == 5):
                os._exit(17)  # hard kill AFTER committing step 5
            if hvd.rank() == 0 and len(ctrl.members()) < 2:
                # hold at the commit boundary until the replacement is
                # admitted: every step must run with both members or the
                # restored shard misses updates
                time.sleep(0.1)
                state.commit()
                continue
            g = np.float32(2.0) * (np.asarray(state.w, np.float32)
                                   - target)
            avg = hvd.allreduce(g, name=f"grad{state.step}",
                                op=hvd.Average)
            state.w = (np.asarray(state.w, np.float32)
                       - np.float32(0.1) * np.asarray(avg, np.float32))
            state.opt_shard = (np.float32(0.5)
                               * np.asarray(state.opt_shard, np.float32)
                               + np.asarray(avg, np.float32))
            state.step += 1
            state.commit()
        return hashlib.sha256(
            np.asarray(state.w, np.float32).tobytes()).hexdigest()

    digest = train(state)
    mgr = ckpt.active()
    blackbox.dump("checkpoint smoke postmortem", force=True)
    return {"digest": digest,
            "restore": mgr.last_restore if mgr is not None else None,
            "shard": float(np.asarray(state.opt_shard)[0])}


def check_ckpt_kill_restore() -> None:
    """Restart-as-a-product smoke (docs/checkpoint.md): SIGKILL a worker
    mid-training with HOROVOD_CKPT_DIR on, then launch a same-rank
    replacement. The replacement must restore its shard from the buddy
    journal (source == "peer" at the victim's last commit), both
    survivors must finish with bit-identical parameters, and the blackbox
    must carry the K_CKPT snapshot/finalize/peer_restore trail."""
    import json
    import pickle
    import tempfile
    import time

    import cloudpickle

    from horovod_tpu.run import rendezvous

    ckptdir = tempfile.mkdtemp(prefix="hvd_ckpt_smoke_")
    bbdir = tempfile.mkdtemp(prefix="hvd_ckpt_smoke_bb_")
    secret = rendezvous.make_secret()
    kv = rendezvous.KVStoreServer(secret).start()
    addr = f"127.0.0.1:{kv.port}"
    client = rendezvous.KVStoreClient(addr, secret)
    client.put("runfunc", "fn",
               cloudpickle.dumps((_ckpt_smoke_fn, (), {})))

    def spawn(rank, victim):
        env = dict(os.environ)
        env.update({
            "HVD_NUM_PROCS": "2",
            "HVD_PROCESS_ID": str(rank),
            "HVD_KV_ADDR": addr,
            "HVD_SECRET": secret,
            "HVD_ELASTIC": "1",
            "HOROVOD_RECONNECT_GRACE": "2",
            "HOROVOD_CKPT_DIR": ckptdir,
            "HOROVOD_CKPT_INTERVAL": "1",
            "HVD_CKPT_VICTIM": "1" if victim else "0",
            "HOROVOD_BLACKBOX": "1",
            "HOROVOD_BLACKBOX_DIR": bbdir,
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "PYTHONPATH": os.pathsep.join(
                [REPO, os.path.dirname(os.path.abspath(__file__))]),
        })
        env.pop("XLA_FLAGS", None)
        return subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.run.task"], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    procs = [spawn(0, False), spawn(1, True)]
    replacement = None
    try:
        deadline = time.time() + 120
        while procs[1].poll() is None and time.time() < deadline:
            time.sleep(0.25)
        assert procs[1].poll() == 17, (
            f"victim did not die with its marker code: {procs[1].poll()}")
        # let the reconnect grace lapse so the coordinator declares the
        # rank lost before the replacement shows up as a joiner
        time.sleep(3.0)
        replacement = spawn(1, False)

        blobs = {}
        deadline = time.time() + 150
        while time.time() < deadline and len(blobs) < 2:
            for r in (0, 1):
                if r not in blobs:
                    blob = client.get("result", str(r))
                    if blob is not None:
                        blobs[r] = blob
            time.sleep(0.25)
        assert len(blobs) == 2, (
            f"job did not finish after the kill; got ranks "
            f"{sorted(blobs)}, exit codes "
            f"{[p.poll() for p in procs + [replacement]]}")
        results = {}
        for r, blob in blobs.items():
            ok, payload = pickle.loads(blob)
            assert ok, f"rank {r} raised:\n{payload}"
            results[r] = payload
    finally:
        for p in procs + ([replacement] if replacement else []):
            if p.poll() is None:
                p.kill()
        kv.stop()

    restore = results[1]["restore"]
    assert restore is not None, "replacement never restored its shard"
    assert restore["source"] == "peer", (
        f"shard came from {restore} — the O(shard) buddy path was "
        "bypassed")
    assert restore["step"] == 5, restore
    assert results[0]["digest"] == results[1]["digest"], (
        f"parameters diverged across the kill-and-restore: {results}")

    # the K_CKPT trail: rank 0 snapshotted and finalized bundles; the
    # replacement's dump carries the peer_restore record
    names = {0: set(), 1: set()}
    for rank in (0, 1):
        path = os.path.join(bbdir, f"rank_{rank}.json")
        assert os.path.exists(path), (
            f"no blackbox dump from rank {rank}; dir has "
            f"{sorted(os.listdir(bbdir))}")
        doc = json.load(open(path))
        names[rank] = {e.get("name") for e in doc.get("events", [])
                       if e.get("kind") == "checkpoint"}
    assert "snapshot" in names[0], names
    assert "finalize" in names[0], names
    assert "peer_restore" in names[1], names
    print("ok: checkpoint kill-and-restore smoke — worker killed at step "
          "5, same-rank replacement restored its shard from the buddy "
          f"journal (step {restore['step']}, {restore['nbytes']} bytes) "
          "and finished bit-identical "
          f"(sha256 {results[0]['digest'][:12]}…)")


def _goodput_chaos_fn():
    """2-rank elastic job with the goodput ledger, a deliberately
    unmeetable SLO and the anomaly watch on; the victim hard-kills itself
    at step 5 and the survivor must come out the other side with nonzero
    recovery badput, a burning SLO gauge, and an hvdtop snapshot."""
    import os
    import subprocess
    import sys
    import time

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import blackbox

    hvd.init()
    state = hvd.elastic.ElasticState(w=np.array([4.0], np.float32), step=0)

    @hvd.elastic.run_fn
    def train(state):
        ctrl = hvd.basics._engine().controller
        while state.step < 12:
            if (os.environ.get("HVD_GOODPUT_VICTIM") == "1"
                    and state.step == 5):
                os._exit(17)  # hard kill AFTER committing step 5
            if hvd.rank() == 0 and len(ctrl.members()) < 2:
                # hold at the commit boundary until the replacement is
                # admitted — this wait is exactly the wall time the
                # ledger must attribute, not lose
                time.sleep(0.1)
                state.commit()
                continue
            g = np.float32(2.0) * (np.asarray(state.w, np.float32) - 1.0)
            avg = hvd.allreduce(g, name=f"grad{state.step}",
                                op=hvd.Average)
            state.w = (np.asarray(state.w, np.float32)
                       - np.float32(0.05) * np.asarray(avg, np.float32))
            state.step += 1
            state.commit()
        return float(np.asarray(state.w)[0])

    train(state)
    # let the watch take a few more SLO samples over the settled counters
    time.sleep(1.5)
    doc = hvd.metrics()
    hvdtop = {"rc": None, "out": ""}
    if hvd.rank() == 0:
        from horovod_tpu.metrics import server_port
        port = server_port()
        if port:
            repo = os.path.dirname(os.path.dirname(
                os.path.abspath(hvd.__file__)))
            r = subprocess.run(
                [sys.executable, os.path.join(repo, "bin", "hvdtop"),
                 "--once", "--url", f"http://127.0.0.1:{port}"],
                capture_output=True, text=True, timeout=30)
            hvdtop = {"rc": r.returncode, "out": r.stdout}
    blackbox.dump("goodput chaos postmortem", force=True)

    bad = {}
    for s in (doc.get("hvd_badput_seconds_total") or {}).get("series") or []:
        c = (s.get("labels") or {}).get("cause", "?")
        bad[c] = bad.get(c, 0.0) + float(s.get("value", 0.0))
    burn = 0.0
    for s in (doc.get("hvd_slo_burn_rate") or {}).get("series") or []:
        burn = max(burn, float(s.get("value", 0.0)))
    return {"badput": bad, "burn": burn, "hvdtop": hvdtop}


def check_goodput_chaos() -> None:
    """Goodput chaos smoke (docs/goodput.md): kill a worker mid-training
    in a 2-rank elastic job running under an unmeetable HOROVOD_SLO with
    the anomaly watch on. After the same-rank replacement finishes the
    job, the survivor's ledger must show nonzero
    ``hvd_badput_seconds_total{cause="recovery"}``, the SLO burn gauge
    must be past the fire threshold, ``bin/hvdtop --once`` must render a
    parseable snapshot off the live endpoint, and ``bin/hvddoctor`` on
    the blackbox bundle must name the exhausted budget and the dominant
    badput cause."""
    import json
    import pickle
    import tempfile
    import time

    import cloudpickle

    from horovod_tpu.run import rendezvous

    bbdir = tempfile.mkdtemp(prefix="hvd_goodput_smoke_bb_")
    ckptdir = tempfile.mkdtemp(prefix="hvd_goodput_smoke_ckpt_")
    secret = rendezvous.make_secret()
    kv = rendezvous.KVStoreServer(secret).start()
    addr = f"127.0.0.1:{kv.port}"
    client = rendezvous.KVStoreClient(addr, secret)
    client.put("runfunc", "fn",
               cloudpickle.dumps((_goodput_chaos_fn, (), {})))

    def spawn(rank, victim):
        env = dict(os.environ)
        env.update({
            "HVD_NUM_PROCS": "2",
            "HVD_PROCESS_ID": str(rank),
            "HVD_KV_ADDR": addr,
            "HVD_SECRET": secret,
            "HVD_ELASTIC": "1",
            "HOROVOD_RECONNECT_GRACE": "2",
            "HOROVOD_CKPT_DIR": ckptdir,
            "HOROVOD_CKPT_INTERVAL": "1",
            "HVD_GOODPUT_VICTIM": "1" if victim else "0",
            # the smoke's SLO is unmeetable by construction (this tiny
            # job is ~all communication), so the burn gauge must be hot
            # at dump time and the doctor must have something to name
            "HOROVOD_SLO": "goodput>=0.99",
            "HOROVOD_ANOMALY_WATCH": "1",
            "HOROVOD_ANOMALY_INTERVAL": "0.5",
            "HOROVOD_METRICS_INTERVAL": "0.5",
            "HOROVOD_METRICS_PORT": "0" if rank == 0 else "",
            "HOROVOD_BLACKBOX": "1",
            "HOROVOD_BLACKBOX_DIR": bbdir,
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "PYTHONPATH": os.pathsep.join(
                [REPO, os.path.dirname(os.path.abspath(__file__))]),
        })
        env.pop("XLA_FLAGS", None)
        if not env["HOROVOD_METRICS_PORT"]:
            env.pop("HOROVOD_METRICS_PORT")
        return subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.run.task"], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    procs = [spawn(0, False), spawn(1, True)]
    replacement = None
    try:
        deadline = time.time() + 120
        while procs[1].poll() is None and time.time() < deadline:
            time.sleep(0.25)
        assert procs[1].poll() == 17, (
            f"victim did not die with its marker code: {procs[1].poll()}")
        time.sleep(3.0)  # let the reconnect grace declare the rank lost
        replacement = spawn(1, False)

        blobs = {}
        deadline = time.time() + 150
        while time.time() < deadline and len(blobs) < 2:
            for r in (0, 1):
                if r not in blobs:
                    blob = client.get("result", str(r))
                    if blob is not None:
                        blobs[r] = blob
            time.sleep(0.25)
        assert len(blobs) == 2, (
            f"job did not finish after the kill; got ranks "
            f"{sorted(blobs)}, exit codes "
            f"{[p.poll() for p in procs + [replacement]]}")
        results = {}
        for r, blob in blobs.items():
            ok, payload = pickle.loads(blob)
            assert ok, f"rank {r} raised:\n{payload}"
            results[r] = payload
    finally:
        for p in procs + ([replacement] if replacement else []):
            if p.poll() is None:
                p.kill()
        kv.stop()

    # every second the kill cost must be on the books as recovery badput
    bad = results[0]["badput"]
    assert bad.get("recovery", 0.0) > 0.0, (
        f"no recovery badput attributed after the kill: {bad}")
    assert results[0]["burn"] >= 2.0, (
        f"SLO burn gauge never crossed the fire threshold: {results[0]}")

    top = results[0]["hvdtop"]
    assert top["rc"] == 0, f"hvdtop --once failed: {top}"
    assert top["out"].startswith("hvdtop — up="), top["out"][:200]
    assert "fleet goodput" in top["out"], top["out"][:400]
    assert "recovery" in top["out"], (
        f"hvdtop badput stack is missing the recovery cause:\n"
        f"{top['out'][:600]}")

    # hvddoctor on the bundle: the budget_exhausted detector must name
    # the exhausted SLO and the dominant badput cause with its ranks
    for rank in (0, 1):
        path = os.path.join(bbdir, f"rank_{rank}.json")
        assert os.path.exists(path), (
            f"no blackbox dump from rank {rank}; dir has "
            f"{sorted(os.listdir(bbdir))}")
    doc = json.load(open(os.path.join(bbdir, "rank_0.json")))
    assert doc.get("metrics"), "rank 0 dump carries no metrics snapshot"
    hvddoctor = os.path.join(REPO, "bin", "hvddoctor")
    d = subprocess.run([sys.executable, hvddoctor, bbdir],
                       capture_output=True, text=True, timeout=60)
    assert d.returncode == 0, (
        f"hvddoctor rejected the bundle:\n{d.stderr[-2000:]}")
    out = d.stdout
    assert "error budget burning" in out, (
        f"doctor did not flag the exhausted budget:\n{out}")
    assert "dominated by" in out, (
        f"doctor did not name the dominant badput cause:\n{out}")
    print("ok: goodput chaos smoke — worker killed at step 5; survivor "
          f"attributed {bad.get('recovery', 0.0):.2f}s of recovery "
          f"badput, SLO burn {results[0]['burn']:.0f}x fired, hvdtop "
          "--once rendered the live snapshot, and hvddoctor named the "
          "dominant badput cause")


def check_tier_rehome() -> None:
    """N-tier control-plane smoke (docs/control-plane.md): a 2-tier tree
    on simulated hosts — 4 fake ranks behind two host-tier
    sub-coordinators behind one mid-tier aggregator — loses the mid-tier
    aggregator under load. Its TierStandby must promote a stateless
    replacement under ``addr.{gen}.t2.0.f1``, the orphaned host-tier
    children must re-home there and re-ship their in-flight ledgers, and
    every rank's per-round response digest must stay identical across the
    failover (replay shards make the re-ship idempotent)."""
    import hashlib
    import socket as _socket
    import threading
    import time

    from horovod_tpu.run import rendezvous
    from horovod_tpu.runtime import wire
    from horovod_tpu.runtime.coordinator import (MSG_HELLO, MSG_LIST,
                                                 MSG_RESP, CoordState,
                                                 CoordinatorServer,
                                                 _publish_key)
    from horovod_tpu.runtime.hierarchy import SubCoordinator, TierStandby

    gen, world, rounds, kill_at = 555, 4, 6, 2
    secret = rendezvous.make_secret()
    kv = rendezvous.KVStoreServer(secret).start()
    saved = {k: os.environ.get(k) for k in ("HVD_KV_ADDR", "HVD_SECRET")}
    os.environ["HVD_KV_ADDR"] = f"127.0.0.1:{kv.port}"
    os.environ["HVD_SECRET"] = secret
    state = CoordState(world, 0, cache_capacity=1024,
                       stall_warning_s=60.0, stall_shutdown_s=0.0)
    server = CoordinatorServer(state, secret)
    mid = standby = None
    hosts = []
    digests = [[None] * rounds for _ in range(world)]
    errors = []
    try:
        mid = SubCoordinator("127.0.0.1", server.port, secret,
                             leader_rank=0, tier=2, index=0, tiers=2)
        _publish_key(f"addr.{gen}.t2.0", f"127.0.0.1:{mid.port}", secret)
        standby = TierStandby(
            gen, 2, 0, secret,
            make_aggregator=lambda: SubCoordinator(
                "127.0.0.1", server.port, secret, leader_rank=0,
                tier=2, index=0, tiers=2),
            probe_interval=0.1, misses=2).start()
        for g in (0, 1):
            hosts.append(SubCoordinator(
                "127.0.0.1", mid.port, secret, leader_rank=2 * g,
                tier=1, index=g, tiers=2,
                up_fail_base=f"addr.{gen}.t2.0"))
        barrier = threading.Barrier(world)

        def worker(rank):
            try:
                sock = _socket.create_connection(
                    ("127.0.0.1", hosts[rank // 2].port), timeout=10)
                sock.settimeout(0.5)
                wire.send_frame(sock, secret, MSG_HELLO, 0, rank)
                stop = threading.Event()
                for i in range(rounds):
                    barrier.wait(timeout=60)
                    if rank == 0 and i == kill_at:
                        mid.stop()  # kill the mid-tier aggregator mid-run
                    m = wire.ReqMeta(f"g{i}", 0, "float32", (4,))
                    wire.send_frame(sock, secret, MSG_LIST, i, rank,
                                    wire.encode_request_list(
                                        0, [], [m], epoch=-1))
                    deadline = time.monotonic() + 60
                    while True:
                        if time.monotonic() > deadline:
                            raise TimeoutError(
                                f"rank {rank} round {i} timed out")
                        try:
                            mt, seq, _, data = wire.recv_frame(
                                sock, secret, stop)
                        except _socket.timeout:
                            continue
                        if mt == MSG_RESP and seq == i:
                            break
                    digests[rank][i] = hashlib.sha256(data).hexdigest()
                sock.close()
            except Exception as exc:  # surfaced below, thread-safe enough
                errors.append((rank, exc))

        ts = [threading.Thread(target=worker, args=(r,))
              for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=180)
        assert all(not t.is_alive() for t in ts), "tier smoke deadlocked"
        assert not errors, f"worker failures: {errors}"
        assert standby.promoted, (
            "tier standby never promoted a replacement aggregator")
        for i in range(rounds):
            row = {digests[r][i] for r in range(world)}
            assert len(row) == 1, (
                f"round {i} diverged across ranks: {row}")
    finally:
        for h in hosts:
            h.stop()
        if standby is not None:
            standby.stop()
        if mid is not None:
            mid.stop()
        server.stop()
        kv.stop()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    print(f"ok: tier smoke — killed the mid-tier aggregator at round "
          f"{kill_at}, children re-homed to the promoted standby, all "
          f"{rounds} rounds bit-identical across {world} ranks")


def main():
    if len(sys.argv) > 1:
        # run only the named checks: `python ci/pod_smoke.py check_split_brain`
        # lets a CI stage (or a human) re-run one smoke without the full
        # pod-day sweep
        for name in sys.argv[1:]:
            fn = globals().get(name)
            assert name.startswith("check_") and callable(fn), (
                f"unknown smoke check {name!r}; available: "
                + ", ".join(sorted(n for n in globals()
                                   if n.startswith("check_"))))
            fn()
        return
    cmds = pod_day_commands() + elastic_commands()
    for cmd in cmds:
        check_command(cmd)
        print(f"ok: {cmd}")
    check_metrics_endpoint()
    check_chaos_reconnect()
    check_nan_skip()
    check_trace_capture()
    check_bucket_overlap()
    check_blackbox_doctor()
    check_coordinator_failover()
    check_split_brain()
    check_tier_rehome()
    check_straggler_adaptive()
    check_adaptive_wire()
    check_gspmd_quantized()
    check_algo_hierarchical()
    check_moe_quantized()
    check_serving_kill()
    check_serving_frontend_kill()
    check_ckpt_kill_restore()
    check_goodput_chaos()
    print(f"pod-day smoke: {len(cmds)} command lines + /metrics endpoint "
          "+ chaos reconnect + nan skip-step + trace capture "
          "+ bucket overlap + blackbox doctor + coordinator failover "
          "+ split-brain partition drill "
          "+ tier aggregator re-home + straggler adaptive + adaptive wire "
          "+ quantized GSPMD wire + hierarchical collective "
          "+ quantized MoE dispatch + serving worker-kill "
          "+ serving frontend-kill failover "
          "+ checkpoint kill-and-restore + goodput chaos valid")


if __name__ == "__main__":
    main()
