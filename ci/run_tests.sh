#!/usr/bin/env bash
# CI pipeline — the `.buildkite/gen-pipeline.sh` equivalent.
#
# Stages mirror the reference's (build, unit suite, launcher-driven smoke
# runs, stall behavior, benchmarks): the unit suite runs on the 8-device
# virtual CPU platform, and the smoke stages run REAL multi-process jobs
# under the launcher (`hvdrun -np 2 ...`), exercising the cross-process
# control plane the way `horovodrun -np 2 pytest` does upstream.
#
# Usage: ci/run_tests.sh [quick]
#   quick — skip the slower benchmark stage.

set -euo pipefail
cd "$(dirname "$0")/.."

QUICK="${1:-}"
export PALLAS_AXON_POOL_IPS=   # never touch real accelerators from CI
export JAX_PLATFORMS=cpu

stage() { echo; echo "=== $1 ==="; }

stage "build: native engine core"
python setup.py build_native

stage "unit suite (8-device virtual CPU platform)"
python -m pytest tests/ -q -m "not integration"

stage "metrics subsystem (registry, wire roundtrip, /metrics endpoint)"
python -m pytest tests/test_metrics.py -q

stage "chaos: fault injection, frame integrity, reconnect/replay, liveness"
python -m pytest tests/test_faultinject.py -q

stage "chaos: data-plane integrity (grad guard, consistency audit, watchdog)"
python -m pytest tests/test_integrity.py tests/test_stall.py -q

stage "chaos: straggler-adaptive execution (policy, partial rounds, EF rejoin)"
python -m pytest tests/test_straggler.py -q -m "not integration"
# acceptance: with a 500 ms chronic straggler injected, the surviving
# ranks' step time must stay within 1.5x the fault-free baseline
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/allreduce_bench.py --chaos slow@rank:500 --iters 6

stage "controlplane: hierarchical negotiation, coordinator failover, storms"
python -m pytest tests/test_coord.py -q -m "not integration"
# the control-plane integrations run on plain CPU (elastic Popen harness):
# SIGKILL the rank-0 coordinator mid-step, a real hierarchical job, and
# SIGKILL rank 0 with hierarchy AND standby enabled together
python -m pytest -q \
    "tests/test_coord.py::test_coordinator_sigkill_failover_bit_identical" \
    "tests/test_coord.py::test_hierarchical_mode_end_to_end" \
    "tests/test_coord.py::test_hierarchical_standby_sigkill"
# the hierarchical path must beat flat negotiation at scale (rounds/s is
# printed; the >=5x acceptance curve lives in docs/control-plane.md)
python benchmarks/coord_bench.py --ranks 256 --rounds 15 --mode both
# N-tier sweep: 1k/10k/100k fake ranks through the aggregation tree; p99
# round latency at 100k must stay within 5x the 1k point, and every sweep
# point appends a direction="lower" row to the perf history
python benchmarks/coord_bench.py --mode tier --ranks 1024,10240,102400 \
    --rounds 15 --warmup 3 --p99-gate 5.0 \
    --history /tmp/hvd_ci_coord_hist.jsonl --check-regression

stage "chaos: partition-tolerant fenced leadership (lease, wire epochs, jepsen)"
python -m pytest tests/test_fencing.py -q -m "not integration"
# the split-brain drill: cut a 2-process job in half mid-training, assert
# the old coordinator self-fences before the lease TTL, the standby takes
# over by acquiring the lease, the healed deposed primary's frames are
# rejected by fencing epoch, and the jepsen-lite checker proves
# single-writer leadership + exactly-once step application
python -m pytest -q \
    "tests/test_fencing.py::test_partition_failover_fenced_bit_identical"
python ci/pod_smoke.py check_split_brain

stage "tracing: clock, spans, merge, hvdprof critical-path report"
python -m pytest tests/test_tracing.py -q

stage "doctor: blackbox flight recorder, signatures, hvddoctor, anomaly watch"
python -m pytest tests/test_blackbox.py -q

stage "goodput: wall-clock attribution ledger, SLO burn alerts, hvdtop"
python -m pytest tests/test_goodput.py -q
# acceptance: a real bench run's metrics dump must attribute >= 99% of
# each rank's wall clock (the ledger's completeness bar, docs/goodput.md)
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    BENCH_IMAGE=32 BENCH_BATCH=2 BENCH_WARMUP=1 BENCH_ROUNDS=1 BENCH_ITERS=2 \
    python bench.py --metrics-dump /tmp/hvd_ci_goodput.json
python - <<'EOF'
import json
doc = json.load(open("/tmp/hvd_ci_goodput.json"))
walls = {s["labels"]["rank"]: s["value"]
         for s in doc["hvd_goodput_wall_seconds"]["series"]}
attributed = {}
for fam in ("hvd_goodput_seconds_total", "hvd_badput_seconds_total"):
    for s in doc.get(fam, {}).get("series", []):
        r = s["labels"]["rank"]
        attributed[r] = attributed.get(r, 0.0) + s["value"]
assert walls, "no goodput attribution in the metrics dump"
for r, wall in walls.items():
    frac = attributed.get(r, 0.0) / wall if wall else 0.0
    print(f"rank {r}: {frac:.1%} of {wall:.2f}s attributed")
    assert frac >= 0.99, f"rank {r} attribution {frac:.1%} < 99%"
EOF

stage "restart: async sharded checkpointing + peer-redundant recovery"
python -m pytest tests/test_ckpt.py -q -m "not integration"
# the write-behind contract is the gate: per-commit stall must stay ~0
# (the step path pays a buffer swap, never disk I/O), and the O(shard)
# peer-restore time appends a direction="lower" row to the perf history.
# the kill-and-replace integration rides the integration suite below.
python benchmarks/ckpt_bench.py --shard-mb 2 --commits 15 \
    --history /tmp/hvd_ci_ckpt_hist.jsonl --check-regression

stage "overlap: bucketed backward drain, fused kernels, hvdprof overlap %"
python -m pytest tests/test_overlap.py -q

stage "compression v2: int4 wire, adaptive bitwidth selector, convergence gate"
python -m pytest tests/test_adaptive.py -q
python -m pytest tests/test_compression.py -q -k "Int4 or int4 or adaptive"
# adaptive wire must hit the <=60% of int8 byte target on the microbench
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/allreduce_bench.py --compression int8,int4,adaptive \
        --sizes-mb 0.25 --iters 3

stage "gspmd: quantized compiled-path ring, EF residual, cache-key pin"
python -m pytest tests/test_gspmd.py -q
# acceptance: three-way head-to-head (coordinator wire vs plain GSPMD vs
# quantized GSPMD) — asserts int4 wire bytes <=60% of plain and int8
# <=1.05 B per moved element (docs/gspmd.md)
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/scaling_bench.py --three-way --iters 3 \
        --elements 65536

stage "algo: collective algorithm zoo, joint tuner, footprint catalog"
python -m pytest tests/test_algo.py -q
# acceptance: the (size x algorithm x bitwidth) sweep on the compiled
# fast path — the per-size tuned argmin >= every fixed combo by
# construction; sub-64KB points exercise the tree's latency regime
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/allreduce_bench.py --algo-sweep \
        --sizes-mb "" --sizes-kb 4,16 --iters 3

stage "moe: capacity-factor Switch dispatch over the quantized all_to_all"
python -m pytest tests/test_moe.py tests/test_expert_parallel.py -q
# acceptance: four-config head-to-head (exact one-hot vs capacity vs
# capacity+int8/int4) — capacity must out-run exact at E=8 and the int4
# dispatch catalog must stay <=60% of a bf16 exchange (docs/moe.md)
XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    LM_MOE_TOKENS=2048 LM_MOE_ITERS=4 \
    python benchmarks/lm_bench.py --moe

stage "serving: continuous batching, paged KV cache, elastic pod serving"
python -m pytest tests/test_serving.py -q -m "not integration"
# in-process load bench (deterministic perf-gate mode); exit 4 on any
# lost request, exit 3 on a p99 regression when a history is supplied
python benchmarks/serving_bench.py --requests 12 --qps 32 --max-new 4

stage "serving-chaos: frontend failover, deadlines, shedding, hedging, drain"
python -m pytest tests/test_serving_failover.py -q -m "not integration"
# the four survivability drills (docs/inference.md failure matrix); each
# exits 4 on any lost or duplicated request delivery (jepsen-checked).
# kill-frontend runs under pod_smoke below so hvddoctor can gate on the
# serving_failover signature over the same blackbox bundle
python benchmarks/serving_bench.py --chaos slow-replica \
    --requests 16 --qps 8 --max-new 4
python benchmarks/serving_bench.py --chaos overload --requests 48 \
    --max-new 4 --history /tmp/hvd_ci_serve_overload.jsonl \
    --check-regression
python benchmarks/serving_bench.py --chaos rolling-restart \
    --requests 24 --qps 16 --max-new 4
# frontend SIGKILL + doctor: hvddoctor must name the serving_failover
python ci/pod_smoke.py check_serving_frontend_kill

stage "integration suite: real multi-process jobs (launcher, SPMD mesh)"
# includes tests/test_spark_real.py (real-pyspark scenarios; they skip
# when pyspark is absent from the image)
python -m pytest tests/ -q -m integration

stage "pod-day smoke: multi-host command lines from docs/running.md"
python ci/pod_smoke.py

stage "launcher smoke: 2-process training job under hvdrun"
cat > /tmp/ci_smoke_worker.py <<'EOF'
import os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.getcwd())
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
import horovod_tpu as hvd
hvd.init()
r = hvd.rank()
w = np.asarray(hvd.broadcast(np.ones(3) * (r + 1), root_rank=0, name="w"))
for i in range(3):
    g = hvd.allreduce(np.ones(3) * (r + 1), name=f"g{i}")
    w = w - 0.1 * np.asarray(g)
assert np.allclose(w, 1.0 - 0.3 * 1.5), w
print(f"rank {r} ok")
hvd.shutdown()
EOF
python bin/hvdrun -np 2 --no-nic-discovery python /tmp/ci_smoke_worker.py

stage "launcher smoke: run() func API across 2 processes"
python examples/interactive_run.py

stage "launcher smoke: ragged alltoall routing across 4 processes"
python examples/alltoallv_routing.py

if [ "$QUICK" != "quick" ]; then
  # outside quick mode: the 2-process run jit-compiles ResNet-50 on CPU,
  # the slowest single stage (unit tests already cover the pipeline)
  stage "real-data input pipeline: rank-sharded image folder across 2 processes"
  rm -rf /tmp/hvd_ci_imgfolder
  python bin/hvdrun -np 2 --no-nic-discovery \
      python examples/imagenet_resnet50_realdata.py \
      --data-dir /tmp/hvd_ci_imgfolder --synthesize 48 \
      --image-size 32 --batch-size 4 --epochs 1

  stage "benchmarks: scaling + allreduce microbench (virtual 8-device mesh)"
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python benchmarks/scaling_bench.py --world-sizes 1,8 \
          --batch-per-device 2 --iters 3
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python benchmarks/allreduce_bench.py --sizes-mb 0.25,1 --iters 5
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python benchmarks/allreduce_bench.py --bucket-mb 0,0.5 --iters 5 \
          --layers 4
fi

echo
echo "CI pipeline passed."
