"""Gradient compression for collectives.

Reference parity: `horovod/tensorflow/compression.py` / `horovod/torch/compression.py`
(74 LoC each) — a ``Compressor`` pair (compress/decompress) selected via
``Compression.none`` / ``Compression.fp16``.

TPU-native note: on TPU the natural 16-bit wire format is **bfloat16** (MXU
native, same exponent range as fp32 so no loss-scaling needed); ``fp16`` is
kept for API parity and ``bf16`` added as the recommended choice.

Beyond the reference's dtype casts this module owns the **block-quantized
int8 wire format** (EQuARX-style, PAPERS.md arXiv:2506.17615): per-block
(default 256 elements) symmetric int8 payload with one fp32 scale per
block. Unlike the cast compressors, int8 quantization cannot run at the
framework layer — per-rank scales don't commute with the sum — so
``Compression.int8`` / ``Compression.int8_dcn`` are *wire markers*:
``compress()`` is the identity and the executor lowers the
quantize → allreduce → dequantize pipeline into its single compiled
collective program (`runtime/executor.py`). The numerics live here
(`quantize_blocks` / `dequantize_blocks`, jnp reference implementation
with a Pallas kernel fast path) so tests, error feedback and the executor
share one definition.

Adaptive v2 (this module + `ops/adaptive.py`): ``int4`` halves the packed
wire again (two values per byte, scale = absmax/7), and ``adaptive`` lets a
per-bucket selector pick int4/int8/bf16 from running statistics of the
reduced gradients — the enqueued wire string is ``adaptive:<mode>`` so the
coordinator negotiates the concrete bitwidth before the collective fires.

Job-wide default: ``HOROVOD_COMPRESSION={none,fp16,bf16,int8,int8-dcn,
int4,adaptive}`` (resolved by :func:`from_env`); ``HOROVOD_INT8_BLOCK``
overrides the block size for every block-quantized mode.
"""

from __future__ import annotations

import os

import jax.numpy as jnp

DEFAULT_BLOCK = 256


def block_size() -> int:
    """Quantization block length (``HOROVOD_INT8_BLOCK``, default 256)."""
    b = int(os.environ.get("HOROVOD_INT8_BLOCK", DEFAULT_BLOCK))
    if b <= 0:
        raise ValueError(f"HOROVOD_INT8_BLOCK={b}: must be positive")
    return b


def _kernels():
    from . import pallas_kernels
    return pallas_kernels


def quantize_blocks(x, block: int | None = None, bits: int = 8):
    """Block-quantize a float array to (int8 payload, fp32 scales).

    ``x`` is flattened; its length must be a multiple of ``block`` (callers
    pad — see :func:`quantize_roundtrip` / the executor's chunk padding).
    Returns ``(q, scales)`` with ``q`` int8 of ``x.size`` elements and
    ``scales`` fp32 of ``x.size // block`` elements, where block ``i`` of
    ``x`` is approximately ``q[i*block:(i+1)*block] * scales[i]``.

    ``bits`` picks the quantization grid: 8 (scale = absmax/127, the
    default) or 4 (scale = absmax/7). The 4-bit grid is returned unpacked
    (one int8 per value) — nibble packing is a wire-layout concern and
    lives in ``pallas_kernels.int4_quantize_pack``; this function is the
    numerics shared by error feedback and the tests.
    """
    if bits not in (4, 8):
        raise ValueError(f"quantize_blocks: bits must be 4 or 8, got {bits}")
    block = block or block_size()
    flat = jnp.ravel(x).astype(jnp.float32)
    if flat.shape[0] % block:
        raise ValueError(
            f"quantize_blocks: size {flat.shape[0]} not a multiple of "
            f"block {block}")
    x2 = flat.reshape(-1, block)
    pk = _kernels()
    if (bits == 8 and pk.int8_supported(x2.shape[0], block)
            and not pk.vma_active(x2)):
        q2, s2 = pk.int8_quantize_2d(x2)
        return q2.reshape(-1), s2[:, 0]
    qmax = 127.0 if bits == 8 else 7.0
    absmax = jnp.max(jnp.abs(x2), axis=1, keepdims=True)
    scale = absmax * (1.0 / qmax)
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q2 = jnp.clip(jnp.round(x2 / safe), -qmax, qmax).astype(jnp.int8)
    return q2.reshape(-1), scale[:, 0]


def dequantize_blocks(q, scales, dtype=jnp.float32, block: int | None = None):
    """Inverse of :func:`quantize_blocks`: int8 payload × per-block scale."""
    block = block or block_size()
    q2 = jnp.ravel(q).reshape(-1, block)
    s2 = jnp.ravel(scales).astype(jnp.float32)[:, None]
    pk = _kernels()
    if pk.int8_supported(q2.shape[0], block) and not pk.vma_active(q2, s2):
        y2 = pk.int8_dequantize_2d(q2, s2)
    else:
        y2 = q2.astype(jnp.float32) * s2
    return y2.reshape(-1).astype(dtype)


def quantize_roundtrip(x, block: int | None = None, bits: int = 8):
    """Quantize→dequantize ``x`` (any shape/float dtype), padding internally.

    This is the exact value the quantized wire delivers for a single-rank
    hop; error feedback (`optim/distributed.py`) uses it to compute the
    residual the wire dropped. ``bits=4`` measures the int4 grid.
    """
    block = block or block_size()
    # metric lives here (the eager entry point), not in the jit-traced
    # quantize/dequantize bodies where an inc would count compiles
    from ..metrics import instruments

    instruments.error_feedback_roundtrips().inc()
    flat = jnp.ravel(x)
    n = flat.shape[0]
    pad = (-n) % block
    if pad:
        flat = jnp.pad(flat, (0, pad))
    q, s = quantize_blocks(flat, block, bits=bits)
    y = dequantize_blocks(q, s, dtype=x.dtype, block=block)
    return y[:n].reshape(x.shape)


def wire_footprint(num_elements: int, mode: str,
                   block: int | None = None) -> int:
    """Bytes a fused bucket of ``num_elements`` fp32 elements moves over the
    wire for one reduce-scatter + allgather round in the given mode
    (``int8-dcn`` counts the quantized DCN hop — its ICI hops ride bf16).
    """
    per_elem = {"none": 4, "fp32": 4, "fp16": 2, "bf16": 2}.get(mode)
    if per_elem is not None:
        return 2 * num_elements * per_elem
    if mode in ("int8", "int8-dcn", "int8_dcn"):
        block = block or block_size()
        blocks = -(-num_elements // block)
        return 2 * (num_elements + 4 * blocks)
    if mode == "int4":
        # packed nibbles: half a byte per element plus the same one-f32-
        # per-block scale overhead as int8 (wire rows are
        # [block//2 payload bytes | 4 scale bytes])
        block = block or block_size()
        blocks = -(-num_elements // block)
        return 2 * (-(-num_elements // 2) + 4 * blocks)
    if mode == "adaptive" or mode.startswith("adaptive:"):
        # mixed wire: the footprint is whatever concrete mode the selector
        # negotiated for this bucket ("adaptive:<mode>"); bare "adaptive"
        # counts the int8 startup default
        concrete = mode.split(":", 1)[1] if ":" in mode else "int8"
        return wire_footprint(num_elements, concrete, block)
    raise ValueError(f"unknown compression mode {mode!r}")


def _gspmd_seg_bytes(elems: int, mode: str, block: int | None) -> int:
    """Bytes one exchanged segment of ``elems`` f32 elements costs on a
    GSPMD wire: packed rows for int8/int4, raw elements otherwise."""
    per_elem = {"none": 4, "fp32": 4, "fp16": 2, "bf16": 2}.get(mode)
    if per_elem is not None:
        return elems * per_elem
    if mode not in ("int8", "int4"):
        raise ValueError(f"unknown GSPMD wire mode {mode!r}")
    block = block or block_size()
    rows = -(-elems // block)
    row_bytes = (block if mode == "int8" else block // 2) + 4
    return rows * row_bytes


def gspmd_wire_footprint(num_elements: int, mode: str, world: int,
                         block: int | None = None,
                         algorithm: str = "ring",
                         hosts: int | None = None) -> int:
    """Bytes ONE rank puts on the wire for one allreduce on the compiled
    path, per zoo member (`spmd.quantized_allreduce` and friends).

    Quantized modes move packed rows — ``[block payload | 4 scale bytes]``
    for int8, ``[block//2 | 4]`` for int4 — over chunks rounded up to
    whole blocks. ``none``/``fp32`` (``bf16``/``fp16``) count the same
    schedule moving raw 4-byte (2-byte) elements with no scale overhead:
    the exact-wire denominator behind ``hvd_quantization_ratio`` and the
    three-way `scaling_bench`. ``world == 1`` is wireless.

    ``algorithm`` rows (docs/autotune.md):

    * ``ring`` — reduce-scatter + all-gather, each phase ``world - 1``
      hops of one per-rank chunk. The ZeRO-1 variant moves the same
      total. Byte-identical to the pre-zoo catalog.
    * ``tree`` — recursive halving/doubling, ``2 * log2(world)``
      exchanges of a payload half (`spmd.quantized_allreduce_tree`);
      non-power-of-two worlds ride the ring and cost ring bytes.
    * ``hier`` — intra-host reduce-scatter + all-gather over
      ``chips = world // hosts`` plus the cross-host phase on the owned
      chunk (`spmd.quantized_allreduce_hier`); ``hosts`` must be a proper
      divisor of ``world`` or the ring row applies.
    """
    if world <= 1:
        return 0
    if algorithm == "tree" and world & (world - 1) == 0:
        half = -(-num_elements // 2)
        rounds = world.bit_length() - 1
        return 2 * rounds * _gspmd_seg_bytes(half, mode, block)
    if (algorithm == "hier" and hosts and 1 < hosts < world
            and world % hosts == 0):
        chips = world // hosts
        chunk = -(-num_elements // chips)
        sub = -(-chunk // hosts)
        intra = 2 * (chips - 1) * _gspmd_seg_bytes(chunk, mode, block)
        cross = 2 * (hosts - 1) * _gspmd_seg_bytes(sub, mode, block)
        return intra + cross
    return (2 * (world - 1)
            * _gspmd_seg_bytes(-(-num_elements // world), mode, block))


def gspmd_cross_host_footprint(num_elements: int, mode: str, world: int,
                               hosts: int, block: int | None = None,
                               algorithm: str = "ring") -> int:
    """Bytes crossing a host boundary, summed over ALL ranks, for one
    allreduce under a host-major ``(hosts, chips)`` layout — the number
    the hierarchical schedule exists to shrink (`ci/pod_smoke.py`
    ``check_algo_hierarchical``).

    ``ring``: the flat ring has ``hosts`` boundary edges and every edge
    carries ``world - 1`` chunk segments per phase. ``hier``: only the
    phase-2 host-ring rows cross hosts — ``chips`` parallel rings of
    ``hosts`` edges, each edge carrying ``hosts - 1`` sub-chunk segments
    per phase. ``tree``: at recursion distance ``d >= chips`` every rank's
    partner is on another host; smaller distances stay intra-host.
    """
    if world <= 1 or hosts <= 1 or world % hosts:
        return 0
    chips = world // hosts
    if algorithm == "hier":
        chunk = -(-num_elements // chips)
        sub = -(-chunk // hosts)
        return (2 * (hosts - 1) * chips * hosts
                * _gspmd_seg_bytes(sub, mode, block))
    if algorithm == "tree" and world & (world - 1) == 0:
        total = 0
        seg = -(-num_elements // 2)
        d = world >> 1
        while d >= 1:
            if d >= chips:  # partner p ^ d sits on another host
                total += 2 * world * _gspmd_seg_bytes(seg, mode, block)
            seg = -(-seg // 2)
            d >>= 1
        return total
    chunk = -(-num_elements // world)
    return 2 * (world - 1) * hosts * _gspmd_seg_bytes(chunk, mode, block)


def moe_wire_footprint(per_peer_elements: int, mode: str, world: int,
                       block: int | None = None) -> int:
    """Bytes ONE device puts on the wire for one capacity-dispatch MoE
    round (`parallel/expert.py`): the dispatch all_to_all plus the
    combine all_to_all over the ``ep`` axis, each moving ``world - 1``
    remote per-peer payloads of ``per_peer_elements`` f32 elements
    (``E_loc * capacity * d``; the slab a device keeps for its own
    experts never touches the wire).

    Quantized modes move packed rows — ``[block | 4 scale bytes]`` for
    int8, ``[block//2 | 4]`` for int4 — with each peer's payload padded
    to whole blocks independently (`spmd.quantized_all_to_all`).
    ``none``/``fp32`` (``bf16``/``fp16``) count the exact exchange moving
    raw 4-byte (2-byte) elements: ``bf16`` is the denominator behind the
    "dispatch bytes ≤60% of the bf16 exchange" CI bar. ``world == 1``
    is wireless.
    """
    if world <= 1:
        return 0
    per_elem = {"none": 4, "fp32": 4, "fp16": 2, "bf16": 2}.get(mode)
    if per_elem is not None:
        return 2 * (world - 1) * per_peer_elements * per_elem
    if mode not in ("int8", "int4"):
        raise ValueError(f"unknown MoE wire mode {mode!r}")
    block = block or block_size()
    rows = -(-per_peer_elements // block)
    row_bytes = (block if mode == "int8" else block // 2) + 4
    return 2 * (world - 1) * rows * row_bytes


class Compressor:
    """Interface: compress before enqueue, decompress after completion.

    ``wire`` names an in-collective wire format the executor should apply
    (None = the wire carries whatever ``compress`` produced).
    """

    wire: str | None = None

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context_for_decompress)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError

    @classmethod
    def roundtrip(cls, tensor):
        """The value the wire delivers for this compressor (lossy part only;
        used by error feedback to measure what the wire dropped)."""
        comp, ctx = cls.compress(tensor)
        return cls.decompress(comp, ctx)


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating):
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(_CastCompressor):
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = jnp.bfloat16


class _WireCompressor(NoneCompressor):
    """Marker: framework-level identity, executor-level quantized wire.

    The tensor is enqueued unchanged; ``TensorTableEntry.compression``
    carries ``wire`` through negotiation so every rank's executor compiles
    the same quantize → collective → dequantize program. Integer/bool
    tensors and buckets below the executor's size floor bypass quantization
    inside the executor (the entry still negotiates the mode so ranks
    agree on the program).
    """

    #: quantization grid the wire applies (4 or 8)
    bits = 8

    @classmethod
    def roundtrip(cls, tensor):
        if not jnp.issubdtype(jnp.asarray(tensor).dtype, jnp.floating):
            return tensor
        return quantize_roundtrip(tensor, bits=cls.bits)


class Int8Compressor(_WireCompressor):
    wire = "int8"


class Int8DcnCompressor(_WireCompressor):
    """int8 on the slow DCN hop only; ICI hops ride bf16 (EQuARX mixed
    mode applied to the two-level hierarchical allreduce)."""

    wire = "int8-dcn"


class Int4Compressor(_WireCompressor):
    """int4 packed wire: two values per byte, scale = absmax/7 per block.
    Roughly half of int8's bytes; pair with ``error_feedback=True`` — the
    4-bit grid drops enough signal that EF is what keeps convergence at
    parity (the convergence gate in ops/adaptive.py measures exactly
    this)."""

    wire = "int4"
    bits = 4


class AdaptiveCompressor(_WireCompressor):
    """Mixed-bitwidth wire (``HOROVOD_COMPRESSION=adaptive``).

    A per-bucket selector (`ops/adaptive.py`) keeps running statistics of
    the *reduced* gradients — absmax/variance EMAs plus the measured
    quantization-residual norm at each candidate grid — and picks the
    cheapest of int4/int8/bf16 whose error stays under tolerance,
    re-deciding every ``HOROVOD_ADAPTIVE_INTERVAL`` observations. The
    statistics come from the allreduced output, which is identical on
    every rank, so decisions are deterministic and cross-rank consistent;
    the enqueued wire string ``adaptive:<mode>`` is still negotiated
    through the coordinator (Response.compression wins), which resolves
    any transition race to the least aggressive proposal.

    Selector state is class-level (one per process): ranks sharing a
    process observe identical reduced buckets, so sharing is harmless, and
    ``reset()`` gives tests a clean slate.
    """

    wire = "adaptive:int8"  # startup default, before any statistics exist
    _selector = None

    @classmethod
    def selector(cls):
        if cls._selector is None:
            from . import adaptive as _adaptive

            cls._selector = _adaptive.BitwidthSelector()
        return cls._selector

    @classmethod
    def reset(cls):
        cls._selector = None

    @classmethod
    def wire_for(cls, name: str) -> str:
        return "adaptive:" + cls.selector().decide(name)

    @classmethod
    def observe(cls, name: str, flat) -> None:
        cls.selector().observe(name, flat)

    @classmethod
    def roundtrip(cls, tensor):
        # EF residual against the most aggressive grid currently active:
        # one residual tree serves every bucket, so this measures the
        # worst-case wire loss (buckets on a finer grid over-correct
        # slightly, which EF tolerates — the residual shrinks next step)
        if not jnp.issubdtype(jnp.asarray(tensor).dtype, jnp.floating):
            return tensor
        bits = cls.selector().min_active_bits()
        if bits >= 16:
            return tensor.astype(jnp.bfloat16).astype(tensor.dtype)
        return quantize_roundtrip(tensor, bits=bits)


class Compression:
    """Parity with the reference's Compression namespace."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor  # TPU-native extension
    int8 = Int8Compressor  # block-quantized wire (executor-fused)
    int8_dcn = Int8DcnCompressor
    int4 = Int4Compressor  # packed-nibble wire (executor-fused)
    adaptive = AdaptiveCompressor  # per-bucket mixed bitwidth


_BY_NAME = {
    "": NoneCompressor,
    "none": NoneCompressor,
    "fp16": FP16Compressor,
    "bf16": BF16Compressor,
    "int8": Int8Compressor,
    "int8-dcn": Int8DcnCompressor,
    "int8_dcn": Int8DcnCompressor,
    "int4": Int4Compressor,
    "adaptive": AdaptiveCompressor,
}

# wire-name → compressor, for reconstructing the negotiated mode from
# control-plane metadata on ranks that had no local entry.
BY_WIRE = {"int8": Int8Compressor, "int8-dcn": Int8DcnCompressor,
           "int4": Int4Compressor}


def by_name(name: str):
    """Resolve a compression mode name (the HOROVOD_COMPRESSION values)."""
    try:
        return _BY_NAME[str(name).strip().lower()]
    except KeyError:
        raise ValueError(
            f"unknown compression {name!r}; expected one of "
            "none/fp16/bf16/int8/int8-dcn/int4/adaptive") from None


def from_env(default=NoneCompressor):
    """Job-wide default compressor from ``HOROVOD_COMPRESSION``."""
    name = os.environ.get("HOROVOD_COMPRESSION")
    return by_name(name) if name else default
