"""Gradient compression for collectives.

Reference parity: `horovod/tensorflow/compression.py` / `horovod/torch/compression.py`
(74 LoC each) — a ``Compressor`` pair (compress/decompress) selected via
``Compression.none`` / ``Compression.fp16``.

TPU-native note: on TPU the natural 16-bit wire format is **bfloat16** (MXU
native, same exponent range as fp32 so no loss-scaling needed); ``fp16`` is
kept for API parity and ``bf16`` added as the recommended choice.
"""

from __future__ import annotations

import jax.numpy as jnp


class Compressor:
    """Interface: compress before enqueue, decompress after completion."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context_for_decompress)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    wire_dtype = None

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating):
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        return tensor if ctx is None else tensor.astype(ctx)


class FP16Compressor(_CastCompressor):
    wire_dtype = jnp.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = jnp.bfloat16


class Compression:
    """Parity with the reference's Compression namespace."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor  # TPU-native extension
