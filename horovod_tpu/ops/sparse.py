"""Sparse (indexed-slices) gradient collectives.

Reference parity: `horovod/tensorflow/__init__.py:75-91` — an allreduce on a
`tf.IndexedSlices` is implemented as TWO allgathers (values + indices), i.e.
the represented dense tensor is summed by concatenating every rank's slice
contributions; Average divides the gathered values by world size. The rows
gathered from different ranks may overlap in index — consumers either apply
them as duplicate scatter-adds (what TF optimizers do) or densify via
``to_dense``.

This module is the framework-neutral engine path (numpy/JAX arrays at the
boundary, ragged dim0 negotiated across ranks by the controller). The in-jit
SPMD variant lives in `horovod_tpu.spmd.allreduce_sparse` (static shapes, XLA
`all_gather`).

Adasum on sparse tensors is rejected, as in the reference (:77-81).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from .. import basics
from ..basics import Adasum, Average, Sum
from . import collective_ops as _ops


class IndexedSlices(NamedTuple):
    """A sparse update: ``dense[indices[i]] += values[i]`` row-wise.

    Mirrors `tf.IndexedSlices` (values ``[k, ...]``, indices ``[k]``,
    ``dense_shape`` of the represented tensor). ``dense_shape`` may be None
    when only gather/apply semantics are needed.
    """

    values: object
    indices: object
    dense_shape: Optional[tuple] = None


def allreduce_sparse_async(slices: IndexedSlices,
                           name: Optional[str] = None):
    """Start the two allgathers; returns a pair of handles."""
    name = name or _ops._auto_name("sparse_allreduce", None)
    hv = _ops.allgather_async(slices.values, name=f"{name}.values")
    hi = _ops.allgather_async(slices.indices, name=f"{name}.indices")
    return hv, hi


def synchronize_sparse(handles, op: int = Average,
                       dense_shape=None) -> IndexedSlices:
    hv, hi = handles
    values = _ops.synchronize(hv)
    indices = _ops.synchronize(hi)
    if op == Average:
        n = basics.size()
        values = values / jnp.asarray(n, values.dtype) \
            if jnp.issubdtype(values.dtype, jnp.floating) else values // n
    return IndexedSlices(values, indices, dense_shape)


def allreduce_sparse(slices: IndexedSlices, name: Optional[str] = None,
                     op: int = Average) -> IndexedSlices:
    """Allreduce of the dense tensor represented by ``slices``, done as
    allgathers (`tensorflow/__init__.py:83-91`). Per-rank row counts may
    differ (ragged dim0 — negotiated like any allgather)."""
    if op == Adasum:
        raise NotImplementedError(
            "The Adasum reduction does not currently support sparse "
            "tensors. As a workaround please pass sparse_as_dense=True to "
            "DistributedOptimizer")
    if op not in (Average, Sum):
        raise ValueError(f"unsupported op for sparse allreduce: {op}")
    return synchronize_sparse(allreduce_sparse_async(slices, name), op=op,
                              dense_shape=slices.dense_shape)


def densify_tree(tree):
    """Replace every IndexedSlices leaf with its dense scatter-add result."""
    is_sparse = lambda x: isinstance(x, IndexedSlices)  # noqa: E731
    return jax.tree_util.tree_map(
        lambda l: to_dense(l) if is_sparse(l) else l, tree,
        is_leaf=is_sparse)


def to_dense(slices: IndexedSlices):
    """Densify with duplicate-index accumulation (scatter-add)."""
    if slices.dense_shape is None:
        raise ValueError("IndexedSlices has no dense_shape; cannot densify")
    values = jnp.asarray(slices.values)
    indices = jnp.asarray(slices.indices)
    out = jnp.zeros(tuple(slices.dense_shape), values.dtype)
    return out.at[indices].add(values)
