"""Eager named-collective API: allreduce / allgather / broadcast / alltoall / join.

Reference parity: the per-framework op surfaces —
`horovod/torch/mpi_ops.py` (allreduce[_async][_], allgather[_async],
broadcast[_async][_], poll, synchronize, join) and
`horovod/tensorflow/mpi_ops.py` + `horovod/tensorflow/__init__.py:44-118`
(allreduce with Average-in-framework, Adasum scaling, compression).

Semantics: every op takes a *named* tensor; ranks negotiate readiness in the
background engine; async variants return an integer handle usable with
``poll``/``synchronize``. Inputs are committed to the calling rank's device;
results come back on the same device.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .. import basics, faultinject
from ..basics import Adasum, Average, Sum
from ..runtime.messages import AlltoallvResult, RequestType, TensorTableEntry
from . import compression as _compression
from .compression import Compression

_auto_counter = {}


def _auto_name(prefix: str, name: Optional[str]) -> str:
    """Stable auto-names per op type (the reference derives names from TF ops /
    torch parameter names; eager callers without a name get a sequence id that
    must line up across ranks by call order)."""
    if name is not None:
        return name
    key = (prefix, basics.rank())
    n = _auto_counter.get(key, 0)
    _auto_counter[key] = n + 1
    return f"{prefix}.noname.{n}"


def _reset_auto_names() -> None:
    """Counters restart with the engine: a shutdown/re-init cycle must not
    carry auto-name positions into the next session — ranks whose previous
    session advanced their counters unevenly (asymmetric branches, error
    paths) would otherwise submit mismatched names forever after."""
    _auto_counter.clear()


basics.register_shutdown_hook(_reset_auto_names)


def _commit(tensor, rank: int):
    arr = jnp.asarray(tensor)
    return jax.device_put(arr, basics.rank_device(rank))


def _enqueue(request_type: RequestType, tensor, name: str, *, root_rank=-1,
             average=False, prescale=1.0, postscale=1.0,
             callback=None, splits=None, wire: str = "",
             fusable: bool = True) -> int:
    eng = basics._engine()
    r = basics.rank()
    # chaos harness: hang@collective / delay@collective hold THIS rank's
    # submission; with HOROVOD_COLLECTIVE_TIMEOUT set, peers waiting on the
    # name get CollectiveTimeoutError instead of hanging forever
    inj = faultinject.shared_for_rank(r)
    if inj is not None:
        inj.fire("collective")
    entry = TensorTableEntry(
        tensor_name=name,
        rank=r,
        request_type=request_type,
        array=_commit(tensor, r),
        root_rank=root_rank,
        average=average,
        prescale_factor=prescale,
        postscale_factor=postscale,
        callback=callback,
        splits=splits,
        compression=wire,
        fusable=fusable,
    )
    from ..integrity import precheck_entry

    precheck_entry(entry)
    return eng.enqueue(entry)


# ----------------------------------------------------------------- allreduce
def allreduce_async(tensor, name: Optional[str] = None, op: int = Average,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0, callback=None,
                    compression=None, fusable: bool = True) -> int:
    """Asynchronous allreduce; returns a handle (`torch/mpi_ops.py:207-229`).
    ``callback(ok, result_or_error)`` fires on the engine thread at
    completion, before ``synchronize`` unblocks (the reference's done-
    callback contract, `mpi_ops_v2.cc:53-79`).

    ``compression=None`` resolves the job-wide ``HOROVOD_COMPRESSION``
    default; wire-mode compressors (``Compression.int8`` / ``int8_dcn``)
    enqueue the tensor unchanged and negotiate the quantized wire program
    through the control plane (cast compressors belong on the synchronous
    ``allreduce`` wrapper, which owns the decompress side).

    ``fusable=False`` marks the tensor as a client-built bucket the
    controller must not merge with others (backward-pass bucket overlap,
    docs/overlap.md); default True preserves the engine's normal fusion."""
    name = _auto_name("allreduce", name)
    if compression is None:
        compression = _compression.from_env()
    if op == Adasum:
        if prescale_factor != 1.0 or postscale_factor != 1.0:
            raise ValueError(
                "prescale_factor/postscale_factor are not supported with "
                "op=Adasum (the combine rule is scale-invariant).")
        return _enqueue(RequestType.ADASUM, tensor, name, callback=callback)
    # adaptive wire: the enqueued string carries this bucket's current
    # bitwidth decision ("adaptive:<mode>") so negotiation can arbitrate it
    wire_for = getattr(compression, "wire_for", None)
    wire = wire_for(name) if wire_for is not None else compression.wire or ""
    return _enqueue(RequestType.ALLREDUCE, tensor, name,
                    average=(op == Average),
                    prescale=prescale_factor, postscale=postscale_factor,
                    callback=callback, wire=wire,
                    fusable=fusable)


def allreduce(tensor, name: Optional[str] = None, op: int = Average,
              compression=None,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0):
    """Synchronous allreduce of a named tensor across all ranks.

    ``op``: Average (default; sum is divided by world size inside the fused
    XLA program), Sum, or Adasum (`tensorflow/__init__.py:44-118`).

    ``compression``: a ``hvd.Compression`` member (default: the
    ``HOROVOD_COMPRESSION`` env choice, else none). fp16/bf16 cast here at
    the framework layer; int8/int8-dcn quantize inside the executor's
    compiled collective program (`docs/compression.md`).
    """
    if compression is None:
        compression = _compression.from_env()
    comp, ctx = compression.compress(jnp.asarray(tensor))
    h = allreduce_async(comp, name=name, op=op,
                        prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor,
                        compression=compression)
    out = synchronize(h)
    return compression.decompress(out, ctx)


# ----------------------------------------------------------------- allgather
def allgather_async(tensor, name: Optional[str] = None) -> int:
    name = _auto_name("allgather", name)
    return _enqueue(RequestType.ALLGATHER, tensor, name)


def allgather(tensor, name: Optional[str] = None):
    """Concatenate each rank's tensor along dim 0 (ragged dim0 allowed, like
    the reference's allgatherv path `mpi_operations.cc:83-166`)."""
    return synchronize(allgather_async(tensor, name=name))


# ----------------------------------------------------------------- broadcast
def broadcast_async(tensor, root_rank: int, name: Optional[str] = None,
                    callback=None) -> int:
    name = _auto_name("broadcast", name)
    return _enqueue(RequestType.BROADCAST, tensor, name, root_rank=root_rank,
                    callback=callback)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Every rank receives root_rank's value."""
    return synchronize(broadcast_async(tensor, root_rank, name=name))


# ------------------------------------------------------------------ alltoall
def alltoall_async(tensor, splits=None, name: Optional[str] = None) -> int:
    """Alltoall (north-star op set extension; API shape of later-horovod
    ``alltoall(tensor, splits)``).

    Without ``splits``: equal split — dim 0 must be divisible by world
    size; rank r receives segment r from every rank. With ``splits`` (a
    length-world sequence of non-negative ints summing to dim 0):
    alltoallv — rank r receives ``splits[r]`` rows from this rank; the
    output concatenates the received chunks in source-rank order. Per-rank
    split metadata is negotiated through the control plane the way ragged
    allgather negotiates dim 0."""
    name = _auto_name("alltoall", name)
    if splits is not None:
        splits = tuple(int(s) for s in splits)
        world = basics.size()
        if len(splits) != world:
            raise ValueError(
                f"alltoall splits must have one entry per rank "
                f"({world}); got {len(splits)}")
        if any(s < 0 for s in splits):
            raise ValueError("alltoall splits must be non-negative")
        d0 = jnp.shape(tensor)[0] if jnp.ndim(tensor) else 0
        if sum(splits) != d0:
            raise ValueError(
                f"alltoall splits sum to {sum(splits)} but tensor dim 0 "
                f"is {d0}")
    return _enqueue(RequestType.ALLTOALL, tensor, name, splits=splits)


def alltoall(tensor, splits=None, name: Optional[str] = None):
    """Without ``splits``: returns the exchanged tensor. With ``splits``:
    returns ``(output, received_splits)`` — received_splits[src] is how many
    dim-0 rows of the output came from rank ``src`` (later-horovod's
    alltoallv return shape; the counts are column ``rank()`` of the
    negotiated send matrix)."""
    res = synchronize(alltoall_async(tensor, splits=splits, name=name))
    if isinstance(res, AlltoallvResult):
        return res.output, jnp.asarray(res.received_splits, dtype=jnp.int32)
    return res


# ------------------------------------------------------------- join / handles
def join() -> int:
    """Signal this rank is out of data; blocks until all ranks join; pending
    allreduces proceed with zero contributions from joined ranks
    (`operations.cc:908-934`, `torch/mpi_ops.py:495-509`). Returns the id of
    the last rank to join."""
    st = basics._require_init()
    eng = basics._engine()
    if (st.mode == "multiprocess" and st.size > 1
            and not getattr(eng.controller, "coordinated", False)):
        raise NotImplementedError(
            "join() in multiprocess mode requires the cross-process control "
            "plane (launch via hvdrun / horovod_tpu.run so ranks share a "
            "coordinator address channel).")
    h = eng.join(basics.rank())
    return eng.handles.synchronize(h)


def poll(handle: int) -> bool:
    """Non-blocking completion check (`torch/mpi_ops.py:460-474`)."""
    return basics._engine().handles.poll(handle)


def synchronize(handle: int):
    """Block until the async op completes; raises HorovodInternalError on
    negotiation/execution failure (`torch/mpi_ops.py:476-492`).

    The blocked wall time here is communication the step could NOT hide
    behind compute — it accumulates into hvd_exposed_comm_seconds and, when
    tracing is on, becomes a WAIT span (docs/tracing.md).  The goodput
    ledger classifies the same interval by outcome: a completed collective
    is ``exposed_comm``, a watchdog failure is ``stall``, a membership
    change is ``recovery`` (docs/goodput.md)."""
    import time

    from .. import tracing as _tracing
    from ..exceptions import CollectiveTimeoutError, RanksChangedError
    from ..goodput import ledger as _goodput
    from ..metrics import instruments

    tr = _tracing.active()
    t0u = _tracing.clock.trace_us() if tr is not None else 0
    led = _goodput.active()
    sp = led.begin("exposed_comm") if led is not None else None
    t0 = time.perf_counter()
    try:
        result = basics._engine().handles.synchronize(handle)
    except CollectiveTimeoutError:
        if sp is not None:
            sp.state = "stall"
        raise
    except RanksChangedError:
        if sp is not None:
            sp.state = "recovery"
        raise
    finally:
        dt = time.perf_counter() - t0
        if led is not None:
            led.end(sp)
        instruments.exposed_comm_seconds().inc(dt)
        if tr is not None:
            tr.add_wait(basics.rank(), t0u, t0u + int(dt * 1e6))
    return result
