"""Pallas TPU kernels for the hot ops.

No reference counterpart file — Horovod 0.18.2 keeps its hot loops in CUDA
(`horovod/common/ops/nccl_operations.cc`, `adasum/adasum.h:98-131` SSE/AVX
kernels); on TPU the equivalent "hand kernel" layer is Pallas/Mosaic. Two
kernels live here:

* ``flash_attention`` / ``flash_attention_step`` — blockwise-softmax attention
  tiled for the MXU (128-row q tiles against k/v tiles streamed through VMEM,
  running max/normalizer in f32). ``flash_attention_step`` has carry-in/out
  ``(m, l, o)`` statistics so it slots directly into the ring-attention loop
  (`horovod_tpu/parallel/ring_attention.py`) as the per-hop block compute.
* ``adasum_combine`` — the Adasum pairwise combine
  (`adasum/adasum.h:331+`: ``a' = (1-dot/2|a|^2) a + (1-dot/2|b|^2) b``) as a
  fused two-pass kernel: one VMEM-tiled pass accumulating dot/|a|^2/|b|^2 in
  SMEM, one elementwise apply pass — the TPU analogue of the reference's
  fused SSE/AVX dot+norm loops.

Gating: kernels engage only where they help — by default on the TPU backend
with tile-aligned shapes; ``HVD_PALLAS=0`` forces them off,
``HVD_PALLAS=interpret`` runs them through the Pallas interpreter (any
backend; this is how the CPU test suite exercises the kernel code paths).
Callers always have a pure-jnp fallback.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")

# The flash kernels run softmax in base 2: the logit scale folds in log2(e)
# (one static multiply — `scale` already multiplies the [BQ, BK] logits
# elementwise), so every `exp` becomes a bare `exp2` on the VPU without the
# change-of-base multiply its lowering would add per element. p, l and o are
# bit-comparable either way (2^((s-m)·log2e) == e^(s-m)); only the running
# max/LSE statistic changes units, and each kernel converts it at its refs
# so the carried/saved m and LSE stay in natural log units (ring hops and
# the step-level LSE = m + log l contract depend on that). Measured: neutral
# at seq 1024, +1% at seq 8192 (the step is DMA-bound, not exp-bound — a
# probe replacing exp with add entirely moved throughput <0.5%).
_LOG2E = 1.4426950408889634
_LN2 = 0.6931471805599453

# Mosaic grid semantics: independent cells may pipeline freely ("parallel");
# an innermost dimension that revisits/accumulates into the same output tile
# must stay sequential ("arbitrary").


def _cparams(*semantics, resident: bool = False):
    """CompilerParams with the given dimension semantics and the measured
    per-kernel Mosaic VMEM budget policy: RESIDENT-layout kernels (whole
    k/v or q/do in VMEM — the short-sequence paths) default to a 96 MB
    limit, measured +1.4% on the lm_bench step (33.2k vs 32.8k tok/s at
    seq 1024; the default 16 MB scoped limit leaves double-buffer room
    unused); STREAMING kernels keep the Mosaic default (96 MB measured
    −1.5% at seq 8192). ``HVD_PALLAS_VMEM_MB`` overrides both (0 = always
    Mosaic default). Resolved at pallas_call-build time — the env can be
    flipped after import, like every other knob (an already-jitted kernel
    keeps its compiled params until its jax cache entry is evicted)."""
    kw = {"dimension_semantics": semantics}
    v = os.environ.get("HVD_PALLAS_VMEM_MB")
    if v:
        try:
            mb = float(v)
        except ValueError:
            raise ValueError(
                f"HVD_PALLAS_VMEM_MB={v!r}: expected a number of MiB "
                "(0 = Mosaic default)") from None
        if mb > 0:
            kw["vmem_limit_bytes"] = int(mb * 2 ** 20)
    elif resident:
        kw["vmem_limit_bytes"] = 96 * 2 ** 20
    return pltpu.CompilerParams(**kw)


def _input_fusion(params, n_tensor_inputs: int):
    """allow_input_fusion on the n tensor inputs (scalar-prefetch operand
    stays unfused): XLA folds cheap producers — the heads-major relayout
    transposes — into the kernel's input reads instead of materializing
    them in HBM. Measured +3.0% (fwd) and +0.7% (bwd) on the lm_bench
    step at seq 1024; bit-identical outputs. HVD_PALLAS_INPUT_FUSION=0
    disables (escape hatch)."""
    if os.environ.get("HVD_PALLAS_INPUT_FUSION", "1") in ("0", "false"):
        return params
    return dataclasses.replace(
        params, allow_input_fusion=[False] + [True] * n_tensor_inputs)


# Param builders, NOT baked constants: each pallas_call site calls these at
# build time so HVD_PALLAS_VMEM_MB/HVD_PALLAS_INPUT_FUSION flipped after
# import behave like every other knob (round-4 verdict weak #4).
def _sem_par2():
    return _cparams("parallel", "parallel")


def _sem_par2_res():
    # the resident-ATTENTION variant of the 2D-parallel grid (flash forward
    # / legacy backward with a whole side in VMEM); adasum's streaming apply
    # pass shares the semantics but not the budget
    return _cparams("parallel", "parallel", resident=True)


def _sem_par_arb():
    return _cparams("parallel", "arbitrary")


def _sem_par2_arb():
    return _cparams("parallel", "parallel", "arbitrary")


def mode() -> str:
    """'on' | 'off' | 'interpret' — resolved from HVD_PALLAS + backend."""
    env = os.environ.get("HVD_PALLAS", "").lower()
    if env in ("0", "off", "false"):
        return "off"
    if env == "interpret":
        return "interpret"
    if env in ("1", "on", "true") or jax.default_backend() == "tpu":
        return "on"
    return "off"


def _interpret() -> bool:
    return mode() == "interpret"


def _tile_ok(t: int, block: int) -> bool:
    return t % block == 0


def _struct(shape, dtype, *like):
    """ShapeDtypeStruct carrying the union of the inputs' varying-mesh-axes —
    required for pallas_call outputs inside ``shard_map(check_vma=True)``."""
    vma = frozenset()
    for x in like:
        vma = vma | getattr(jax.typeof(x), "vma", frozenset())
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def vma_active(*arrays) -> bool:
    """True when tracing inside ``shard_map(check_vma=True)`` with varying
    operands — pallas_call kernels can't satisfy the vma checker's
    constant-vs-varying rules there, so callers fall back to jnp. The perf
    paths (plain jit/GSPMD, ``shard_map(check_vma=False)``) report empty vma
    and keep the kernels."""
    return any(getattr(jax.typeof(x), "vma", frozenset()) for x in arrays)


def _env_block(name: str) -> Optional[int]:
    """Tile-edge env override, clamped to >= 8 (below that the power-of-2
    divide-search in _pick_block could never terminate on a divisor)."""
    v = os.environ.get(name)
    return max(8, int(v)) if v else None


def _pick_block(t: int, preferred: int = None,
                side: Optional[str] = None) -> Optional[int]:
    """Largest power-of-2 tile ≤ preferred dividing t (None if none ≥ 8).

    Default tile edges are asymmetric — q-side 512, k-side 1024: bigger
    tiles mean quadratically fewer grid cells (the per-cell grid overhead,
    not FLOPs, dominated the attention kernels at 128), and the k side can
    afford the larger edge because the kernels iterate over k within a
    cell. lm_bench ladder on a v5e, batch 8 / seq 1024:
    128/128 → 26.3k, 256/256 → 32.8k, 512/512 → 37.7k,
    512/1024 → 38.7k tok/s (1024/1024 exceeds scoped VMEM — the f32
    score tile alone is 4 MB).  ``HVD_PALLAS_BLOCK`` overrides both sides;
    ``HVD_PALLAS_BLOCK_Q`` / ``HVD_PALLAS_BLOCK_K`` override each
    independently for tuning."""
    if preferred is None:
        if side is not None:
            preferred = _env_block(f"HVD_PALLAS_BLOCK_{side.upper()}")
        if preferred is None:
            preferred = _env_block("HVD_PALLAS_BLOCK")
        if preferred is None:
            preferred = 1024 if side == "k" else 512
    b = preferred
    while b >= 8:
        if t % b == 0:
            return b
        b //= 2
    return None


def _pick_bh_block(bh: int, per_g_bytes: int = 0, cap: int = 0) -> int:
    """Rows of the fused batch·head dimension handled per grid cell in the
    RESIDENT kernels (``HVD_PALLAS_BLOCK_BH``): G sub-problems share one
    cell (statically unrolled in-kernel), dividing the cell count by G —
    the grid-geometry lever applied to the third axis. Measured on the
    lm_bench step: G=2 exactly neutral (38.46k vs 38.45k tok/s), G=4
    exceeds the 16 MB scoped-VMEM stack (17.98M) at the Q512/K1024 tile
    defaults — so the default is 1 and the knob exists for parts/configs
    with different VMEM headroom.

    G is floored to a power of two, then halved until it both divides
    ``bh`` AND keeps ``G * per_g_bytes`` within ``cap`` (when given) —
    one loop so neither constraint can be satisfied while silently
    breaking the other (a non-divisor G would leave trailing bh rows
    unvisited by the grid)."""
    g = max(1, int(os.environ.get("HVD_PALLAS_BLOCK_BH", "1")))
    g = 1 << (g.bit_length() - 1)                     # power-of-two floor
    while g > 1 and (bh % g or (cap and g * per_g_bytes > cap)):
        g //= 2
    return g


# =========================================================== flash attention
def _flash_accum(q, k_ref, v_ref, g, hi, m, l, o, *, q_off, k_off, causal,
                 scale, block_k):
    """Online-softmax accumulation of q against k/v blocks ``[0, hi)`` of
    slice ``g`` — THE shared inner body of the ring-step and single-shot
    forward kernels (one copy, so the base-2/masked-row convention cannot
    drift between them; the backward recompute depends on it). ``m`` is in
    base-2 units; dot operands stay in the input dtype (bf16 models run
    the MXU at bf16 rate), accumulation is f32."""
    bq = q.shape[0]
    in_dt = q.dtype

    def body(j, carry):
        m, l, o = carry
        k = k_ref[g, pl.ds(j * block_k, block_k), :]
        v = v_ref[g, pl.ds(j * block_k, block_k), :]
        # [BQ, BK] base-2 logits on the MXU; scale on the f32 result
        s = (scale * _LOG2E) * lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            qpos = q_off + lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            kpos = (k_off + j * block_k
                    + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1))
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp2(s - m_safe[:, None])             # exp2(-inf) == 0
        alpha = jnp.exp2(m - m_safe)                  # m=-inf -> 0
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = lax.dot_general(p.astype(in_dt), v,
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        o_new = o * alpha[:, None] + pv
        return m_new, l_new, o_new

    return lax.fori_loop(0, hi, body, (m, l, o))


def _flash_step_kernel(offs_ref, q_ref, k_ref, v_ref, m_ref, l_ref, o_ref,
                       mo_ref, lo_ref, oo_ref, *, causal, scale, block_k):
    """G q-tiles (G = bh-block, statically unrolled) of flash accumulation,
    each against its whole resident k/v block.

    Refs (VMEM): q [G, BQ, D], k/v [G, TK, D], m/l [G, BQ, 1] (trailing
    singleton keeps the block tile-legal: (BQ, 1) instead of (1, BQ)),
    o [G, BQ, D]; offs (scalar prefetch): [q_off, k_off] global sequence
    origins for causal masking (ring hop offsets) — shared by all G
    sub-problems (they are different batch·head slices of one sequence).
    """
    iq = pl.program_id(1)
    bq = q_ref.shape[1]
    tk = k_ref.shape[1]
    q_off = offs_ref[0] + iq * bq
    k_off = offs_ref[1]

    nk = tk // block_k
    if causal:
        # k blocks past the last unmasked key for this q tile contribute
        # nothing — bound the loop (exact: those blocks are fully masked)
        hi = jnp.clip((q_off + bq - k_off + block_k - 1) // block_k, 0, nk)
    else:
        hi = nk

    for g in range(q_ref.shape[0]):
        q = q_ref[g]                                  # [BQ, D]
        # carried m enters in natural units; base-2 inside (_LOG2E note)
        m = m_ref[g, :, 0].astype(jnp.float32) * _LOG2E   # [BQ]
        l = l_ref[g, :, 0].astype(jnp.float32)
        o = o_ref[g].astype(jnp.float32)              # [BQ, D]
        m, l, o = _flash_accum(q, k_ref, v_ref, g, hi, m, l, o,
                               q_off=q_off, k_off=k_off, causal=causal,
                               scale=scale, block_k=block_k)
        mo_ref[g, :, 0] = m * _LN2                    # back to natural units
        lo_ref[g, :, 0] = l
        oo_ref[g] = o


def _flash_fwd_once_kernel(offs_ref, q_ref, k_ref, v_ref, oo_ref, lse_ref,
                           *, causal, scale, block_k):
    """Single-shot forward: the resident step kernel minus the ring-carry
    plumbing. No (m, l, o) stream in — the statistics initialize in
    registers — and the output is NORMALIZED in-kernel (FlashAttention-2
    epilogue) and written in the input dtype beside the f32 row-LSE the
    backward consumes. Per call this halves HBM traffic vs the step kernel
    (~65 MB vs ~130 MB at the GPT-2-medium bench shapes: no f32 o in/out,
    no m/l streams) and retires the separate finalize fusion + zero-init
    copies (measured breakdown in docs/benchmarks.md round 5)."""
    iq = pl.program_id(1)
    bq = q_ref.shape[1]
    tk = k_ref.shape[1]
    q_off = offs_ref[0] + iq * bq
    k_off = offs_ref[1]

    nk = tk // block_k
    if causal:
        hi = jnp.clip((q_off + bq - k_off + block_k - 1) // block_k, 0, nk)
    else:
        hi = nk

    for g in range(q_ref.shape[0]):
        q = q_ref[g]                                  # [BQ, D]
        m = jnp.full((bq,), NEG_INF, jnp.float32)
        l = jnp.zeros((bq,), jnp.float32)
        o = jnp.zeros((bq, q_ref.shape[2]), jnp.float32)
        m, l, o = _flash_accum(q, k_ref, v_ref, g, hi, m, l, o,
                               q_off=q_off, k_off=k_off, causal=causal,
                               scale=scale, block_k=block_k)
        # the _masked_row_stats convention, fused into the epilogue:
        # l == 0 -> out 0, lse sentinel log(1) on top of a zeroed m
        l_safe = jnp.where(l == 0, 1.0, l)
        oo_ref[g] = (o / l_safe[:, None]).astype(oo_ref.dtype)
        m_nat = jnp.where(m == NEG_INF, 0.0, m * _LN2)
        lse_ref[g, :, 0] = m_nat + jnp.log(l_safe)


def _flash_fwd_once_call(qt, kt, vt, offs, *, causal, scale, block_q,
                         block_k, interpret):
    """Resident-layout dispatch of the single-shot forward.
    qt: [BH, TQ, D]; kt/vt: [BH, TK, D] → (out [BH, TQ, D] in qt.dtype,
    lse [BH, TQ, 1] f32). Caller guarantees the resident budget."""
    bh, tq, d = qt.shape
    tk = kt.shape[1]
    it = kt.dtype.itemsize
    # same footprint model as the step call, minus the carried f32 o tile
    per_g = (2 * tk * d * it + block_q * block_k * 4
             + 2 * block_q * d * 4)
    g = _pick_bh_block(bh, per_g, _BH_VMEM_CAP)
    grid = (bh // g, tq // block_q)
    return pl.pallas_call(
        functools.partial(_flash_fwd_once_kernel, causal=causal,
                          scale=scale, block_k=block_k),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((g, block_q, d), lambda i, j, offs: (i, j, 0)),
                pl.BlockSpec((g, tk, d), lambda i, j, offs: (i, 0, 0)),
                pl.BlockSpec((g, tk, d), lambda i, j, offs: (i, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((g, block_q, d), lambda i, j, offs: (i, j, 0)),
                pl.BlockSpec((g, block_q, 1), lambda i, j, offs: (i, j, 0)),
            ],
        ),
        out_shape=[
            _struct((bh, tq, d), qt.dtype, qt, kt, offs),
            _struct((bh, tq, 1), jnp.float32, qt, kt, offs),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * tq * tk * d,
            bytes_accessed=2 * (2 * bh * tq * d + 2 * bh * tk * d),
            transcendentals=bh * tq * tk),
        compiler_params=_input_fusion(_sem_par2_res(), 3),
        interpret=interpret,
    )(offs, qt, kt, vt)


def _flash_step_stream_kernel(offs_ref, q_ref, k_ref, v_ref, m_ref, l_ref,
                              o_ref, mo_ref, lo_ref, oo_ref, *, causal,
                              scale):
    """Streaming forward: one (q tile, k tile) grid cell of flash
    accumulation. The k grid dimension is innermost and revisits the same
    (m, l, o) output tiles, so VMEM holds single tiles regardless of
    sequence length; the carried-in statistics seed the outputs on the
    first k step (ring hops carry (m, l, o) across calls)."""
    iq, jk = pl.program_id(1), pl.program_id(2)
    bq, bk = q_ref.shape[1], k_ref.shape[1]
    in_dt = q_ref.dtype
    q_off = offs_ref[0] + iq * bq
    k_off = offs_ref[1] + jk * bk

    @pl.when(jk == 0)
    def _():
        mo_ref[0] = m_ref[0]
        lo_ref[0] = l_ref[0]
        oo_ref[0] = o_ref[0].astype(jnp.float32)

    live = (q_off + bq - 1 >= k_off) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[0]                                  # [BQ, D]
        k = k_ref[0]                                  # [BK, D]
        v = v_ref[0]
        # the revisited mo tile stays in natural units (a masked cell's
        # skipped body couldn't convert it back) — base-2 only inside
        m = mo_ref[0, :, 0] * _LOG2E                  # f32 [BQ]
        l = lo_ref[0, :, 0]
        o = oo_ref[0]                                 # f32 [BQ, D]
        s = (scale * _LOG2E) * lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            qpos = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp2(s - m_safe[:, None])             # exp2(-inf) == 0
        alpha = jnp.exp2(m - m_safe)                  # m=-inf -> 0
        pv = lax.dot_general(p.astype(in_dt), v, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        mo_ref[0, :, 0] = m_new * _LN2
        lo_ref[0, :, 0] = l * alpha + jnp.sum(p, axis=-1)
        oo_ref[0] = o * alpha[:, None] + pv


def _causal_maps(causal, block_q, block_k, nq):
    """Index maps for streaming grids with causal DMA elision: a fully-
    masked cell's kernel body is skipped by pl.when, but its input tiles
    would still be fetched — clamping the dead cell's map onto the nearest
    LIVE tile makes consecutive steps request the same index, which the
    Mosaic pipeline elides. Returns (kmap, qmap): the k/v-side map for
    (bh, iq, jk-innermost) grids and the q/do-side map for
    (bh, jk, iq-innermost) grids."""
    if not causal:
        passthrough = lambda i, j, n, offs: (i, n, 0)
        return passthrough, passthrough

    def kmap(i, j, n, offs):
        n_max = jnp.maximum(
            (offs[0] + (j + 1) * block_q - 1 - offs[1]) // block_k, 0)
        return (i, jnp.minimum(n, n_max), 0)

    def qmap(i, j, n, offs):
        lo = jnp.clip((offs[1] + j * block_k - offs[0]) // block_q,
                      0, nq - 1)
        return (i, jnp.maximum(n, lo), 0)

    return kmap, qmap


def _flash_step_call_streaming(qt, kt, vt, mt, lt, ot, offs, *, causal,
                               scale, block_q, block_k, interpret):
    """Streaming-layout dispatch of the forward step (k/v too long to keep
    resident)."""
    bh, tq, d = qt.shape
    tk = kt.shape[1]

    kmap, _ = _causal_maps(causal, block_q, block_k, tq // block_q)
    qtile = pl.BlockSpec((1, block_q, d), lambda i, j, n, offs: (i, j, 0))
    stat = pl.BlockSpec((1, block_q, 1), lambda i, j, n, offs: (i, j, 0))

    return pl.pallas_call(
        functools.partial(_flash_step_stream_kernel, causal=causal,
                          scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, tq // block_q, tk // block_k),
            in_specs=[
                qtile,
                pl.BlockSpec((1, block_k, d), kmap),
                pl.BlockSpec((1, block_k, d), kmap),
                stat, stat, qtile,
            ],
            out_specs=[stat, stat, qtile],
        ),
        out_shape=[
            _struct((bh, tq, 1), jnp.float32, qt, kt, mt, offs),
            _struct((bh, tq, 1), jnp.float32, qt, kt, mt, offs),
            _struct((bh, tq, d), jnp.float32, qt, kt, mt, offs),
        ],
        # k is innermost and ACCUMULATES into the revisited q-side tiles
        compiler_params=_sem_par2_arb(),
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * tq * tk * d,
            bytes_accessed=4 * (2 * bh * tq * d + 2 * bh * tk * d),
            transcendentals=bh * tq * tk),
        interpret=interpret,
    )(offs, qt, kt, vt, mt, lt, ot)


def _flash_step_call(qt, kt, vt, mt, lt, ot, offs, *, causal, scale,
                     block_q, block_k, interpret):
    """qt/ot: [BH, T, D]; kt/vt: [BH, TK, D]; mt/lt: [BH, T, 1] f32."""
    bh, tq, d = qt.shape
    tk = kt.shape[1]
    if tk * d * kt.dtype.itemsize > _KV_VMEM_CAP:
        return _flash_step_call_streaming(
            qt, kt, vt, mt, lt, ot, offs, causal=causal, scale=scale,
            block_q=block_q, block_k=block_k, interpret=interpret)
    # clamp G on an estimate of the full per-slice VMEM footprint — the
    # f32 score tile (block_q x block_k) dominates, not the resident k/v;
    # the estimate + _BH_VMEM_CAP reproduce the measured cliff (G=2 fits,
    # G=4 -> 17.98M > 16M scoped at the Q512/K1024 defaults)
    it = kt.dtype.itemsize
    per_g = (2 * tk * d * it + block_q * block_k * 4
             + 3 * block_q * d * 4)
    g = _pick_bh_block(bh, per_g, _BH_VMEM_CAP)
    grid = (bh // g, tq // block_q)
    kernel = functools.partial(_flash_step_kernel, causal=causal, scale=scale,
                               block_k=block_k)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((g, block_q, d), lambda i, j, offs: (i, j, 0)),
            pl.BlockSpec((g, tk, d), lambda i, j, offs: (i, 0, 0)),
            pl.BlockSpec((g, tk, d), lambda i, j, offs: (i, 0, 0)),
            pl.BlockSpec((g, block_q, 1), lambda i, j, offs: (i, j, 0)),
            pl.BlockSpec((g, block_q, 1), lambda i, j, offs: (i, j, 0)),
            pl.BlockSpec((g, block_q, d), lambda i, j, offs: (i, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((g, block_q, 1), lambda i, j, offs: (i, j, 0)),
            pl.BlockSpec((g, block_q, 1), lambda i, j, offs: (i, j, 0)),
            pl.BlockSpec((g, block_q, d), lambda i, j, offs: (i, j, 0)),
        ],
    )
    flops = 4 * bh * tq * tk * d  # 2 matmuls
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _struct((bh, tq, 1), jnp.float32, qt, kt, mt, offs),
            _struct((bh, tq, 1), jnp.float32, qt, kt, mt, offs),
            _struct((bh, tq, d), jnp.float32, qt, kt, mt, offs),
        ],
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=4 * (2 * bh * tq * d + 2 * bh * tk * d),
            transcendentals=bh * tq * tk),
        # independent grid cells: Mosaic may pipeline across bh and q tiles;
        # producers (the heads-major relayouts) fuse into the input reads
        compiler_params=_input_fusion(_sem_par2_res(), 6),
        interpret=interpret,
    )(offs, qt, kt, vt, mt, lt, ot)


# Per-operand VMEM budget for the resident k/v block: the pipeline double-
# buffers input blocks, so worst-case VMEM ≈ 2 (buffering) × 2 (k+v) × this
# plus the q/o tiles. Measured on v5e: 1 MB/operand (seq 8192 at d=64 bf16)
# compiles within the 16 MB scoped-VMEM limit, 2 MB (seq 16384) does not —
# longer k/v take the streaming forward.
_KV_VMEM_CAP = 2 ** 20
# Budget for the backward's whole-resident layout; beyond it _flash_bwd
# switches to the streaming 3D-grid kernels (any length works there).
# Tighter than the forward's: the resident dkv pass holds q AND do (plus
# lse/dd and double-buffered tiles). Re-measured at the Q512/K1024 default
# tiles: 256 KB/operand (seq 2048 at d=64 bf16) compiles within the 16 MB
# scoped-VMEM limit, 512 KB (seq 4096) exceeds it by 1.45 MB — the old
# 512 KB cap dated from the 128-edge-tile era.
_BWD_RESIDENT_CAP = 256 * 2 ** 10
# dq-scratch budget for the ONE-pass fused backward: the whole [TQ, D] f32
# dq accumulator lives in VMEM beside the f32 score/p/dp tiles (~2 MB each
# at Q512/K1024) and the streamed operand tiles. 4 MB covers seq 16384 at
# d=64 (or 8192 at d=128); longer falls back to the legacy two-pass
# streaming layout.
_DQ_SCRATCH_CAP = int(os.environ.get("HVD_PALLAS_DQ_SCRATCH_CAP",
                                     4 * 2 ** 20))
# Per-grid-cell VMEM budget for bh-blocking (G): half the 16 MB scoped
# limit, leaving the rest for Mosaic's double buffering. With the per-g
# footprint estimates at the call sites (2.6 MB per slice at the
# lm_bench shapes) this admits the measured-working G=2
# (2 x 2.6 = 5.2 MB <= 8 MB) and rejects the measured-failing G=4
# (10.5 MB) at the Q512/K1024 defaults.
_BH_VMEM_CAP = 8 * 2 ** 20


def step_supported(q, k) -> bool:
    """True if ``flash_attention_step`` can run these shapes as a TPU kernel
    (tile-aligned seq lens, lane-aligned head dim — no length cap: k/v
    beyond the resident VMEM budget take the streaming layout)."""
    if mode() == "off":
        return False
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if d % 128 != 0 and d not in (64,):  # MXU lane width; 64 still maps
        return False
    # no length cap: k/v beyond _KV_VMEM_CAP take the streaming forward
    if vma_active(q, k):
        return False
    # probe with the SAME side= the call sites use, so per-side env
    # overrides (HVD_PALLAS_BLOCK_Q/K) cannot pass here and fail there
    return (_pick_block(tq, side="q") is not None
            and _pick_block(tk, side="k") is not None)


def flash_attention_step(q, k, v, m, l, o, q_off, k_off, *,
                         causal: bool = False, scale: float = 1.0):
    """Flash-accumulate ``q`` against one resident ``(k, v)`` block.

    Same contract as the ring-attention inner step: shapes
    q/o ``[B, T, H, D]``, k/v ``[B, TK, H, D]``, m/l ``[B, H, T]`` (f32 running
    max / normalizer), ``q_off``/``k_off`` global sequence origins (traced
    scalars OK). Returns updated ``(m, l, o)``.
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    block_q = _pick_block(tq, side="q")
    block_k = _pick_block(tk, side="k")
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    mt = m.reshape(b * h, tq, 1)
    lt = l.reshape(b * h, tq, 1)
    ot = o.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    mt, lt, ot = _flash_step_call(
        qt, kt, vt, mt, lt, ot, offs, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, interpret=_interpret())
    m_new = mt.reshape(b, h, tq)
    l_new = lt.reshape(b, h, tq)
    o_new = ot.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    return m_new, l_new, o_new


# (The pre-FA2 "Pallas forward + rematerialized jnp backward" step wrapper
# lived here; the blockwise backward kernels below cover every supported
# shape — resident or streaming — so the quadratic-HBM jnp VJP is gone.)


# ------------------------------------------------- flash attention backward
def _flash_bwd_dq_kernel_res(offs_ref, lse_ref, dd_ref, q_ref, k_ref, v_ref,
                         do_ref, dq_ref, *, causal, scale, block_k):
    """dq for one q tile against the whole resident k/v (FlashAttention-2
    backward, dq pass — VMEM-RESIDENT variant for shapes whose full k/v
    fits VMEM; the streaming 3D-grid variant covers longer sequences):
    recompute p = exp(scale*qk^T - LSE) blockwise, then
    ds = p*(do v^T - D)*scale, dq += ds k.  LSE = m + log l (row logsumexp),
    D = rowsum(do * out) — both precomputed outside. offs (scalar prefetch):
    [q_off, k_off] global sequence origins (ring hop offsets)."""
    iq = pl.program_id(1)
    bq = q_ref.shape[1]
    tk = k_ref.shape[1]
    nk = tk // block_k
    in_dt = q_ref.dtype  # dot operands in input dtype, f32 accumulation
    q_off = offs_ref[0] + iq * bq
    k_off = offs_ref[1]
    hi = jnp.clip((q_off + bq - k_off + block_k - 1) // block_k, 0, nk) \
        if causal else nk

    for g in range(q_ref.shape[0]):                   # bh-block unroll
        q = q_ref[g]                                  # [BQ, D]
        do = do_ref[g]                                # [BQ, D]
        lse = lse_ref[g] * _LOG2E                     # [BQ, 1] f32, base-2
        dd = dd_ref[g]                                # [BQ, 1] f32

        def body(j, acc, q=q, do=do, lse=lse, dd=dd):
            k = k_ref[g, pl.ds(j * block_k, block_k), :]
            v = v_ref[g, pl.ds(j * block_k, block_k), :]
            s = (scale * _LOG2E) * lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if causal:
                qpos = q_off + lax.broadcasted_iota(
                    jnp.int32, (bq, block_k), 0)
                kpos = (k_off + j * block_k
                        + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1))
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            p = jnp.exp2(s - lse)                     # exp2(-inf) == 0
            dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
            ds = (p * (dp - dd) * scale).astype(in_dt)
            return acc + lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

        dq_ref[g] = lax.fori_loop(0, hi, body,
                                  jnp.zeros(q.shape, jnp.float32))


def _flash_bwd_dkv_kernel_res(offs_ref, lse_ref, dd_ref, q_ref, k_ref, v_ref,
                          do_ref, dk_ref, dv_ref, *, causal, scale, block_q):
    """dk/dv for one k/v tile against the whole resident q/do (dkv pass):
    dv += p^T do; dk += (p*(do v^T - D)*scale)^T q."""
    jk = pl.program_id(1)
    bk = k_ref.shape[1]
    tq = q_ref.shape[1]
    nq = tq // block_q
    in_dt = q_ref.dtype  # dot operands in input dtype, f32 accumulation
    q_off = offs_ref[0]
    k_off = offs_ref[1] + jk * bk
    lo = jnp.clip((k_off - q_off) // block_q, 0, nq) if causal else 0

    for g in range(q_ref.shape[0]):                   # bh-block unroll
        k = k_ref[g]                                  # [BK, D]
        v = v_ref[g]

        def body(i, carry, k=k, v=v):
            dk, dv = carry
            q = q_ref[g, pl.ds(i * block_q, block_q), :]
            do = do_ref[g, pl.ds(i * block_q, block_q), :]
            lse = lse_ref[g, pl.ds(i * block_q, block_q), :] * _LOG2E
            dd = dd_ref[g, pl.ds(i * block_q, block_q), :]
            s = (scale * _LOG2E) * lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if causal:
                qpos = (q_off + i * block_q
                        + lax.broadcasted_iota(jnp.int32, (block_q, bk), 0))
                kpos = k_off + lax.broadcasted_iota(
                    jnp.int32, (block_q, bk), 1)
                s = jnp.where(qpos >= kpos, s, NEG_INF)
            p = jnp.exp2(s - lse)                     # [BQ, BK] f32
            pc = p.astype(in_dt)
            dv = dv + lax.dot_general(pc, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
            ds = (p * (dp - dd) * scale).astype(in_dt)
            dk = dk + lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            return dk, dv

        dk, dv = lax.fori_loop(lo, nq, body,
                               (jnp.zeros(k.shape, jnp.float32),
                                jnp.zeros(v.shape, jnp.float32)))
        dk_ref[g] = dk
        dv_ref[g] = dv


def _flash_bwd_dq_kernel(offs_ref, lse_ref, dd_ref, q_ref, k_ref, v_ref,
                         do_ref, dq_ref, *, causal, scale):
    """dq accumulation for one (q tile, k tile) grid cell (FlashAttention-2
    backward, dq pass): recompute p = exp(scale*qk^T - LSE), then
    ds = p*(do v^T - D)*scale, dq += ds k.  LSE = m + log l (row logsumexp),
    D = rowsum(do * out) — both precomputed outside. offs (scalar prefetch):
    [q_off, k_off] global sequence origins (ring hop offsets). The k grid
    dimension is innermost and revisits the same dq tile, so VMEM holds one
    tile of each operand regardless of sequence length."""
    iq, jk = pl.program_id(1), pl.program_id(2)
    bq, bk = q_ref.shape[1], k_ref.shape[1]
    in_dt = q_ref.dtype  # dot operands in input dtype, f32 accumulation
    q_off = offs_ref[0] + iq * bq
    k_off = offs_ref[1] + jk * bk

    @pl.when(jk == 0)
    def _():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])

    # causal: a block with every pair masked contributes nothing
    live = (q_off + bq - 1 >= k_off) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[0]                                  # [BQ, D]
        do = do_ref[0]
        lse = lse_ref[0] * _LOG2E                     # [BQ, 1] f32, base-2
        dd = dd_ref[0]
        k = k_ref[0]                                  # [BK, D]
        v = v_ref[0]
        s = (scale * _LOG2E) * lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            qpos = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp2(s - lse)                         # exp2(-inf) == 0
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - dd) * scale).astype(in_dt)
        dq_ref[0] += lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)


def _flash_bwd_dkv_kernel(offs_ref, lse_ref, dd_ref, q_ref, k_ref, v_ref,
                          do_ref, dk_ref, dv_ref, *, causal, scale):
    """dk/dv accumulation for one (k tile, q tile) grid cell (dkv pass):
    dv += p^T do; dk += (p*(do v^T - D)*scale)^T q. The q grid dimension is
    innermost and revisits the same dk/dv tiles."""
    jk, iq = pl.program_id(1), pl.program_id(2)
    bk, bq = k_ref.shape[1], q_ref.shape[1]
    in_dt = q_ref.dtype  # dot operands in input dtype, f32 accumulation
    q_off = offs_ref[0] + iq * bq
    k_off = offs_ref[1] + jk * bk

    @pl.when(iq == 0)
    def _():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    live = (q_off + bq - 1 >= k_off) if causal else True

    @pl.when(live)
    def _():
        k = k_ref[0]                                  # [BK, D]
        v = v_ref[0]
        q = q_ref[0]                                  # [BQ, D]
        do = do_ref[0]
        lse = lse_ref[0] * _LOG2E                     # [BQ, 1], base-2
        dd = dd_ref[0]
        s = (scale * _LOG2E) * lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            qpos = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp2(s - lse)                         # [BQ, BK] f32
        dv_ref[0] += lax.dot_general(p.astype(in_dt), do,
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - dd) * scale).astype(in_dt)
        dk_ref[0] += lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)


def _flash_bwd_fused_kernel(offs_ref, lse_ref, dd_ref, q_ref, k_ref, v_ref,
                            do_ref, dq_ref, dk_ref, dv_ref, *maybe_acc,
                            causal, scale):
    """ONE-pass FlashAttention-2 backward: grid (bh, k tiles, q tiles) with
    q innermost; each cell recomputes p ONCE and emits all three gradient
    contributions. The legacy pair of kernels (dq pass + dkv pass) each
    streamed the operands and rebuilt p/dp separately — twice the operand
    DMA and 7 matmuls per (q, k) tile pair; this kernel does 5.

    dk/dv accumulate in their revisited output tiles (q innermost, so the
    visits are consecutive). dq accumulates in a whole-[TQ, D] f32 VMEM
    scratch that persists across the bh-slice's grid cells (zeroed at the
    slice's first cell); the current q tile of the scratch is flushed
    through the dq output block every visit — tile i's bytes are final
    from its last live k sweep onward, and later sweeps rewrite the same
    final bytes (last-write-wins), so the output is correct for causal
    and non-causal alike at the cost of nk-1 redundant tile writes.

    Single-k-sweep fast path (nk == 1, e.g. the seq-1024 headline config):
    dq completes within one cell, so the dispatch allocates NO dq scratch
    and the kernel writes dq directly — skipping a read-modify-write plus
    a flush copy of the tile per cell.

    Gradients leave the kernel in the INPUT dtype: accumulation stays f32
    (dk/dv in the per-cell VMEM scratch pair, consecutive iq revisits),
    cast once at the final write — a bf16 model never round-trips 3x f32
    gradient tensors through HBM plus three XLA cast fusions (measured
    ladder in docs/benchmarks.md round 5)."""
    if len(maybe_acc) == 3:
        dq_acc, dk_acc, dv_acc = maybe_acc
    else:
        dq_acc, (dk_acc, dv_acc) = None, maybe_acc
    jk, iq = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)
    bq, bk = q_ref.shape[1], k_ref.shape[1]
    in_dt = q_ref.dtype  # dot operands in input dtype, f32 accumulation
    q_off = offs_ref[0] + iq * bq
    k_off = offs_ref[1] + jk * bk

    if dq_acc is not None:
        @pl.when(jnp.logical_and(jk == 0, iq == 0))
        def _():
            dq_acc[...] = jnp.zeros_like(dq_acc)

    @pl.when(iq == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = (q_off + bq - 1 >= k_off) if causal else True

    if dq_acc is None and causal:
        # a fully-masked cell contributes nothing: its dq tile is zero
        @pl.when(jnp.logical_not(live))
        def _():
            dq_ref[0] = jnp.zeros_like(dq_ref[0])

    @pl.when(live)
    def _():
        q = q_ref[0]                                  # [BQ, D]
        do = do_ref[0]
        lse = lse_ref[0] * _LOG2E                     # [BQ, 1] f32, base-2
        dd = dd_ref[0]
        k = k_ref[0]                                  # [BK, D]
        v = v_ref[0]
        s = (scale * _LOG2E) * lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            qpos = q_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = k_off + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        p = jnp.exp2(s - lse)                         # exp2(-inf) == 0
        dv_acc[...] += lax.dot_general(p.astype(in_dt), do,
                                       (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = (p * (dp - dd) * scale).astype(in_dt)
        dk_acc[...] += lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.float32)
        dq_contrib = lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        if dq_acc is None:
            dq_ref[0] = dq_contrib.astype(dq_ref.dtype)
        else:
            dq_acc[pl.ds(iq * bq, bq), :] += dq_contrib

    @pl.when(iq == nq - 1)
    def _():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)

    if dq_acc is not None:
        dq_ref[0] = dq_acc[pl.ds(iq * bq, bq), :].astype(dq_ref.dtype)


def _flash_bwd_fused(qt, kt, vt, dot, lset, ddt, offs, d, *, causal, scale,
                     block_q, block_k, interpret, out_dtype=None):
    """Dispatch of the one-pass backward (any length: k/v tiles stream
    through the grid, dq rides the VMEM scratch). ``out_dtype`` picks the
    gradient output dtype (default f32); the ring path keeps f32 so its
    cross-hop accumulators never ingest pre-rounded contributions, while
    the single-device VJP requests the input dtype directly."""
    out_dtype = jnp.float32 if out_dtype is None else out_dtype
    bh, tq = qt.shape[0], qt.shape[1]
    tk = kt.shape[1]
    _, qmap = _causal_maps(causal, block_q, block_k, tq // block_q)
    ktile = pl.BlockSpec((1, block_k, d), lambda i, j, n, offs: (i, j, 0))

    return pl.pallas_call(
        functools.partial(_flash_bwd_fused_kernel, causal=causal,
                          scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            # q innermost: dk/dv revisits are consecutive; j sweeps
            # accumulate dq in the persistent scratch
            grid=(bh, tk // block_k, tq // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, 1), qmap),
                pl.BlockSpec((1, block_q, 1), qmap),
                pl.BlockSpec((1, block_q, d), qmap),
                ktile, ktile,
                pl.BlockSpec((1, block_q, d), qmap),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda i, j, n, offs: (i, n, 0)),
                ktile, ktile,
            ],
            # single k sweep: dq finishes inside its cell — no dq scratch;
            # dk/dv always accumulate f32 in the scratch pair and cast on
            # the final (iq == nq-1) write
            scratch_shapes=(([] if tk // block_k == 1
                             else [pltpu.VMEM((tq, d), jnp.float32)])
                            + [pltpu.VMEM((block_k, d), jnp.float32),
                               pltpu.VMEM((block_k, d), jnp.float32)]),
        ),
        out_shape=[
            _struct((bh, tq, d), out_dtype, qt, kt, offs),
            _struct((bh, tk, d), out_dtype, qt, kt, offs),
            _struct((bh, tk, d), out_dtype, qt, kt, offs),
        ],
        cost_estimate=pl.CostEstimate(
            flops=10 * bh * tq * tk * d,  # 5 matmuls per tile pair
            bytes_accessed=4 * bh * (4 * tq * d + 4 * tk * d),
            transcendentals=bh * tq * tk),
        # j and the innermost q dim both accumulate into revisited state;
        # single-sweep (k resident per cell) gets the resident VMEM budget
        # and producer input fusion (the multi-sweep form measured -1.9%
        # with fusion at seq 8192 — streaming re-reads amplify any fused
        # producer recompute, so it stays off there)
        compiler_params=(
            _input_fusion(_cparams("parallel", "arbitrary", "arbitrary",
                                   resident=True), 6)
            if tk // block_k == 1
            else _cparams("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(offs, lset, ddt, qt, kt, vt, dot)


def _flash_bwd_resident(qt, kt, vt, dot, lset, ddt, offs, d, *,
                        causal, scale, block_q, block_k, interpret):
    """Whole-resident backward dispatch: dq pass keeps full k/v in VMEM,
    dkv pass keeps full q/do in VMEM (heads-major [BH, T, D] operands in,
    heads-major f32 gradients out)."""
    bh, tq = qt.shape[0], qt.shape[1]
    tk = kt.shape[1]
    # clamp G on the fuller of the two passes' per-slice VMEM footprints
    # (dq holds resident k/v, dkv holds resident q/do; both build the f32
    # score tile) — same estimate/cap scheme as the forward
    it = qt.dtype.itemsize
    per_g = (2 * max(tq, tk) * d * it + block_q * block_k * 4
             + 3 * max(block_q, block_k) * d * 4)
    g = _pick_bh_block(bh, per_g, _BH_VMEM_CAP)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel_res, causal=causal,
                          scale=scale, block_k=block_k),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh // g, tq // block_q),
            in_specs=[
                pl.BlockSpec((g, block_q, 1), lambda i, j, offs: (i, j, 0)),
                pl.BlockSpec((g, block_q, 1), lambda i, j, offs: (i, j, 0)),
                pl.BlockSpec((g, block_q, d), lambda i, j, offs: (i, j, 0)),
                pl.BlockSpec((g, tk, d), lambda i, j, offs: (i, 0, 0)),
                pl.BlockSpec((g, tk, d), lambda i, j, offs: (i, 0, 0)),
                pl.BlockSpec((g, block_q, d), lambda i, j, offs: (i, j, 0)),
            ],
            out_specs=pl.BlockSpec((g, block_q, d),
                                   lambda i, j, offs: (i, j, 0)),
        ),
        out_shape=_struct((bh, tq, d), jnp.float32, qt, kt, offs),
        cost_estimate=pl.CostEstimate(
            flops=6 * bh * tq * tk * d,
            bytes_accessed=4 * bh * (3 * tq * d + 2 * tk * d),
            transcendentals=bh * tq * tk),
        compiler_params=_sem_par2_res(),
        interpret=interpret,
    )(offs, lset, ddt, qt, kt, vt, dot)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel_res, causal=causal,
                          scale=scale, block_q=block_q),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh // g, tk // block_k),
            in_specs=[
                pl.BlockSpec((g, tq, 1), lambda i, j, offs: (i, 0, 0)),
                pl.BlockSpec((g, tq, 1), lambda i, j, offs: (i, 0, 0)),
                pl.BlockSpec((g, tq, d), lambda i, j, offs: (i, 0, 0)),
                pl.BlockSpec((g, block_k, d), lambda i, j, offs: (i, j, 0)),
                pl.BlockSpec((g, block_k, d), lambda i, j, offs: (i, j, 0)),
                pl.BlockSpec((g, tq, d), lambda i, j, offs: (i, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((g, block_k, d), lambda i, j, offs: (i, j, 0)),
                pl.BlockSpec((g, block_k, d), lambda i, j, offs: (i, j, 0)),
            ],
        ),
        out_shape=[
            _struct((bh, tk, d), jnp.float32, qt, kt, offs),
            _struct((bh, tk, d), jnp.float32, qt, kt, offs),
        ],
        cost_estimate=pl.CostEstimate(
            flops=8 * bh * tq * tk * d,
            bytes_accessed=4 * bh * (3 * tq * d + 3 * tk * d),
            transcendentals=bh * tq * tk),
        compiler_params=_sem_par2_res(),
        interpret=interpret,
    )(offs, lset, ddt, qt, kt, vt, dot)

    return dq, dk, dv


def _flash_bwd(q, k, v, out, lse, dout, q_off=0, k_off=0, *, causal, scale):
    """Blockwise backward for normalized flash attention, [B, T, H, D]
    layout.  ``q_off``/``k_off`` are global sequence origins (traced scalars
    OK — ring hops).  Returns (dq, dk, dv) in f32."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    bh = b * h

    def heads_major(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, x.shape[1], d)

    qt, kt, vt, dot = map(heads_major, (q, k, v, dout))
    # D = rowsum(dout * out) per row — cheap and linear, precomputed in jnp
    dd = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                 axis=-1)                              # [B, T, H]
    ddt = dd.transpose(0, 2, 1).reshape(bh, tq, 1)
    lset = lse.reshape(bh, tq, 1)
    dq, dk, dv = _flash_bwd_hm(qt, kt, vt, dot, lset, ddt, q_off, k_off,
                               causal=causal, scale=scale)
    return (_heads_minor(dq, b, h, tq, d), _heads_minor(dk, b, h, tk, d),
            _heads_minor(dv, b, h, tk, d))


def _flash_bwd_hm(qt, kt, vt, dot, lset, ddt, q_off=0, k_off=0, *,
                  causal, scale, out_dtype=None):
    """Heads-major core of :func:`_flash_bwd`: operands/grads all
    ``[BH, T, D]`` (lse/dd ``[BH, T, 1]``) so a caller that already holds
    heads-major tensors (the full-attention VJP saves its residuals that
    way) pays no relayout. Returns (dq, dk, dv) heads-major f32."""
    bh, tq, d = qt.shape
    tk = kt.shape[1]
    # backward tiles follow the forward defaults unless overridden
    # independently (HVD_PALLAS_BLOCK_BWD_Q/K) — the fused one-pass kernel
    # has a different VMEM profile (dq scratch + 3 outputs) than the
    # forward, so its optimum can differ. Measured on the lm_bench step:
    # BWD_K=512 neutral, BWD_Q=1024 +0.5% (noise) — defaults kept.
    block_q = _pick_block(tq, preferred=_env_block("HVD_PALLAS_BLOCK_BWD_Q"),
                          side="q")
    block_k = _pick_block(tk, preferred=_env_block("HVD_PALLAS_BLOCK_BWD_K"),
                          side="k")
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    interpret = _interpret()

    # Preferred layout: the ONE-pass fused kernel (dq+dk+dv from a single
    # streaming of the operands, 5 matmuls per tile pair instead of the
    # legacy passes' 7). Its dq scratch must fit VMEM alongside the score
    # tiles; beyond the cap — or with HVD_PALLAS_FUSED_BWD=0 for A/B — the
    # legacy two-pass layouts below take over.
    if (os.environ.get("HVD_PALLAS_FUSED_BWD", "1") not in ("0", "false")
            and tq * d * 4 <= _DQ_SCRATCH_CAP):
        return _flash_bwd_fused(
            qt, kt, vt, dot, lset, ddt, offs, d, causal=causal, scale=scale,
            block_q=block_q, block_k=block_k, interpret=interpret,
            out_dtype=out_dtype)

    # Two legacy kernel layouts: whole-resident (one side of the score
    # matrix stays in VMEM; ~20% faster at short T — no tile re-fetch) and
    # streaming 3D-grid (every operand tiled through the grid; the only
    # option once a full k/v or q/do side exceeds the VMEM budget).
    if (tk * d * kt.dtype.itemsize <= _BWD_RESIDENT_CAP
            and tq * d * qt.dtype.itemsize <= _BWD_RESIDENT_CAP):
        return _flash_bwd_resident(
            qt, kt, vt, dot, lset, ddt, offs, d, causal=causal,
            scale=scale, block_q=block_q, block_k=block_k,
            interpret=interpret)

    kmap, qmap = _causal_maps(causal, block_q, block_k, tq // block_q)

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            # k innermost: consecutive grid steps revisit the same dq tile
            grid=(bh, tq // block_q, tk // block_k),
            in_specs=[
                pl.BlockSpec((1, block_q, 1), lambda i, j, n, offs: (i, j, 0)),
                pl.BlockSpec((1, block_q, 1), lambda i, j, n, offs: (i, j, 0)),
                pl.BlockSpec((1, block_q, d), lambda i, j, n, offs: (i, j, 0)),
                pl.BlockSpec((1, block_k, d), kmap),
                pl.BlockSpec((1, block_k, d), kmap),
                pl.BlockSpec((1, block_q, d), lambda i, j, n, offs: (i, j, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda i, j, n, offs: (i, j, 0)),
        ),
        out_shape=_struct((bh, tq, d), jnp.float32, qt, kt, offs),
        cost_estimate=pl.CostEstimate(
            flops=6 * bh * tq * tk * d,
            bytes_accessed=4 * bh * (3 * tq * d + 2 * tk * d),
            transcendentals=bh * tq * tk),
        compiler_params=_sem_par2_arb(),
        interpret=interpret,
    )(offs, lset, ddt, qt, kt, vt, dot)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            # q innermost: consecutive grid steps revisit the same dk/dv tiles
            grid=(bh, tk // block_k, tq // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, 1), qmap),
                pl.BlockSpec((1, block_q, 1), qmap),
                pl.BlockSpec((1, block_q, d), qmap),
                pl.BlockSpec((1, block_k, d), lambda i, j, n, offs: (i, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda i, j, n, offs: (i, j, 0)),
                pl.BlockSpec((1, block_q, d), qmap),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda i, j, n, offs: (i, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda i, j, n, offs: (i, j, 0)),
            ],
        ),
        out_shape=[
            _struct((bh, tk, d), jnp.float32, qt, kt, offs),
            _struct((bh, tk, d), jnp.float32, qt, kt, offs),
        ],
        cost_estimate=pl.CostEstimate(
            flops=8 * bh * tq * tk * d,
            bytes_accessed=4 * bh * (3 * tq * d + 3 * tk * d),
            transcendentals=bh * tq * tk),
        compiler_params=_sem_par2_arb(),
        interpret=interpret,
    )(offs, lset, ddt, qt, kt, vt, dot)

    return dq, dk, dv


def _heads_minor(x, b, h, t, d):
    """[BH, T, D] → [B, T, H, D] (inverse of the heads-major packing)."""
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _masked_row_stats(m, l):
    """(l_safe, lse) from raw flash statistics, any matching shapes.

    THE single source of the fully-masked-row convention (l == 0 → divide
    by 1 → out 0; m == -inf → LSE sentinel 0). The backward kernels'
    ``p = exp(s - lse)`` recompute depends on it — every score in such a
    row is -inf, so p recomputes to 0 regardless of the sentinel. Both the
    ring/step epilogue (:func:`finalize_attention_stats`) and the
    single-device heads-major VJP forward use this helper so the
    convention cannot drift between them."""
    l_safe = jnp.where(l == 0, 1.0, l)
    lse = jnp.where(m == NEG_INF, 0.0, m) + jnp.log(l_safe)
    return l_safe, lse


def finalize_attention_stats(m, l, o, out_dtype):
    """(m, l, o) flash statistics → (normalized out, row-LSE); m/l
    ``[B, H, T]``, o ``[B, T, H, D]``. Masked-row convention from
    :func:`_masked_row_stats`."""
    l_safe, lse = _masked_row_stats(m, l)                    # [B, H, T]
    out = (o / l_safe.transpose(0, 2, 1)[..., None]).astype(out_dtype)
    return out, lse


@functools.lru_cache(maxsize=None)
def _flash_fullattn_vjp(causal: bool, scale: float):
    """Normalized flash attention with a full Pallas backward
    (FlashAttention-2): forward saves only (q, k, v, out, LSE) — O(T)
    residuals — and the backward recomputes p blockwise on the MXU instead
    of materializing the [T, T] score/softmax tensors in HBM (which the
    step-level jnp VJP does, and which costs ~40% of a GPT-2-medium train
    step, measured on v5e).

    The whole pipeline is heads-major ``[B·H, T, D]`` internally — ONE
    relayout of each operand on the way in and one of out/dq/dk/dv on the
    way out. Residuals are saved heads-major, so the backward re-transposes
    nothing (the earlier [B, T, H, D] residual contract relayouted q/k/v a
    second time in the backward)."""

    def fwd_hm(q, k, v):
        b, tq, h, d = q.shape
        tk = k.shape[1]
        bh = b * h
        qt = q.transpose(0, 2, 1, 3).reshape(bh, tq, d)
        kt = k.transpose(0, 2, 1, 3).reshape(bh, tk, d)
        vt = v.transpose(0, 2, 1, 3).reshape(bh, tk, d)
        offs = jnp.zeros((2,), jnp.int32)
        if (tk * d * kt.dtype.itemsize <= _KV_VMEM_CAP
                and os.environ.get("HVD_PALLAS_ONESHOT_FWD", "1") != "0"):
            # resident shapes take the single-shot kernel: no ring-carry
            # streams, normalized-in-kernel output (measured +6.2% on the
            # lm_bench step at seq 1024, +4.8% at seq 8192 —
            # docs/benchmarks.md round 5)
            out_t, lse_t = _flash_fwd_once_call(
                qt, kt, vt, offs, causal=causal, scale=scale,
                block_q=_pick_block(tq, side="q"),
                block_k=_pick_block(tk, side="k"), interpret=_interpret())
            return qt, kt, vt, out_t, lse_t
        mt = jnp.full((bh, tq, 1), NEG_INF, jnp.float32)
        lt = jnp.zeros((bh, tq, 1), jnp.float32)
        ot = jnp.zeros((bh, tq, d), jnp.float32)
        mt, lt, ot = _flash_step_call(
            qt, kt, vt, mt, lt, ot, offs, causal=causal, scale=scale,
            block_q=_pick_block(tq, side="q"),
            block_k=_pick_block(tk, side="k"), interpret=_interpret())
        # heads-major finalize; masked-row convention shared with the ring
        # epilogue via _masked_row_stats (backward recompute relies on it)
        l_safe, lse_t = _masked_row_stats(mt, lt)            # [BH, T, 1]
        out_t = (ot / l_safe).astype(q.dtype)
        return qt, kt, vt, out_t, lse_t

    @jax.custom_vjp
    def fa(q, k, v):
        b, tq, h, d = q.shape
        out_t = fwd_hm(q, k, v)[3]
        return _heads_minor(out_t, b, h, tq, d)

    def fwd(q, k, v):
        b, tq, h, d = q.shape
        qt, kt, vt, out_t, lse_t = fwd_hm(q, k, v)
        return (_heads_minor(out_t, b, h, tq, d),
                (qt, kt, vt, out_t, lse_t))

    def bwd(res, dout):
        qt, kt, vt, out_t, lse_t = res
        b, tq, h, d = dout.shape
        tk = kt.shape[1]
        dot = dout.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
        ddt = jnp.sum(dot.astype(jnp.float32) * out_t.astype(jnp.float32),
                      axis=-1, keepdims=True)          # [BH, T, 1]
        dq, dk, dv = _flash_bwd_hm(qt, kt, vt, dot, lse_t, ddt,
                                   causal=causal, scale=scale,
                                   out_dtype=qt.dtype)
        return (_heads_minor(dq, b, h, tq, d).astype(qt.dtype),
                _heads_minor(dk, b, h, tk, d).astype(kt.dtype),
                _heads_minor(dv, b, h, tk, d).astype(vt.dtype))

    fa.defvjp(fwd, bwd)
    return fa


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None):
    """Single-device flash attention, ``[B, T, H, D]`` layout.

    The full-sequence special case of the ring step (one hop, offsets 0),
    with the Pallas FlashAttention-2 backward when shapes allow. Falls back
    to plain jnp attention when the kernel is gated off or shapes are not
    tile-aligned.
    """
    d = q.shape[-1]
    if scale is None:
        scale = d ** -0.5
    if not step_supported(q, k):
        from ..parallel.ring_attention import reference_attention
        return reference_attention(q, k, v, causal=causal, scale=scale)
    return _flash_fullattn_vjp(causal, float(scale))(q, k, v)


# ==================================================================== adasum
def _adasum_reduce_kernel(a_ref, b_ref, out_ref, acc_ref):
    """Accumulate [dot(a,b), |a|^2, |b|^2] over row-tiles into SMEM scratch;
    emit into a (8,128) VMEM tile (positions [0,0..2]; the only tile-legal
    home for 3 scalars) on the pair's last grid step. One read pass over
    both operands."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc_ref[0] = 0.0
        acc_ref[1] = 0.0
        acc_ref[2] = 0.0

    a = a_ref[0].astype(jnp.float32)
    b = b_ref[0].astype(jnp.float32)
    acc_ref[0] += jnp.sum(a * b)
    acc_ref[1] += jnp.sum(a * a)
    acc_ref[2] += jnp.sum(b * b)

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        # place the 3 scalars at [0, 0..2] via iota masks (scatter/.at[].set
        # does not lower in Mosaic)
        row = lax.broadcasted_iota(jnp.int32, (8, _LANES), 0)
        col = lax.broadcasted_iota(jnp.int32, (8, _LANES), 1)
        buf = jnp.where(
            (row == 0) & (col == 0), acc_ref[0],
            jnp.where((row == 0) & (col == 1), acc_ref[1],
                      jnp.where((row == 0) & (col == 2), acc_ref[2], 0.0)))
        out_ref[0] = buf


def _adasum_apply_kernel(s_ref, a_ref, b_ref, out_ref):
    """out = ac*a + bc*b with coefficients from the reduced scalars
    (zero-norm guard as `adasum/adasum.h:331+` / executor combine)."""
    dot, na, nb = s_ref[0, 0, 0], s_ref[0, 0, 1], s_ref[0, 0, 2]
    ac = jnp.where(na == 0.0, 1.0, 1.0 - dot / (2.0 * jnp.where(na == 0.0, 1.0, na)))
    bc = jnp.where(nb == 0.0, 1.0, 1.0 - dot / (2.0 * jnp.where(nb == 0.0, 1.0, nb)))
    a = a_ref[0].astype(jnp.float32)
    b = b_ref[0].astype(jnp.float32)
    out_ref[0] = (ac * a + bc * b).astype(out_ref.dtype)


_LANES = 128
_ROWS = 512  # 512x128 f32 tile = 256 KB per operand per step


def adasum_supported(n_elements: int) -> bool:
    return mode() != "off" and n_elements % _LANES == 0


def adasum_combine_pairs(a, b):
    """Fused Adasum combine of ``m`` independent pairs: ``a``/``b`` are
    ``[m, ...]``; pair ``i`` combines ``a[i]`` with ``b[i]``.

    ``a' = (1 - dot/(2|a|^2)) a + (1 - dot/(2|b|^2)) b`` with dot/norms
    accumulated in f32 (`adasum/adasum.h:331+`). Two passes over HBM instead
    of the unfused three (dot+norms, then apply); the pair dimension rides
    the grid, so one launch covers a whole tree level of `spmd.adasum`.
    """
    shape, dtype = a.shape, a.dtype
    m = shape[0]
    n = int(np.prod(shape[1:])) if len(shape) > 1 else 1
    if not adasum_supported(n):
        raise ValueError("adasum_combine: per-pair size must be lane-aligned "
                         f"({_LANES}); got {n}")
    rows = n // _LANES
    block_rows = min(_ROWS, rows)
    while rows % block_rows:
        block_rows //= 2
    af = a.reshape(m, rows, _LANES)
    bf = b.reshape(m, rows, _LANES)
    grid = (m, rows // block_rows)
    interpret = _interpret()
    tile = pl.BlockSpec((1, block_rows, _LANES), lambda i, j: (i, j, 0))
    # one (8,128) scalar tile per pair; same block for every j (kept resident)
    s_tile = pl.BlockSpec((1, 8, _LANES), lambda i, j: (i, 0, 0))

    scalars = pl.pallas_call(
        _adasum_reduce_kernel,
        grid=grid,
        in_specs=[tile, tile],
        out_specs=s_tile,
        out_shape=_struct((m, 8, _LANES), jnp.float32, af, bf),
        scratch_shapes=[pltpu.SMEM((3,), jnp.float32)],
        # j accumulates dot/norms into the SAME revisited scalar tile
        compiler_params=_sem_par_arb(),
        interpret=interpret,
    )(af, bf)

    out = pl.pallas_call(
        _adasum_apply_kernel,
        grid=grid,
        in_specs=[s_tile, tile, tile],
        out_specs=tile,
        out_shape=_struct((m, rows, _LANES), dtype, af, bf),
        compiler_params=_sem_par2(),
        interpret=interpret,
    )(scalars, af, bf)
    return out.reshape(shape)


def adasum_combine(a, b):
    """Fused Adasum pairwise combine of two same-shape arrays (single-pair
    convenience over :func:`adasum_combine_pairs`)."""
    return adasum_combine_pairs(a[None], b[None])[0]


# ================================================================ layernorm
# XLA's LayerNorm on TPU is a multi-pass f32 chain (measured ~28 ms of a
# 209 ms GPT-2-medium train step across 49 norms — ~14x off the HBM
# roofline for what is one read + one write of the activation). The fused
# forward below measured 0.03 ms/norm in-step (vs XLA's 0.25). The
# backward stays plain jnp ON PURPOSE: a Pallas backward walls off the LN
# gradient from the backward chain XLA fuses it into, and the all-Pallas
# variant measured a net end-to-end LOSS (38.7k -> 37.3k tok/s on
# lm_bench); the hybrid is neutral end-to-end on the training step and
# wins where the norm is not surrounded by fusible ops (inference).
# Reference surface being replaced: flax ``nn.LayerNorm``; statistics
# always f32.

def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mu_ref, rs_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)                # [BR, D]
    d = x.shape[1]
    mean = jnp.sum(x, axis=1, keepdims=True) / d      # [BR, 1]
    xc = x - mean
    var = jnp.sum(xc * xc, axis=1, keepdims=True) / d
    rstd = lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[...].astype(jnp.float32) + b_ref[...].astype(
        jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mu_ref[...] = mean
    rs_ref[...] = rstd


def _ln_rows_block(n: int, d: int) -> Optional[int]:
    """Row-tile height: largest power of 2 <= 256 dividing n whose f32 tile
    stays within ~1 MB of VMEM per operand."""
    cap = max(8, (1 << 20) // (4 * d))
    b = 256
    while b >= 8:
        if b <= cap and n % b == 0:
            return b
        b //= 2
    return None


def ln_supported(x) -> bool:
    """True when the fused kernels take this shape: last dim lane-aligned,
    row count tileable (the wrapper falls back to plain jnp otherwise)."""
    n = int(np.prod(x.shape[:-1])) if x.ndim > 1 else 0
    d = x.shape[-1]
    return (mode() != "off" and x.ndim >= 2 and d % _LANES == 0
            and n > 0 and _ln_rows_block(n, d) is not None)


def _ln_reference(x, gamma, beta, eps):
    """jnp fallback with the same math/dtype contract as the kernels
    (flax ``nn.LayerNorm`` semantics: f32 statistics, output in x.dtype)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * lax.rsqrt(var + eps) * gamma.astype(jnp.float32) \
        + beta.astype(jnp.float32)
    return y.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln_fused(x2, gamma, beta, eps):
    y, _, _ = _ln_fused_fwd_call(x2, gamma, beta, eps)
    return y


def _ln_fused_fwd_call(x2, gamma, beta, eps):
    n, d = x2.shape
    br = _ln_rows_block(n, d)
    row = pl.BlockSpec((br, d), lambda i: (i, 0))
    vec = pl.BlockSpec((1, d), lambda i: (0, 0))
    col = pl.BlockSpec((br, 1), lambda i: (i, 0))
    y, mu, rs = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(n // br,),
        in_specs=[row, vec, vec],
        out_specs=[row, col, col],
        out_shape=[_struct((n, d), x2.dtype, x2, gamma),
                   _struct((n, 1), jnp.float32, x2, gamma),
                   _struct((n, 1), jnp.float32, x2, gamma)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(x2, gamma[None], beta[None])
    return y, mu, rs


def _ln_fused_vjp_fwd(x2, gamma, beta, eps):
    y, mu, rs = _ln_fused_fwd_call(x2, gamma, beta, eps)
    return y, (x2, mu, rs, gamma)


def _ln_fused_vjp_bwd(eps, res, dy):
    """Backward in plain jnp ON PURPOSE (see section note): fusible into
    the surrounding gradient chain, off the kernel's saved f32 stats."""
    x2, mu, rs, gamma = res
    d = x2.shape[1]
    xf = x2.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    xhat = (xf - mu) * rs
    g = dyf * gamma.astype(jnp.float32)
    c1 = jnp.sum(g * xhat, axis=1, keepdims=True) / d
    c2 = jnp.sum(g, axis=1, keepdims=True) / d
    dx = (rs * (g - xhat * c1 - c2)).astype(x2.dtype)
    dg = jnp.sum(dyf * xhat, axis=0).astype(gamma.dtype)
    db = jnp.sum(dyf, axis=0).astype(gamma.dtype)
    return dx, dg, db


_ln_fused.defvjp(_ln_fused_vjp_fwd, _ln_fused_vjp_bwd)


def fused_layer_norm(x, gamma, beta, *, eps: float = 1e-6):
    """LayerNorm over the last axis with a one-pass Pallas forward.

    ``x`` any shape ``[..., D]``; ``gamma``/``beta`` shape ``[D]``.
    Statistics in f32, output in ``x.dtype``, parameter grads in the
    parameters' dtype. Falls back to an identical-contract jnp
    implementation off-TPU or for non-tileable shapes.
    """
    if not ln_supported(x) or vma_active(x, gamma, beta):
        return _ln_reference(x, gamma, beta, eps)
    n = int(np.prod(x.shape[:-1]))
    y = _ln_fused(x.reshape(n, x.shape[-1]), gamma, beta, eps)
    return y.reshape(x.shape)


# ====================================================== int8 block quantize
# The wire-compression kernels for the quantized allreduce path
# (`runtime/executor.py` / `ops/compression.py`): per-block symmetric int8
# with an f32 scale per block — the EQuARX wire format. One row of the 2D
# view is one quantization block, so the reduction that computes absmax is
# a lane-dimension max and the grid is embarrassingly parallel over rows.


def _int8_quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[...].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = absmax * (1.0 / 127.0)
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q_ref[...] = jnp.clip(jnp.round(x / safe), -127.0, 127.0).astype(jnp.int8)
    s_ref[...] = scale


def _int8_dequant_kernel(q_ref, s_ref, y_ref):
    y_ref[...] = q_ref[...].astype(jnp.float32) * s_ref[...]


def int8_supported(rows: int, block: int) -> bool:
    """Kernel path engages for lane-aligned blocks and tileable row counts;
    everything else takes the caller's jnp fallback (identical contract)."""
    return (mode() != "off" and block % 128 == 0
            and _pick_block(rows, 256) is not None)


def int8_quantize_2d(x2):
    """[rows, block] float → ([rows, block] int8, [rows, 1] f32 scales)."""
    rows, block = x2.shape
    br = _pick_block(rows, 256)
    row = pl.BlockSpec((br, block), lambda i: (i, 0))
    col = pl.BlockSpec((br, 1), lambda i: (i, 0))
    return pl.pallas_call(
        _int8_quant_kernel,
        grid=(rows // br,),
        in_specs=[row],
        out_specs=[row, col],
        out_shape=[_struct((rows, block), jnp.int8, x2),
                   _struct((rows, 1), jnp.float32, x2)],
        compiler_params=_cparams("parallel"),
        interpret=_interpret(),
    )(x2)


def int8_dequantize_2d(q2, s2):
    """([rows, block] int8, [rows, 1] f32) → [rows, block] f32."""
    rows, block = q2.shape
    br = _pick_block(rows, 256)
    row = pl.BlockSpec((br, block), lambda i: (i, 0))
    col = pl.BlockSpec((br, 1), lambda i: (i, 0))
    return pl.pallas_call(
        _int8_dequant_kernel,
        grid=(rows // br,),
        in_specs=[row, col],
        out_specs=row,
        out_shape=_struct((rows, block), jnp.float32, q2, s2),
        compiler_params=_cparams("parallel"),
        interpret=_interpret(),
    )(q2, s2)


# =================================================== fused quantize + pack
# Single-pass wire assembly for the packed int8 allreduce
# (HOROVOD_PACKED_WIRE, `runtime/executor.py`): instead of quantizing into
# TWO buffers (payload + scales) that ride TWO collectives, each block row
# becomes one int8 row ``[q_0..q_{B-1} | scale as 4 raw bytes]`` written by
# ONE store — the fusion-buffer layout itself, so the separate quantize
# pass and the second collective both disappear. The quantization formula
# is byte-identical to `_int8_quant_kernel` above (same absmax/scale/clip
# chain); only the destination layout differs.

PACK_SCALE_BYTES = 4  # one f32 scale per block row, bitcast to raw bytes


def _int8_quant_pack_kernel(x_ref, p_ref):
    x = x_ref[...].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = absmax * (1.0 / 127.0)
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q = jnp.clip(jnp.round(x / safe), -127.0, 127.0).astype(jnp.int8)
    sbytes = lax.bitcast_convert_type(scale, jnp.int8).reshape(
        x.shape[0], PACK_SCALE_BYTES)
    p_ref[...] = jnp.concatenate([q, sbytes], axis=1)


def int8_quantize_pack_2d(x2):
    """[rows, block] float → [rows, block+4] int8 packed rows."""
    rows, block = x2.shape
    br = _pick_block(rows, 256)
    row = pl.BlockSpec((br, block), lambda i: (i, 0))
    prow = pl.BlockSpec((br, block + PACK_SCALE_BYTES), lambda i: (i, 0))
    return pl.pallas_call(
        _int8_quant_pack_kernel,
        grid=(rows // br,),
        in_specs=[row],
        out_specs=prow,
        out_shape=_struct((rows, block + PACK_SCALE_BYTES), jnp.int8, x2),
        compiler_params=_cparams("parallel"),
        interpret=_interpret(),
    )(x2)


def int8_quantize_pack_ref(x2):
    """jnp fallback — the exact kernel formula, bit-identical packed rows."""
    xf = x2.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=1, keepdims=True)
    scale = absmax * (1.0 / 127.0)
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q = jnp.clip(jnp.round(xf / safe), -127.0, 127.0).astype(jnp.int8)
    sbytes = lax.bitcast_convert_type(scale, jnp.int8).reshape(
        x2.shape[0], PACK_SCALE_BYTES)
    return jnp.concatenate([q, sbytes], axis=1)


def int8_quantize_pack(x2):
    """Kernel when the shape tiles and no vma constraint applies; jnp
    fallback otherwise. Same bits either way."""
    rows, block = x2.shape
    if int8_supported(rows, block) and not vma_active(x2):
        return int8_quantize_pack_2d(x2)
    return int8_quantize_pack_ref(x2)


def int8_unpack(p2):
    """[rows, block+4] packed int8 → ([rows, block] int8, [rows, 1] f32).
    Pure layout surgery (slice + bitcast); XLA fuses it into the consumer,
    so no kernel is needed on the unpack side."""
    rows = p2.shape[0]
    block = p2.shape[1] - PACK_SCALE_BYTES
    q = p2[:, :block]
    scales = lax.bitcast_convert_type(
        p2[:, block:].reshape(rows, 1, PACK_SCALE_BYTES), jnp.float32)
    return q, scales.reshape(rows, 1)


# ====================================================== int4 packed wire
# int4 halves the packed payload again: two quantized values per byte with
# a per-block f32 scale (absmax/7, clip ±7 — the EQuARX aggressive tier).
# Nibble layout is HALF-SPLIT: byte j of a row holds element j in the low
# nibble and element j + block//2 in the high nibble, so pack and unpack
# operate on contiguous half-row slices (lane-friendly) instead of a
# strided even/odd interleave. int4 always rides packed rows —
# ``[block//2 payload bytes | 4 raw f32 scale bytes]`` — one all_to_all +
# one all_gather, the same wire shape as HOROVOD_PACKED_WIRE's int8 rows.

INT4_QMAX = 7.0


def int4_supported(rows: int, block: int) -> bool:
    """Kernel path: the packed payload (block//2 bytes) must stay
    lane-aligned, so the block needs 256-divisibility; row counts tile
    like int8. Everything else takes the bit-identical jnp fallback."""
    return (mode() != "off" and block % 256 == 0
            and _pick_block(rows, 256) is not None)


def _int4_pack_rows(x):
    """The shared quantize+pack formula (kernel body and jnp reference both
    call this exact chain, so the two paths are bit-identical)."""
    xf = x.astype(jnp.float32)
    half = xf.shape[1] // 2
    absmax = jnp.max(jnp.abs(xf), axis=1, keepdims=True)
    scale = absmax * (1.0 / INT4_QMAX)
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q = jnp.clip(jnp.round(xf / safe), -INT4_QMAX, INT4_QMAX).astype(jnp.int8)
    b = jnp.bitwise_or(jnp.bitwise_and(q[:, :half], jnp.int8(15)),
                       jnp.left_shift(q[:, half:], 4)).astype(jnp.int8)
    sbytes = lax.bitcast_convert_type(scale, jnp.int8).reshape(
        xf.shape[0], PACK_SCALE_BYTES)
    return jnp.concatenate([b, sbytes], axis=1)


def _int4_quant_pack_kernel(x_ref, p_ref):
    p_ref[...] = _int4_pack_rows(x_ref[...])


def int4_quantize_pack_2d(x2):
    """[rows, block] float → [rows, block//2 + 4] int8 packed rows."""
    rows, block = x2.shape
    br = _pick_block(rows, 256)
    row = pl.BlockSpec((br, block), lambda i: (i, 0))
    prow = pl.BlockSpec((br, block // 2 + PACK_SCALE_BYTES),
                        lambda i: (i, 0))
    return pl.pallas_call(
        _int4_quant_pack_kernel,
        grid=(rows // br,),
        in_specs=[row],
        out_specs=prow,
        out_shape=_struct((rows, block // 2 + PACK_SCALE_BYTES), jnp.int8,
                          x2),
        compiler_params=_cparams("parallel"),
        interpret=_interpret(),
    )(x2)


def int4_quantize_pack_ref(x2):
    """jnp fallback — the exact kernel formula, bit-identical packed rows."""
    return _int4_pack_rows(x2)


def int4_quantize_pack(x2):
    """Kernel when the shape tiles and no vma constraint applies; jnp
    fallback otherwise. Same bits either way. ``block`` must be even
    (two values per byte)."""
    rows, block = x2.shape
    if block % 2:
        raise ValueError(
            f"int4 packing needs an even block; got {block} "
            "(HOROVOD_INT8_BLOCK)")
    if int4_supported(rows, block) and not vma_active(x2):
        return int4_quantize_pack_2d(x2)
    return int4_quantize_pack_ref(x2)


def int4_unpack(p2):
    """[rows, block//2 + 4] packed int4 → ([rows, block] int8, [rows, 1]
    f32). Sign extension is two arithmetic shifts per nibble (int8 shifts
    are arithmetic); pure layout surgery otherwise, fused by XLA into the
    consumer like ``int8_unpack``."""
    rows = p2.shape[0]
    half = p2.shape[1] - PACK_SCALE_BYTES
    b = p2[:, :half]
    lo = jnp.right_shift(jnp.left_shift(b, 4), 4)
    hi = jnp.right_shift(b, 4)
    q = jnp.concatenate([lo, hi], axis=1)
    scales = lax.bitcast_convert_type(
        p2[:, half:].reshape(rows, 1, PACK_SCALE_BYTES), jnp.float32)
    return q, scales.reshape(rows, 1)


# ============================================= fused matmul + reduce-scatter
# The tail-linear / LM-head pattern: x [R, Kl] and w [Kl, N] are the local
# shards of a contraction-sharded matmul, so the full product is
# sum_over_ranks(x_j @ w_j) and each rank only needs its own row chunk of
# the sum — matmul feeding reduce-scatter. The fused form decomposes the
# local product into per-chunk partial matmuls and rotates the accumulator
# around the ring: every hop's ppermute is data-independent of the chunk
# matmul issued next to it, so the compiler overlaps wire and MXU instead
# of serializing full-matmul-then-collective.


def _mm_kernel(x_ref, w_ref, o_ref, acc_ref, *, k_steps):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(x_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _emit():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def matmul_tiles(mdim: int, kdim: int, ndim: int):
    """(bm, bk, bn) MXU tiling for the matmul kernel, or None when the
    shape doesn't tile (caller uses jnp.dot — identical contraction)."""
    if mode() == "off" or kdim % _LANES or ndim % _LANES:
        return None
    bm = _pick_block(mdim, 256)
    bk = _pick_block(kdim, 512)
    bn = _pick_block(ndim, 256)
    if bm is None or bk is None or bn is None:
        return None
    return bm, bk, bn


def matmul_2d(x2, w2):
    """Tiled MXU matmul with f32 accumulation (k innermost, sequential —
    the grid revisits one output tile per (i, j))."""
    mdim, kdim = x2.shape
    ndim = w2.shape[1]
    bm, bk, bn = matmul_tiles(mdim, kdim, ndim)
    return pl.pallas_call(
        functools.partial(_mm_kernel, k_steps=kdim // bk),
        grid=(mdim // bm, ndim // bn, kdim // bk),
        in_specs=[pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
                  pl.BlockSpec((bk, bn), lambda i, j, k: (k, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=_struct((mdim, ndim), jnp.result_type(x2, w2), x2, w2),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_sem_par2_arb(),
        interpret=_interpret(),
    )(x2, w2)


def _mm_chunk(xs, w):
    mdim, kdim = xs.shape
    if matmul_tiles(mdim, kdim, w.shape[1]) is not None \
            and not vma_active(xs, w):
        return matmul_2d(xs, w)
    return jnp.dot(xs, w)


def matmul_reduce_scatter_reference(x, w, axis_name):
    """Unfused reference: full local matmul, then a tiled psum_scatter of
    the product (same result up to f32 addition order)."""
    return lax.psum_scatter(x @ w, axis_name, scatter_dimension=0,
                            tiled=True)


def matmul_reduce_scatter(x, w, axis_name):
    """``psum_scatter(x @ w)`` fused into a compute/permute ring.

    Call inside shard_map/pmap over ``axis_name`` with ``x`` [R, Kl] and
    ``w`` [Kl, N] (contraction-sharded); returns this rank's [R/m, N] row
    chunk of the cross-rank sum. Rank p seeds its accumulator with the
    local partial of chunk (p-1) mod m; each of the m-1 hops rotates the
    accumulator one rank forward and adds the local partial of chunk
    (p-k-1) mod m, so after hop k=m-1 rank p holds chunk p summed over
    every rank — and every hop's wire transfer is independent of the
    matmul scheduled beside it. Falls back to the unfused reference when
    rows don't split evenly, the kernels are off, or vma checking is
    active (addition order matches psum_scatter only in the fallback;
    the ring result differs by f32 reassociation, like any ring
    reduce-scatter)."""
    m = lax.psum(1, axis_name)
    rows = x.shape[0]
    if m == 1 or rows % m or mode() == "off" or vma_active(x, w):
        return matmul_reduce_scatter_reference(x, w, axis_name)
    p = lax.axis_index(axis_name)
    c = rows // m

    def partial_chunk(k):
        idx = jnp.mod(p - k - 1, m)
        xs = lax.dynamic_slice_in_dim(x, idx * c, c, axis=0)
        return _mm_chunk(xs, w)

    acc = partial_chunk(0)
    perm = [(j, (j + 1) % m) for j in range(m)]
    for k in range(1, m):
        acc = lax.ppermute(acc, axis_name, perm) + partial_chunk(k)
    return acc
