"""Per-bucket bitwidth selection for the mixed-precision wire (adaptive v2).

EQuARX (PAPERS.md arXiv:2506.17615) observes that the right wire bitwidth
is a per-tensor property of the gradient distribution: well-conditioned
buckets survive 4-bit block quantization, heavy-tailed ones need 8 bits or
a bf16 fallback. This module owns everything that makes that choice:

* :class:`BucketStats` / :class:`BitwidthSelector` — running statistics
  (absmax/variance EMAs and the measured relative quantization-residual
  norm at each candidate grid) per bucket name, re-deciding the wire mode
  every ``HOROVOD_ADAPTIVE_INTERVAL`` observations with hysteresis. The
  statistics are computed from the *reduced* bucket (identical bytes on
  every rank) with a deterministic sample, so every rank's selector makes
  the same decision sequence — cross-rank agreement by construction, and
  the coordinator's negotiation (Response.compression) still arbitrates
  any transition race.
* :class:`ConvergenceGate` — the A/B convergence harness (chaos-style,
  like the PR 4/5 convergence tests): trains the same deterministic proxy
  problem twice, once with exact gradient updates and once with
  bitwidth-quantized + error-feedback updates, and admits a grid only at
  measured loss parity. Pure numpy, fixed seed → identical verdict on
  every rank, cached after the first call.
* :class:`BitwidthTuner` — the rank-0 autotune extension: explores
  gate-admitted bitwidth *caps* in episodes, scoring each by the wire-true
  bytes the coordinator already aggregates, and settles on the cheapest.
  The chosen cap broadcasts to every rank as the third ``tuned`` field
  (runtime/wire.py) and lands here via :func:`set_autotuned_cap`.

Knobs (all read per call, unset keeps the wire exactly as before):
``HOROVOD_ADAPTIVE_TOL`` (relative residual tolerance, default 0.2),
``HOROVOD_ADAPTIVE_INTERVAL`` (observations between decisions, default 10),
``HOROVOD_ADAPTIVE_GATE`` (0 disables the convergence gate, default on).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

import numpy as np

#: candidate wire modes, cheapest (most aggressive) first
MODES = ("int4", "int8", "bf16")
BITS = {"int4": 4, "int8": 8, "bf16": 16}

#: collective algorithm zoo for the compiled fast path (spmd.py), in
#: exploration order: the ring is the incumbent (byte-identical to the
#: pre-zoo wire), the tree is latency-optimal for small payloads, the
#: hierarchical schedule wins on multi-host factorizations
ALGORITHMS = ("ring", "tree", "hier")
#: gauge encoding for hvd_collective_algorithm{class}
ALGO_CODES = {"ring": 0, "tree": 1, "hier": 2}

#: payload-size classes the joint (algorithm, bitwidth) tuner scores
#: independently — the winning algorithm is a function of payload size
#: (PAPERS.md arXiv:1810.11112), so one global argmin would let large
#: buckets outvote the latency-bound small ones. Bounds in wire bytes.
SIZE_CLASSES = (("small", 1 << 16), ("medium", 1 << 22), ("large", None))


def size_class(nbytes: int) -> str:
    """Class name for one round's payload bytes (upper bounds inclusive)."""
    for name, bound in SIZE_CLASSES:
        if bound is None or nbytes <= bound:
            return name
    return SIZE_CLASSES[-1][0]

#: elements of the reduced bucket sampled per observation (deterministic
#: prefix — identical on every rank, cheap on the host)
SAMPLE = 4096

_QMAX = {4: 7.0, 8: 127.0}


def tolerance() -> float:
    """Relative quantization-residual tolerance (HOROVOD_ADAPTIVE_TOL).

    Default 0.2: a Gaussian block at int4 measures ~0.14 relative RMS
    residual (absmax≈3.5σ, 15 levels), so well-behaved buckets go 4-bit;
    heavy-tailed blocks (absmax ≫ rms) exceed it and stay at int8/bf16."""
    v = float(os.environ.get("HOROVOD_ADAPTIVE_TOL", 0.2))
    if v <= 0:
        raise ValueError(f"HOROVOD_ADAPTIVE_TOL={v}: must be positive")
    return v


def interval() -> int:
    """Observations between bitwidth decisions (HOROVOD_ADAPTIVE_INTERVAL)."""
    v = int(os.environ.get("HOROVOD_ADAPTIVE_INTERVAL", 10))
    if v <= 0:
        raise ValueError(f"HOROVOD_ADAPTIVE_INTERVAL={v}: must be positive")
    return v


def gate_enabled() -> bool:
    return os.environ.get("HOROVOD_ADAPTIVE_GATE", "1").strip() not in (
        "0", "false", "False", "off")


# ------------------------------------------------------------- autotuned cap
# The coordinator's BitwidthTuner broadcasts a floor on the wire grid (a
# cap on aggressiveness): decisions may not go below cap bits. "int4" (the
# default) is no restriction; "bf16" forbids integer grids entirely.
_cap_lock = threading.Lock()
_autotuned_cap = "int4"


def set_autotuned_cap(cap: str) -> None:
    global _autotuned_cap
    if cap not in MODES:
        return  # a newer coordinator speaking an unknown mode: ignore
    with _cap_lock:
        _autotuned_cap = cap


def autotuned_cap() -> str:
    with _cap_lock:
        return _autotuned_cap


# The coordinator's joint tuner broadcasts the winning collective algorithm
# as the fourth tuned field (runtime/wire.py flag byte 3); "" means no
# broadcast has arrived and spmd.resolve_algorithm falls back to its static
# size/topology heuristic.
_autotuned_algo = ""


def set_autotuned_algorithm(algo: str) -> None:
    global _autotuned_algo
    if algo not in ALGORITHMS:
        return  # a newer coordinator speaking an unknown member: ignore
    with _cap_lock:
        _autotuned_algo = algo


def autotuned_algorithm() -> str:
    with _cap_lock:
        return _autotuned_algo


def reset() -> None:
    """Test hook: forget the broadcast cap/algorithm and the cached gate
    verdicts."""
    global _autotuned_cap, _autotuned_algo
    with _cap_lock:
        _autotuned_cap = "int4"
        _autotuned_algo = ""
    ConvergenceGate.shared().forget()


def admit_wire(wire: str) -> str:
    """Gate admission for an integer wire grid, shared by every compiled-path
    knob (``HOROVOD_GSPMD_WIRE``, ``HOROVOD_MOE_WIRE``): int4 must pass the
    :class:`ConvergenceGate` A/B harness; a refusal downgrades to int8
    rather than risking the 4-bit grid on a model the deterministic proxy
    couldn't converge. int8 (and anything else) passes through unchanged —
    it shipped with its own convergence tests."""
    if wire == "int4" and not ConvergenceGate.shared().allows("int4"):
        return "int8"
    return wire


# ---------------------------------------------------------------- numerics
def _block_roundtrip(x: np.ndarray, bits: int, block: int = 256) -> np.ndarray:
    """Numpy mirror of ``compression.quantize_roundtrip`` (same formula:
    symmetric per-block grid, scale = absmax/qmax). Kept in numpy so the
    selector and the gate never touch jax from control-plane threads."""
    qmax = _QMAX[bits]
    n = x.shape[0]
    pad = (-n) % block
    if pad:
        x = np.pad(x, (0, pad))
    x2 = x.reshape(-1, block).astype(np.float32)
    absmax = np.max(np.abs(x2), axis=1, keepdims=True)
    scale = absmax * (1.0 / qmax)
    safe = np.where(scale > 0.0, scale, 1.0)
    q = np.clip(np.round(x2 / safe), -qmax, qmax)
    y = (q * scale).reshape(-1)
    return y[:n] if pad else y


def _bf16_roundtrip(x: np.ndarray) -> np.ndarray:
    """bf16 cast loss: truncate the mantissa to 8 bits (round-to-nearest
    via the +0x8000 carry), bit-exact with an ml_dtypes cast."""
    u = x.astype(np.float32).view(np.uint32)
    u = (u + 0x8000 + ((u >> 16) & 1)) & 0xFFFF0000
    return u.astype(np.uint32).view(np.float32)


def relative_residual(x: np.ndarray, mode: str) -> float:
    """‖x − wire(x)‖ / ‖x‖ for one candidate grid — the EF-residual-norm
    statistic the selector tracks (what error feedback would have to carry
    if this bucket rode that wire)."""
    xf = np.asarray(x, dtype=np.float32).reshape(-1)
    norm = float(np.linalg.norm(xf))
    if norm == 0.0:
        return 0.0
    if mode == "bf16":
        y = _bf16_roundtrip(xf)
    else:
        y = _block_roundtrip(xf, BITS[mode])
    return float(np.linalg.norm(xf - y)) / norm


# ------------------------------------------------------------ bucket stats
class BucketStats:
    """Running statistics for one bucket name (EMAs, decay 0.8)."""

    __slots__ = ("count", "absmax", "var", "err", "mode")

    def __init__(self):
        self.count = 0
        self.absmax = 0.0
        self.var = 0.0
        self.err: Dict[str, float] = {}
        self.mode = "int8"  # startup default (matches the static wire)

    def update(self, sample: np.ndarray) -> None:
        a = float(np.max(np.abs(sample))) if sample.size else 0.0
        v = float(np.var(sample)) if sample.size else 0.0
        d = 0.8
        self.absmax = a if self.count == 0 else d * self.absmax + (1 - d) * a
        self.var = v if self.count == 0 else d * self.var + (1 - d) * v
        for m in MODES:
            e = relative_residual(sample, m)
            prev = self.err.get(m)
            self.err[m] = e if prev is None else d * prev + (1 - d) * e
        self.count += 1


class BitwidthSelector:
    """Per-bucket int4/int8/bf16 choice from running gradient statistics.

    ``observe(name, flat)`` feeds the reduced bucket after each drain;
    ``decide(name)`` returns the wire mode the next enqueue should request.
    Decisions refresh every :func:`interval` observations; between
    refreshes the previous choice holds, so every rank requests the same
    mode for the same (name, step). Hysteresis: switching to a *different*
    mode than the current one requires its residual under 0.8×tol, while
    the incumbent only needs tol — no flapping at the boundary.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, BucketStats] = {}
        self._gate = ConvergenceGate.shared()

    def observe(self, name: str, flat) -> None:
        x = np.asarray(flat).reshape(-1)[:SAMPLE]
        if not np.issubdtype(x.dtype, np.floating):
            return
        with self._lock:
            st = self._stats.setdefault(name, BucketStats())
            st.update(x.astype(np.float32))
            if st.count % interval() == 0:
                self._redecide(name, st)

    def decide(self, name: str) -> str:
        with self._lock:
            st = self._stats.get(name)
            return st.mode if st is not None else "int8"

    def min_active_bits(self) -> int:
        """Most aggressive grid currently chosen across buckets (8 before
        any decision) — what the EF roundtrip measures against."""
        with self._lock:
            if not self._stats:
                return 8
            return min(BITS[st.mode] for st in self._stats.values())

    def decisions(self) -> Dict[str, str]:
        with self._lock:
            return {n: st.mode for n, st in self._stats.items()}

    def _redecide(self, name: str, st: BucketStats) -> None:
        tol = tolerance()
        cap_bits = BITS[autotuned_cap()]
        pick = "bf16"
        for m in MODES:  # cheapest first
            if BITS[m] < cap_bits:
                continue
            if m == "int4" and not self._gate.allows("int4"):
                continue
            margin = tol if m == st.mode else 0.8 * tol
            if m == "bf16" or st.err.get(m, np.inf) <= margin:
                pick = m
                break
        if pick != st.mode:
            old, st.mode = st.mode, pick
            self._record(name, old, pick)

    @staticmethod
    def _record(name: str, old: str, new: str) -> None:
        from .. import blackbox as _blackbox
        from ..metrics import instruments

        _blackbox.record(_blackbox.K_BITWIDTH, name, f"{old}->{new}")
        instruments.bitwidth_decisions().labels(wire=new).inc()
        instruments.adaptive_bitwidth().set(BITS[new])


# -------------------------------------------------------- convergence gate
class ConvergenceGate:
    """A/B convergence harness gating aggressive bitwidths.

    Trains one deterministic proxy problem (least-squares regression on
    fixed-seed Gaussian data, plain gradient descent) twice: with exact
    gradients, and with gradients pushed through the candidate wire grid
    plus EF-SGD error feedback — the same update rule
    ``DistributedOptimizer(error_feedback=True)`` applies to the real
    model. A grid is admitted only if its final loss is within
    ``rel_tol`` of the exact run's. Seeded numpy end to end, so the
    verdict is bit-identical on every rank and cacheable.
    """

    _shared: Optional["ConvergenceGate"] = None

    @classmethod
    def shared(cls) -> "ConvergenceGate":
        if cls._shared is None:
            cls._shared = ConvergenceGate()
        return cls._shared

    def __init__(self, steps: int = 150, dim: int = 256, lr: float = 0.05,
                 rel_tol: float = 0.05, seed: int = 1234):
        self.steps = steps
        self.dim = dim
        self.lr = lr
        self.rel_tol = rel_tol
        self.seed = seed
        self._lock = threading.Lock()
        self._verdicts: Dict[str, bool] = {}
        self._losses: Dict[str, Tuple[float, float]] = {}

    def forget(self) -> None:
        with self._lock:
            self._verdicts.clear()
            self._losses.clear()

    def allows(self, mode: str) -> bool:
        if mode != "int4":
            return True  # int8/bf16 shipped with their own convergence tests
        if not gate_enabled():
            return True
        with self._lock:
            v = self._verdicts.get(mode)
            if v is None:
                exact, quant = self._ab_losses(BITS[mode])
                v = quant <= exact * (1.0 + self.rel_tol)
                self._verdicts[mode] = v
                self._losses[mode] = (exact, quant)
            return v

    def losses(self, mode: str) -> Tuple[float, float]:
        """(exact_loss, quantized_loss) of the A/B pair; runs it if needed."""
        with self._lock:
            if mode not in self._losses:
                self._losses[mode] = self._ab_losses(BITS[mode])
            return self._losses[mode]

    def _ab_losses(self, bits: int) -> Tuple[float, float]:
        return (self._train(None), self._train(bits))

    def _train(self, bits: Optional[int]) -> float:
        rng = np.random.RandomState(self.seed)
        n, d = 4 * self.dim, self.dim
        x = rng.randn(n, d).astype(np.float32)
        w_true = rng.randn(d).astype(np.float32)
        y = x @ w_true + 0.01 * rng.randn(n).astype(np.float32)
        w = np.zeros(d, dtype=np.float32)
        residual = np.zeros(d, dtype=np.float32)
        for _ in range(self.steps):
            g = (2.0 / n) * (x.T @ (x @ w - y))
            if bits is not None:
                corrected = g + residual
                g_wire = _block_roundtrip(corrected, bits)
                residual = corrected - g_wire
                g = g_wire
            w -= self.lr * g
        return float(np.mean((x @ w - y) ** 2))


# ---------------------------------------------------------- autotune caps
class BitwidthTuner:
    """Rank-0 bitwidth-cap search riding the coordinator's autotune scores.

    The GP/EI native tuner keeps owning fusion threshold and cycle time;
    bitwidth is a small discrete axis, so this explores it directly:
    each gate-admitted cap (least → most aggressive) runs for
    ``episode_rounds`` scored negotiation rounds, accumulating the
    wire-true bytes the coordinator already aggregates; after the sweep
    the cap with the fewest mean bytes/round wins (ties go to the more
    aggressive cap) and the tuner settles. The current cap is broadcast
    every round as the third ``tuned`` field.
    """

    def __init__(self, episode_rounds: int = 8):
        self.episode_rounds = episode_rounds
        gate = ConvergenceGate.shared()
        # least aggressive first: exploration starts byte-identical to the
        # pre-autotune wire and only then tries cheaper grids
        self._candidates = [m for m in reversed(MODES)
                            if m != "int4" or gate.allows("int4")]
        self._idx = 0
        self._rounds = 0
        self._bytes: Dict[str, list] = {m: [] for m in self._candidates}
        self._settled: Optional[str] = None

    def active(self) -> bool:
        return self._settled is None

    def cap(self) -> str:
        if self._settled is not None:
            return self._settled
        return self._candidates[self._idx]

    def observe(self, round_bytes: int, round_seconds: float) -> None:
        """One scored negotiation round under the current cap."""
        if self._settled is not None or round_bytes <= 0:
            return
        cur = self._candidates[self._idx]
        self._bytes[cur].append(float(round_bytes))
        self._rounds += 1
        if self._rounds >= self.episode_rounds:
            self._rounds = 0
            self._idx += 1
            if self._idx >= len(self._candidates):
                self._settle()

    def _settle(self) -> None:
        best, best_mean = None, None
        # reversed: on a tie the later (more aggressive) candidate sticks
        for m in self._candidates:
            vals = self._bytes[m]
            if not vals:
                continue
            mean = sum(vals) / len(vals)
            if best_mean is None or mean < best_mean:
                best, best_mean = m, mean
        self._settled = best or self._candidates[-1]


class _ClassSearch:
    """Episode walk over (algorithm, cap) combos for ONE payload-size
    class (:class:`JointTuner` state)."""

    __slots__ = ("combos", "idx", "rounds", "seconds", "settled")

    def __init__(self, combos):
        self.combos = combos
        self.idx = 0
        self.rounds = 0
        self.seconds: Dict[Tuple[str, str], list] = {c: [] for c in combos}
        self.settled: Optional[Tuple[str, str]] = None

    def current(self) -> Tuple[str, str]:
        return self.settled if self.settled is not None \
            else self.combos[self.idx]


class JointTuner:
    """Rank-0 joint ``(algorithm, bitwidth-cap)`` search, per payload-size
    class (autotune v3 — the :class:`BitwidthTuner` grown an algorithm
    axis).

    Every gate-admitted combination — zoo member x bitwidth cap, least
    aggressive first, so exploration starts schedule- and byte-identical
    to the pre-autotune wire — runs for ``episode_rounds`` scored
    negotiation rounds inside its payload-size class (:func:`size_class`
    of the round's wire bytes: the winning algorithm is a function of
    payload size, so classes settle independently). Episodes are scored by
    measured step time, not bytes: a cheaper wire on a slower schedule
    loses. After the walk the per-class argmin mean step time wins (ties
    go to the later, more aggressive combo) and that class settles.

    :meth:`cap` and :meth:`algorithm` expose the combo for the most
    recently observed round's class — what the next tuned ``ResponseList``
    broadcast (fields 3 and 4, runtime/wire.py) should carry so every
    rank applies the winner for the traffic actually in flight. Settling
    records one blackbox ``K_ALGO`` decision event per class.
    """

    def __init__(self, episode_rounds: int = 8):
        self.episode_rounds = episode_rounds
        gate = ConvergenceGate.shared()
        caps = [m for m in reversed(MODES)
                if m != "int4" or gate.allows("int4")]
        self._combos = [(a, c) for a in ALGORITHMS for c in caps]
        self._cls: Dict[str, _ClassSearch] = {
            name: _ClassSearch(list(self._combos))
            for name, _ in SIZE_CLASSES}
        self._last_cls = SIZE_CLASSES[0][0]

    def active(self) -> bool:
        return any(s.settled is None for s in self._cls.values())

    def choice(self, cls: Optional[str] = None) -> Tuple[str, str]:
        return self._cls[cls or self._last_cls].current()

    def cap(self) -> str:
        return self.choice()[1]

    def algorithm(self) -> str:
        return self.choice()[0]

    def observe(self, round_bytes: int, round_seconds: float) -> None:
        """One scored negotiation round under the current class combo."""
        if round_bytes <= 0 or round_seconds <= 0:
            return
        cls = size_class(int(round_bytes))
        self._last_cls = cls
        s = self._cls[cls]
        if s.settled is not None:
            return
        s.seconds[s.combos[s.idx]].append(float(round_seconds))
        s.rounds += 1
        if s.rounds >= self.episode_rounds:
            s.rounds = 0
            s.idx += 1
            if s.idx >= len(s.combos):
                self._settle(cls, s)

    def _settle(self, cls: str, s: _ClassSearch) -> None:
        best, best_mean = None, None
        for c in s.combos:
            vals = s.seconds[c]
            if not vals:
                continue
            mean = sum(vals) / len(vals)
            # <=: on a tie the later (more aggressive) combo sticks
            if best_mean is None or mean <= best_mean:
                best, best_mean = c, mean
        s.settled = best or s.combos[-1]
        from .. import blackbox as _blackbox
        from ..metrics import instruments

        _blackbox.record(_blackbox.K_ALGO, cls,
                         "settled %s/%s" % s.settled)
        instruments.collective_algorithm().labels(**{"class": cls}).set(
            ALGO_CODES.get(s.settled[0], 0))
