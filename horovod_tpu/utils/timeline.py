"""Chrome-tracing timeline profiler.

Reference parity: `horovod/common/timeline.{h,cc}` — per-tensor NEGOTIATE spans,
top-level op spans, and named activities written as Chrome tracing JSON by a
dedicated writer thread fed through a queue (`timeline.h:47-75`). Enabled via
``HOROVOD_TIMELINE=/path.json`` (`operations.cc:389-396`);
``HOROVOD_TIMELINE_MARK_CYCLES=1`` adds engine-tick instant events
(`operations.cc:400`). Device-side detail comes from ``jax.profiler`` traces —
see :func:`trace_device` — replacing the CUDA-event replay of
`cuda_operations.cc:77-93`.

The Timeline is now a thin adapter over the tracing subsystem's primitives:
the queue-fed writer thread lives in
:class:`horovod_tpu.tracing.writer.ChromeTraceWriter`, and all timestamps
come from :func:`horovod_tpu.tracing.clock.trace_us` — one monotonic
(``time.perf_counter_ns``-anchored) clock for every begin/end pair, so a
span's end can never precede its begin even if the system wall clock steps
between the two (the old ``time.time()`` stamps could go backward under NTP
slew). Cross-rank span correlation lives in :mod:`horovod_tpu.tracing`
(docs/tracing.md); this file keeps the per-rank activity surface.
"""

from __future__ import annotations

import os
from typing import Optional

from ..tracing import clock as _clock
from ..tracing.writer import ChromeTraceWriter


class Timeline:
    """Host-side span recorder; no-op unless a path is configured."""

    def __init__(self, path: Optional[str]):
        self._path = path
        self._enabled = bool(path)
        self._mark_cycles = os.environ.get(
            "HOROVOD_TIMELINE_MARK_CYCLES", "") in ("1", "true", "True")
        self._tid = {}
        self._next_tid = 1
        self._writer: Optional[ChromeTraceWriter] = None
        if self._enabled:
            self._writer = ChromeTraceWriter(path)

    @property
    def enabled(self) -> bool:
        return self._enabled

    def _emit(self, ev: dict) -> None:
        if self._enabled:
            self._writer.emit(ev)

    def _ts(self) -> int:
        # single monotonic clock for every begin/end pair (shared with the
        # distributed-tracing spans so both land on one timeline)
        return _clock.trace_us()

    def _tensor_tid(self, name: str) -> int:
        t = self._tid.get(name)
        if t is None:
            t = self._next_tid
            self._next_tid += 1
            self._tid[name] = t
            self._emit({"name": "thread_name", "ph": "M", "pid": 0, "tid": t,
                        "args": {"name": name}})
        return t

    # span API mirroring Timeline::NegotiateStart/Start/ActivityStart/End
    def negotiate_start(self, name: str, rank: int) -> None:
        if not self._enabled:
            return
        self._emit({"name": f"NEGOTIATE_{name}", "ph": "B", "pid": 0,
                    "tid": self._tensor_tid(name), "ts": self._ts(),
                    "args": {"rank": rank}})

    def op_start(self, name: str, op: str) -> None:
        if not self._enabled:
            return
        tid = self._tensor_tid(name)
        self._emit({"name": f"NEGOTIATE_{name}", "ph": "E", "pid": 0,
                    "tid": tid, "ts": self._ts()})
        self._emit({"name": op, "ph": "B", "pid": 0, "tid": tid,
                    "ts": self._ts()})

    def activity(self, name: str, activity: str) -> None:
        if not self._enabled:
            return
        self._emit({"name": activity, "ph": "i", "pid": 0,
                    "tid": self._tensor_tid(name), "ts": self._ts(), "s": "t"})

    def op_end(self, name: str) -> None:
        if not self._enabled:
            return
        self._emit({"name": "op", "ph": "E", "pid": 0,
                    "tid": self._tensor_tid(name), "ts": self._ts()})

    def cycle_tick(self) -> None:
        if self._enabled and self._mark_cycles:
            self._emit({"name": "CYCLE", "ph": "i", "pid": 0, "tid": 0,
                        "ts": self._ts(), "s": "g"})

    def epoch_marker(self, epoch: int) -> None:
        """Global instant event on every elastic membership epoch change, so a
        trace shows exactly which collectives straddled a reset
        (docs/elastic.md)."""
        if self._enabled:
            self._emit({"name": f"EPOCH_{epoch}", "ph": "i", "pid": 0,
                        "tid": 0, "ts": self._ts(), "s": "g"})

    def cache_counter(self, hits: int, misses: int) -> None:
        """Chrome counter track of response-cache hits/misses (the fast
        path that skips negotiation, reference `controller.cc:171-185`)."""
        if self._enabled:
            self._emit({"name": "response_cache", "ph": "C", "pid": 0,
                        "ts": self._ts(),
                        "args": {"hits": hits, "misses": misses}})

    def close(self) -> None:
        if not self._enabled:
            return
        self._writer.close()
        self._enabled = False


def trace_device(path: str):
    """Context manager: capture a ``jax.profiler`` device trace alongside the
    host timeline (TPU analogue of the CUDA activity events)."""
    import jax

    return jax.profiler.trace(path)
