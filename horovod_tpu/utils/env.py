"""Shared HOROVOD_* env parsing (one definition of boolean truthiness, so
every knob accepts the same spellings)."""

import os


def env_on(name: str) -> bool:
    return os.environ.get(name, "").lower() in ("1", "true")


def env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default
