"""Autotune sample log (HOROVOD_AUTOTUNE_LOG / --autotune-log).

Reference parity: the parameter manager's CSV sample log
(`horovod/common/parameter_manager.cc` SetAutoTuningLog role) — one line per
scored interval (~10 intervals feed each GP sample) while the tuner is still
exploring, ending with the settling update, so a user can see what the GP
explored and where it settled. Written by whichever component runs the
tuner: the in-process engine (standalone/cluster modes, per-rank suffix in
the uncoordinated multiprocess fallback) or the rank-0 coordinator.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

_lock = threading.Lock()
_header_written: set = set()


def log_sample(path: Optional[str], nbytes: int, seconds: float,
               fusion_threshold: int, cycle_time_ms: float) -> None:
    """Append one CSV sample; creates the file with a header on first use.
    Never raises — a broken log path must not take down training."""
    if not path:
        return
    try:
        with _lock:
            new = path not in _header_written and (
                not os.path.exists(path) or os.path.getsize(path) == 0)
            with open(path, "a") as f:
                if new:
                    f.write("timestamp,bytes,seconds,score_bytes_per_sec,"
                            "fusion_threshold,cycle_time_ms\n")
                score = nbytes / seconds if seconds > 0 else 0.0
                f.write(f"{time.time():.3f},{nbytes},{seconds:.6f},"
                        f"{score:.1f},{fusion_threshold},"
                        f"{cycle_time_ms:.3f}\n")
            _header_written.add(path)
    except OSError:
        pass
