"""JAX version compatibility shims.

The codebase targets the modern jax surface (``jax.shard_map`` with
``check_vma=``, ``jax.typeof``); older installs (<= 0.4.x) only ship
``jax.experimental.shard_map.shard_map`` with the ``check_rep=`` spelling
and no ``jax.typeof``. ``install()`` bridges the gap by publishing the
modern names on the ``jax`` module when absent, so every call site (and
user test code importing ``horovod_tpu`` first) can use one spelling.

Idempotent and a no-op on jax versions that already provide the names.
"""

from __future__ import annotations

import jax


def _shard_map_fallback():
    from jax.experimental.shard_map import shard_map as _sm

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_rep=True, **kwargs):
        # Modern jax spells the replication check ``check_vma``; the
        # experimental API spells it ``check_rep``. Accept both.
        if "check_vma" in kwargs:
            check_rep = bool(kwargs.pop("check_vma"))
        kwargs.pop("axis_names", None)  # modern-only cosmetic kwarg
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check_rep, **kwargs)

    shard_map.__doc__ = _sm.__doc__
    return shard_map


def _typeof_fallback():
    def typeof(x):
        return jax.core.get_aval(x)

    return typeof


def install():
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_fallback()
    if not hasattr(jax, "typeof"):
        jax.typeof = _typeof_fallback()
    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:  # pragma: no cover - pallas always ships with jax
        return
    if not hasattr(pltpu, "CompilerParams"):
        # renamed from TPUCompilerParams in newer jax
        pltpu.CompilerParams = pltpu.TPUCompilerParams


install()
