"""Host-wire executor for elastic jobs.

In elastic mode (``HVD_ELASTIC=1``) ``jax.distributed`` is never initialized:
XLA's cross-process runtime pins the process set at startup and a single dead
worker wedges every collective in it. Instead each process runs single-process
JAX and collective *payloads* ride the coordinator's TCP channel — the same
socket that already carries negotiation — as MSG_DATA frames aggregated per
``(epoch, dseq)`` over the current member set (coordinator.py
``CoordState.data_exchange``).

This trades bandwidth for survivability: the host wire is the pod's DCN-class
control network, not ICI, so elastic mode is for jobs where "keeps training
through a preemption" beats raw step time (docs/elastic.md). Only ALLREDUCE
and BROADCAST are supported — exactly what :class:`~..elastic.state.ElasticState`
sync and gradient averaging need.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..exceptions import HorovodInternalError
from ..runtime.messages import Response, ResponseType, TensorTableEntry


class ElasticExecutor:
    """Executes one Response by shipping the fused buffer over the
    coordinator wire. Interface-compatible with
    :class:`~..runtime.executor.Executor` (``execute`` + wire accounting
    attrs) so the engine is agnostic."""

    def __init__(self, state, controller):
        self._state = state
        self._controller = controller
        # wire accounting the engine reads after execute(); the host wire has
        # no quantized mode, so mode stays "" and autotune scores raw bytes
        self.last_wire_mode: str = ""
        self.last_wire_bytes: int = 0

    def execute(self, response: Response,
                entries_by_rank: Dict[int, List[TensorTableEntry]]):
        rt = response.response_type
        self.last_wire_mode = ""
        self.last_wire_bytes = 0
        if rt not in (ResponseType.ALLREDUCE, ResponseType.BROADCAST):
            raise HorovodInternalError(
                f"{rt.name} is not supported in elastic mode (only allreduce "
                "and broadcast ride the host wire; see docs/elastic.md)")
        self_rank = self._state.rank0
        entries = entries_by_rank.get(self_rank, [])
        by_name = {e.tensor_name: e for e in entries}

        # Build this rank's fused contribution in negotiated name order.
        # A joined rank (no local entry for a name) contributes zeros using
        # the negotiated shape/dtype, exactly like the coordinated
        # multi-controller path (`controller.cc:202-256`).
        dtype = np.dtype(response.tensor_dtype or (
            entries[0].array.dtype if entries else np.float32))
        parts = []
        shapes = []
        for i, name in enumerate(response.tensor_names):
            e = by_name.get(name)
            if e is not None:
                arr = np.asarray(e.array, dtype=dtype)
            elif i < len(response.tensor_shapes):
                arr = np.zeros(response.tensor_shapes[i], dtype=dtype)
            else:
                raise HorovodInternalError(
                    f"elastic executor has no local entry and no negotiated "
                    f"shape for '{name}'")
            shapes.append(arr.shape)
            parts.append(arr.ravel())
        flat = (np.concatenate(parts) if parts
                else np.zeros((0,), dtype=dtype))
        if rt == ResponseType.ALLREDUCE and response.prescale != 1.0:
            flat = flat * dtype.type(response.prescale)

        from ..runtime.messages import RequestType

        op = (int(RequestType.BROADCAST) if rt == ResponseType.BROADCAST
              else int(RequestType.ALLREDUCE))
        combined, nparticipants = self._controller.data_exchange(
            op, response.root_rank, flat)
        # one send + one receive of the fused buffer
        self.last_wire_bytes = 2 * int(flat.size) * dtype.itemsize

        combined = np.asarray(combined, dtype=dtype)
        if rt == ResponseType.ALLREDUCE:
            if response.average and nparticipants > 0:
                combined = combined / dtype.type(nparticipants)
            if response.postscale != 1.0:
                combined = combined * dtype.type(response.postscale)
            combined = combined.astype(dtype, copy=False)

        import jax.numpy as jnp

        outs = []
        off = 0
        for shape in shapes:
            n = int(np.prod(shape, dtype=np.int64)) if shape else 1
            outs.append(jnp.asarray(
                combined[off:off + n].reshape(shape)))
            off += n
        # results keyed by rank, entries in name order — but only for names
        # this rank actually enqueued (joined names produced zeros purely to
        # keep the wire layout identical; they have no handle to complete)
        results: Dict[int, List] = {}
        if entries:
            name_to_out = dict(zip(response.tensor_names, outs))
            results[self_rank] = [name_to_out[e.tensor_name] for e in entries]
        return results
