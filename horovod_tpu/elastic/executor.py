"""Host-wire executor for elastic jobs.

In elastic mode (``HVD_ELASTIC=1``) ``jax.distributed`` is never initialized:
XLA's cross-process runtime pins the process set at startup and a single dead
worker wedges every collective in it. Instead each process runs single-process
JAX and collective *payloads* ride the coordinator's TCP channel — the same
socket that already carries negotiation — as MSG_DATA frames aggregated per
``(epoch, dseq)`` over the current member set (coordinator.py
``CoordState.data_exchange``).

This trades bandwidth for survivability: the host wire is the pod's DCN-class
control network, not ICI, so elastic mode is for jobs where "keeps training
through a preemption" beats raw step time (docs/elastic.md). Only ALLREDUCE
and BROADCAST are supported — exactly what :class:`~..elastic.state.ElasticState`
sync and gradient averaging need.

Straggler-adaptive rounds (runtime/straggler.py): the coordinator may combine
an allreduce over a subgroup that excludes this rank. The DATA_OK reply then
carries the actual contributor list; a sender absent from it keeps its fused
contribution in a per-name error-feedback residual and folds it into the NEXT
round's send, so no gradient mass is silently dropped — the same EF discipline
the quantized wire applies to quantization error (ops/quantize.py).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..exceptions import HorovodInternalError
from ..runtime.messages import Response, ResponseType, TensorTableEntry


class ElasticExecutor:
    """Executes one Response by shipping the fused buffer over the
    coordinator wire. Interface-compatible with
    :class:`~..runtime.executor.Executor` (``execute`` + wire accounting
    attrs) so the engine is agnostic."""

    # the data plane divides by the REAL participant count (DATA_OK carries
    # it), so the engine must not rescale partial averages a second time
    partial_aware = True

    def __init__(self, state, controller):
        self._state = state
        self._controller = controller
        # wire accounting the engine reads after execute(); the host wire has
        # no quantized mode, so mode stays "" and autotune scores raw bytes
        self.last_wire_mode: str = ""
        self.last_wire_bytes: int = 0
        # EF residuals, keyed by tensor name, in WIRE space (post-prescale):
        # a contribution the subgroup round dropped, waiting to fold into
        # this rank's next send of the same tensor
        self._residuals: Dict[str, np.ndarray] = {}

    def residual_mass(self) -> float:
        """Sum of |residual| over all tensors — the EF accounting surface
        the chaos tests (and DistributedOptimizer.straggler_residual_mass)
        assert against: non-zero while excluded, exactly 0.0 after the
        fold-back round lands."""
        return float(sum(float(np.abs(r).sum())
                         for r in self._residuals.values()))

    # ---- checkpoint surface (ckpt/manager.py): EF residuals are part of a
    # rank's shard — dropping them on a restart would silently lose the
    # gradient mass owed back to the job, so a resumed trajectory could
    # never be bit-identical with an uninterrupted one
    def residual_state(self) -> Dict[str, np.ndarray]:
        """Copy of the EF residual buffers for the checkpoint shard."""
        return {k: np.array(v, copy=True)
                for k, v in self._residuals.items()}

    def load_residual_state(self, residuals: Dict[str, np.ndarray]) -> None:
        """Install restored EF residual buffers (replaces any present)."""
        self._residuals = {k: np.asarray(v)
                           for k, v in (residuals or {}).items()}

    def execute(self, response: Response,
                entries_by_rank: Dict[int, List[TensorTableEntry]]):
        rt = response.response_type
        self.last_wire_mode = ""
        self.last_wire_bytes = 0
        if rt not in (ResponseType.ALLREDUCE, ResponseType.BROADCAST):
            raise HorovodInternalError(
                f"{rt.name} is not supported in elastic mode (only allreduce "
                "and broadcast ride the host wire; see docs/elastic.md)")
        self_rank = self._state.rank0
        entries = entries_by_rank.get(self_rank, [])
        by_name = {e.tensor_name: e for e in entries}

        # Build this rank's fused contribution in negotiated name order.
        # A joined rank (no local entry for a name) contributes zeros using
        # the negotiated shape/dtype, exactly like the coordinated
        # multi-controller path (`controller.cc:202-256`).
        dtype = np.dtype(response.tensor_dtype or (
            entries[0].array.dtype if entries else np.float32))
        parts = []
        shapes = []
        for i, name in enumerate(response.tensor_names):
            e = by_name.get(name)
            if e is not None:
                arr = np.asarray(e.array, dtype=dtype)
            elif i < len(response.tensor_shapes):
                arr = np.zeros(response.tensor_shapes[i], dtype=dtype)
            else:
                raise HorovodInternalError(
                    f"elastic executor has no local entry and no negotiated "
                    f"shape for '{name}'")
            shapes.append(arr.shape)
            parts.append(arr.ravel())
        flat = (np.concatenate(parts) if parts
                else np.zeros((0,), dtype=dtype))
        if rt == ResponseType.ALLREDUCE and response.prescale != 1.0:
            flat = flat * dtype.type(response.prescale)
        if rt == ResponseType.ALLREDUCE and self._residuals:
            # EF fold-in: add any residual carried from rounds where this
            # rank's contribution was dropped (same wire space as flat —
            # post-prescale — so the two compose exactly)
            flat = np.array(flat, copy=True)
            off = 0
            for name, shape in zip(response.tensor_names, shapes):
                n = int(np.prod(shape, dtype=np.int64)) if shape else 1
                res = self._residuals.get(name)
                if res is not None and res.size == n:
                    flat[off:off + n] += res.astype(dtype, copy=False)
                off += n

        from ..runtime.messages import RequestType

        op = (int(RequestType.BROADCAST) if rt == ResponseType.BROADCAST
              else int(RequestType.ALLREDUCE))
        combined, nparticipants = self._controller.data_exchange(
            op, response.root_rank, flat)
        # one send + one receive of the fused buffer
        self.last_wire_bytes = 2 * int(flat.size) * dtype.itemsize

        if rt == ResponseType.ALLREDUCE:
            # EF accounting against the ACTUAL contributor list of this
            # round (None = everyone made it in). A sender the combine
            # dropped banks what it sent (entry + any folded residual) for
            # the next round; a sender the combine included starts clean.
            contributors = getattr(self._controller,
                                   "last_data_contributors", None)
            dropped = (contributors is not None
                       and self_rank not in contributors)
            off = 0
            for name, shape in zip(response.tensor_names, shapes):
                n = int(np.prod(shape, dtype=np.int64)) if shape else 1
                if dropped and name in by_name:
                    self._residuals[name] = np.array(flat[off:off + n],
                                                     copy=True)
                else:
                    self._residuals.pop(name, None)
                off += n

        combined = np.asarray(combined, dtype=dtype)
        if rt == ResponseType.ALLREDUCE:
            if response.average and nparticipants > 0:
                combined = combined / dtype.type(nparticipants)
            if response.postscale != 1.0:
                combined = combined * dtype.type(response.postscale)
            combined = combined.astype(dtype, copy=False)

        import jax.numpy as jnp

        outs = []
        off = 0
        for shape in shapes:
            n = int(np.prod(shape, dtype=np.int64)) if shape else 1
            outs.append(jnp.asarray(
                combined[off:off + n].reshape(shape)))
            off += n
        # results keyed by rank, entries in name order — but only for names
        # this rank actually enqueued (joined names produced zeros purely to
        # keep the wire layout identical; they have no handle to complete)
        results: Dict[int, List] = {}
        if entries:
            name_to_out = dict(zip(response.tensor_names, outs))
            results[self_rank] = [name_to_out[e.tensor_name] for e in entries]
        return results
