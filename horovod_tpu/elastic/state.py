"""Elastic training state: commit / restore / sync around membership changes.

Reference parity: `horovod/common/elastic.py` (``State``/``ObjectState``) and
`horovod/torch/elastic.py` — the reference wraps model+optimizer state, commits
a known-good snapshot each N batches, and on ``HorovodInternalError`` restores
the snapshot, re-initializes collectives, and broadcasts state from a surviving
rank before resuming. Here the pytree IS the state container, the reset signal
is :class:`~..exceptions.RanksChangedError`, and the re-broadcast rides
:func:`~..optim.broadcast.broadcast_pytree` over the epoch's surviving member
set (docs/elastic.md).

Typical use::

    import horovod_tpu as hvd

    state = hvd.elastic.ElasticState(params=params, opt_state=opt_state,
                                     step=0)

    @hvd.elastic.run
    def train(state):
        while state.step < total_steps:
            state.params, state.opt_state = train_step(state.params,
                                                       state.opt_state)
            state.step += 1
            state.commit()

    train(state)
"""

from __future__ import annotations

import copy
import functools
import logging

import numpy as np

from ..exceptions import NotInitializedError, RanksChangedError

logger = logging.getLogger("horovod_tpu.elastic")


def _snapshot_leaf(x):
    """Copy a leaf so later in-place mutation can't corrupt the snapshot.
    jax.Arrays are immutable — share them; numpy buffers and python scalars
    get copied."""
    if isinstance(x, np.ndarray):
        return x.copy()
    try:
        import jax

        if isinstance(x, jax.Array):
            return x
    except Exception:
        pass
    return copy.deepcopy(x)


def _copy_tree(tree):
    import jax

    return jax.tree_util.tree_map(_snapshot_leaf, tree)


def _controller():
    """The live engine's controller, or None before init / after shutdown —
    ElasticState must stay usable as a plain local snapshot container in
    single-process code and unit tests."""
    from .. import basics

    try:
        return basics._engine().controller
    except (NotInitializedError, AttributeError):
        return None


class ElasticState:
    """Named slots of training state (each an arbitrary pytree) with
    transactional commit/restore and membership-aware sync.

    Attribute access is the API: ``state.params = ...`` registers/updates a
    slot, ``state.params`` reads it. ``commit()`` snapshots every slot AND
    marks a commit boundary on the control plane (where waiting joiners are
    admitted); ``restore()`` rolls back to the last snapshot; ``sync()``
    re-broadcasts every slot from the lowest surviving rank and commits.
    """

    def __init__(self, **slots):
        object.__setattr__(self, "_values", {})
        object.__setattr__(self, "_committed", {})
        object.__setattr__(self, "_reset_count", 0)
        object.__setattr__(self, "_sharded", set())
        object.__setattr__(self, "_commit_count", 0)
        object.__setattr__(self, "_synced", False)
        object.__setattr__(self, "_in_recovery", False)
        object.__setattr__(self, "_last_commit_t", None)
        for k, v in slots.items():
            self._values[k] = v
        # local-only initial snapshot: a restore() before the first commit()
        # (e.g. a joiner failing mid-first-sync) rolls back to construction
        # values instead of KeyErroring
        self._committed.update(
            {k: _copy_tree(v) for k, v in self._values.items()})

    # ---- attribute protocol: public names are slots
    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return object.__getattribute__(self, "_values")[name]
        except KeyError:
            raise AttributeError(
                f"ElasticState has no slot '{name}'") from None

    def __setattr__(self, name, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self._values[name] = value

    # ---- introspection
    def slots(self):
        return sorted(self._values)

    def mark_sharded(self, *names: str) -> None:
        """Declare slots whose value is RANK-LOCAL (a ZeRO-1 optimizer
        shard, a flat-space partition): ``sync()`` never broadcasts them —
        each rank keeps its own, and a replacement rank restores its slot
        from the checkpoint buddy journal in O(shard)
        (docs/checkpoint.md) instead of an O(model) re-broadcast."""
        for n in names:
            if n not in self._values:
                raise AttributeError(
                    f"ElasticState has no slot '{n}' to mark sharded")
            self._sharded.add(n)

    def sharded_slots(self):
        return sorted(self._sharded)

    @property
    def reset_count(self) -> int:
        """How many membership resets this state has synced through."""
        return self._reset_count

    # ---- transaction API
    def commit(self) -> None:
        """Snapshot every slot and mark a commit boundary on the control
        plane. The boundary is where waiting joiners are admitted: the
        coordinator holds new workers until every current member has
        committed, so admission never lands mid-collective
        (coordinator.py ``_maybe_admit_locked``)."""
        self._committed.clear()
        self._committed.update(
            {k: _copy_tree(v) for k, v in self._values.items()})
        ctrl = _controller()
        fn = getattr(ctrl, "commit", None)
        if fn is not None:
            fn()
        self._commit_count += 1
        import time as _time

        self._last_commit_t = _time.monotonic()
        self._maybe_checkpoint()

    def _ckpt_step(self) -> int:
        """The step a checkpoint of this commit is stamped with: the
        integer ``step`` slot when one exists (the conventional layout),
        else the running commit count."""
        step = self._committed.get("step")
        if isinstance(step, (int, np.integer)):
            return int(step)
        return self._commit_count

    def _maybe_checkpoint(self) -> None:
        import os

        if not os.environ.get("HOROVOD_CKPT_DIR"):
            return  # subsystem off: commit() behaves exactly as before
        from .. import ckpt

        mgr = ckpt.ensure_manager()
        if mgr is not None:
            mgr.on_state_commit(self, self._ckpt_step())

    def restore(self) -> None:
        """Roll every slot back to the last committed snapshot (the partial
        step that raised is discarded — its collectives may have completed on
        a subset of ranks)."""
        self._values.clear()
        self._values.update(
            {k: _copy_tree(v) for k, v in self._committed.items()})

    def sync(self, root_rank=None) -> None:
        """Re-align all ranks: clear the controller's reset latch, broadcast
        every slot from ``root_rank`` (default: the lowest surviving rank) to
        everyone — joiners receive the committed state, survivors confirm it
        — then commit the agreed snapshot.

        Slots marked via :meth:`mark_sharded` are rank-local and never ride
        the broadcast: survivors keep their own restored values, and a fresh
        process (a promoted spare, a whole-job restart) pulls its slot from
        the checkpoint buddy journal or the latest complete disk bundle
        before the replicated broadcast runs — O(shard) bytes, not
        O(model) (docs/checkpoint.md)."""
        import os

        from ..goodput import ledger as _goodput
        from ..optim.broadcast import broadcast_pytree

        led = _goodput.active()
        # a sync after a membership reset is recovery time; the ordinary
        # first sync of a stable job is a (short) stall
        span = None
        if led is not None:
            span = led.begin(
                "recovery" if self._in_recovery else "stall")
        try:
            ctrl = _controller()
            resume = getattr(ctrl, "resume", None)
            if resume is not None:
                resume()
            if root_rank is None:
                members = getattr(ctrl, "members", None)
                root_rank = min(members()) if members is not None else 0
            sharded = self._sharded & set(self._values)
            if (sharded and not self._synced
                    and os.environ.get("HOROVOD_CKPT_DIR")):
                from .. import ckpt

                mgr = ckpt.ensure_manager()
                if mgr is not None:
                    mgr.restore_sharded_slots(self)
            for key in sorted(self._values):
                if key in sharded:
                    continue
                self._values[key] = broadcast_pytree(
                    self._values[key], root_rank=root_rank,
                    prefix=f"elastic_sync/{key}")
            self._synced = True
            self.commit()
            self._in_recovery = False
        finally:
            if led is not None:
                led.end(span)


def _note_lost_work(state) -> None:
    """Charge the work discarded by a reset to the goodput ledger: the wall
    time since the last commit is exactly the partial progress restore()
    throws away (lost-steps x step-time without needing a step clock). The
    entry is *synthetic* — counter-only, outside the rank's wall-clock
    budget — because those seconds were already attributed live as compute/
    comm while they happened (docs/goodput.md)."""
    import time as _time

    from ..goodput import ledger as _goodput

    led = _goodput.active()
    t = state._last_commit_t
    if led is None or t is None:
        return
    lost = _time.monotonic() - t
    if lost > 0:
        led.add("recovery", lost, synthetic=True)


def run_fn(func):
    """Wrap a training function taking ``(state, *args, **kwargs)`` in the
    elastic retry loop: sync state across the current members, run, and on
    :class:`~..exceptions.RanksChangedError` (worker lost or joined) restore
    the last commit and go again under the new membership epoch. Everything
    the function must not lose across a reset belongs in ``state``."""

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        while True:
            try:
                # sync() is inside the retry: a fresh joiner's very first
                # sync raises RanksChangedError when its admission bumps
                # the epoch, and a second membership change can land while
                # a previous reset is still re-syncing
                state.sync()
                return func(state, *args, **kwargs)
            except RanksChangedError as exc:
                state._reset_count += 1
                state._in_recovery = True
                logger.warning(
                    "elastic reset #%d (%s): restoring last commit and "
                    "re-syncing", state.reset_count, exc)
                _note_lost_work(state)
                state.restore()

    return wrapper


# decorator alias mirroring the reference's ``@hvd.elastic.run``
run = run_fn
