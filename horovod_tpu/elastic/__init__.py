"""Elastic training: survive worker loss, absorb worker arrival, no restart.

See docs/elastic.md. Public surface:

- :class:`ElasticState` — commit/restore/sync wrapper around training pytrees
- :func:`run_fn` (alias :func:`run`) — retry-loop decorator catching
  membership resets
- :class:`~.executor.ElasticExecutor` — internal: host-wire data plane the
  engine installs when ``HVD_ELASTIC=1``
"""

from .state import ElasticState, run, run_fn  # noqa: F401
