"""Elastic training: survive worker loss, absorb worker arrival, no restart.

See docs/elastic.md. Public surface:

- :class:`ElasticState` — commit/restore/sync wrapper around training pytrees
- :func:`run_fn` (alias :func:`run`) — retry-loop decorator catching
  membership resets
- :class:`~.executor.ElasticExecutor` — internal: host-wire data plane the
  engine installs when ``HVD_ELASTIC=1``

Interplay with control-plane fault tolerance (docs/fault-tolerance.md): a
dropped worker connection no longer reaches ``rank_lost`` directly. The
worker first gets ``HOROVOD_RECONNECT_GRACE`` seconds to reconnect and
replay its in-flight exchange (transparent recovery — no membership reset,
no epoch bump). Only when the grace window expires, or when heartbeats go
silent past ``HOROVOD_HEARTBEAT_TIMEOUT``, does the coordinator feed the
rank into the elastic ``rank_lost`` path and the machinery in this package
takes over: epoch bump, barrier release with RANKS_CHANGED, re-rendezvous,
state re-sync. Transient network blips therefore cost a reconnect instead
of a full membership reset.

Interplay with checkpointing (docs/checkpoint.md): with ``HOROVOD_CKPT_DIR``
set, every ``ElasticState.commit()`` doubles as the checkpoint boundary —
the async bundle writer snapshots this rank's shard off the step path, and
slots declared via :meth:`ElasticState.mark_sharded` (rank-local ZeRO-1
state, EF residuals) are journaled to the ring-successor buddy so a
replacement worker resumes the bit-identical trajectory from an O(shard)
peer transfer instead of an O(model) broadcast.
"""

from .state import ElasticState, run, run_fn  # noqa: F401
