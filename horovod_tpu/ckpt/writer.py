"""Async shard writer: host-memory double buffer + off-path writer thread.

The step path pays only for handing a snapshot over (a buffer swap under a
lock — ``hvd_checkpoint_stall_seconds`` measures exactly that hand-off and
must stay ~0); the writer thread owns every byte of disk I/O. Double
buffering means at most one snapshot is in flight and one pending: a new
snapshot arriving while the writer is busy REPLACES the pending one (the
freshest commit wins — trickling a stale snapshot to disk after a newer
one exists would only age the bundle).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from .. import blackbox as _blackbox
from ..metrics import instruments
from . import bundle

logger = logging.getLogger("horovod_tpu.ckpt")


class AsyncShardWriter:
    """Trickles (step, epoch, shard bytes[, replica bytes]) snapshots to
    ``root/step_{s}/rank_{index}.shard`` off the step path. The shard
    index rides each submit (not the constructor): a rank's slot in the
    sorted member list changes across membership epochs, and the writer
    thread must land the file under the slot current at snapshot time.

    ``on_written(step, epoch, index, nbytes, crc)`` fires from the writer
    thread after the shard file (and replica blob, when given) landed —
    the hook the manager uses to send MSG_CKPT_DONE and push the buddy
    journal.
    """

    def __init__(self, root: str, on_written: Optional[Callable] = None,
                 rank: int = 0):
        self.root = root
        self.rank = rank
        self.on_written = on_written
        self._cv = threading.Condition()
        self._pending = None       # (step, epoch, index, shard, replica)
        self._busy = False
        self._stop = False
        self.dropped = 0           # pending snapshots replaced before write
        self.written_steps = 0
        self._thread = threading.Thread(target=self._run,
                                        name="hvd_ckpt_writer", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ step path
    def submit(self, step: int, epoch: int, index: int, shard: bytes,
               replica: Optional[bytes] = None) -> float:
        """Hand a committed snapshot to the writer. Never blocks on I/O;
        returns the seconds the step path spent inside (accounted into
        ``hvd_checkpoint_stall_seconds``)."""
        t0 = time.perf_counter()
        with self._cv:
            if self._pending is not None:
                self.dropped += 1
            self._pending = (step, epoch, index, shard, replica)
            self._cv.notify()
        stall = time.perf_counter() - t0
        instruments.checkpoint_stall_seconds().inc(stall)
        return stall

    # -------------------------------------------------------- writer thread
    def _run(self) -> None:
        while True:
            with self._cv:
                while self._pending is None and not self._stop:
                    self._cv.wait()
                if self._stop and self._pending is None:
                    return
                step, epoch, index, shard, replica = self._pending
                self._pending = None
                self._busy = True
            try:
                t0 = time.perf_counter()
                nbytes, crc = bundle.write_shard(self.root, step,
                                                 index, shard)
                total = nbytes
                if replica is not None:
                    rn, _rcrc = bundle.write_replica(self.root, step,
                                                     replica)
                    total += rn
                instruments.checkpoint_bytes().labels(kind="disk").inc(
                    total)
                bb = _blackbox.active()
                if bb is not None:
                    bb.record(_blackbox.K_CKPT, "snapshot",
                              "step=%d epoch=%d index=%d nbytes=%d "
                              "write_s=%.4f" % (step, epoch, index,
                                                total,
                                                time.perf_counter() - t0),
                              self.rank)
                self.written_steps += 1
                if self.on_written is not None:
                    self.on_written(step, epoch, index, nbytes, crc)
            except Exception:
                logger.warning("ckpt writer: shard write for step %d "
                               "failed", step, exc_info=True)
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    # ------------------------------------------------------------ lifecycle
    def drain(self, timeout: float = 30.0) -> bool:
        """Block until nothing is pending or in flight (tests, shutdown)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending is not None or self._busy:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
        return True

    def stop(self, drain: bool = True) -> None:
        if drain:
            self.drain()
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=5)
