"""Peer-redundant shard journaling over the standby replication framing.

Each rank journals its committed shard to the NEXT member on the ring
(rank at position ``(index + 1) % world``), so every shard exists twice:
once on its owner, once in its buddy's host memory. A lost rank's
hot-spare replacement then restores from the buddy in O(shard) — no
checkpoint read off disk, no O(model) re-broadcast from a survivor.

The stream reuses the hardened control-plane framing and the standby
replication frame types (``MSG_REPL_HELLO`` / ``MSG_SNAPSHOT`` /
``MSG_JOURNAL`` / ``MSG_BYE``, runtime/standby.py): the hello payload
names the role — ``push:{index}`` from the shard's owner, ``fetch:{index}``
from a replacement restoring it. After the first full-shard SNAPSHOT the
owner ships only JOURNAL deltas: the fixed-size blocks whose bytes changed
since the last push, which keeps steady-state journal traffic proportional
to what the optimizer actually touched, not to the shard.
"""

from __future__ import annotations

import logging
import socket
import threading
from typing import Callable, Dict, List, Optional, Tuple

from .. import blackbox as _blackbox
from ..exceptions import ShutdownError
from ..metrics import instruments
from ..runtime import wire
from ..runtime.coordinator import (MSG_BYE, MSG_JOURNAL, MSG_REPL_HELLO,
                                   MSG_SNAPSHOT)

logger = logging.getLogger("horovod_tpu.ckpt")

#: delta granularity: a journal block is shipped iff any byte in it changed
DELTA_BLOCK = 64 << 10


def shard_delta(prev: Optional[bytes], cur: bytes,
                block: int = DELTA_BLOCK) -> List[Tuple[int, bytes]]:
    """The ``(offset, bytes)`` blocks of ``cur`` that differ from ``prev``.
    A length change (or no prior push) degenerates to one whole-shard
    block — correctness never depends on the delta being small."""
    if prev is None or len(prev) != len(cur):
        return [(0, cur)]
    out = []
    for off in range(0, len(cur), block):
        a, b = prev[off:off + block], cur[off:off + block]
        if a != b:
            out.append((off, b))
    return out


def apply_delta(prev: Optional[bytes], total_len: int,
                blocks: List[Tuple[int, bytes]]) -> bytes:
    """Patch ``blocks`` over ``prev`` into a ``total_len``-byte shard."""
    buf = bytearray(prev if prev is not None and len(prev) == total_len
                    else total_len)
    for off, data in blocks:
        buf[off:off + len(data)] = data
    return bytes(buf)


class BuddyServer:
    """Holds the journaled shards pushed by this rank's ring predecessors
    and serves them to fetching replacements. One daemon accept thread;
    one thread per stream, mirroring CoordinatorServer's replication
    shipper."""

    def __init__(self, secret: str, rank: int = 0, host: str = "0.0.0.0"):
        self.secret = secret
        self.rank = rank
        #: fires once per shard index on the FIRST bytes journaled here —
        #: the manager's cue to advertise this host as that shard's
        #: restore source
        self.on_hold: Optional[Callable[[int], None]] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # shard index -> (journal head step, shard bytes)
        self._shards: Dict[int, Tuple[int, bytes]] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(16)
        self._sock.settimeout(0.5)
        self.port = self._sock.getsockname()[1]
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="hvd_ckpt_buddy", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- inventory
    def head(self, index: int) -> Optional[int]:
        """Journal-head step held for shard ``index`` (None = nothing)."""
        with self._lock:
            ent = self._shards.get(index)
            return ent[0] if ent else None

    def get(self, index: int) -> Optional[Tuple[int, bytes]]:
        with self._lock:
            return self._shards.get(index)

    def put(self, index: int, step: int, data: bytes) -> None:
        with self._lock:
            fresh = index not in self._shards
            self._shards[index] = (step, data)
        if fresh and self.on_hold is not None:
            try:
                self.on_hold(index)
            except Exception:
                logger.debug("ckpt buddy: on_hold(%d) failed", index,
                             exc_info=True)

    # ----------------------------------------------------------------- serve
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(0.5)
            threading.Thread(target=self._serve, args=(conn,),
                             name="hvd_ckpt_buddy_conn",
                             daemon=True).start()

    def _serve(self, conn) -> None:
        try:
            mt, _, peer, payload = wire.recv_frame(conn, self.secret,
                                                   self._stop)
            if mt != MSG_REPL_HELLO:
                return
            role, _, idx = payload.decode("utf-8", "replace").partition(":")
            index = int(idx)
            if role == "fetch":
                self._serve_fetch(conn, peer, index)
            elif role == "push":
                self._serve_push(conn, peer, index)
        except (ShutdownError, ConnectionError, OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_fetch(self, conn, peer: int, index: int) -> None:
        ent = self.get(index)
        if ent is None:
            # nothing journaled for that slot: BYE = "restore elsewhere"
            wire.send_frame(conn, self.secret, MSG_BYE, 0, self.rank)
            return
        step, data = ent
        wire.send_frame(conn, self.secret, MSG_SNAPSHOT, 0, self.rank,
                        wire.encode_shard_snapshot(index, step, data))
        bb = _blackbox.active()
        if bb is not None:
            bb.record(_blackbox.K_CKPT, "peer_serve",
                      "index=%d step=%d nbytes=%d -> rank %d"
                      % (index, step, len(data), peer), self.rank)

    def _serve_push(self, conn, peer: int, index: int) -> None:
        while not self._stop.is_set():
            mt, _, _, payload = wire.recv_frame(conn, self.secret,
                                                self._stop)
            if mt == MSG_BYE:
                return
            if mt == MSG_SNAPSHOT:
                idx, step, data = wire.decode_shard_snapshot(payload)
                self.put(idx, step, data)
            elif mt == MSG_JOURNAL:
                idx, step, total, blocks = wire.decode_shard_journal(
                    payload)
                with self._lock:
                    prev = self._shards.get(idx)
                    self._shards[idx] = (step, apply_delta(
                        prev[1] if prev else None, total, blocks))

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class BuddyClient:
    """The shard owner's journaling stream to its ring successor. Lazy
    dial; a push failure tears the stream down and the next push re-dials
    and resends a full snapshot (the buddy may have restarted with empty
    memory — deltas only ride a stream that began with a snapshot)."""

    def __init__(self, addr: Tuple[str, int], secret: str, index: int,
                 rank: int = 0):
        self.addr = addr
        self.secret = secret
        self.index = index
        self.rank = rank
        self._sock: Optional[socket.socket] = None
        self._last: Optional[bytes] = None
        self.pushed_bytes = 0

    def _dial(self) -> None:
        from ..runtime.standby import dial_repl

        self._sock = dial_repl(self.addr, self.secret, self.rank,
                               ("push:%d" % self.index).encode())
        self._last = None

    def push(self, step: int, data: bytes) -> int:
        """Journal the committed shard; returns payload bytes shipped.
        Raises ConnectionError/OSError after one redial attempt fails —
        the caller treats the buddy as gone and relies on disk."""
        for attempt in (0, 1):
            try:
                if self._sock is None:
                    self._dial()
                if self._last is None:
                    payload = wire.encode_shard_snapshot(self.index, step,
                                                         data)
                    wire.send_frame(self._sock, self.secret, MSG_SNAPSHOT,
                                    0, self.rank, payload)
                else:
                    blocks = shard_delta(self._last, data)
                    payload = wire.encode_shard_journal(
                        self.index, step, len(data), blocks)
                    wire.send_frame(self._sock, self.secret, MSG_JOURNAL,
                                    0, self.rank, payload)
                self._last = data
                n = len(payload)
                self.pushed_bytes += n
                instruments.checkpoint_bytes().labels(kind="peer").inc(n)
                return n
            except (ConnectionError, OSError):
                self.close()
                if attempt:
                    raise
        raise ConnectionError("unreachable")  # pragma: no cover

    def close(self) -> None:
        if self._sock is not None:
            try:
                wire.send_frame(self._sock, self.secret, MSG_BYE, 0,
                                self.rank)
            except (ConnectionError, OSError):
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._last = None


def fetch_shard(addr: Tuple[str, int], secret: str, index: int,
                rank: int = 0,
                timeout: float = 5.0) -> Optional[Tuple[int, bytes]]:
    """One-shot restore: dial a buddy and fetch shard ``index``. Returns
    (journal head step, shard bytes), or None when the buddy holds
    nothing for that slot."""
    from ..runtime.standby import dial_repl

    stop = threading.Event()
    sock = dial_repl(addr, secret, rank, ("fetch:%d" % index).encode(),
                     timeout=timeout)
    try:
        mt, _, _, payload = wire.recv_frame(sock, secret, stop)
        if mt != MSG_SNAPSHOT:
            return None
        _, step, data = wire.decode_shard_snapshot(payload)
        return step, data
    finally:
        try:
            sock.close()
        except OSError:
            pass
