"""On-disk bundle format for async sharded checkpoints (docs/checkpoint.md).

A bundle is one directory per checkpointed step::

    HOROVOD_CKPT_DIR/
      step_000120/
        rank_0.shard          # shard slot 0's bytes
        rank_1.shard
        replica.blob          # replicated slots (written by slot 0 only)
        manifest.json         # written LAST, atomically — the commit record

The manifest is the bundle's commit record: it is renamed into place
(temp file + ``os.replace``, the same convention as ``checkpoint.py``)
only after every member shard of the SAME step has landed, so a crash at
any earlier point leaves a ``step_*`` directory without a manifest — an
incomplete bundle that restore ignores. The previous complete bundle
stays authoritative; no reader can ever observe a half-written one.

Shard files themselves are also written via temp-file + rename, so a
partially-flushed shard never carries the final name. Every row in the
manifest records the shard's byte length and CRC32; readers verify both.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
import zlib
from typing import Dict, List, Optional, Tuple

MANIFEST = "manifest.json"
REPLICA = "replica.blob"
SCHEMA_VERSION = 1

_STEP_RE = re.compile(r"^step_(\d+)$")


def atomic_write_bytes(path: str, data: bytes) -> int:
    """Write ``data`` at ``path`` atomically (temp file in the same
    directory + ``os.replace``) — the one code path every checkpoint
    write in the tree goes through. Returns bytes written."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".ckpt_tmp_")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return len(data)


def step_dir(root: str, step: int) -> str:
    return os.path.join(root, "step_%06d" % step)


def shard_path(root: str, step: int, index: int) -> str:
    return os.path.join(step_dir(root, step), "rank_%d.shard" % index)


def replica_path(root: str, step: int) -> str:
    return os.path.join(step_dir(root, step), REPLICA)


def write_shard(root: str, step: int, index: int,
                data: bytes) -> Tuple[int, int]:
    """Land one shard file (atomic). Returns (nbytes, crc32)."""
    atomic_write_bytes(shard_path(root, step, index), data)
    return len(data), zlib.crc32(data) & 0xFFFFFFFF


def write_replica(root: str, step: int, data: bytes) -> Tuple[int, int]:
    """Land the replicated-slots blob (written by shard slot 0 only)."""
    atomic_write_bytes(replica_path(root, step), data)
    return len(data), zlib.crc32(data) & 0xFFFFFFFF


def finalize_manifest(root: str, step: int, epoch: int,
                      shards: Dict[int, dict],
                      replica: Optional[dict] = None,
                      total_len: Optional[int] = None) -> str:
    """Write the bundle's commit record — call ONLY once every member
    shard of ``step`` has landed. ``shards`` maps shard index ->
    ``{"nbytes": int, "crc": int}``. Atomic rename, so a crash mid-write
    leaves the previous complete bundle authoritative."""
    doc = {
        "schema": SCHEMA_VERSION,
        "step": int(step),
        "epoch": int(epoch),
        "world": len(shards),
        "shards": {str(i): {"file": "rank_%d.shard" % i,
                            "nbytes": int(info["nbytes"]),
                            "crc": int(info["crc"])}
                   for i, info in shards.items()},
    }
    if replica is not None:
        doc["replica"] = {"file": REPLICA,
                          "nbytes": int(replica["nbytes"]),
                          "crc": int(replica["crc"])}
    if total_len is not None:
        doc["total_len"] = int(total_len)
    path = os.path.join(step_dir(root, step), MANIFEST)
    atomic_write_bytes(path, json.dumps(doc, sort_keys=True,
                                        indent=1).encode())
    return path


def read_manifest(root: str, step: int) -> Optional[dict]:
    """The bundle's manifest, or None when absent/corrupt (an incomplete
    bundle — a crash beat the rename; restore must skip it)."""
    try:
        with open(os.path.join(step_dir(root, step), MANIFEST), "rb") as f:
            doc = json.loads(f.read())
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("schema", 0) > SCHEMA_VERSION:
        return None
    return doc


def _bundle_complete(root: str, step: int, doc: dict) -> bool:
    d = step_dir(root, step)
    entries: List[dict] = list((doc.get("shards") or {}).values())
    if doc.get("replica"):
        entries.append(doc["replica"])
    for info in entries:
        p = os.path.join(d, info.get("file", ""))
        try:
            if os.path.getsize(p) != int(info.get("nbytes", -1)):
                return False
        except OSError:
            return False
    return True


def complete_steps(root: str) -> List[int]:
    """Steps with a finalized manifest AND every listed file present at
    its recorded size, ascending."""
    try:
        names = os.listdir(root)
    except OSError:
        return []
    out = []
    for name in names:
        m = _STEP_RE.match(name)
        if not m:
            continue
        step = int(m.group(1))
        doc = read_manifest(root, step)
        if doc is not None and _bundle_complete(root, step, doc):
            out.append(step)
    return sorted(out)


def latest_complete_step(root: str) -> Optional[int]:
    steps = complete_steps(root)
    return steps[-1] if steps else None


def read_shard(root: str, step: int, index: int,
               verify: bool = True) -> bytes:
    with open(shard_path(root, step, index), "rb") as f:
        data = f.read()
    if verify:
        doc = read_manifest(root, step) or {}
        info = (doc.get("shards") or {}).get(str(index))
        if info is not None and (zlib.crc32(data) & 0xFFFFFFFF
                                 != int(info["crc"])):
            raise IOError("checkpoint shard %s (step %d) fails its "
                          "manifest CRC" % (index, step))
    return data


def read_replica(root: str, step: int, verify: bool = True) -> bytes:
    with open(replica_path(root, step), "rb") as f:
        data = f.read()
    if verify:
        doc = read_manifest(root, step) or {}
        info = doc.get("replica")
        if info is not None and (zlib.crc32(data) & 0xFFFFFFFF
                                 != int(info["crc"])):
            raise IOError("checkpoint replica blob (step %d) fails its "
                          "manifest CRC" % step)
    return data


def read_bundle_bytes(root: str, step: int) -> bytes:
    """Concatenate every shard of a byte-partitioned bundle in slot order
    and trim to the manifest's ``total_len`` (the full serialized state
    under plain data parallelism)."""
    doc = read_manifest(root, step)
    if doc is None:
        raise FileNotFoundError(
            "no complete checkpoint bundle for step %d under %s"
            % (step, root))
    blob = b"".join(read_shard(root, step, i)
                    for i in sorted(int(k) for k in doc["shards"]))
    total = doc.get("total_len")
    return blob[:total] if total is not None else blob


def prune_bundles(root: str, keep: int = 2) -> List[int]:
    """Drop the oldest complete bundles beyond ``keep``, plus any
    incomplete ``step_*`` directory older than the newest complete bundle
    (debris from a crash mid-write). Returns the steps removed."""
    steps = complete_steps(root)
    removed = []
    for step in steps[:-keep] if keep > 0 else steps:
        shutil.rmtree(step_dir(root, step), ignore_errors=True)
        removed.append(step)
    latest = steps[-1] if steps else None
    try:
        names = os.listdir(root)
    except OSError:
        return removed
    for name in names:
        m = _STEP_RE.match(name)
        if not m:
            continue
        step = int(m.group(1))
        if (latest is not None and step < latest
                and read_manifest(root, step) is None):
            shutil.rmtree(step_dir(root, step), ignore_errors=True)
            removed.append(step)
    return sorted(set(removed))
