"""Per-rank checkpoint orchestrator (docs/checkpoint.md).

``CkptManager`` hangs off the commit boundary: when ``HOROVOD_CKPT_DIR``
is set, every ``ElasticState.commit()`` that crosses the configured step
interval packs this rank's shard — its sharded slots plus the elastic
executor's error-feedback residuals, or its byte-partition chunk of the
full replica when no slot is marked sharded — into host memory, hands it
to the :class:`~.writer.AsyncShardWriter`, and announces
``MSG_CKPT_MARK`` to the coordinator. Off the step path the writer lands
the shard file, reports ``MSG_CKPT_DONE`` (the coordinator finalizes the
bundle manifest once every member shard of the same step landed), and
journals the shard to the ring successor's :class:`~.buddy.BuddyServer`.

Knobs: ``HOROVOD_CKPT_DIR`` (bundle root; unset = the whole subsystem is
off and no new wire frames exist), ``HOROVOD_CKPT_INTERVAL`` (commit
steps between snapshots, default 10), ``HOROVOD_CKPT_BUDDY`` (peer
journaling on/off, default on), ``HOROVOD_CKPT_KEEP`` (complete bundles
retained, default 2).
"""

from __future__ import annotations

import logging
import os
import threading
import time
import zlib
from typing import Any, Dict, Optional, Tuple

from flax import serialization

from .. import blackbox as _blackbox
from ..metrics import instruments
from . import buddy as buddy_mod
from . import bundle
from .writer import AsyncShardWriter

logger = logging.getLogger("horovod_tpu.ckpt")

_mgr: Optional["CkptManager"] = None
_mgr_lock = threading.Lock()


# ------------------------------------------------------------------- knobs
def ckpt_dir() -> Optional[str]:
    return os.environ.get("HOROVOD_CKPT_DIR") or None


def ckpt_interval() -> int:
    try:
        return max(1, int(os.environ.get("HOROVOD_CKPT_INTERVAL", "10")))
    except ValueError:
        return 10


def buddy_enabled() -> bool:
    return os.environ.get("HOROVOD_CKPT_BUDDY", "1") not in (
        "0", "false", "False", "off")


def _keep_bundles() -> int:
    try:
        return max(1, int(os.environ.get("HOROVOD_CKPT_KEEP", "2")))
    except ValueError:
        return 2


# -------------------------------------------------------------- blob packing
def pack_tree(tree: Any) -> bytes:
    """Template-free serialization (msgpack): restore needs no structure
    handed in, so a replacement process can unpack a buddy's journal head
    before it has built any state of its own."""
    import jax

    return serialization.msgpack_serialize(
        jax.tree_util.tree_map(lambda x: x, jax.device_get(tree)))


def unpack_tree(data: bytes) -> Any:
    return serialization.msgpack_restore(data)


def partition_bounds(total: int, world: int, index: int) -> Tuple[int, int]:
    """Byte bounds of shard ``index`` when a full replica is partitioned
    1/N (plain-DP mode) — ``optim.zero.shard_bounds`` with a 1-byte block:
    exact slices, so concatenation in slot order reassembles the blob
    byte-for-byte."""
    from ..optim.zero import shard_bounds

    return shard_bounds(total, max(1, world), index, block=1)


class CkptManager:
    """One per process. Thread-safety: ``on_state_commit`` runs on the
    training thread; ``_on_written`` runs on the writer thread; the buddy
    server threads only touch their own store."""

    def __init__(self, root: str, rank: int, world: int,
                 controller=None, interval: Optional[int] = None,
                 buddy: Optional[bool] = None, secret: str = ""):
        self.root = root
        self.rank = rank
        self.world = max(1, world)
        self.controller = controller
        self.interval = interval if interval is not None else ckpt_interval()
        self.secret = secret or os.environ.get("HVD_SECRET", "")
        self._buddy_on = buddy if buddy is not None else buddy_enabled()
        self._lock = threading.Lock()
        self._last_snap_step = -1
        self._last_done_step = -1
        self.last_restore: Optional[dict] = None  # forensics for tests
        self.writer = AsyncShardWriter(root, on_written=self._on_written,
                                       rank=rank)
        # journal receiver for my ring predecessor's shard
        self.buddy_server: Optional[buddy_mod.BuddyServer] = None
        self._buddy_client: Optional[buddy_mod.BuddyClient] = None
        # after a failed push, skip buddy traffic for a few seconds: the
        # push is synchronous with commit, and paying a resolve/dial
        # timeout on every step while the successor is down would turn a
        # redundancy feature into a straggler
        self._push_retry_at = 0.0
        if self._buddy_on:
            advertise, bind = self._addresses()
            self.buddy_server = buddy_mod.BuddyServer(self.secret,
                                                      rank=rank, host=bind)
            self.buddy_server.on_hold = self._publish_held_shard
            self._publish("ckpt.buddy.%d" % rank,
                          "%s:%d" % (advertise, self.buddy_server.port))
        # rank 0 hosts the coordinator state machine: point its finalize
        # hook at the bundle writer so the manifest lands exactly when the
        # last member DONE arrives
        state = getattr(controller, "_state", None)
        if state is not None:
            state.on_ckpt_finalize = self._finalize_bundle

    # ------------------------------------------------------------ addressing
    @staticmethod
    def _addresses() -> Tuple[str, str]:
        from ..runtime.coordinator import _advertise_host

        advertise = _advertise_host()
        return advertise, ("127.0.0.1" if advertise == "127.0.0.1"
                           else "0.0.0.0")

    def _publish(self, key: str, addr: str) -> None:
        from ..runtime.coordinator import _publish_key, has_address_channel

        if not has_address_channel():
            return
        try:
            _publish_key(key, addr, self.secret)
        except Exception:
            logger.debug("ckpt: publish %s failed", key, exc_info=True)

    def _publish_held_shard(self, index: int) -> None:
        """A predecessor started journaling shard ``index`` here: advertise
        this host as its restore source for a future replacement."""
        if self.buddy_server is not None:
            advertise, _ = self._addresses()
            self._publish("ckpt.shard.%d" % index,
                          "%s:%d" % (advertise, self.buddy_server.port))

    @staticmethod
    def _resolve(key: str, timeout: float) -> Optional[Tuple[str, int]]:
        from ..runtime.coordinator import _resolve_key, has_address_channel

        if not has_address_channel():
            return None
        try:
            addr, _secret = _resolve_key(key, timeout)
            host, _, port = addr.rpartition(":")
            return host, int(port)
        except Exception:
            return None

    # ------------------------------------------------------------ membership
    def _membership(self) -> Tuple[list, int]:
        ctrl = self.controller
        if ctrl is not None:
            try:
                return sorted(ctrl.members()), ctrl.epoch()
            except Exception:
                pass
        return list(range(self.world)), 0

    def shard_index(self) -> int:
        members, _ = self._membership()
        try:
            return members.index(self.rank)
        except ValueError:
            return self.rank

    # ------------------------------------------------------- commit boundary
    def on_state_commit(self, state, step: int) -> bool:
        """Called from ``ElasticState.commit()``. Returns True when a disk
        snapshot was taken (interval due).

        Sharded mode (``state.mark_sharded`` used): the buddy journal is
        pushed SYNCHRONOUSLY on every commit — the journal is part of the
        commit transaction, so a rank's journal head never lags its last
        commit and a replacement's restore is bit-identical with the
        survivors' restored snapshots. Disk snapshots stay interval-gated
        and fully async. Plain DP: every rank already holds the full
        replica (a lost rank costs nothing unique), so both the
        byte-partition disk shard and the buddy push are interval-gated."""
        from ..goodput import ledger as _goodput

        led = _goodput.active()
        span = led.begin("checkpoint") if led is not None else None
        try:
            return self._on_state_commit(state, step)
        finally:
            if led is not None:
                led.end(span)

    def _on_state_commit(self, state, step: int) -> bool:
        members, epoch = self._membership()
        if self.rank not in members:
            return False
        index = members.index(self.rank)
        sharded = sorted(getattr(state, "_sharded", ()) or ())
        committed = dict(getattr(state, "_committed", {}) or {})
        due = (self._last_snap_step < 0
               or step - self._last_snap_step >= self.interval)
        if sharded:
            shard_tree: Dict[str, Any] = {
                "slots": {k: committed[k] for k in sharded
                          if k in committed},
                "ef": self._ef_snapshot(),
            }
            shard = pack_tree(shard_tree)
            if due:
                replica = None
                if index == 0:
                    repl = {k: v for k, v in committed.items()
                            if k not in shard_tree["slots"]}
                    replica = pack_tree({"slots": repl})
                self.snapshot(step, epoch, index, shard, replica)
            if self._buddy_on:
                self._push_buddy(step, index, shard)
            return due
        if not due:
            return False
        # plain DP: shard = this slot's exact byte-partition chunk of the
        # serialized state, so the union of shards IS the checkpoint and
        # no rank writes O(model) bytes
        blob = pack_tree({"slots": committed, "ef": self._ef_snapshot()})
        lo, hi = partition_bounds(len(blob), len(members), index)
        shard = blob[lo:hi]
        self.snapshot(step, epoch, index, shard, None)
        if self._buddy_on:
            self._push_buddy(step, index, shard)
        return True

    def snapshot(self, step: int, epoch: int, index: int, shard: bytes,
                 replica: Optional[bytes] = None) -> None:
        """Double-buffer one shard snapshot and announce MSG_CKPT_MARK."""
        with self._lock:
            self._last_snap_step = step
        self.writer.submit(step, epoch, index, shard, replica)
        ctrl = self.controller
        if ctrl is not None and hasattr(ctrl, "send_ckpt_mark"):
            ctrl.send_ckpt_mark(step, epoch, index)
        age = step - self._last_done_step if self._last_done_step >= 0 \
            else 0
        instruments.ckpt_bundle_age_steps().set(age)

    # ----------------------------------------------------- writer completion
    def _on_written(self, step: int, epoch: int, index: int, nbytes: int,
                    crc: int) -> None:
        ctrl = self.controller
        if ctrl is not None and hasattr(ctrl, "send_ckpt_done"):
            ctrl.send_ckpt_done(step, epoch, index, nbytes, crc)
        elif self.world == 1:
            self._finalize_bundle(step, epoch,
                                  {index: {"nbytes": nbytes, "crc": crc}})

    def _push_buddy(self, step: int, index: int, shard: bytes) -> None:
        members, _ = self._membership()
        if len(members) < 2 or time.monotonic() < self._push_retry_at:
            return
        succ = members[(members.index(self.rank) + 1) % len(members)] \
            if self.rank in members else None
        if succ is None:
            return
        client = self._buddy_client
        if client is None or client.index != index:
            addr = self._resolve("ckpt.buddy.%d" % succ, timeout=2.0)
            if addr is None:
                self._push_retry_at = time.monotonic() + 3.0
                return
            if client is not None:
                client.close()
            client = buddy_mod.BuddyClient(addr, self.secret, index,
                                           rank=self.rank)
            self._buddy_client = client
        try:
            client.push(step, shard)
            self._push_retry_at = 0.0
        except (ConnectionError, OSError) as exc:
            logger.debug("ckpt: buddy push to rank %s failed (%s); disk "
                         "bundle remains the restore source", succ, exc)
            # drop the cached stream: the successor may come back at a new
            # address (hot-spare replacement republished ckpt.buddy.N), so
            # the next push must re-resolve, not redial the corpse
            client.close()
            self._buddy_client = None

    # ---------------------------------------------------- bundle finalization
    def _finalize_bundle(self, step: int, epoch: int,
                         shards: Dict[int, dict]) -> None:
        """Rank 0 only (coordinator callback / single-process path): land
        the manifest — the bundle's atomic commit record."""
        try:
            replica = None
            rp = bundle.replica_path(self.root, step)
            if os.path.exists(rp):
                with open(rp, "rb") as f:
                    data = f.read()
                replica = {"nbytes": len(data),
                           "crc": zlib.crc32(data) & 0xFFFFFFFF}
            bundle.finalize_manifest(self.root, step, epoch, shards,
                                     replica=replica)
            bundle.prune_bundles(self.root, keep=_keep_bundles())
        except Exception:
            logger.warning("ckpt: manifest finalize for step %d failed",
                           step, exc_info=True)
            return
        self.note_finalized(step)
        bb = _blackbox.active()
        if bb is not None:
            bb.record(_blackbox.K_CKPT, "finalize",
                      "step=%d epoch=%d shards=%d" % (step, epoch,
                                                      len(shards)),
                      self.rank)

    def note_finalized(self, step: int) -> None:
        with self._lock:
            if step > self._last_done_step:
                self._last_done_step = step
        instruments.ckpt_bundle_age_steps().set(0)

    # ---------------------------------------------------------------- restore
    def _ef_snapshot(self) -> Dict[str, Any]:
        ex = self._executor()
        return ex.residual_state() if ex is not None else {}

    def _ef_load(self, st: Dict[str, Any]) -> None:
        ex = self._executor()
        if ex is not None and st:
            ex.load_residual_state(st)

    @staticmethod
    def _executor():
        from .. import basics

        try:
            ex = getattr(basics._engine(), "_executor", None)
        except Exception:
            return None
        return ex if hasattr(ex, "residual_state") else None

    def fetch_peer_shard(self, index: int,
                         timeout: float = 3.0) -> Optional[Tuple[int, bytes]]:
        """The journal head for shard ``index`` from whichever host holds
        it (O(shard) bytes over the wire), or None."""
        addr = self._resolve("ckpt.shard.%d" % index, timeout=timeout)
        if addr is None:
            return None
        try:
            return buddy_mod.fetch_shard(addr, self.secret, index,
                                         rank=self.rank, timeout=timeout)
        except (ConnectionError, OSError):
            return None

    def restore_sharded_slots(self, state) -> bool:
        """Replacement-rank restore path (called from
        ``ElasticState.sync`` before the replicated broadcast): install
        the journal head for this rank's shard slot into the state's
        sharded slots and the executor's EF residuals. Peer first
        (O(shard), no disk); the latest complete disk bundle second.
        Returns True when a shard was restored."""
        from ..goodput import ledger as _goodput

        led = _goodput.active()
        span = led.begin("checkpoint") if led is not None else None
        try:
            return self._restore_sharded_slots(state)
        finally:
            if led is not None:
                led.end(span)

    def _restore_sharded_slots(self, state) -> bool:
        sharded = sorted(getattr(state, "_sharded", ()) or ())
        if not sharded:
            return False
        index = self.shard_index()
        got = self.fetch_peer_shard(index)
        source = "peer"
        journal_head = got[0] if got is not None else -1
        if got is None:
            step = bundle.latest_complete_step(self.root)
            if step is None:
                return False
            doc = bundle.read_manifest(self.root, step) or {}
            members, _ = self._membership()
            if doc.get("world") != len(members):
                # shard layout belongs to a different world size; a
                # mis-sliced restore is worse than a fresh start
                logger.warning("ckpt: bundle step %d has world=%s, job "
                               "has %d members — skipping restore",
                               step, doc.get("world"), len(members))
                return False
            try:
                got = (step, bundle.read_shard(self.root, step, index))
            except OSError:
                return False
            source = "bundle"
            if doc.get("replica"):
                # whole-job restart: every rank installs the replicated
                # slots from the bundle too (identical bytes everywhere,
                # so the sync broadcast that follows only confirms them)
                try:
                    rep = unpack_tree(bundle.read_replica(self.root, step))
                    for k, v in ((rep or {}).get("slots") or {}).items():
                        if k in state._values:
                            state._values[k] = v
                except OSError:
                    pass
        step, data = got
        tree = unpack_tree(data)
        slots = (tree or {}).get("slots") or {}
        for k in sharded:
            if k in slots:
                state._values[k] = slots[k]
        self._ef_load((tree or {}).get("ef") or {})
        if source == "bundle":
            # the buddy may still hold a newer journal head we could not
            # reach; probe once more so stale restores are on the record
            head = self.fetch_peer_shard(index, timeout=0.5)
            journal_head = head[0] if head is not None else -1
        nbytes = len(data)
        self.last_restore = {"source": source, "step": step,
                             "journal_head": journal_head,
                             "index": index, "nbytes": nbytes}
        bb = _blackbox.active()
        if bb is not None:
            name = "peer_restore" if source == "peer" else "restore"
            bb.record(_blackbox.K_CKPT, name,
                      "source=%s step=%d journal_head=%d index=%d "
                      "nbytes=%d" % (source, step, journal_head, index,
                                     nbytes), self.rank)
        logger.info("ckpt: restored shard %d from %s (step %d, %d bytes)",
                    index, source, step, nbytes)
        return True

    # -------------------------------------------------------------- lifecycle
    def drain(self, timeout: float = 30.0) -> bool:
        return self.writer.drain(timeout)

    def stop(self) -> None:
        self.writer.stop()
        if self._buddy_client is not None:
            self._buddy_client.close()
        if self.buddy_server is not None:
            self.buddy_server.stop()


# ------------------------------------------------------------ module surface
def active() -> Optional[CkptManager]:
    """The process's manager, or None when ``HOROVOD_CKPT_DIR`` is unset —
    the one check every integration point makes, so knobs-unset jobs pay
    a single attribute read and produce zero new frames."""
    return _mgr


def ensure_manager() -> Optional[CkptManager]:
    """Build the process manager on first use (idempotent). Reads the
    runtime's rank/world/controller when initialized; falls back to a
    single-process manager otherwise (legacy ``checkpoint.save``
    delegation, benches, unit tests)."""
    global _mgr
    root = ckpt_dir()
    if root is None:
        return None
    with _mgr_lock:
        if _mgr is not None:
            return _mgr
        rank, world, ctrl = 0, 1, None
        from .. import basics

        if basics.is_initialized():
            rank, world = basics.rank(), basics.size()
            try:
                ctrl = basics._engine().controller
            except Exception:
                ctrl = None
        _mgr = CkptManager(root, rank, world, controller=ctrl)
        basics.register_shutdown_hook(shutdown)
        return _mgr


def shutdown() -> None:
    global _mgr
    with _mgr_lock:
        mgr, _mgr = _mgr, None
    if mgr is not None:
        mgr.stop()


def load_latest(root: str) -> Optional[Tuple[int, dict]]:
    """Offline restore helper: the latest complete bundle as
    ``(step, {"slots": ..., "ef": ...})`` — replica blob merged with every
    shard's sharded slots (slot layout), or the reassembled byte-partition
    blob (plain-DP layout)."""
    step = bundle.latest_complete_step(root)
    if step is None:
        return None
    doc = bundle.read_manifest(root, step) or {}
    if doc.get("replica"):
        out: dict = {"slots": {}, "ef": {}}
        rep = unpack_tree(bundle.read_replica(root, step))
        out["slots"].update((rep or {}).get("slots") or {})
        for i in sorted(int(k) for k in doc.get("shards") or {}):
            tree = unpack_tree(bundle.read_shard(root, step, i))
            out["slots"].update((tree or {}).get("slots") or {})
        return step, out
    return step, unpack_tree(bundle.read_bundle_bytes(root, step))
