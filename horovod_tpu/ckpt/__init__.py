"""Async sharded checkpointing with peer-redundant shard recovery.

Three cooperating pieces (docs/checkpoint.md):

- :mod:`.bundle` — the on-disk format: one directory per step, one shard
  file per member, a manifest renamed into place atomically once every
  shard landed. A crash mid-write leaves the previous complete bundle
  authoritative.
- :mod:`.writer` — :class:`AsyncShardWriter`, the host-memory double
  buffer + off-path writer thread that keeps
  ``hvd_checkpoint_stall_seconds`` ~0.
- :mod:`.buddy` — shard journaling to the ring successor over the standby
  replication framing, so a replacement restores in O(shard) from its
  buddy's host memory with no disk read and no O(model) broadcast.

:mod:`.manager` ties them to the commit boundary and the coordinator's
``MSG_CKPT_MARK`` / ``MSG_CKPT_DONE`` consistency epoch. The whole
subsystem is off — zero new frames, byte-identical wire traffic — unless
``HOROVOD_CKPT_DIR`` is set.
"""

from . import bundle  # noqa: F401
from .buddy import (BuddyClient, BuddyServer, apply_delta,  # noqa: F401
                    fetch_shard, shard_delta)
from .bundle import (atomic_write_bytes, complete_steps,  # noqa: F401
                     finalize_manifest, latest_complete_step,
                     prune_bundles, read_bundle_bytes, read_manifest,
                     read_shard, write_shard)
from .manager import (CkptManager, active, buddy_enabled,  # noqa: F401
                      ckpt_dir, ckpt_interval, ensure_manager,
                      load_latest, pack_tree, partition_bounds, shutdown,
                      unpack_tree)
from .writer import AsyncShardWriter  # noqa: F401
