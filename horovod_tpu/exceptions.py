"""Error types for the TPU-native collective engine.

Reference parity: the C++ `Status` model (`horovod/common/common.h:150-250`) carries
OK / UNKNOWN_ERROR / PRECONDITION_ERROR / ABORTED / INVALID_ARGUMENT / IN_PROGRESS.
Here those surface as Python exceptions raised from `synchronize()` on a handle,
matching the framework bindings' behavior (`horovod/torch/mpi_ops.py:476-492`).
"""


class HorovodError(Exception):
    """Base class for all framework errors."""


class HorovodInternalError(HorovodError):
    """An error reported by the collective engine (negotiation or execution).

    Mirrors coordinator-constructed ERROR responses
    (`horovod/common/controller.cc:358-534`).
    """


class DuplicateNameError(HorovodInternalError):
    """A rank enqueued two tensors with the same name before completion.

    Mirrors DUPLICATE_NAME_ERROR (`horovod/common/common.h:160-163`).
    """


class ShutdownError(HorovodInternalError):
    """Collective enqueued after engine shutdown.

    Mirrors SHUT_DOWN_ERROR (`horovod/common/common.h:155-158`,
    `operations.cc:824-826`). Subclasses HorovodInternalError so generic
    ``except HorovodInternalError`` handlers around ``synchronize()`` match.
    """


class RanksChangedError(HorovodInternalError):
    """Cluster membership changed under an in-flight collective.

    Raised from ``synchronize()`` when the coordinator bumped the membership
    epoch (a worker was lost or admitted) while this collective was pending.
    Elastic drivers (``horovod_tpu.elastic.run_fn``) catch this, restore the
    last committed state, ``sync()`` from the lowest surviving rank and
    resume; non-elastic callers see it as a fatal engine error. Mirrors
    later-horovod's ``HorovodInternalError`` recovery contract
    (`horovod/common/elastic.py`).
    """


class WorkerLostError(RanksChangedError):
    """Membership changed because a worker dropped its control-plane
    connection (crash, preemption, kill) — as opposed to a planned
    join/resize. Subclasses RanksChangedError so one handler covers both.
    """


class NotInitializedError(HorovodError):
    """API used before ``init()`` was called.

    Mirrors `horovod/common/operations.cc:660-663` (NOT_INITIALIZED_ERROR).
    """


class NonFiniteError(HorovodInternalError):
    """A gradient (or allreduce input) contained NaN/Inf under
    ``HOROVOD_GRAD_GUARD=abort`` (docs/fault-tolerance.md, data-plane
    integrity). The message names the offending tensors, the ranks that
    produced them and the optimizer step."""


class ParameterDesyncError(HorovodInternalError):
    """Replica parameters diverged across ranks and the consistency
    auditor runs under ``HOROVOD_CONSISTENCY_POLICY=abort``. The message
    lists the divergent tensors and the ranks whose digests differ from
    the root's (docs/fault-tolerance.md)."""


class CollectiveTimeoutError(HorovodInternalError):
    """A collective stalled past ``HOROVOD_COLLECTIVE_TIMEOUT``: some
    ranks submitted the tensor and the remainder never arrived. Raised
    from ``synchronize()`` on the ranks that did submit, naming the
    tensor and the missing ranks — the enforced form of the stall
    inspector's warning (stall_inspector.h:75)."""
