"""Keras-namespace callbacks (`horovod/keras/callbacks.py` parity).

The reference's ``horovod.keras.callbacks`` module re-exports the shared
implementations from ``horovod/_keras/callbacks.py``; same shape here — the
framework-agnostic implementations live in ``horovod_tpu.callbacks``.
"""

from ..callbacks import (  # noqa: F401
    BroadcastGlobalVariablesCallback,
    Callback,
    CallbackList,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
)
