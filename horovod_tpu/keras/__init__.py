"""Keras-surface parity for the TPU framework.

Reference parity: `horovod/keras/__init__.py` (150 LoC) and
`horovod/_keras/__init__.py` (127 LoC). The reference wraps a Keras
optimizer so `get_gradients` allreduces before applying
(`_keras/__init__.py:35-63`), re-exports the collective ops and basics, and
`load_model` re-wraps the deserialized optimizer in a DistributedOptimizer
(`keras/__init__.py:111-127`, `_keras/__init__.py:111-127`).

On TPU the "Keras model" is a flax module + an optax optimizer; this module
maps the same surface onto that world:

  * ``DistributedOptimizer(tx)`` — optax GradientTransformation wrapper that
    allreduces gradients before the inner update (same object as
    ``horovod_tpu.DistributedOptimizer``; re-exported here so
    ``hvd.keras.DistributedOptimizer`` reads like the reference).
  * ``broadcast_global_variables(state, root_rank)`` — rank-0 state sync
    (`keras/__init__.py:75-83`).
  * ``save_model`` / ``load_model`` — msgpack (flax.serialization) round-trip
    of ``{"params", "opt_state"}``; ``load_model`` re-wraps the optimizer.
  * ``callbacks`` — BroadcastGlobalVariablesCallback, MetricAverageCallback,
    LearningRateScheduleCallback, LearningRateWarmupCallback.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .. import basics
from ..basics import (  # noqa: F401  (reference re-exports `keras/__init__.py:20-46`)
    Adasum,
    Average,
    Sum,
    ddl_built,
    gloo_built,
    gloo_enabled,
    init,
    is_homogeneous,
    local_rank,
    local_size,
    mlsl_built,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    shutdown,
    size,
)
from ..ops.collective_ops import allgather, allreduce, broadcast  # noqa: F401
from ..ops.compression import Compression  # noqa: F401
from ..optim.broadcast import broadcast_optimizer_state, broadcast_parameters
from ..optim.distributed import DistributedOptimizer  # noqa: F401
from . import callbacks  # noqa: F401


def broadcast_global_variables(state: Dict[str, Any], root_rank: int = 0):
    """Broadcast a training-state dict (``params`` + optional ``opt_state``)
    from ``root_rank`` to all ranks (`keras/__init__.py:75-83`).

    Returns the state dict with synced values (functional: caller rebinds).
    """
    out = dict(state)
    if "params" in out:
        out["params"] = broadcast_parameters(out["params"], root_rank)
    if "opt_state" in out and out["opt_state"] is not None:
        out["opt_state"] = broadcast_optimizer_state(out["opt_state"],
                                                     root_rank)
    return out


def save_model(path: str, params, opt_state=None, extra: Optional[dict] = None):
    """Serialize training state to ``path`` (msgpack via flax.serialization).

    The reference pattern is rank-0 saves, everyone restores-then-broadcasts
    (SURVEY §5 checkpoint/resume); this helper is the save half. Only rank 0
    writes (atomic, via :mod:`horovod_tpu.checkpoint`); other ranks no-op.
    """
    from .. import checkpoint

    checkpoint.save(path, {"params": params,
                           "opt_state": opt_state if opt_state is not None
                           else {},
                           "extra": extra or {}})


def load_model(path: str, template: Dict[str, Any], tx=None,
               compression=Compression.none, broadcast: bool = True):
    """Deserialize training state and re-wrap the optimizer, the
    `keras/__init__.py:111-127` flow: load → wrap optimizer in
    DistributedOptimizer → broadcast so every rank starts identical.

    ``template`` is a dict with the same structure as what ``save_model``
    wrote (``{"params": ..., "opt_state": ...}``) used as the
    deserialization target. Returns ``(state_dict, wrapped_tx)`` where
    ``wrapped_tx`` is ``DistributedOptimizer(tx)`` (or None if no ``tx``).
    """
    from .. import checkpoint

    tmpl_opt = template.get("opt_state")
    # {} is the "absent" marker save_model writes; a present-but-falsy optax
    # state (e.g. EmptyState()) must NOT be treated as absent
    has_opt = tmpl_opt is not None and not (
        isinstance(tmpl_opt, dict) and not tmpl_opt)
    target = {"params": template["params"],
              "opt_state": tmpl_opt if has_opt else {},
              "extra": template.get("extra") or {}}
    if broadcast and basics.is_initialized() and basics.size() > 1:
        # only rank 0 is guaranteed to see the file (save_model writes on
        # rank 0 only; on a multi-host pod the path may be host-local) —
        # root reads, the bytes ride the broadcast wire
        state = checkpoint.restore_and_broadcast(path, target,
                                                 name="load_model.bytes")
    else:
        state = checkpoint.restore(path, target)
    wrapped = DistributedOptimizer(tx, compression=compression) \
        if tx is not None else None
    return state, wrapped
