// Compact binary wire format for engine messages.
//
// TPU-native rebuild of horovod/common/wire/message.fbs + message.{h,cc}:
// the reference serializes Request/Response lists with FlatBuffers for the
// MPI/Gloo control plane; here a little-endian length-prefixed encoding is
// used for (a) returning negotiated ResponseLists across the C/Python
// boundary and (b) the cross-process control plane over the launcher's KV
// service. Layout (all integers little-endian):
//
//   ResponseList := u32 count, Response*
//   Response     := i32 type, u32 nnames, (u32 len, bytes)* names,
//                   u32 errlen, bytes err, u8 average,
//                   f64 prescale, f64 postscale, i32 root_rank
//   RequestList  := u32 count, Request*
//   Request      := i32 rank, i32 type, u32 namelen, bytes name, i32 dtype,
//                   u32 ndim, i64* dims, i32 root_rank, u8 average,
//                   f64 prescale, f64 postscale
#pragma once

#include <cstring>
#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {
namespace wire {

class Writer {
 public:
  std::string out;
  void u8(uint8_t v) { out.push_back(static_cast<char>(v)); }
  void u32(uint32_t v) { raw(&v, 4); }
  void i32(int32_t v) { raw(&v, 4); }
  void i64(int64_t v) { raw(&v, 8); }
  void f64(double v) { raw(&v, 8); }
  void str(const std::string& s) {
    u32(static_cast<uint32_t>(s.size()));
    out.append(s);
  }

 private:
  void raw(const void* p, size_t n) {
    out.append(reinterpret_cast<const char*>(p), n);
  }
};

class Reader {
 public:
  Reader(const char* data, size_t len) : p_(data), end_(data + len) {}
  bool ok() const { return ok_; }
  uint8_t u8() { uint8_t v = 0; raw(&v, 1); return v; }
  uint32_t u32() { uint32_t v = 0; raw(&v, 4); return v; }
  int32_t i32() { int32_t v = 0; raw(&v, 4); return v; }
  int64_t i64() { int64_t v = 0; raw(&v, 8); return v; }
  double f64() { double v = 0; raw(&v, 8); return v; }
  std::string str() {
    uint32_t n = u32();
    if (p_ + n > end_) { ok_ = false; return {}; }
    std::string s(p_, n);
    p_ += n;
    return s;
  }

 private:
  void raw(void* dst, size_t n) {
    if (p_ + n > end_) { ok_ = false; std::memset(dst, 0, n); return; }
    std::memcpy(dst, p_, n);
    p_ += n;
  }
  const char* p_;
  const char* end_;
  bool ok_ = true;
};

inline void EncodeResponse(Writer& w, const Response& r) {
  w.i32(static_cast<int32_t>(r.type));
  w.u32(static_cast<uint32_t>(r.names.size()));
  for (const auto& n : r.names) w.str(n);
  w.str(r.error_message);
  w.u8(r.average ? 1 : 0);
  w.f64(r.prescale);
  w.f64(r.postscale);
  w.i32(r.root_rank);
}

inline std::string EncodeResponseList(const std::vector<Response>& rs) {
  Writer w;
  w.u32(static_cast<uint32_t>(rs.size()));
  for (const auto& r : rs) EncodeResponse(w, r);
  return w.out;
}

inline Response DecodeResponse(Reader& rd) {
  Response r;
  r.type = static_cast<ResponseType>(rd.i32());
  uint32_t n = rd.u32();
  for (uint32_t i = 0; i < n; ++i) r.names.push_back(rd.str());
  r.error_message = rd.str();
  r.average = rd.u8() != 0;
  r.prescale = rd.f64();
  r.postscale = rd.f64();
  r.root_rank = rd.i32();
  return r;
}

inline std::vector<Response> DecodeResponseList(const char* data, size_t len) {
  Reader rd(data, len);
  uint32_t n = rd.u32();
  std::vector<Response> out;
  for (uint32_t i = 0; i < n && rd.ok(); ++i) out.push_back(DecodeResponse(rd));
  return out;
}

inline void EncodeRequest(Writer& w, const PendingEntry& e) {
  w.i32(e.rank);
  w.i32(static_cast<int32_t>(e.type));
  w.str(e.name);
  w.i32(static_cast<int32_t>(e.dtype));
  w.u32(static_cast<uint32_t>(e.shape.size()));
  for (auto d : e.shape) w.i64(d);
  w.i32(e.root_rank);
  w.u8(e.average ? 1 : 0);
  w.f64(e.prescale);
  w.f64(e.postscale);
}

inline PendingEntry DecodeRequest(Reader& rd) {
  PendingEntry e;
  e.rank = rd.i32();
  e.type = static_cast<RequestType>(rd.i32());
  e.name = rd.str();
  e.dtype = static_cast<DType>(rd.i32());
  uint32_t nd = rd.u32();
  for (uint32_t i = 0; i < nd; ++i) e.shape.push_back(rd.i64());
  e.root_rank = rd.i32();
  e.average = rd.u8() != 0;
  e.prescale = rd.f64();
  e.postscale = rd.f64();
  return e;
}

}  // namespace wire
}  // namespace hvdtpu
