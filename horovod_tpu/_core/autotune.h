// Runtime parameter autotuning: Gaussian-process Bayesian optimization of
// fusion threshold and cycle time, scored by observed throughput.
//
// TPU-native rebuild of horovod/common/parameter_manager.{h,cc} +
// optim/bayesian_optimization.{h,cc} + optim/gaussian_process.{h,cc}:
// the reference fits a GP (Eigen + L-BFGS) over (fusion_threshold,
// cycle_time) with bytes/sec as score and picks the next sample by expected
// improvement. Here the GP uses an RBF kernel with hand-rolled Cholesky
// (no Eigen in-image) and EI is maximized over a random candidate set —
// the same algorithm at the fidelity this 2-D, ~tens-of-samples problem
// needs.
#pragma once

#include <cstdint>
#include <random>
#include <vector>

namespace hvdtpu {

// Minimal dense GP regression with RBF kernel on normalized inputs.
class GaussianProcess {
 public:
  GaussianProcess(double length_scale = 0.3, double noise = 1e-4)
      : ls_(length_scale), noise_(noise) {}
  void Fit(const std::vector<std::vector<double>>& X,
           const std::vector<double>& y);
  // predictive mean + stddev at x
  void Predict(const std::vector<double>& x, double* mean, double* std) const;

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;
  double ls_, noise_;
  std::vector<std::vector<double>> X_;
  std::vector<double> alpha_;       // K^-1 y
  std::vector<std::vector<double>> L_;  // Cholesky factor of K
  double ymean_ = 0;
};

// Expected-improvement Bayesian optimizer over a unit hypercube.
class BayesianOptimizer {
 public:
  explicit BayesianOptimizer(int dims, uint64_t seed = 0)
      : dims_(dims), rng_(seed) {}
  void AddSample(const std::vector<double>& x, double y);
  std::vector<double> NextSample();
  // GP observation noise on the standardized scores
  // (HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE, parameter_manager.cc:31)
  void SetNoise(double noise) { gp_ = GaussianProcess(0.3, noise); }

 private:
  int dims_;
  std::mt19937_64 rng_;
  std::vector<std::vector<double>> X_;
  std::vector<double> y_;
  std::vector<double> ynorm_;  // standardized scores the GP is fit on
  GaussianProcess gp_;
};

// ParameterManager: drives (fusion_threshold_mb, cycle_time_ms) from scores.
// Mirrors parameter_manager.h:88 Update(): accumulate bytes+time per step,
// re-tune every `steps_per_sample` steps.
class ParameterManager {
 public:
  ParameterManager(int64_t initial_threshold, double initial_cycle_ms,
                   uint64_t seed = 0);
  void SetEnabled(bool e) { enabled_ = e; }
  bool enabled() const { return enabled_; }

  // The reference's four HOROVOD_AUTOTUNE_* tuning knobs
  // (parameter_manager.cc:42-59); values <= 0 keep the current setting.
  void Configure(int warmup_samples, int steps_per_sample, int max_samples,
                 double gp_noise);

  // record bytes moved in an interval; returns true if params changed
  bool Update(int64_t bytes, double seconds);
  int64_t fusion_threshold() const { return threshold_; }
  double cycle_time_ms() const { return cycle_ms_; }
  double best_score() const { return best_score_; }

 private:
  std::vector<double> Encode() const;
  void Decode(const std::vector<double>& x);

  bool enabled_ = false;
  int64_t threshold_;
  double cycle_ms_;
  BayesianOptimizer opt_;
  int64_t acc_bytes_ = 0;
  double acc_seconds_ = 0;
  int steps_ = 0;
  int steps_per_sample_ = 10;
  double best_score_ = 0;
  int64_t best_threshold_;
  double best_cycle_ms_;
  int samples_ = 0;
  int max_samples_ = 40;  // then settle on best (parameter_manager stops too)
  // sample windows discarded before scoring starts (measurements during
  // spin-up are unstable; reference parameter_manager.cc:177-181)
  int warmup_remaining_ = 0;
};

}  // namespace hvdtpu
