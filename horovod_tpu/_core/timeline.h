// Chrome-tracing timeline writer with a dedicated writer thread.
//
// TPU-native rebuild of horovod/common/timeline.{h,cc}: per-tensor NEGOTIATE
// spans, top-level op spans and named activities, buffered through a queue to
// a writer thread (timeline.h:47-75 uses a boost lock-free SPSC; a mutexed
// deque suffices at engine-tick rates). Output is Chrome tracing JSON loadable
// in chrome://tracing / Perfetto.
#pragma once

#include <condition_variable>
#include <cstdint>
#include <deque>
#include <fstream>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

namespace hvdtpu {

class TimelineWriter {
 public:
  explicit TimelineWriter(const std::string& path);
  ~TimelineWriter();

  void NegotiateStart(const std::string& tensor, int32_t rank, int64_t ts_us);
  void OpStart(const std::string& tensor, const std::string& op, int64_t ts_us);
  void Activity(const std::string& tensor, const std::string& activity,
                int64_t ts_us);
  void OpEnd(const std::string& tensor, int64_t ts_us);
  void CycleMarker(int64_t ts_us);
  void CacheCounter(uint64_t hits, uint64_t misses, int64_t ts_us);
  void Close();
  bool enabled() const { return enabled_; }

 private:
  struct Event {
    std::string json;
  };
  void Emit(const std::string& json);
  int32_t Tid(const std::string& tensor);
  void Loop();

  bool enabled_ = false;
  std::ofstream f_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Event> q_;
  bool done_ = false;
  std::thread thread_;
  std::unordered_map<std::string, int32_t> tids_;
  int32_t next_tid_ = 1;
};

}  // namespace hvdtpu
