#include "timeline.h"
#include <cstdio>

#include <sstream>

namespace hvdtpu {

static std::string JsonEscape(const std::string& s) {
  std::string out;
  char buf[8];
  for (unsigned char c : s) {
    if (c == '"' || c == '\\') {
      out.push_back('\\');
      out.push_back(static_cast<char>(c));
    } else if (c < 0x20) {  // all control chars must be escaped in JSON
      std::snprintf(buf, sizeof(buf), "\\u%04x", c);
      out += buf;
    } else {
      out.push_back(static_cast<char>(c));
    }
  }
  return out;
}

TimelineWriter::TimelineWriter(const std::string& path) {
  if (path.empty()) return;
  f_.open(path);
  if (!f_.is_open()) return;
  enabled_ = true;
  f_ << "[\n";
  thread_ = std::thread(&TimelineWriter::Loop, this);
}

TimelineWriter::~TimelineWriter() { Close(); }

void TimelineWriter::Emit(const std::string& json) {
  if (!enabled_) return;
  {
    std::lock_guard<std::mutex> l(mu_);
    q_.push_back({json});
  }
  cv_.notify_one();
}

int32_t TimelineWriter::Tid(const std::string& tensor) {
  std::lock_guard<std::mutex> l(mu_);
  auto it = tids_.find(tensor);
  if (it != tids_.end()) return it->second;
  int32_t t = next_tid_++;
  tids_[tensor] = t;
  std::ostringstream os;
  os << "{\"name\":\"thread_name\",\"ph\":\"M\",\"pid\":0,\"tid\":" << t
     << ",\"args\":{\"name\":\"" << JsonEscape(tensor) << "\"}}";
  q_.push_back({os.str()});
  cv_.notify_one();
  return t;
}

void TimelineWriter::NegotiateStart(const std::string& tensor, int32_t rank,
                                    int64_t ts_us) {
  if (!enabled_) return;
  int32_t tid = Tid(tensor);
  std::ostringstream os;
  os << "{\"name\":\"NEGOTIATE_" << JsonEscape(tensor)
     << "\",\"ph\":\"B\",\"pid\":0,\"tid\":" << tid << ",\"ts\":" << ts_us
     << ",\"args\":{\"rank\":" << rank << "}}";
  Emit(os.str());
}

void TimelineWriter::OpStart(const std::string& tensor, const std::string& op,
                             int64_t ts_us) {
  if (!enabled_) return;
  int32_t tid = Tid(tensor);
  std::ostringstream os;
  os << "{\"name\":\"NEGOTIATE_" << JsonEscape(tensor)
     << "\",\"ph\":\"E\",\"pid\":0,\"tid\":" << tid << ",\"ts\":" << ts_us
     << "}";
  Emit(os.str());
  std::ostringstream os2;
  os2 << "{\"name\":\"" << JsonEscape(op) << "\",\"ph\":\"B\",\"pid\":0,"
      << "\"tid\":" << tid << ",\"ts\":" << ts_us << "}";
  Emit(os2.str());
}

void TimelineWriter::Activity(const std::string& tensor,
                              const std::string& activity, int64_t ts_us) {
  if (!enabled_) return;
  int32_t tid = Tid(tensor);
  std::ostringstream os;
  os << "{\"name\":\"" << JsonEscape(activity)
     << "\",\"ph\":\"i\",\"pid\":0,\"tid\":" << tid << ",\"ts\":" << ts_us
     << ",\"s\":\"t\"}";
  Emit(os.str());
}

void TimelineWriter::OpEnd(const std::string& tensor, int64_t ts_us) {
  if (!enabled_) return;
  int32_t tid = Tid(tensor);
  std::ostringstream os;
  os << "{\"ph\":\"E\",\"pid\":0,\"tid\":" << tid << ",\"ts\":" << ts_us
     << "}";
  Emit(os.str());
}

void TimelineWriter::CycleMarker(int64_t ts_us) {
  if (!enabled_) return;
  std::ostringstream os;
  os << "{\"name\":\"CYCLE\",\"ph\":\"i\",\"pid\":0,\"tid\":0,\"ts\":" << ts_us
     << ",\"s\":\"g\"}";
  Emit(os.str());
}

void TimelineWriter::CacheCounter(uint64_t hits, uint64_t misses,
                                  int64_t ts_us) {
  // Chrome counter track of response-cache hits/misses (the fast path that
  // skips negotiation, reference controller.cc:171-185).
  if (!enabled_) return;
  std::ostringstream os;
  os << "{\"name\":\"response_cache\",\"ph\":\"C\",\"pid\":0,\"ts\":" << ts_us
     << ",\"args\":{\"hits\":" << hits << ",\"misses\":" << misses << "}}";
  Emit(os.str());
}

void TimelineWriter::Loop() {
  std::unique_lock<std::mutex> l(mu_);
  while (true) {
    cv_.wait(l, [&] { return done_ || !q_.empty(); });
    while (!q_.empty()) {
      Event e = std::move(q_.front());
      q_.pop_front();
      l.unlock();
      f_ << e.json << ",\n";
      l.lock();
    }
    if (done_) return;
    f_.flush();
  }
}

void TimelineWriter::Close() {
  if (!enabled_) return;
  {
    std::lock_guard<std::mutex> l(mu_);
    done_ = true;
  }
  cv_.notify_one();
  if (thread_.joinable()) thread_.join();
  f_ << "{\"name\":\"end\",\"ph\":\"M\",\"pid\":0}\n]\n";
  f_.close();
  enabled_ = false;
}

}  // namespace hvdtpu
