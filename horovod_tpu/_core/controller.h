// Negotiation controller: decides per tick which named tensors are ready on
// every active rank, validates cross-rank agreement, fuses ready tensors into
// byte-bounded buckets, tracks join state and stalls.
//
// TPU-native rebuild of horovod/common/controller.{h,cc}
// (ComputeResponseList :55, ConstructResponse :358, FuseResponses :626,
// IncrementTensorCount :778), tensor_queue.{h,cc} (duplicate detection),
// stall_inspector.{h,cc} and response_cache.{h,cc}. The MPI gather/bcast
// legs are absent: in-process ranks share this table directly; cross-process
// agreement is by SPMD program order (future: KV control plane exchanging
// wire-encoded RequestLists).
#pragma once

#include <cstdint>
#include <deque>
#include <mutex>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"

namespace hvdtpu {

// LRU cache of negotiated response signatures: lets steady-state training
// skip validation/fusion planning (fast path of controller.cc:171-185).
class ResponseCache {
 public:
  explicit ResponseCache(size_t capacity) : capacity_(capacity) {}
  // membership test; counts hits/misses (the negotiated-response payloads are
  // deterministic from the signature, so only presence is stored — bounded by
  // capacity)
  bool Lookup(const std::string& sig);
  void Insert(const std::string& sig);
  size_t size() const { return index_.size(); }
  uint64_t hits = 0, misses = 0;

 private:
  size_t capacity_;
  std::unordered_map<std::string, int64_t> index_;
  std::deque<std::string> lru_;
};

struct ControllerOptions {
  int32_t world = 1;
  int64_t fusion_threshold_bytes = 64ll * 1024 * 1024;  // operations.cc:404
  double stall_warning_s = 60.0;   // stall_inspector.h:75
  double stall_shutdown_s = 0.0;   // stall_inspector.h:80
  // enforced watchdog (HOROVOD_COLLECTIVE_TIMEOUT): >0 fails a tensor still
  // missing ranks after this many seconds with an ERROR response naming
  // them, instead of warning forever. 0 keeps warn-only stall inspection.
  double collective_timeout_s = 0.0;
  size_t cache_capacity = 1024;    // HOROVOD_CACHE_CAPACITY
  bool fusion_enabled = true;
  // multiprocess mode: only self_rank submits to this process's table
  // (readiness = local rank only; cross-process agreement is SPMD program
  // order until the KV control plane lands). world stays the GLOBAL size for
  // validation (root range, adasum power-of-2, alltoall divisibility).
  bool local_only = false;
  int32_t self_rank = 0;
};

struct TickResult {
  std::vector<Response> responses;
  // per-response per-rank entry handles, ordered like response.names:
  // handles[resp_idx] = flat list of (rank, handle) pairs
  std::vector<std::vector<std::pair<int32_t, int64_t>>> handles;
  std::vector<int64_t> join_handles_released;  // handles to complete
  int32_t last_joined = -1;
  std::vector<std::string> stall_warnings;
  bool stall_shutdown = false;
};

class Controller {
 public:
  explicit Controller(const ControllerOptions& opts) : opts_(opts) {}

  // Returns handle (>=0), or -1 duplicate-name, -2 after shutdown.
  int64_t Submit(const PendingEntry& e);
  int64_t Join(int32_t rank);
  void Shutdown(std::vector<int64_t>* orphan_handles);

  // One negotiation tick (RunLoopOnce analogue). now_us: monotonic clock.
  TickResult Tick(int64_t now_us);

  // stats for introspection / autotune
  uint64_t cache_hits() const { return cache_.hits; }
  uint64_t cache_misses() const { return cache_.misses; }
  void set_fusion_threshold(int64_t b) { std::lock_guard<std::mutex> l(mu_);
                                         opts_.fusion_threshold_bytes = b; }
  int64_t fusion_threshold() const { return opts_.fusion_threshold_bytes; }

 private:
  struct NameState {
    std::unordered_map<int32_t, PendingEntry> by_rank;
    int64_t first_seen_us = 0;
    bool stall_warned = false;
  };

  // validation (ConstructResponse); returns empty on OK else error message
  std::string Validate(const std::string& name, const NameState& st) const;
  std::string FusionSig(const PendingEntry& e) const;

  ControllerOptions opts_;
  mutable std::mutex mu_;
  bool shutdown_ = false;
  int64_t next_handle_ = 0;
  std::vector<std::string> order_;  // first-submission order
  std::unordered_map<std::string, NameState> table_;
  std::set<int32_t> joined_;
  std::unordered_map<int32_t, int64_t> join_handles_;
  int32_t last_joined_ = -1;
  ResponseCache cache_{1024};
};

}  // namespace hvdtpu
