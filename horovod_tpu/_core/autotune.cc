#include "autotune.h"

#include <algorithm>
#include <cmath>

namespace hvdtpu {

// ---------------------------------------------------------- GaussianProcess
double GaussianProcess::Kernel(const std::vector<double>& a,
                               const std::vector<double>& b) const {
  double d2 = 0;
  for (size_t i = 0; i < a.size(); ++i) {
    double d = a[i] - b[i];
    d2 += d * d;
  }
  return std::exp(-d2 / (2 * ls_ * ls_));
}

void GaussianProcess::Fit(const std::vector<std::vector<double>>& X,
                          const std::vector<double>& y) {
  X_ = X;
  size_t n = X.size();
  ymean_ = 0;
  for (double v : y) ymean_ += v;
  if (n) ymean_ /= n;
  std::vector<double> yc(n);
  for (size_t i = 0; i < n; ++i) yc[i] = y[i] - ymean_;

  // K + noise*I, Cholesky K = L L^T
  std::vector<std::vector<double>> K(n, std::vector<double>(n));
  for (size_t i = 0; i < n; ++i)
    for (size_t j = 0; j < n; ++j)
      K[i][j] = Kernel(X[i], X[j]) + (i == j ? noise_ : 0.0);
  L_.assign(n, std::vector<double>(n, 0.0));
  for (size_t i = 0; i < n; ++i) {
    for (size_t j = 0; j <= i; ++j) {
      double s = K[i][j];
      for (size_t k = 0; k < j; ++k) s -= L_[i][k] * L_[j][k];
      if (i == j)
        L_[i][j] = std::sqrt(std::max(s, 1e-12));
      else
        L_[i][j] = s / L_[j][j];
    }
  }
  // alpha = K^-1 yc via forward/back substitution
  std::vector<double> z(n);
  for (size_t i = 0; i < n; ++i) {
    double s = yc[i];
    for (size_t k = 0; k < i; ++k) s -= L_[i][k] * z[k];
    z[i] = s / L_[i][i];
  }
  alpha_.assign(n, 0.0);
  for (size_t ii = n; ii-- > 0;) {
    double s = z[ii];
    for (size_t k = ii + 1; k < n; ++k) s -= L_[k][ii] * alpha_[k];
    alpha_[ii] = s / L_[ii][ii];
  }
}

void GaussianProcess::Predict(const std::vector<double>& x, double* mean,
                              double* stddev) const {
  size_t n = X_.size();
  if (n == 0) {
    *mean = 0;
    *stddev = 1;
    return;
  }
  std::vector<double> k(n);
  for (size_t i = 0; i < n; ++i) k[i] = Kernel(x, X_[i]);
  double m = ymean_;
  for (size_t i = 0; i < n; ++i) m += k[i] * alpha_[i];
  // var = k(x,x) - v^T v, v = L^-1 k
  std::vector<double> v(n);
  for (size_t i = 0; i < n; ++i) {
    double s = k[i];
    for (size_t kk = 0; kk < i; ++kk) s -= L_[i][kk] * v[kk];
    v[i] = s / L_[i][i];
  }
  double var = 1.0;
  for (size_t i = 0; i < n; ++i) var -= v[i] * v[i];
  *mean = m;
  *stddev = std::sqrt(std::max(var, 1e-12));
}

// --------------------------------------------------------- BayesianOptimizer
void BayesianOptimizer::AddSample(const std::vector<double>& x, double y) {
  X_.push_back(x);
  y_.push_back(y);
  // standardize scores before fitting: raw throughput is ~1e8-1e9 bytes/sec
  // while the GP prior variance is 1, so unnormalized EI would degenerate to
  // greedy mean-maximization (the reference normalizes in ParameterManager
  // before its GP too)
  double mean = 0, var = 0;
  for (double v : y_) mean += v;
  mean /= y_.size();
  for (double v : y_) var += (v - mean) * (v - mean);
  double sd = y_.size() > 1 ? std::sqrt(var / (y_.size() - 1)) : 1.0;
  if (sd < 1e-12) sd = 1.0;
  std::vector<double> yn(y_.size());
  for (size_t i = 0; i < y_.size(); ++i) yn[i] = (y_[i] - mean) / sd;
  ynorm_ = yn;
  gp_.Fit(X_, yn);
}

static double NormCdf(double z) { return 0.5 * std::erfc(-z / std::sqrt(2.0)); }
static double NormPdf(double z) {
  return std::exp(-0.5 * z * z) / std::sqrt(2.0 * M_PI);
}

std::vector<double> BayesianOptimizer::NextSample() {
  std::uniform_real_distribution<double> u(0.0, 1.0);
  if (X_.size() < 3) {  // bootstrap with random exploration
    std::vector<double> x(dims_);
    for (auto& v : x) v = u(rng_);
    return x;
  }
  double best = *std::max_element(ynorm_.begin(), ynorm_.end());
  std::vector<double> argmax(dims_, 0.5);
  double best_ei = -1;
  for (int c = 0; c < 256; ++c) {  // EI over random candidates
    std::vector<double> x(dims_);
    for (auto& v : x) v = u(rng_);
    double m, s;
    gp_.Predict(x, &m, &s);
    double z = (m - best - 0.01) / s;
    double ei = (m - best - 0.01) * NormCdf(z) + s * NormPdf(z);
    if (ei > best_ei) {
      best_ei = ei;
      argmax = x;
    }
  }
  return argmax;
}

// ----------------------------------------------------------- ParameterManager
static const double kMinThreshMB = 1, kMaxThreshMB = 256;
static const double kMinCycleMs = 1, kMaxCycleMs = 25;

ParameterManager::ParameterManager(int64_t initial_threshold,
                                   double initial_cycle_ms, uint64_t seed)
    : threshold_(initial_threshold),
      cycle_ms_(initial_cycle_ms),
      opt_(2, seed),
      best_threshold_(initial_threshold),
      best_cycle_ms_(initial_cycle_ms) {}

std::vector<double> ParameterManager::Encode() const {
  double tmb = threshold_ / (1024.0 * 1024.0);
  double x0 = (std::log2(tmb) - std::log2(kMinThreshMB)) /
              (std::log2(kMaxThreshMB) - std::log2(kMinThreshMB));
  double x1 = (cycle_ms_ - kMinCycleMs) / (kMaxCycleMs - kMinCycleMs);
  return {std::clamp(x0, 0.0, 1.0), std::clamp(x1, 0.0, 1.0)};
}

void ParameterManager::Decode(const std::vector<double>& x) {
  double lt = std::log2(kMinThreshMB) +
              x[0] * (std::log2(kMaxThreshMB) - std::log2(kMinThreshMB));
  threshold_ = static_cast<int64_t>(std::pow(2.0, lt) * 1024 * 1024);
  cycle_ms_ = kMinCycleMs + x[1] * (kMaxCycleMs - kMinCycleMs);
}

void ParameterManager::Configure(int warmup_samples, int steps_per_sample,
                                 int max_samples, double gp_noise) {
  if (warmup_samples >= 0) warmup_remaining_ = warmup_samples;
  if (steps_per_sample > 0) steps_per_sample_ = steps_per_sample;
  if (max_samples > 0) max_samples_ = max_samples;
  if (gp_noise > 0) opt_.SetNoise(gp_noise);
}

bool ParameterManager::Update(int64_t bytes, double seconds) {
  if (!enabled_) return false;
  acc_bytes_ += bytes;
  acc_seconds_ += seconds;
  if (++steps_ < steps_per_sample_) return false;
  double score = acc_seconds_ > 0 ? acc_bytes_ / acc_seconds_ : 0;
  acc_bytes_ = 0;
  acc_seconds_ = 0;
  steps_ = 0;
  if (warmup_remaining_ > 0) {  // discard spin-up windows entirely
    warmup_remaining_--;
    return false;
  }
  if (score > best_score_) {
    best_score_ = score;
    best_threshold_ = threshold_;
    best_cycle_ms_ = cycle_ms_;
  }
  opt_.AddSample(Encode(), score);
  if (++samples_ >= max_samples_) {  // settle on the best seen
    threshold_ = best_threshold_;
    cycle_ms_ = best_cycle_ms_;
    enabled_ = false;
    return true;
  }
  Decode(opt_.NextSample());
  return true;
}

}  // namespace hvdtpu
