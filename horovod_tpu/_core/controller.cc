#include "controller.h"

#include <algorithm>
#include <sstream>

namespace hvdtpu {

// ------------------------------------------------------------ ResponseCache
bool ResponseCache::Lookup(const std::string& sig) {
  auto it = index_.find(sig);
  if (it == index_.end()) {
    ++misses;
    return false;
  }
  ++hits;
  return true;
}

void ResponseCache::Insert(const std::string& sig) {
  if (index_.count(sig)) return;
  if (lru_.size() >= capacity_ && !lru_.empty()) {
    index_.erase(lru_.front());
    lru_.pop_front();
  }
  index_[sig] = 1;
  lru_.push_back(sig);
}

// ---------------------------------------------------------------- Controller
int64_t Controller::Submit(const PendingEntry& e) {
  std::lock_guard<std::mutex> l(mu_);
  if (shutdown_) return -2;
  auto& st = table_[e.name];
  if (st.by_rank.count(e.rank)) return -1;  // DUPLICATE_NAME_ERROR
  if (st.by_rank.empty()) {
    st.first_seen_us = e.enqueue_us;
    order_.push_back(e.name);
  }
  PendingEntry copy = e;
  copy.handle = next_handle_++;
  int64_t h = copy.handle;
  st.by_rank.emplace(e.rank, std::move(copy));
  return h;
}

int64_t Controller::Join(int32_t rank) {
  std::lock_guard<std::mutex> l(mu_);
  if (shutdown_) return -2;
  auto it = join_handles_.find(rank);
  if (it != join_handles_.end()) return it->second;  // repeated join: same
                                                     // barrier handle
  int64_t h = next_handle_++;
  joined_.insert(rank);
  join_handles_[rank] = h;
  last_joined_ = rank;
  return h;
}

void Controller::Shutdown(std::vector<int64_t>* orphan_handles) {
  std::lock_guard<std::mutex> l(mu_);
  shutdown_ = true;
  if (orphan_handles) {
    for (auto& kv : table_)
      for (auto& re : kv.second.by_rank)
        orphan_handles->push_back(re.second.handle);
    for (auto& jh : join_handles_) orphan_handles->push_back(jh.second);
  }
  table_.clear();
  order_.clear();
  join_handles_.clear();
  joined_.clear();
}

std::string Controller::Validate(const std::string& name,
                                 const NameState& st) const {
  const PendingEntry* e0 = nullptr;
  for (auto& kv : st.by_rank) { e0 = &kv.second; break; }
  std::ostringstream err;
  for (auto& kv : st.by_rank) {
    const auto& e = kv.second;
    if (e.type != e0->type) {
      err << "Mismatched collective operations for tensor '" << name << "'";
      return err.str();
    }
    if (e.dtype != e0->dtype) {
      err << "Mismatched data types for tensor '" << name << "'";
      return err.str();
    }
    if (e.average != e0->average || e.prescale != e0->prescale ||
        e.postscale != e0->postscale) {
      err << "Mismatched reduction op/scale factors for tensor '" << name
          << "'";
      return err.str();
    }
  }
  bool a2a_ragged =
      e0->type == RequestType::ALLTOALL && !e0->splits.empty();
  bool shapes_equal_required =
      e0->type == RequestType::ALLREDUCE || e0->type == RequestType::ADASUM ||
      e0->type == RequestType::BROADCAST ||
      (e0->type == RequestType::ALLTOALL && !a2a_ragged);
  if (shapes_equal_required) {
    for (auto& kv : st.by_rank) {
      if (kv.second.shape != e0->shape) {
        err << "Mismatched tensor shapes for '" << name << "': rank "
            << kv.first;
        return err.str();
      }
    }
  }
  if (e0->type == RequestType::ALLGATHER) {
    if (opts_.local_only && opts_.world > 1) {
      // per-rank dim0 sizes live on other processes; requires the
      // cross-process control plane (size negotiation over DCN)
      return "Allgather is not yet supported in multiprocess mode "
             "(cross-process size negotiation not implemented).";
    }
    for (auto& kv : st.by_rank) {
      const auto& s = kv.second.shape;
      if (s.empty())
        return "Allgather of scalar tensor '" + name + "' is not supported.";
      if (s.size() != e0->shape.size() ||
          !std::equal(s.begin() + 1, s.end(), e0->shape.begin() + 1)) {
        err << "Mismatched allgather tensor shapes beyond first dimension "
               "for '" << name << "'";
        return err.str();
      }
    }
  }
  if (e0->type == RequestType::ADASUM) {
    if (opts_.world & (opts_.world - 1)) {
      err << "Adasum requires a power-of-2 number of ranks; got "
          << opts_.world << ".";
      return err.str();
    }
  }
  if (e0->type == RequestType::ALLTOALL) {
    // ragged (alltoallv) vs equal-split must agree across ranks
    for (auto& kv : st.by_rank) {
      if (kv.second.splits.empty() == a2a_ragged) {
        err << "Mismatched alltoall splits usage for tensor '" << name
            << "': rank " << e0->rank << (a2a_ragged ? " passed" : " omitted")
            << " splits, rank " << kv.first << " did not match.";
        return err.str();
      }
    }
    if (a2a_ragged) {
      if (opts_.local_only && opts_.world > 1) {
        // peer splits live on other processes; needs the coordinated plane
        return "Ragged alltoall is not supported in multiprocess mode "
               "without the cross-process control plane (launch via hvdrun "
               "so ranks share a coordinator address channel).";
      }
      for (auto& kv : st.by_rank) {
        const auto& e = kv.second;
        if (e.shape.empty())
          return "Alltoall of scalar tensor '" + name +
                 "' is not supported.";
        if (static_cast<int32_t>(e.splits.size()) != opts_.world) {
          err << "Alltoall splits for tensor '" << name << "' on rank "
              << kv.first << " has " << e.splits.size()
              << " entries; expected world size " << opts_.world << ".";
          return err.str();
        }
        int64_t sum = 0;
        for (int64_t s : e.splits) {
          if (s < 0) {
            err << "Alltoall splits for tensor '" << name << "' on rank "
                << kv.first << " contains a negative entry.";
            return err.str();
          }
          sum += s;
        }
        if (sum != e.shape[0]) {
          err << "Alltoall splits for tensor '" << name << "' on rank "
              << kv.first << " sum to " << sum << " but dim 0 is "
              << e.shape[0] << ".";
          return err.str();
        }
        if (e.shape.size() != e0->shape.size() ||
            !std::equal(e.shape.begin() + 1, e.shape.end(),
                        e0->shape.begin() + 1)) {
          err << "Mismatched alltoall tensor shapes beyond first dimension "
                 "for '" << name << "'";
          return err.str();
        }
      }
    } else {
      int64_t d0 = e0->shape.empty() ? 0 : e0->shape[0];
      if (e0->shape.empty() || d0 % opts_.world != 0) {
        err << "Alltoall tensor '" << name << "' first dimension (" << d0
            << ") must be divisible by world size " << opts_.world << ".";
        return err.str();
      }
    }
  }
  if (e0->type == RequestType::BROADCAST) {
    for (auto& kv : st.by_rank) {
      if (kv.second.root_rank != e0->root_rank) {
        err << "Mismatched root ranks for broadcast '" << name << "'";
        return err.str();
      }
    }
    if (e0->root_rank < 0 || e0->root_rank >= opts_.world) {
      err << "Invalid root rank " << e0->root_rank << " for broadcast '"
          << name << "' (world size " << opts_.world << ").";
      return err.str();
    }
  }
  if (!joined_.empty() && (e0->type == RequestType::ALLGATHER ||
                           e0->type == RequestType::BROADCAST ||
                           e0->type == RequestType::ALLTOALL)) {
    // parity: controller.cc:434-437, 510-513
    err << (e0->type == RequestType::ALLGATHER
                ? "ALLGATHER"
                : e0->type == RequestType::BROADCAST ? "BROADCAST"
                                                     : "ALLTOALL")
        << " is not supported while a rank has joined.";
    return err.str();
  }
  return "";
}

std::string Controller::FusionSig(const PendingEntry& e) const {
  std::ostringstream s;
  s << static_cast<int>(e.type) << '|' << static_cast<int>(e.dtype) << '|'
    << (e.average ? 1 : 0) << '|' << e.prescale << '|' << e.postscale << '|'
    << e.root_rank;
  return s.str();
}

TickResult Controller::Tick(int64_t now_us) {
  std::lock_guard<std::mutex> l(mu_);
  TickResult out;
  if (shutdown_) return out;

  std::set<int32_t> active;
  if (opts_.local_only) {
    if (!joined_.count(opts_.self_rank)) active.insert(opts_.self_rank);
  } else {
    for (int32_t r = 0; r < opts_.world; ++r)
      if (!joined_.count(r)) active.insert(r);
  }

  // all joined + nothing pending -> release join barrier
  // (controller.cc:202-256)
  bool all_joined = opts_.local_only
                        ? joined_.count(opts_.self_rank) > 0
                        : static_cast<int32_t>(joined_.size()) == opts_.world;
  if (!joined_.empty() && all_joined && table_.empty()) {
    for (auto& jh : join_handles_) out.join_handles_released.push_back(jh.second);
    out.last_joined = last_joined_;
    join_handles_.clear();
    joined_.clear();
    return out;
  }

  // readiness scan in first-submission order
  std::vector<std::string> ready;
  std::vector<std::string> still_waiting;
  for (const auto& name : order_) {
    auto it = table_.find(name);
    if (it == table_.end()) continue;
    auto& st = it->second;
    bool all_in = true;
    for (int32_t r : active)
      if (!st.by_rank.count(r)) { all_in = false; break; }
    if (all_in) {
      ready.push_back(name);
    } else {
      double waited_s = (now_us - st.first_seen_us) / 1e6;
      if (opts_.collective_timeout_s > 0 &&
          waited_s > opts_.collective_timeout_s) {
        // enforced watchdog: fail every submitted handle with an error
        // naming the missing ranks (message format shared with the Python
        // controllers; the engine keys CollectiveTimeoutError off the
        // "collective timeout" prefix)
        std::ostringstream msg;
        msg << "collective timeout: tensor '" << name << "' waited "
            << static_cast<int64_t>(waited_s) << "s on ranks [";
        bool first = true;
        for (int32_t r : active) {
          if (!st.by_rank.count(r)) {
            if (!first) msg << ", ";
            msg << r;
            first = false;
          }
        }
        msg << "] (HOROVOD_COLLECTIVE_TIMEOUT=" << opts_.collective_timeout_s
            << "s exceeded)";
        Response resp;
        resp.type = ResponseType::ERROR;
        resp.names = {name};
        resp.error_message = msg.str();
        std::vector<std::pair<int32_t, int64_t>> rhs;
        for (auto& kv : st.by_rank)
          rhs.push_back({kv.first, kv.second.handle});
        std::sort(rhs.begin(), rhs.end());
        out.responses.push_back(std::move(resp));
        out.handles.push_back(std::move(rhs));
        table_.erase(it);
        continue;
      }
      still_waiting.push_back(name);
      if (waited_s > opts_.stall_warning_s && !st.stall_warned) {
        st.stall_warned = true;
        out.stall_warnings.push_back(name);
      }
      if (opts_.stall_shutdown_s > 0 && waited_s > opts_.stall_shutdown_s)
        out.stall_shutdown = true;
    }
  }
  if (ready.empty()) {
    // keep order_ compacted to names still pending
    order_ = still_waiting;
    return out;
  }

  // validate -> single responses (or errors)
  struct Single {
    std::string name;
    PendingEntry e0;
    int64_t bytes;
    std::vector<std::pair<int32_t, int64_t>> rank_handles;
    bool used = false;
    std::string sig;
  };
  std::vector<Single> singles;
  for (const auto& name : ready) {
    auto it = table_.find(name);
    auto& st = it->second;
    std::string err = Validate(name, st);
    std::vector<std::pair<int32_t, int64_t>> rhs;
    for (auto& kv : st.by_rank) rhs.push_back({kv.first, kv.second.handle});
    std::sort(rhs.begin(), rhs.end());
    if (!err.empty()) {
      Response r;
      r.type = ResponseType::ERROR;
      r.names = {name};
      r.error_message = err;
      out.responses.push_back(std::move(r));
      out.handles.push_back(std::move(rhs));
      table_.erase(it);
      continue;
    }
    Single s;
    s.name = name;
    // lowest-rank entry is canonical (all validated equal)
    s.e0 = st.by_rank.begin()->second;
    for (auto& kv : st.by_rank)
      if (kv.first < s.e0.rank) s.e0 = kv.second;
    s.bytes = s.e0.num_bytes();
    s.rank_handles = std::move(rhs);
    s.sig = FusionSig(s.e0);
    singles.push_back(std::move(s));
    table_.erase(it);
  }
  order_ = still_waiting;

  // fusion with lookahead (FuseResponses, controller.cc:626-750)
  for (size_t i = 0; i < singles.size(); ++i) {
    if (singles[i].used) continue;
    singles[i].used = true;
    std::vector<size_t> bucket{i};
    int64_t total = singles[i].bytes;
    RequestType t = singles[i].e0.type;
    bool fusable = opts_.fusion_enabled &&
                   (t == RequestType::ALLREDUCE || t == RequestType::ADASUM ||
                    t == RequestType::ALLGATHER);
    if (fusable) {
      for (size_t j = i + 1; j < singles.size(); ++j) {
        if (singles[j].used) continue;
        if (singles[j].sig == singles[i].sig &&
            total + singles[j].bytes <= opts_.fusion_threshold_bytes) {
          singles[j].used = true;
          bucket.push_back(j);
          total += singles[j].bytes;
        }
      }
    }
    Response r;
    r.type = static_cast<ResponseType>(static_cast<int32_t>(t));
    r.average = singles[i].e0.average;
    r.prescale = singles[i].e0.prescale;
    r.postscale = singles[i].e0.postscale;
    r.root_rank = singles[i].e0.root_rank;
    std::vector<std::pair<int32_t, int64_t>> hs;
    for (size_t k : bucket) {
      r.names.push_back(singles[k].name);
      for (auto& rh : singles[k].rank_handles) hs.push_back(rh);
    }
    // cache the fused signature (ResponseCache fast-path bookkeeping)
    std::string fused_sig = singles[i].sig;
    for (size_t k : bucket) fused_sig += '|' + singles[k].name;
    if (!cache_.Lookup(fused_sig)) cache_.Insert(fused_sig);
    out.responses.push_back(std::move(r));
    out.handles.push_back(std::move(hs));
  }
  return out;
}

}  // namespace hvdtpu
