// Shared value types for the native engine core.
//
// TPU-native rebuild of horovod/common/common.h (Status, TensorShape,
// TensorTableEntry) and message.h (RequestType/ResponseType). The data plane
// is XLA, so tensors never cross this boundary — only metadata does: the
// engine negotiates, validates, fuses and schedules; Python executes the
// fused XLA collective it is handed.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace hvdtpu {

enum class RequestType : int32_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  JOIN = 3,
  ADASUM = 4,
  ALLTOALL = 5,
};

enum class ResponseType : int32_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  JOIN = 3,
  ADASUM = 4,
  ALLTOALL = 5,
  ERROR = 6,
};

// dtype codes shared with the Python side (runtime/native.py)
enum class DType : int32_t {
  F16 = 0, BF16 = 1, F32 = 2, F64 = 3,
  I8 = 4, I16 = 5, I32 = 6, I64 = 7,
  U8 = 8, U16 = 9, U32 = 10, U64 = 11,
  BOOL = 12,
};

inline int64_t DTypeSize(DType d) {
  switch (d) {
    case DType::I8: case DType::U8: case DType::BOOL: return 1;
    case DType::F16: case DType::BF16: case DType::I16: case DType::U16:
      return 2;
    case DType::F32: case DType::I32: case DType::U32: return 4;
    default: return 8;
  }
}

// One rank's pending named-tensor request (metadata only).
struct PendingEntry {
  std::string name;
  int32_t rank = 0;
  RequestType type = RequestType::ALLREDUCE;
  DType dtype = DType::F32;
  std::vector<int64_t> shape;
  int32_t root_rank = -1;
  bool average = false;
  double prescale = 1.0;
  double postscale = 1.0;
  // ragged alltoall: rows of dim 0 sent to each peer (empty = equal split)
  std::vector<int64_t> splits;
  int64_t handle = -1;
  int64_t enqueue_us = 0;  // monotonic microseconds at submit

  int64_t num_elements() const {
    int64_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
  int64_t num_bytes() const { return num_elements() * DTypeSize(dtype); }
};

// Coordinator decision: one (possibly fused) operation, or an error.
struct Response {
  ResponseType type = ResponseType::ERROR;
  std::vector<std::string> names;
  std::string error_message;
  bool average = false;
  double prescale = 1.0;
  double postscale = 1.0;
  int32_t root_rank = -1;
};

}  // namespace hvdtpu
