// C ABI for the native engine core, consumed from Python via ctypes.
//
// TPU-native analogue of the reference's C API surface (operations.cc:642-934
// horovod_init/.../EnqueueTensorAllreduce) reshaped for the split control
// plane (C++) / data plane (XLA): Python submits tensor *metadata*, ticks the
// controller, receives wire-encoded ResponseLists, executes the fused XLA
// collective, and reports completion + throughput scores back for autotuning.
#include <chrono>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "autotune.h"
#include "common.h"
#include "controller.h"
#include "timeline.h"
#include "wire.h"

using namespace hvdtpu;

namespace {

int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct EngineCore {
  std::unique_ptr<Controller> controller;
  std::unique_ptr<TimelineWriter> timeline;
  std::unique_ptr<ParameterManager> params;
  // last tick's encoded payloads, kept alive until the next call
  std::string tick_buf;
  std::mutex buf_mu;
};

std::mutex g_mu;
std::unordered_map<int64_t, std::unique_ptr<EngineCore>> g_engines;
int64_t g_next = 1;

EngineCore* Get(int64_t h) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_engines.find(h);
  return it == g_engines.end() ? nullptr : it->second.get();
}

}  // namespace

extern "C" {

// returns engine handle
int64_t hvd_core_create(int32_t world, int64_t fusion_threshold_bytes,
                        double stall_warning_s, double stall_shutdown_s,
                        int64_t cache_capacity, int32_t fusion_enabled,
                        const char* timeline_path, int32_t autotune,
                        double cycle_time_ms, int32_t local_only,
                        int32_t self_rank) {
  auto core = std::make_unique<EngineCore>();
  ControllerOptions opts;
  opts.world = world;
  opts.fusion_threshold_bytes = fusion_threshold_bytes;
  opts.stall_warning_s = stall_warning_s;
  opts.stall_shutdown_s = stall_shutdown_s;
  opts.cache_capacity = static_cast<size_t>(cache_capacity);
  opts.fusion_enabled = fusion_enabled != 0;
  opts.local_only = local_only != 0;
  opts.self_rank = self_rank;
  // read here rather than threaded through the C ABI: the create signature
  // is shared with older prebuilt libraries (see native.py rebuild-on-
  // missing-symbol), and the knob is process-wide anyway
  if (const char* ct = std::getenv("HOROVOD_COLLECTIVE_TIMEOUT")) {
    char* end = nullptr;
    double v = std::strtod(ct, &end);
    if (end != ct && v > 0) opts.collective_timeout_s = v;
  }
  core->controller = std::make_unique<Controller>(opts);
  core->timeline = std::make_unique<TimelineWriter>(
      timeline_path ? timeline_path : "");
  core->params = std::make_unique<ParameterManager>(
      fusion_threshold_bytes, cycle_time_ms);
  core->params->SetEnabled(autotune != 0);
  std::lock_guard<std::mutex> l(g_mu);
  int64_t h = g_next++;
  g_engines[h] = std::move(core);
  return h;
}

void hvd_core_destroy(int64_t eng) {
  std::lock_guard<std::mutex> l(g_mu);
  g_engines.erase(eng);
}

// submit one named tensor; returns handle >= 0, -1 duplicate, -2 shutdown,
// -3 bad engine
int64_t hvd_core_submit(int64_t eng, const char* name, int32_t rank,
                        int32_t req_type, int32_t dtype, int32_t ndim,
                        const int64_t* dims, int32_t root_rank,
                        int32_t average, double prescale, double postscale,
                        const int64_t* splits, int32_t nsplits) {
  EngineCore* c = Get(eng);
  if (!c) return -3;
  PendingEntry e;
  e.name = name;
  e.rank = rank;
  e.type = static_cast<RequestType>(req_type);
  e.dtype = static_cast<DType>(dtype);
  e.shape.assign(dims, dims + ndim);
  e.root_rank = root_rank;
  e.average = average != 0;
  e.prescale = prescale;
  e.postscale = postscale;
  if (nsplits > 0 && splits) e.splits.assign(splits, splits + nsplits);
  e.enqueue_us = NowUs();
  int64_t h = c->controller->Submit(e);
  if (h >= 0) c->timeline->NegotiateStart(e.name, rank, e.enqueue_us);
  return h;
}

int64_t hvd_core_join(int64_t eng, int32_t rank) {
  EngineCore* c = Get(eng);
  if (!c) return -3;
  return c->controller->Join(rank);
}

// One negotiation tick. Returns byte length of the encoded payload (0 = no
// work) and sets *data to an internal buffer valid until the next tick call.
// Payload layout: wire ResponseList, then for each response
// u32 n_handle_pairs, (i32 rank, i64 handle)*, then u32 n_released_join,
// i64*, i32 last_joined, u32 n_stall_warnings, str*, u8 stall_shutdown.
int64_t hvd_core_tick(int64_t eng, const char** data) {
  EngineCore* c = Get(eng);
  if (!c) return -3;
  TickResult r = c->controller->Tick(NowUs());
  if (r.responses.empty() && r.join_handles_released.empty() &&
      r.stall_warnings.empty() && !r.stall_shutdown)
    return 0;
  wire::Writer w;
  w.out = wire::EncodeResponseList(r.responses);
  for (auto& hs : r.handles) {
    w.u32(static_cast<uint32_t>(hs.size()));
    for (auto& p : hs) {
      w.i32(p.first);
      w.i64(p.second);
    }
  }
  w.u32(static_cast<uint32_t>(r.join_handles_released.size()));
  for (auto h : r.join_handles_released) w.i64(h);
  w.i32(r.last_joined);
  w.u32(static_cast<uint32_t>(r.stall_warnings.size()));
  for (auto& s : r.stall_warnings) w.str(s);
  w.u8(r.stall_shutdown ? 1 : 0);
  std::lock_guard<std::mutex> l(c->buf_mu);
  c->tick_buf = std::move(w.out);
  *data = c->tick_buf.data();
  return static_cast<int64_t>(c->tick_buf.size());
}

// shutdown: returns orphan handles to fail (same buffer protocol)
int64_t hvd_core_shutdown(int64_t eng, const char** data) {
  EngineCore* c = Get(eng);
  if (!c) return -3;
  std::vector<int64_t> orphans;
  c->controller->Shutdown(&orphans);
  c->timeline->Close();
  wire::Writer w;
  w.u32(static_cast<uint32_t>(orphans.size()));
  for (auto h : orphans) w.i64(h);
  std::lock_guard<std::mutex> l(c->buf_mu);
  c->tick_buf = std::move(w.out);
  *data = c->tick_buf.data();
  return static_cast<int64_t>(c->tick_buf.size());
}

// timeline hooks for the execution phase (fired from Python around the XLA
// call; ts recorded here so host clock is consistent)
void hvd_core_timeline_op_start(int64_t eng, const char* tensor,
                                const char* op) {
  EngineCore* c = Get(eng);
  if (c) c->timeline->OpStart(tensor, op, NowUs());
}
void hvd_core_timeline_activity(int64_t eng, const char* tensor,
                                const char* activity) {
  EngineCore* c = Get(eng);
  if (c) c->timeline->Activity(tensor, activity, NowUs());
}
void hvd_core_timeline_op_end(int64_t eng, const char* tensor) {
  EngineCore* c = Get(eng);
  if (c) c->timeline->OpEnd(tensor, NowUs());
}
void hvd_core_timeline_cache(int64_t eng, uint64_t hits, uint64_t misses) {
  EngineCore* c = Get(eng);
  if (c) c->timeline->CacheCounter(hits, misses, NowUs());
}

void hvd_core_timeline_cycle(int64_t eng) {
  EngineCore* c = Get(eng);
  if (c) c->timeline->CycleMarker(NowUs());
}

// apply the reference's four HOROVOD_AUTOTUNE_* tuning knobs
// (parameter_manager.cc:42-59) to the engine-internal tuner; pass -1
// (or <=0 for the float) to keep a knob at its default — warmup accepts 0
void hvd_core_tuner_configure(int64_t eng, int32_t warmup_samples,
                              int32_t steps_per_sample, int32_t max_samples,
                              double gp_noise) {
  EngineCore* c = Get(eng);
  if (c && c->params) {
    c->params->Configure(warmup_samples, steps_per_sample, max_samples,
                         gp_noise);
  }
}

// autotune: report an execution interval; returns 1 if params changed
int32_t hvd_core_report_score(int64_t eng, int64_t bytes, double seconds) {
  EngineCore* c = Get(eng);
  if (!c) return 0;
  bool changed = c->params->Update(bytes, seconds);
  if (changed)
    c->controller->set_fusion_threshold(c->params->fusion_threshold());
  return changed ? 1 : 0;
}

int64_t hvd_core_fusion_threshold(int64_t eng) {
  EngineCore* c = Get(eng);
  return c ? c->controller->fusion_threshold() : -1;
}

double hvd_core_cycle_time_ms(int64_t eng) {
  EngineCore* c = Get(eng);
  return c ? c->params->cycle_time_ms() : -1.0;
}

uint64_t hvd_core_cache_hits(int64_t eng) {
  EngineCore* c = Get(eng);
  return c ? c->controller->cache_hits() : 0;
}

uint64_t hvd_core_cache_misses(int64_t eng) {
  EngineCore* c = Get(eng);
  return c ? c->controller->cache_misses() : 0;
}

// ---------------------------------------------------------------------------
// Standalone parameter-manager handles: the cross-process coordinator runs
// the SAME GP/EI tuner at rank 0 and broadcasts the tuned
// (fusion_threshold, cycle_time) in its ResponseList — the role the
// reference's coordinator plays when it re-broadcasts parameter-manager
// updates to all workers. Kept separate from EngineCore so the Python
// control plane can own one without instantiating a native controller.

namespace {
std::unordered_map<int64_t, std::unique_ptr<hvdtpu::ParameterManager>> g_tuners;
}  // namespace

int64_t hvd_tuner_create(int64_t fusion_threshold_bytes, double cycle_time_ms,
                         uint64_t seed) {
  auto t = std::make_unique<hvdtpu::ParameterManager>(
      fusion_threshold_bytes, cycle_time_ms, seed);
  t->SetEnabled(true);
  std::lock_guard<std::mutex> l(g_mu);
  int64_t h = g_next++;
  g_tuners[h] = std::move(t);
  return h;
}

// the reference's four HOROVOD_AUTOTUNE_* tuning knobs; <=0 keeps defaults
void hvd_tuner_configure(int64_t h, int32_t warmup_samples,
                         int32_t steps_per_sample, int32_t max_samples,
                         double gp_noise) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_tuners.find(h);
  if (it != g_tuners.end()) {
    it->second->Configure(warmup_samples, steps_per_sample, max_samples,
                          gp_noise);
  }
}

// returns 1 if (threshold, cycle_time) changed
int32_t hvd_tuner_update(int64_t h, int64_t bytes, double seconds) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_tuners.find(h);
  return (it != g_tuners.end() && it->second->Update(bytes, seconds)) ? 1 : 0;
}

// 1 while still exploring; 0 once settled on the best configuration
int32_t hvd_tuner_active(int64_t h) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_tuners.find(h);
  return (it != g_tuners.end() && it->second->enabled()) ? 1 : 0;
}

int32_t hvd_core_autotune_active(int64_t eng) {
  EngineCore* c = Get(eng);
  return (c && c->params->enabled()) ? 1 : 0;
}

int64_t hvd_tuner_threshold(int64_t h) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_tuners.find(h);
  return it == g_tuners.end() ? -1 : it->second->fusion_threshold();
}

double hvd_tuner_cycle_ms(int64_t h) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_tuners.find(h);
  return it == g_tuners.end() ? -1.0 : it->second->cycle_time_ms();
}

void hvd_tuner_destroy(int64_t h) {
  std::lock_guard<std::mutex> l(g_mu);
  g_tuners.erase(h);
}

}  // extern "C"
