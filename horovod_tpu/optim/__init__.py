from .broadcast import (  # noqa: F401
    broadcast_optimizer_state,
    broadcast_parameters,
    broadcast_pytree,
)
from .distributed import (  # noqa: F401
    DistributedAdasumOptimizer,
    DistributedOptimizer,
    allreduce_gradients,
)
from .fused import AdamWState, fused_adamw  # noqa: F401
from .zero import shard_opt_state, zero1_shardings  # noqa: F401
