"""Parameter / optimizer-state / object broadcast.

Reference parity:
  - `horovod/torch/__init__.py:437-466` ``broadcast_parameters`` — broadcast
    every named parameter from root.
  - `horovod/torch/__init__.py:469-585` ``broadcast_optimizer_state`` — walks
    optimizer state, wraps scalar options into tensors, casts back after.
  - `horovod/tensorflow/__init__.py:139-227` ``broadcast_variables`` /
    ``BroadcastGlobalVariablesHook``.

The checkpoint/resume pattern this enables is the reference's supported one
(SURVEY §5): rank 0 restores from disk, everyone else receives via broadcast.
Pytrees replace the name→tensor dicts; names are derived from key paths so
every rank negotiates the same tensor names.
"""

from __future__ import annotations

import pickle
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import basics
from ..ops import collective_ops as ops


def _named_leaves(tree, prefix: str):
    leaves = jax.tree_util.tree_leaves_with_path(tree)
    out = []
    for path, leaf in leaves:
        name = prefix + jax.tree_util.keystr(path)
        out.append((name, leaf))
    return out


def broadcast_parameters(params, root_rank: int = 0, prefix: str = "param"):
    """Broadcast every leaf of a pytree from ``root_rank``; returns the tree
    with every rank holding root's values."""
    if basics.size() == 1:
        return params
    named = _named_leaves(params, prefix)
    handles = [ops.broadcast_async(jnp.asarray(v), root_rank, name=n)
               for n, v in named]
    results = [ops.synchronize(h) for h in handles]
    flat = [r.reshape(np.shape(v)) if hasattr(r, "reshape") else r
            for r, (_, v) in zip(results, named)]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, flat)


def broadcast_pytree(tree, root_rank: int = 0, prefix: str = "tree"):
    """Broadcast an arbitrary pytree from ``root_rank``, tolerating python
    scalar leaves (step counts, schedule positions): scalars are wrapped into
    arrays for the wire and cast back after, mirroring the scalar-wrapping in
    `torch/__init__.py:469-585`. Array leaves go through
    :func:`broadcast_parameters` unchanged."""
    if basics.size() == 1:
        return tree
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    wrapped = []
    kinds = []  # remember python scalar types to cast back
    for leaf in leaves:
        if isinstance(leaf, (int, float)):
            kinds.append(type(leaf))
            wrapped.append(jnp.asarray(leaf))
        else:
            kinds.append(None)
            wrapped.append(leaf)
    full = jax.tree_util.tree_unflatten(treedef, wrapped)
    full = broadcast_parameters(full, root_rank, prefix=prefix)
    leaves2 = jax.tree_util.tree_leaves(full)
    restored = [k(l) if k is not None else l for k, l in zip(kinds, leaves2)]
    return jax.tree_util.tree_unflatten(treedef, restored)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast optimizer state (optax pytree). Delegates to
    :func:`broadcast_pytree` for the scalar-leaf handling."""
    return broadcast_pytree(opt_state, root_rank, prefix="opt")


def broadcast_object(obj: Any, root_rank: int = 0, name: Optional[str] = None):
    """Broadcast an arbitrary picklable object (config, RNG key tuple, ...).

    Serialization rides the byte-collective: length broadcast first (so
    non-root ranks can size their buffer), then the payload as uint8.
    """
    return broadcast_from_root(lambda: obj, root_rank, name=name)


def broadcast_from_root(producer, root_rank: int = 0,
                        name: Optional[str] = None):
    """Run ``producer()`` on the root rank and broadcast its (picklable)
    result to every rank.

    Root-side failures — in ``producer`` itself (file reads, deserialization)
    or in pickling — are broadcast as an error sentinel and re-raised as the
    SAME ``RuntimeError`` on every rank: if root raised before the collective,
    peers would hang in broadcast forever. Non-root ranks never call
    ``producer`` (the resource may only exist on root's host).

    Wire format: a 3xint32 header (error flag, then the payload length split
    into two int32 halves — int64 would be silently canonicalized to int32 by
    the collective layer when jax_enable_x64 is off, wrapping for >= 2 GiB
    payloads) followed by the uint8 payload.
    """
    if basics.size() == 1:
        return producer()
    name = name or "broadcast_object"
    if basics.rank() == root_rank:
        try:
            payload = np.frombuffer(pickle.dumps(producer()),
                                    dtype=np.uint8).copy()
            failed = 0
        except Exception as e:  # ANY root failure must reach all ranks
            msg = (f"broadcast_from_root: root rank {root_rank} failed: "
                   f"{type(e).__name__}: {e}")
            payload = np.frombuffer(pickle.dumps(msg), dtype=np.uint8).copy()
            failed = 1
        header = np.array([failed, payload.size >> 31,
                           payload.size & 0x7FFFFFFF], np.int32)
    else:
        payload = np.zeros((0,), dtype=np.uint8)
        header = np.zeros((3,), np.int32)
    h = np.asarray(ops.broadcast(header, root_rank, name=f"{name}.len"))
    failed, nbytes = int(h[0]), (int(h[1]) << 31) | int(h[2])
    if basics.rank() != root_rank:
        payload = np.zeros((nbytes,), dtype=np.uint8)
    data = ops.broadcast(payload, root_rank, name=f"{name}.data")
    result = pickle.loads(np.asarray(data).tobytes())
    if failed:
        raise RuntimeError(result)  # same error, every rank
    return result
