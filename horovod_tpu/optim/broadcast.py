"""Parameter / optimizer-state / object broadcast.

Reference parity:
  - `horovod/torch/__init__.py:437-466` ``broadcast_parameters`` — broadcast
    every named parameter from root.
  - `horovod/torch/__init__.py:469-585` ``broadcast_optimizer_state`` — walks
    optimizer state, wraps scalar options into tensors, casts back after.
  - `horovod/tensorflow/__init__.py:139-227` ``broadcast_variables`` /
    ``BroadcastGlobalVariablesHook``.

The checkpoint/resume pattern this enables is the reference's supported one
(SURVEY §5): rank 0 restores from disk, everyone else receives via broadcast.
Pytrees replace the name→tensor dicts; names are derived from key paths so
every rank negotiates the same tensor names.
"""

from __future__ import annotations

import pickle
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import basics
from ..ops import collective_ops as ops


def _named_leaves(tree, prefix: str):
    leaves = jax.tree_util.tree_leaves_with_path(tree)
    out = []
    for path, leaf in leaves:
        name = prefix + jax.tree_util.keystr(path)
        out.append((name, leaf))
    return out


def broadcast_parameters(params, root_rank: int = 0, prefix: str = "param"):
    """Broadcast every leaf of a pytree from ``root_rank``; returns the tree
    with every rank holding root's values."""
    if basics.size() == 1:
        return params
    named = _named_leaves(params, prefix)
    handles = [ops.broadcast_async(jnp.asarray(v), root_rank, name=n)
               for n, v in named]
    results = [ops.synchronize(h) for h in handles]
    flat = [r.reshape(np.shape(v)) if hasattr(r, "reshape") else r
            for r, (_, v) in zip(results, named)]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, flat)


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast optimizer state (optax pytree). Non-array leaves (step counts,
    schedules as scalars) are wrapped into arrays for the wire and unwrapped
    after, mirroring the scalar-wrapping in `torch/__init__.py:469-585`."""
    if basics.size() == 1:
        return opt_state
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    wrapped = []
    kinds = []  # remember python scalar types to cast back
    for leaf in leaves:
        if isinstance(leaf, (int, float)):
            kinds.append(type(leaf))
            wrapped.append(jnp.asarray(leaf))
        else:
            kinds.append(None)
            wrapped.append(leaf)
    tree = jax.tree_util.tree_unflatten(treedef, wrapped)
    tree = broadcast_parameters(tree, root_rank, prefix="opt")
    leaves2 = jax.tree_util.tree_leaves(tree)
    restored = [k(l) if k is not None else l for k, l in zip(kinds, leaves2)]
    return jax.tree_util.tree_unflatten(treedef, restored)


def broadcast_object(obj: Any, root_rank: int = 0, name: Optional[str] = None):
    """Broadcast an arbitrary picklable object (config, RNG key tuple, ...).

    Serialization rides the byte-collective: length broadcast first (so
    non-root ranks can size their buffer), then the payload as uint8.
    """
    if basics.size() == 1:
        return obj
    name = name or "broadcast_object"
    if basics.rank() == root_rank:
        # a root-side failure must fail every rank symmetrically — if root
        # raised before the collective, peers would hang in broadcast forever.
        # A negative length header marks "payload is a pickled error string".
        try:
            payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8).copy()
            header = payload.size
        except Exception as e:  # pickling/serialization failure of any kind
            msg = f"broadcast_object root failure: {type(e).__name__}: {e}"
            payload = np.frombuffer(pickle.dumps(msg), dtype=np.uint8).copy()
            header = -payload.size
    else:
        payload = np.zeros((0,), dtype=np.uint8)
        header = 0
    # int64 header: checkpoints >= 2 GiB must not overflow the length wire
    n = ops.broadcast(np.array([header], np.int64), root_rank,
                      name=f"{name}.len")
    signed = int(np.asarray(n)[0])
    nbytes = abs(signed)
    if basics.rank() != root_rank:
        payload = np.zeros((nbytes,), dtype=np.uint8)
    data = ops.broadcast(payload, root_rank, name=f"{name}.data")
    result = pickle.loads(np.asarray(data).tobytes())
    if signed < 0:
        raise RuntimeError(result)  # same error, every rank
    return result
