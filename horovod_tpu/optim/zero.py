"""ZeRO-1 optimizer-state sharding over the data-parallel mesh axis.

No reference counterpart (Horovod 0.18.2 replicates optimizer state on every
worker; DeepSpeed-style state partitioning postdates it) — this is the
TPU-native extension the round-2 verdict asked for: AdamW's m/v for a P-param
model cost 8P bytes fp32, and replicating them on every chip caps the batch
size long before the MXU saturates.

TPU-first design: ZeRO-1 here is a SHARDING ANNOTATION, not a communication
schedule. Each optimizer-state leaf is partitioned along its first
dp-divisible dimension over the ``dp`` axis; params stay replicated. Under
``jit`` GSPMD then materializes exactly the ZeRO-1 dataflow by itself:
gradients reduce-scatter into the state shards, the elementwise optimizer
math runs shard-locally (1/N of the state per chip — the memory win), and
the param delta all-gathers back to the replicated params. No hand-written
gather/scatter, no step barrier — the XLA scheduler overlaps the collectives
with the backward pass like any other GSPMD program.

Usage::

    tx = optax.adamw(3e-4)
    opt_state = tx.init(params)
    shardings = zero1_shardings(opt_state, mesh)          # pytree of specs
    opt_state = jax.device_put(opt_state, shardings)      # place sharded
    step = jax.jit(step_fn, donate_argnums=(0, 1),
                   in_shardings=(repl, shardings, ...),
                   out_shardings=(repl, shardings, ...))

or the one-call helper :func:`horovod_tpu.spmd.make_train_step` with
``zero1=True``.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..basics import MESH_AXIS


def _leaf_spec(leaf, n: int, axis: str) -> P:
    """Partition along the FIRST axis-divisible dimension; replicate
    otherwise (scalars like Adam's step count, odd-shaped leaves)."""
    shape = np.shape(leaf)
    for dim, size in enumerate(shape):
        if size % n == 0 and size > 0:
            return P(*([None] * dim + [axis]))
    return P()


def zero1_shardings(opt_state: Any, mesh: Mesh,
                    axis: str = MESH_AXIS) -> Any:
    """Pytree of ``NamedSharding`` matching ``opt_state``: every leaf
    partitioned 1/N over the ``axis`` mesh dimension where divisible."""
    n = mesh.shape[axis]

    def spec(leaf):
        return NamedSharding(mesh, _leaf_spec(leaf, n, axis))

    return jax.tree_util.tree_map(spec, opt_state)


def shard_opt_state(opt_state: Any, mesh: Optional[Mesh] = None,
                    axis: str = MESH_AXIS) -> Any:
    """Place an (already materialized) optimizer state as ZeRO-1 shards."""
    from .. import basics

    mesh = mesh or basics.mesh()
    sh = zero1_shardings(opt_state, mesh, axis)
    return jax.tree_util.tree_map(jax.device_put, opt_state, sh)


def ring_chunk(total: int, world: int, block: int) -> int:
    """Per-rank chunk of the flattened parameter vector on the quantized
    ring (`spmd.quantized_reduce_scatter`): ceil(total/world) rounded up to
    whole quantization blocks so every hop's packed rows have no ragged
    tail."""
    per_rank = -(-total // world)
    return -(-per_rank // block) * block


def shard_bounds(total: int, world: int, index: int,
                 block: int = 1) -> Tuple[int, int]:
    """Exact ``[lo, hi)`` element bounds of shard ``index`` in a 1/N
    partition of a ``total``-length flat vector, with ``lo`` aligned to
    ``block`` boundaries and ``hi`` clamped to ``total`` — the last shard
    absorbs the ragged tail instead of padding it. With ``block=1`` this
    is the byte partition the checkpoint bundle uses (ckpt/manager.py):
    concatenating every shard in slot order reassembles the vector
    byte-for-byte, no trim step needed. With the quantization block it is
    the start/stop of the rank's :func:`ring_chunk` region."""
    per = ring_chunk(total, world, block)
    lo = min(index * per, total)
    return lo, min(lo + per, total)


def flat_zero1_state(tx, total: int, mesh: Mesh, block: int,
                     axis: str = MESH_AXIS) -> Any:
    """Optimizer state for the quantized-ring ZeRO-1 step
    (`spmd.make_train_step(compression=..., zero1=True)`).

    Where plain ZeRO-1 above is a sharding annotation on the tree-shaped
    state (GSPMD infers the reduce-scatter), the quantized ring makes the
    schedule explicit, so the state lives in FLAT space: the transform is
    initialized over the zero-padded flattened parameter vector and every
    full-length leaf is sharded 1/N — each rank holds exactly the m/v/
    momentum for its ring chunk, the same 1/N memory win. Valid for
    elementwise transforms (sgd/momentum/adam/adamw), where the flat-space
    update equals the tree-space update leaf-for-leaf.
    """
    import jax.numpy as jnp

    n = mesh.shape[axis]
    padded = n * ring_chunk(total, n, block)
    state = tx.init(jnp.zeros((padded,), jnp.float32))

    def _put(leaf):
        if np.shape(leaf) == (padded,):
            return jax.device_put(leaf, NamedSharding(mesh, P(axis)))
        return jax.device_put(leaf, NamedSharding(mesh, P()))

    return jax.tree_util.tree_map(_put, state)
