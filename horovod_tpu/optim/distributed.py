"""Distributed optimizer and gradient wrappers (JAX/optax surface).

Reference parity:
  - `horovod/torch/__init__.py:115-209` ``_DistributedOptimizer`` — hooks fire
    per-gradient async allreduce during backward, ``synchronize()`` drains
    before ``step()``; ``backward_passes_per_step`` accumulates locally.
  - `horovod/tensorflow/__init__.py:473-530` ``DistributedGradientTape`` and
    :230-295 ``_DistributedOptimizer.compute_gradients``.

JAX shape: gradients are a pytree produced by ``jax.grad``. Two modes:

  * **Eager engine mode** (`DistributedOptimizer` / `allreduce_gradients`) —
    each gradient leaf becomes a named async allreduce through the background
    engine, overlapping collectives exactly like the torch hook flow. Used for
    op-by-op training loops and API parity.
  * **SPMD mode** (`horovod_tpu.spmd.make_train_step`) — the whole step is one
    XLA program; gradient averaging is compiler-inserted. Use this for peak
    throughput.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from .. import basics
from ..basics import Adasum, Average, Sum
from ..ops import collective_ops as ops
from ..ops.compression import Compression


def allreduce_gradients(grads, op: int = Average,
                        compression=Compression.none, prefix: str = "grad",
                        sparse_as_dense: bool = False):
    """Average a gradient pytree across ranks through the engine: one named
    async allreduce per leaf, all in flight simultaneously (the hook-overlap
    pattern of `torch/__init__.py:115-150`), then drained in order.

    `ops.sparse.IndexedSlices` leaves (embedding-style sparse grads) take
    the two-allgather path (`tensorflow/__init__.py:75-91`); pass
    ``sparse_as_dense=True`` to densify them first
    (`_keras/__init__.py:50-53`).
    """
    from ..ops import sparse as _sparse

    is_sparse = lambda x: isinstance(x, _sparse.IndexedSlices)  # noqa: E731
    if basics.size() == 1:
        # Keep single-rank and multi-rank return types consistent:
        # sparse_as_dense must densify here too, or optax would tree_map
        # into the IndexedSlices on single-process debug runs.
        return _sparse.densify_tree(grads) if sparse_as_dense else grads
    pairs, treedef = jax.tree_util.tree_flatten_with_path(
        grads, is_leaf=is_sparse)
    started = []
    for path, leaf in pairs:
        name = prefix + jax.tree_util.keystr(path)
        if is_sparse(leaf):
            if sparse_as_dense:
                leaf = _sparse.to_dense(leaf)
            else:
                if op == Adasum:
                    raise NotImplementedError(
                        "Adasum does not support sparse gradients; pass "
                        "sparse_as_dense=True")
                started.append(
                    ("sparse", _sparse.allreduce_sparse_async(leaf, name),
                     leaf))
                continue
        comp, ctx = compression.compress(jnp.asarray(leaf))
        started.append(("dense", ops.allreduce_async(comp, name=name, op=op),
                        ctx))
    outs = []
    for kind, h, meta in started:
        if kind == "sparse":
            outs.append(_sparse.synchronize_sparse(
                h, op=op, dense_shape=meta.dense_shape))
        else:
            outs.append(compression.decompress(ops.synchronize(h), meta))
    return jax.tree_util.tree_unflatten(treedef, outs)


class DistributedOptimizer:
    """optax-compatible GradientTransformation wrapper: allreduces gradients
    across ranks before delegating to the inner transformation.

    Parameters mirror the reference surface (`torch/__init__.py:80-113`):
    ``compression``, ``op`` (Average/Sum/Adasum), ``backward_passes_per_step``
    (local accumulation before communicating). Use with plain optax::

        tx = hvd.DistributedOptimizer(optax.sgd(0.01))
        state = tx.init(params)
        updates, state = tx.update(grads, state, params)
    """

    def __init__(self, tx, compression=Compression.none, op: int = Average,
                 backward_passes_per_step: int = 1, prefix: str = "grad",
                 sparse_as_dense: bool = False):
        self._tx = tx
        self._compression = compression
        self._op = op
        self._prefix = prefix
        self._k = backward_passes_per_step
        self._micro = 0
        self._acc = None
        self._sparse_as_dense = sparse_as_dense

    def init(self, params):
        return self._tx.init(params)

    def update(self, grads, state, params=None):
        # Local accumulation first, ONE communication every k micro-steps —
        # that is the point of backward_passes_per_step
        # (`torch/__init__.py:171-189`). The raw accumulated SUM goes on the
        # wire — the reference does not divide by the pass count; users scale
        # their loss. Stable tensor names across steps (like torch parameter
        # names); safe because the communicating step drains all handles
        # before returning.
        if self._k > 1:
            from ..ops import sparse as _sparse

            has_sparse = any(
                isinstance(l, _sparse.IndexedSlices)
                for l in jax.tree_util.tree_leaves(
                    grads,
                    is_leaf=lambda x: isinstance(x, _sparse.IndexedSlices)))
            if has_sparse:
                if not self._sparse_as_dense:
                    # accumulating IndexedSlices with tree_map would add
                    # the *indices* arrays — densify or fail loudly
                    raise NotImplementedError(
                        "backward_passes_per_step > 1 with sparse gradient "
                        "leaves requires sparse_as_dense=True")
                grads = _sparse.densify_tree(grads)
            if self._acc is None:
                self._acc = grads
            else:
                self._acc = jax.tree_util.tree_map(jnp.add, self._acc, grads)
            self._micro += 1
            if self._micro < self._k:
                zero = jax.tree_util.tree_map(jnp.zeros_like, grads)
                return zero, state
            grads = self._acc
            self._acc = None
            self._micro = 0
        grads = allreduce_gradients(
            grads, op=self._op, compression=self._compression,
            prefix=self._prefix, sparse_as_dense=self._sparse_as_dense)
        # optax transformations tree_map over leaves, which would scale an
        # IndexedSlices' indices/dense_shape too (TF optimizers handle
        # IndexedSlices natively; optax does not) — densify the gathered
        # result before handing it to the inner transformation.
        from ..ops import sparse as _sparse

        grads = _sparse.densify_tree(grads)
        return self._tx.update(grads, state, params)


class DistributedGradientTape:
    """TF2-parity surface (`tensorflow/__init__.py:473-530`): wraps a gradient
    function so returned gradients are allreduced.

    JAX-native use::

        grad_fn = hvd.DistributedGradientTape(jax.grad(loss_fn))
        grads = grad_fn(params, batch)      # already averaged across ranks
    """

    def __init__(self, grad_fn, compression=Compression.none,
                 op: int = Average, prefix: str = "tape",
                 has_aux: bool = False):
        self._grad_fn = grad_fn
        self._compression = compression
        self._op = op
        self._prefix = prefix
        self._has_aux = has_aux

    def __call__(self, *args, **kwargs):
        out = self._grad_fn(*args, **kwargs)
        if self._has_aux:
            # only the gradients cross the wire; aux stays rank-local
            grads, aux = out
            grads = allreduce_gradients(
                grads, op=self._op, compression=self._compression,
                prefix=self._prefix)
            return grads, aux
        return allreduce_gradients(
            out, op=self._op, compression=self._compression,
            prefix=self._prefix)


def grad(loss_fn, op: int = Average, compression=Compression.none, **grad_kwargs):
    """``jax.grad`` drop-in whose output gradients are rank-averaged.

    ``has_aux=True`` is honored: aux outputs stay rank-local; only gradients
    are reduced.
    """
    return DistributedGradientTape(jax.grad(loss_fn, **grad_kwargs),
                                   compression=compression, op=op,
                                   has_aux=bool(grad_kwargs.get("has_aux")))
