"""Distributed optimizer and gradient wrappers (JAX/optax surface).

Reference parity:
  - `horovod/torch/__init__.py:115-209` ``_DistributedOptimizer`` — hooks fire
    per-gradient async allreduce during backward, ``synchronize()`` drains
    before ``step()``; ``backward_passes_per_step`` accumulates locally.
  - `horovod/tensorflow/__init__.py:473-530` ``DistributedGradientTape`` and
    :230-295 ``_DistributedOptimizer.compute_gradients``.

JAX shape: gradients are a pytree produced by ``jax.grad``. Two modes:

  * **Eager engine mode** (`DistributedOptimizer` / `allreduce_gradients`) —
    each gradient leaf becomes a named async allreduce through the background
    engine, overlapping collectives exactly like the torch hook flow. Used for
    op-by-op training loops and API parity.
  * **SPMD mode** (`horovod_tpu.spmd.make_train_step`) — the whole step is one
    XLA program; gradient averaging is compiler-inserted. Use this for peak
    throughput.
"""

from __future__ import annotations

import os
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from .. import basics
from .. import tracing as _tracing
from ..basics import Adasum, Average, Sum
from ..goodput import ledger as _goodput
from ..ops import collective_ops as ops
from ..ops import compression as _compression
from ..ops.compression import Compression


def _bucket_bytes() -> int:
    """``HOROVOD_BUCKET_MB`` resolved to bytes (0 = bucket overlap off).
    Read per call, like every other knob, so tests/benchmarks can flip it
    between steps without re-importing."""
    v = os.environ.get("HOROVOD_BUCKET_MB", "")
    if not v:
        return 0
    try:
        return int(float(v) * 2 ** 20)
    except ValueError:
        raise ValueError(
            f"HOROVOD_BUCKET_MB={v!r}: expected a number of MiB "
            "(0 = disabled)") from None


def partition_buckets(sizes_bytes, dtypes, bucket_bytes: int):
    """Partition leaf indices into reverse-order buckets of <= bucket_bytes.

    ``sizes_bytes``/``dtypes`` are per-leaf, in tree order; the result
    walks the leaves in REVERSE tree order (the approximation of
    backward-pass production order — the last layers' gradients
    materialize first under reverse-mode AD) and closes a bucket when the
    byte budget would overflow or the dtype changes (a fused buffer is one
    typed concat). Every bucket holds at least one leaf, so oversized
    leaves ride alone. Deterministic by construction: same tree + same
    knob → same buckets on every rank.
    """
    buckets, cur, cur_bytes = [], [], 0
    for i in reversed(range(len(sizes_bytes))):
        if cur and (dtypes[i] != dtypes[cur[-1]]
                    or cur_bytes + sizes_bytes[i] > bucket_bytes):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += sizes_bytes[i]
    if cur:
        buckets.append(cur)
    return buckets


def _allreduce_gradients_bucketed(grads, op, compression, prefix,
                                  sparse_as_dense, bucket_bytes):
    """Bucketed backward-pass overlap (HOROVOD_BUCKET_MB, docs/overlap.md).

    Dense leaves concat into reverse-production-order flat buckets, each
    enqueued as its own NON-fusable allreduce — the first buckets are on
    the wire while later buckets are still being assembled/enqueued, and
    the controller cannot re-merge them into one serial mega-bucket.
    Values are bit-identical to the per-leaf path: the engine's fusion
    buffer is itself a concat, and the reduction is elementwise, so
    grouping cannot change any element's cross-rank sum. Sparse leaves
    keep the per-leaf two-allgather path (ragged — not concatable).
    """
    from ..ops import sparse as _sparse

    is_sparse = lambda x: isinstance(x, _sparse.IndexedSlices)  # noqa: E731
    pairs, treedef = jax.tree_util.tree_flatten_with_path(
        grads, is_leaf=is_sparse)
    tr = _tracing.active()
    launch_span = (tr.begin_block(_tracing.K_PHASE, basics.rank(),
                                  "GRAD_LAUNCH", _tracing.clock.trace_us())
                   if tr is not None else None)
    dense = []          # (pos, compressed leaf, ctx) in tree order
    sparse_items = []   # (pos, name, leaf)
    for pos, (path, leaf) in enumerate(pairs):
        if is_sparse(leaf):
            if sparse_as_dense:
                leaf = _sparse.to_dense(leaf)
            else:
                sparse_items.append(
                    (pos, prefix + jax.tree_util.keystr(path), leaf))
                continue
        comp, ctx = compression.compress(jnp.asarray(leaf))
        dense.append((pos, comp, ctx))
    buckets = partition_buckets(
        [int(c.size) * c.dtype.itemsize for _, c, _ in dense],
        [c.dtype for _, c, _ in dense], bucket_bytes)
    started = []
    for i, idxs in enumerate(buckets):
        members = [dense[j] for j in idxs]
        flat = (jnp.ravel(members[0][1]) if len(members) == 1
                else jnp.concatenate([jnp.ravel(c) for _, c, _ in members]))
        bname = f"{prefix}.bucket.{i}"
        h = ops.allreduce_async(flat, name=bname, op=op,
                                compression=compression, fusable=False)
        started.append(("bucket", h, (bname, members)))
    for pos, name, leaf in sparse_items:
        started.append(
            ("sparse", _sparse.allreduce_sparse_async(leaf, name),
             (pos, leaf)))
    if tr is not None:
        tr.end_block(launch_span, _tracing.clock.trace_us())
        drain_span = tr.begin_block(_tracing.K_PHASE, basics.rank(),
                                    "GRAD_DRAIN", _tracing.clock.trace_us())
    observe = getattr(compression, "observe", None)
    outs: list = [None] * len(pairs)
    try:
        for kind, h, meta in started:
            if kind == "sparse":
                pos, leaf = meta
                outs[pos] = _sparse.synchronize_sparse(
                    h, op=op, dense_shape=leaf.dense_shape)
                continue
            bname, members = meta
            flat = ops.synchronize(h)
            if observe is not None:
                # adaptive wire: feed the reduced bucket (identical on
                # every rank) to the bitwidth selector's statistics
                observe(bname, flat)
            off = 0
            for pos, comp, ctx in members:
                n = int(comp.size)
                outs[pos] = compression.decompress(
                    flat[off:off + n].reshape(comp.shape), ctx)
                off += n
    finally:
        if tr is not None:
            tr.end_block(drain_span, _tracing.clock.trace_us())
    return jax.tree_util.tree_unflatten(treedef, outs)


def allreduce_gradients(grads, op: int = Average,
                        compression=None, prefix: str = "grad",
                        sparse_as_dense: bool = False, _guard: bool = True):
    """Average a gradient pytree across ranks through the engine: one named
    async allreduce per leaf, all in flight simultaneously (the hook-overlap
    pattern of `torch/__init__.py:115-150`), then drained in order.

    `ops.sparse.IndexedSlices` leaves (embedding-style sparse grads) take
    the two-allgather path (`tensorflow/__init__.py:75-91`); pass
    ``sparse_as_dense=True`` to densify them first
    (`_keras/__init__.py:50-53`).

    Under ``HOROVOD_GRAD_GUARD`` (integrity/gradguard.py) the pytree is
    checked for NaN/Inf before anything hits the wire; on a global
    ``skip`` verdict the returned gradients are all-zero — this surface
    has no optimizer step to drop, so a skipped step degrades to a no-op
    update. ``DistributedOptimizer`` pre-applies the guard (and truly
    drops the step) and disables it here via ``_guard=False``.
    """
    from ..ops import sparse as _sparse

    if _guard:
        from .. import integrity

        verdict, grads = integrity.default_guard().apply(grads,
                                                         prefix=prefix)
        if verdict == integrity.SKIP:
            return jax.tree_util.tree_map(jnp.zeros_like, grads)
    if compression is None:
        compression = _compression.from_env()
    is_sparse = lambda x: isinstance(x, _sparse.IndexedSlices)  # noqa: E731
    if basics.size() == 1:
        # Keep single-rank and multi-rank return types consistent:
        # sparse_as_dense must densify here too, or optax would tree_map
        # into the IndexedSlices on single-process debug runs.
        return _sparse.densify_tree(grads) if sparse_as_dense else grads
    # Bucketed backward overlap (HOROVOD_BUCKET_MB, docs/overlap.md).
    # Adasum keeps the per-leaf path: its combine rule is not elementwise,
    # so reducing a concat would change the math.
    bucket_bytes = _bucket_bytes() if op != Adasum else 0
    if bucket_bytes > 0:
        return _allreduce_gradients_bucketed(
            grads, op, compression, prefix, sparse_as_dense, bucket_bytes)
    pairs, treedef = jax.tree_util.tree_flatten_with_path(
        grads, is_leaf=is_sparse)
    tr = _tracing.active()
    launch_span = (tr.begin_block(_tracing.K_PHASE, basics.rank(),
                                  "GRAD_LAUNCH", _tracing.clock.trace_us())
                   if tr is not None else None)
    started = []
    for path, leaf in pairs:
        name = prefix + jax.tree_util.keystr(path)
        if is_sparse(leaf):
            if sparse_as_dense:
                leaf = _sparse.to_dense(leaf)
            else:
                if op == Adasum:
                    raise NotImplementedError(
                        "Adasum does not support sparse gradients; pass "
                        "sparse_as_dense=True")
                started.append(
                    ("sparse", _sparse.allreduce_sparse_async(leaf, name),
                     leaf))
                continue
        comp, ctx = compression.compress(jnp.asarray(leaf))
        started.append(("dense",
                        ops.allreduce_async(comp, name=name, op=op,
                                            compression=compression),
                        (name, ctx)))
    if tr is not None:
        # launch vs drain phases make backward/wire overlap visible in the
        # merged trace: wire spans overlapping GRAD_LAUNCH are hidden comm,
        # wire spans inside GRAD_DRAIN are exposed
        tr.end_block(launch_span, _tracing.clock.trace_us())
        drain_span = tr.begin_block(_tracing.K_PHASE, basics.rank(),
                                    "GRAD_DRAIN", _tracing.clock.trace_us())
    observe = getattr(compression, "observe", None)
    outs = []
    try:
        for kind, h, meta in started:
            if kind == "sparse":
                outs.append(_sparse.synchronize_sparse(
                    h, op=op, dense_shape=meta.dense_shape))
            else:
                name, ctx = meta
                flat = ops.synchronize(h)
                if observe is not None:
                    observe(name, flat)
                outs.append(compression.decompress(flat, ctx))
    finally:
        if tr is not None:
            tr.end_block(drain_span, _tracing.clock.trace_us())
    return jax.tree_util.tree_unflatten(treedef, outs)


def _densify_or_raise(grads, sparse_as_dense: bool, context: str):
    """If the pytree has IndexedSlices leaves: densify them when allowed,
    else raise ``context`` (tree_map over a raw IndexedSlices NamedTuple
    would corrupt the indices)."""
    from ..ops import sparse as _sparse

    is_sparse = lambda x: isinstance(x, _sparse.IndexedSlices)  # noqa: E731
    has_sparse = any(is_sparse(l) for l in jax.tree_util.tree_leaves(
        grads, is_leaf=is_sparse))
    if not has_sparse:
        return grads
    if not sparse_as_dense:
        raise NotImplementedError(context)
    return _sparse.densify_tree(grads)


class _GradAccumulation:
    """Shared backward_passes_per_step bookkeeping: accumulate k micro-grads
    locally, communicate on the k-th (`torch/__init__.py:171-189`; the raw
    accumulated SUM goes on the wire — the reference does not divide by the
    pass count; users scale their loss)."""

    def _init_accumulation(self, k: int, sparse_as_dense: bool):
        self._k = k
        self._micro = 0
        self._acc = None
        self._sparse_as_dense = sparse_as_dense

    def _accumulate(self, grads):
        """Returns ``(communicate, grads)``: on a communication micro-step
        the accumulated grads, otherwise the (densified) micro-grads for
        shaping the zero update."""
        if self._k <= 1:
            return True, grads
        grads = _densify_or_raise(
            grads, self._sparse_as_dense,
            "backward_passes_per_step > 1 with sparse gradient leaves "
            "requires sparse_as_dense=True")
        if self._acc is None:
            self._acc = grads
        else:
            self._acc = jax.tree_util.tree_map(jnp.add, self._acc, grads)
        self._micro += 1
        if self._micro < self._k:
            return False, grads
        grads = self._acc
        self._acc = None
        self._micro = 0
        return True, grads


class DistributedOptimizer(_GradAccumulation):
    """optax-compatible GradientTransformation wrapper: allreduces gradients
    across ranks before delegating to the inner transformation.

    Parameters mirror the reference surface (`torch/__init__.py:80-113`):
    ``compression``, ``op`` (Average/Sum/Adasum), ``backward_passes_per_step``
    (local accumulation before communicating). ``op=Adasum`` on a multi-rank
    world constructs the delta-flow ``DistributedAdasumOptimizer`` instead,
    like the reference factory (`torch/__init__.py:428-435`). Use with plain
    optax::

        tx = hvd.DistributedOptimizer(optax.sgd(0.01))
        state = tx.init(params)
        updates, state = tx.update(grads, state, params)

    ``error_feedback=True`` (EF-SGD, for lossy ``compression`` — int8 wire
    or fp16/bf16 casts): each step communicates ``grads + residual`` and the
    residual becomes what the wire dropped, ``corrected -
    compression.roundtrip(corrected)``, so quantization error accumulates
    into the next step's gradients instead of being lost. The residual is a
    rank-local pytree (like the accumulation buffer); it measures this
    rank's local quantization loss — the standard EF approximation of the
    dequant-sum-requant wire.
    """

    def __new__(cls, tx=None, compression=None, op: int = Average,
                backward_passes_per_step: int = 1, prefix: str = "grad",
                sparse_as_dense: bool = False, error_feedback: bool = False):
        if op == Adasum and error_feedback:
            raise ValueError(
                "error_feedback is not supported with op=Adasum (the "
                "delta-flow optimizer communicates updates, not "
                "gradients)")
        if op == Adasum and basics.size() > 1:
            return DistributedAdasumOptimizer(
                tx, compression=compression,
                backward_passes_per_step=backward_passes_per_step,
                sparse_as_dense=sparse_as_dense)
        return super().__new__(cls)

    def __init__(self, tx, compression=None, op: int = Average,
                 backward_passes_per_step: int = 1, prefix: str = "grad",
                 sparse_as_dense: bool = False, error_feedback: bool = False):
        self._tx = tx
        self._compression = (compression if compression is not None
                             else _compression.from_env())
        self._op = op
        self._prefix = prefix
        self._error_feedback = error_feedback
        self._ef_residual = None
        self._init_accumulation(backward_passes_per_step, sparse_as_dense)

    def init(self, params):
        return self._tx.init(params)

    @staticmethod
    def straggler_residual_mass() -> float:
        """Sum of |residual| the straggler policy is currently carrying for
        THIS rank (elastic data plane, runtime/straggler.py): non-zero only
        while this rank is excluded and its dropped contributions are
        banked for the rejoin fold-back; exactly 0.0 once they land. The EF
        accounting surface the chaos acceptance test asserts against —
        distinct from the quantization residual above, which lives in
        optimizer state, not the executor."""
        try:
            eng = basics._engine()
        except Exception:
            return 0.0
        fn = getattr(getattr(eng, "_executor", None), "residual_mass", None)
        return float(fn()) if callable(fn) else 0.0

    def _apply_error_feedback(self, grads):
        """corrected = grads + residual; the new residual is the part of
        ``corrected`` the lossy wire will drop this step."""
        grads = _densify_or_raise(
            grads, self._sparse_as_dense,
            "error_feedback with sparse gradient leaves requires "
            "sparse_as_dense=True")
        if self._ef_residual is not None:
            grads = jax.tree_util.tree_map(jnp.add, grads, self._ef_residual)
        rt = self._compression.roundtrip
        self._ef_residual = jax.tree_util.tree_map(
            lambda g: g - rt(g), grads)
        return grads

    def update(self, grads, state, params=None):
        # Stable tensor names across steps (like torch parameter names);
        # safe because the communicating step drains all handles before
        # returning.
        communicate, grads = self._accumulate(grads)
        if not communicate:
            zero = jax.tree_util.tree_map(jnp.zeros_like, grads)
            return zero, state
        tr = _tracing.active()
        step_span = (tr.begin_block(_tracing.K_STEP, basics.rank(), "STEP",
                                    _tracing.clock.trace_us())
                     if tr is not None else None)
        # goodput: the communicating update is the "useful work" span;
        # nested synchronize()/ckpt spans subtract themselves from it
        led = _goodput.active()
        gp_span = led.begin("compute") if led is not None else None
        try:
            return self._communicating_update(grads, state, params)
        finally:
            if led is not None:
                led.end(gp_span)
            if tr is not None:
                tr.end_block(step_span, _tracing.clock.trace_us())

    def _communicating_update(self, grads, state, params):
        # GradGuard before error feedback: a poisoned step must not leak
        # NaN into the EF residual, and a global skip leaves the residual
        # exactly as it was (the step never happened on any rank)
        from .. import integrity

        verdict, grads = integrity.default_guard().apply(grads,
                                                         prefix=self._prefix)
        if verdict == integrity.SKIP:
            zero = jax.tree_util.tree_map(jnp.zeros_like, grads)
            return zero, state
        if self._error_feedback:
            grads = self._apply_error_feedback(grads)
        grads = allreduce_gradients(
            grads, op=self._op, compression=self._compression,
            prefix=self._prefix, sparse_as_dense=self._sparse_as_dense,
            _guard=False)
        # optax transformations tree_map over leaves, which would scale an
        # IndexedSlices' indices/dense_shape too (TF optimizers handle
        # IndexedSlices natively; optax does not) — densify the gathered
        # result before handing it to the inner transformation.
        from ..ops import sparse as _sparse

        grads = _sparse.densify_tree(grads)
        return self._tx.update(grads, state, params)


class DistributedAdasumOptimizer(_GradAccumulation):
    """Delta-flow Adasum optimizer (`torch/__init__.py:211-379`,
    `tensorflow/__init__.py:313-407`).

    Instead of reducing *gradients* before the update, the inner optimizer
    runs locally and the resulting parameter *delta* is combined across
    ranks with the scale-invariant Adasum rule. In optax terms the local
    delta IS the update pytree (``new_params = params + updates``), so the
    flow is: inner ``tx.update`` → Adasum-allreduce each update leaf →
    return the combined updates. With ``backward_passes_per_step=k``,
    gradients accumulate locally for k micro-steps and one combined
    update+reduce happens on the k-th (the torch reference's delay
    counter, `torch/__init__.py:330-339`).

    fp16 compression composes (BASELINE config 5): the Adasum rule is
    scale-invariant, so the cast loses precision but not correctness.
    """

    def __init__(self, tx, compression=None,
                 backward_passes_per_step: int = 1,
                 prefix: str = "adasum", sparse_as_dense: bool = False):
        self._tx = tx
        self._compression = (compression if compression is not None
                             else Compression.none)
        self._prefix = prefix
        self._init_accumulation(backward_passes_per_step, sparse_as_dense)

    def init(self, params):
        return self._tx.init(params)

    def update(self, grads, state, params=None):
        # Adasum cannot combine IndexedSlices (parity:
        # `tensorflow/__init__.py:77-81`) — densify up front or fail loudly
        # before tree_map could corrupt the indices.
        grads = _densify_or_raise(
            grads, self._sparse_as_dense,
            "The Adasum reduction does not support sparse gradients; "
            "pass sparse_as_dense=True")
        communicate, grads = self._accumulate(grads)
        if not communicate:
            zero = jax.tree_util.tree_map(jnp.zeros_like, grads)
            return zero, state
        updates, state = self._tx.update(grads, state, params)
        updates = allreduce_gradients(
            updates, op=Adasum, compression=self._compression,
            prefix=self._prefix)
        return updates, state


class DistributedGradientTape:
    """TF2-parity surface (`tensorflow/__init__.py:473-530`): wraps a gradient
    function so returned gradients are allreduced.

    JAX-native use::

        grad_fn = hvd.DistributedGradientTape(jax.grad(loss_fn))
        grads = grad_fn(params, batch)      # already averaged across ranks
    """

    def __init__(self, grad_fn, compression=Compression.none,
                 op: int = Average, prefix: str = "tape",
                 has_aux: bool = False):
        self._grad_fn = grad_fn
        self._compression = compression
        self._op = op
        self._prefix = prefix
        self._has_aux = has_aux

    def __call__(self, *args, **kwargs):
        out = self._grad_fn(*args, **kwargs)
        if self._has_aux:
            # only the gradients cross the wire; aux stays rank-local
            grads, aux = out
            grads = allreduce_gradients(
                grads, op=self._op, compression=self._compression,
                prefix=self._prefix)
            return grads, aux
        return allreduce_gradients(
            out, op=self._op, compression=self._compression,
            prefix=self._prefix)


def grad(loss_fn, op: int = Average, compression=Compression.none, **grad_kwargs):
    """``jax.grad`` drop-in whose output gradients are rank-averaged.

    ``has_aux=True`` is honored: aux outputs stay rank-local; only gradients
    are reduced.
    """
    return DistributedGradientTape(jax.grad(loss_fn, **grad_kwargs),
                                   compression=compression, op=op,
                                   has_aux=bool(grad_kwargs.get("has_aux")))
