"""Fused AdamW: the whole moment+parameter update in one Pallas pass.

Why: XLA compiles ``optax.adamw``'s update into one fusion per parameter
tensor, and on a v5e those fusions measured ~32 ms of a 209 ms
GPT-2-medium train step — 3-4x off the HBM roofline for what is one
read of (g, p, mu, nu) and one write of (p, mu, nu). Unlike a norm or an
activation, the optimizer update has no neighbouring ops XLA could fuse
it INTO (it is the terminal consumer of the gradients), so a hand kernel
pays no fusion-boundary cost — it just moves fewer bytes in fewer passes.

No reference counterpart: Horovod delegates the optimizer step to the
framework (`horovod/torch/__init__.py:152-169` runs the wrapped
``optimizer.step()`` after synchronize); the TPU-native analogue of "make
the step fast" is this kernel.

API is a minimal init/apply pair (NOT an optax ``GradientTransformation``:
optax's contract returns *updates* for a separate ``apply_updates`` add,
which would force the parameter write back out of the fused pass):

    opt = fused_adamw(3e-4, weight_decay=0.01, mu_dtype=jnp.bfloat16)
    state = opt.init(params)
    params, state = opt.apply(grads, state, params)

Numerics match ``optax.adamw`` (same bias correction, eps placement, and
decoupled weight decay; moments computed in f32 and stored in
``mu_dtype``/f32 exactly like optax's ``mu_dtype`` handling). Leaves whose
size is not lane-aligned (or off-TPU) take an identical-formula jnp path.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops import pallas_kernels as _pk

_LANES = 128
# per-leaf size below which the custom-call overhead outweighs the win;
# small leaves (LN scales, biases) take the jnp formulas instead
_MIN_FUSED = 1 << 16


class AdamWState(NamedTuple):
    count: jax.Array  # int32 step counter (shared by all leaves)
    mu: Any           # first-moment tree, in mu_dtype
    nu: Any           # second-moment tree, f32


def _adamw_kernel(sc_ref, g_ref, p_ref, mu_ref, nu_ref,
                  po_ref, muo_ref, nuo_ref, *, b1, b2, eps, wd):
    """One row-tile: read (g, p, mu, nu), write (p', mu', nu').
    sc (scalar prefetch): [lr, 1/(1-b1^t), 1/(1-b2^t)] f32."""
    lr, ibc1, ibc2 = sc_ref[0], sc_ref[1], sc_ref[2]
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    mu = b1 * mu_ref[...].astype(jnp.float32) + (1.0 - b1) * g
    nu = b2 * nu_ref[...].astype(jnp.float32) + (1.0 - b2) * g * g
    upd = (mu * ibc1) / (jnp.sqrt(nu * ibc2) + eps) + wd * p
    po_ref[...] = (p - lr * upd).astype(po_ref.dtype)
    muo_ref[...] = mu.astype(muo_ref.dtype)
    nuo_ref[...] = nu


def _leaf_supported(n: int) -> bool:
    return n >= _MIN_FUSED and _pk.mode() != "off"


def _rows_block(rows: int) -> int:
    # 2048 x 128 f32 = 1 MB/operand (7 operands inside VMEM); leaves are
    # zero-PADDED up to a block multiple rather than degrading to tiny
    # tiles (a divisor-only rule turns e.g. a 50257-row vocab leaf into
    # ~50k sequential 8x128 cells)
    b = 2048
    while b > 8 and rows < b:
        b //= 2
    return b


def _apply_leaf_fused(sc, g, p, mu, nu, *, b1, b2, eps, wd):
    shape, n = p.shape, p.size
    rows = -(-n // _LANES)
    br = _rows_block(rows)
    rows_p = -(-rows // br) * br
    pad = rows_p * _LANES - n

    def flat(x):
        x = x.reshape(-1)
        if pad:
            x = jnp.pad(x, (0, pad))  # zero rows: updated, then discarded
        return x.reshape(rows_p, _LANES)

    tile = pl.BlockSpec((br, _LANES), lambda i, sc: (i, 0))
    p2, mu2, nu2 = pl.pallas_call(
        functools.partial(_adamw_kernel, b1=b1, b2=b2, eps=eps, wd=wd),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(rows_p // br,),
            in_specs=[tile, tile, tile, tile],
            out_specs=[tile, tile, tile],
        ),
        out_shape=[jax.ShapeDtypeStruct((rows_p, _LANES), p.dtype),
                   jax.ShapeDtypeStruct((rows_p, _LANES), mu.dtype),
                   jax.ShapeDtypeStruct((rows_p, _LANES), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_pk._interpret(),
    )(sc, flat(g), flat(p), flat(mu), flat(nu))

    def unflat(x):
        return x.reshape(-1)[:n].reshape(shape)

    return unflat(p2), unflat(mu2), unflat(nu2)


def _apply_leaf_jnp(sc, g, p, mu, nu, *, b1, b2, eps, wd):
    lr, ibc1, ibc2 = sc[0], sc[1], sc[2]
    gf = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    mu_f = b1 * mu.astype(jnp.float32) + (1.0 - b1) * gf
    nu_f = b2 * nu.astype(jnp.float32) + (1.0 - b2) * gf * gf
    upd = (mu_f * ibc1) / (jnp.sqrt(nu_f * ibc2) + eps) + wd * pf
    return ((pf - lr * upd).astype(p.dtype), mu_f.astype(mu.dtype), nu_f)


class FusedAdamW(NamedTuple):
    init: Any
    apply: Any


def fused_adamw(learning_rate, b1: float = 0.9, b2: float = 0.999,
                eps: float = 1e-8, weight_decay: float = 0.0,
                mu_dtype=None) -> FusedAdamW:
    """AdamW with the per-leaf update in one fused Pallas pass.

    Decoupled weight decay applies to every leaf (pass 0.0 to disable),
    matching ``optax.adamw``'s default ``mask=None``.

    ``learning_rate`` may be a static float or an optax-style schedule
    (a callable of the step count, evaluated against ``state.count``
    inside ``apply``).

    Known numerics deviation from ``optax.adamw``: the second moment ``nu``
    is always stored in f32, where optax keeps it in the param dtype (e.g.
    bf16 for bf16 params). bf16 nu loses ~5 bits of mantissa on an
    accumulating statistic, so the f32 choice is deliberately the safer
    numerics; expect bit differences vs optax on sub-f32 params.
    """

    def init(params):
        mu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=mu_dtype or p.dtype), params)
        nu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return AdamWState(jnp.zeros((), jnp.int32), mu, nu)

    def apply(grads, state, params):
        count = state.count + 1
        t = count.astype(jnp.float32)
        # optax schedules are indexed by the PRE-increment step count
        lr = learning_rate(state.count) if callable(learning_rate) \
            else learning_rate
        sc = jnp.stack([
            jnp.asarray(lr, jnp.float32),
            1.0 / (1.0 - jnp.float32(b1) ** t),
            1.0 / (1.0 - jnp.float32(b2) ** t),
        ])
        kw = dict(b1=b1, b2=b2, eps=eps, wd=weight_decay)

        def leaf(g, p, mu, nu):
            if _leaf_supported(p.size):
                return _apply_leaf_fused(sc, g, p, mu, nu, **kw)
            return _apply_leaf_jnp(sc, g, p, mu, nu, **kw)

        out = jax.tree_util.tree_map(leaf, grads, params, state.mu,
                                     state.nu)
        three = jax.tree_util.tree_transpose(
            jax.tree_util.tree_structure(params),
            jax.tree_util.tree_structure((0, 0, 0)), out)
        new_p, new_mu, new_nu = three
        return new_p, AdamWState(count, new_mu, new_nu)

    return FusedAdamW(init, apply)
