"""Rank-sharded input pipeline for data-parallel training.

Reference parity: the flagship examples' real-data flow —
`examples/keras_imagenet_resnet50.py:64-86` (per-rank generator iterators
over an on-disk image folder) and `examples/pytorch_imagenet_resnet50.py`
(``torch.utils.data.distributed.DistributedSampler`` with per-epoch
``set_epoch`` reshuffling). This module is the TPU-native answer to "shard a
real dataset by ``hvd.rank()`` and feed the SPMD step":

* :func:`list_image_folder` — deterministic (path, label) scan of a
  ``root/<class>/<image>`` tree (the Keras ``flow_from_directory`` layout).
* :class:`ShardedImageFolder` — the DistributedSampler math on top of that
  scan: one GLOBAL permutation per epoch (seeded identically on every rank,
  reseeded by ``set_epoch`` exactly like the sampler's), strided rank
  sharding ``indices[rank::size]``, equal step counts per rank so the SPMD
  collectives never diverge on batch count.

Decoding uses PIL when the files are images and plain ``np.load`` for
``.npy`` arrays (CI fixtures); all hosts see the same file list, so the
pipeline works unchanged on a pod where every host reads shared storage —
only ``rank``/``size`` differ. The HBM-side cost is unchanged from the
synthetic examples: batches arrive as host numpy, and the caller's
``device_put``/jit boundary commits them to the chip.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

_IMG_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif")


def list_image_folder(root: str) -> Tuple[List[str], List[int], List[str]]:
    """Scan a ``root/<class>/<file>`` tree into (paths, labels, classes).

    Classes are the sorted subdirectory names, labels their indices; files
    are sorted within each class — the listing is deterministic, so every
    rank/host derives the identical order (a prerequisite for the shared
    global permutation, like the reference sampler's ``len(dataset)``
    contract)."""
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    if not classes:
        raise ValueError(f"no class subdirectories under {root!r} "
                         "(expected root/<class>/<image> layout)")
    paths: List[str] = []
    labels: List[int] = []
    for li, cls in enumerate(classes):
        cdir = os.path.join(root, cls)
        for fname in sorted(os.listdir(cdir)):
            if fname.lower().endswith(_IMG_EXTS + (".npy",)):
                paths.append(os.path.join(cdir, fname))
                labels.append(li)
    if not paths:
        raise ValueError(f"no images found under {root!r}")
    return paths, labels, classes


def _load_image(path: str, image_size: Optional[int]) -> np.ndarray:
    """One file -> float32 HWC in [0, 1]."""
    if path.endswith(".npy"):
        arr = np.load(path)
        if arr.ndim == 2:
            arr = np.stack([arr] * 3, axis=-1)
        # scale by DTYPE, not by value: a per-file value heuristic would mix
        # 0-1 and 0-255 scales within one dataset (a dark uint8-saved-as-float
        # image must not come out 255x brighter than its neighbours)
        if np.issubdtype(arr.dtype, np.integer):
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
            # float fixtures are taken at face value as [0, 1]; a float
            # array of 0-255 pixel values would silently train 255x out of
            # range, so fail loudly (1.5 leaves headroom for slightly
            # out-of-gamut normalized data while catching 0-255 scales)
            amax = float(arr.max()) if arr.size else 0.0
            if amax > 1.5:
                raise ValueError(
                    f"{path}: float .npy fixture has max value {amax:.3g} "
                    "but float fixtures are NOT rescaled — expected [0, 1] "
                    "data (store uint8 for 0-255 pixel data, or divide by "
                    "255 before saving)")
    else:
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert("RGB")
            if image_size is not None:
                im = im.resize((image_size, image_size))
            arr = np.asarray(im, dtype=np.float32) / 255.0
    if image_size is not None and arr.shape[:2] != (image_size, image_size):
        raise ValueError(
            f"{path}: got shape {arr.shape}, expected "
            f"({image_size}, {image_size}, 3) — resize only applies to "
            "image files; .npy fixtures must be stored at size")
    return arr


class ShardedImageFolder:
    """Per-rank iterator over an image folder with DistributedSampler
    semantics.

    Every rank holds the SAME global permutation (seeded by
    ``seed + epoch``); rank ``r`` reads ``perm[r::size]``. The global
    length is truncated to a multiple of ``batch_size * size`` so each
    rank runs the identical number of steps per epoch — a rank with one
    extra batch would hang the others' collectives (the reference solves
    the same problem with DistributedSampler's padding; truncation keeps
    epochs exact-data at the cost of dropping a partial tail batch).

    Usage (the reference's `pytorch_imagenet_resnet50.py` loop shape)::

        ds = ShardedImageFolder(root, batch_size=32, image_size=224,
                                rank=hvd.rank(), size=hvd.size())
        for epoch in range(epochs):
            ds.set_epoch(epoch)          # reshuffle, identically on all ranks
            for x, y in ds:              # numpy [B,H,W,3] f32, [B] i32
                step(params, x, y)       # SPMD/engine step
    """

    def __init__(self, root: str, batch_size: int,
                 image_size: Optional[int] = None,
                 rank: Optional[int] = None, size: Optional[int] = None,
                 shuffle: bool = True, seed: int = 0):
        if rank is None or size is None:
            from . import basics

            rank = basics.rank() if rank is None else rank
            size = basics.size() if size is None else size
        if not 0 <= rank < size:
            raise ValueError(f"rank {rank} not in [0, {size})")
        self.paths, self.labels, self.classes = list_image_folder(root)
        self.batch_size = int(batch_size)
        self.image_size = image_size
        self.rank, self.size = int(rank), int(size)
        self.shuffle = shuffle
        self.seed = int(seed)
        self._epoch = 0
        per_step = self.batch_size * self.size
        self._global_len = (len(self.paths) // per_step) * per_step
        if self._global_len == 0:
            raise ValueError(
                f"{len(self.paths)} images < one global batch "
                f"({self.batch_size} x {self.size} ranks)")

    @property
    def steps_per_epoch(self) -> int:
        return self._global_len // (self.batch_size * self.size)

    def set_epoch(self, epoch: int) -> None:
        """Reseed the shared permutation (DistributedSampler.set_epoch
        parity) — call before iterating each epoch, with the same epoch
        number on every rank."""
        self._epoch = int(epoch)

    def _indices(self) -> np.ndarray:
        if self.shuffle:
            perm = np.random.RandomState(self.seed + self._epoch).permutation(
                len(self.paths))
        else:
            perm = np.arange(len(self.paths))
        return perm[:self._global_len][self.rank::self.size]

    def __len__(self) -> int:
        return self.steps_per_epoch

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        idx = self._indices()
        for s in range(self.steps_per_epoch):
            batch = idx[s * self.batch_size:(s + 1) * self.batch_size]
            imgs = [_load_image(self.paths[i], self.image_size)
                    for i in batch]
            shapes = {im.shape for im in imgs}
            if len(shapes) > 1:
                raise ValueError(
                    f"batch mixes image shapes {sorted(shapes)} — pass "
                    "image_size= to ShardedImageFolder to resize on load "
                    "(required for datasets with non-uniform dimensions)")
            x = np.stack(imgs)
            y = np.asarray([self.labels[i] for i in batch], np.int32)
            yield x, y


def shard_sizes(n_examples: int, batch_size: int, size: int) -> dict:
    """Pod-day shard math (docs/running.md): how one epoch divides."""
    per_step = batch_size * size
    steps = n_examples // per_step
    return {
        "global_batch": per_step,
        "steps_per_epoch": steps,
        "examples_used": steps * per_step,
        "examples_dropped": n_examples - steps * per_step,
        "examples_per_rank_per_epoch": steps * batch_size,
    }
