"""SPMD fast path: collectives *inside* a jitted step over the device mesh.

This is the performance path that replaces the reference's whole background
engine for training loops: where Horovod's `DistributedOptimizer` enqueues one
NCCL allreduce per gradient tensor with 64 MB fusion
(`horovod/torch/__init__.py:115-169`, `nccl_operations.cc:55-105`), here the
entire train step — forward, backward, gradient averaging, optimizer update —
is ONE compiled XLA program over the replica mesh. XLA schedules the gradient
all-reduces on ICI, overlaps them with the backward pass (latency-hiding
scheduler), and fuses the optimizer update; there is nothing left to negotiate
at runtime. This is the design stance from SURVEY.md §7: negotiation machinery
for the eager path, static scheduling for the hot path.

Two usage levels:

1. Collective primitives with the ``"hvd"`` axis for custom ``shard_map`` code:
   ``spmd.allreduce/allgather/alltoall/broadcast/...``
2. Whole-step builders: ``make_train_step(loss_fn, tx)`` returns a jitted
   data-parallel step with batch sharded over replicas and params replicated.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import basics
from .basics import MESH_AXIS, Adasum, Average, Sum


# --------------------------------------------------------- in-jit primitives
def allreduce(x, op: int = Average, axis: str = MESH_AXIS):
    """Collective reduce across the replica axis; call inside shard_map/pmap.

    TPU-native form of `EnqueueTensorAllreduce` (`operations.cc:783`) for code
    already running under SPMD.
    """
    if op == Adasum:
        return adasum(x, axis=axis)
    s = jax.lax.psum(x, axis)
    if op == Average:
        n = jax.lax.psum(jnp.ones((), jnp.int32), axis)
        if jnp.issubdtype(s.dtype, jnp.integer):
            s = s // n.astype(s.dtype)  # match eager engine int semantics
        else:
            s = s / n.astype(s.dtype)
    return s


def pmean(x, axis: str = MESH_AXIS):
    return jax.lax.pmean(x, axis)


def allgather(x, axis: str = MESH_AXIS):
    return jax.lax.all_gather(x, axis, tiled=True)


def alltoall(x, axis: str = MESH_AXIS, split_axis: int = 0, concat_axis: int = 0):
    return jax.lax.all_to_all(x, axis, split_axis, concat_axis, tiled=True)


def broadcast(x, root_rank: int, axis: str = MESH_AXIS):
    """Every replica receives replica ``root_rank``'s value."""
    idx = jax.lax.axis_index(axis)
    masked = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis)


def reduce_scatter(x, axis: str = MESH_AXIS, scatter_axis: int = 0):
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                                tiled=True)


def allreduce_sparse(values, indices, op: int = Average, axis: str = MESH_AXIS):
    """In-jit sparse allreduce (`tensorflow/__init__.py:75-91` rebuilt for
    SPMD): allgather rows + indices instead of reducing the dense tensor.

    Unlike the eager engine path (`ops.sparse.allreduce_sparse`, ragged dim0
    negotiated at runtime), XLA requires a static, equal per-device row count
    — pad with a sentinel row (e.g. index 0, zero values) to equalize.
    Returns ``(gathered_values [n*k, ...], gathered_indices [n*k])``; apply
    with scatter-add, duplicates accumulate.
    """
    if op == Adasum:
        raise NotImplementedError(
            "Adasum does not support sparse tensors; densify first")
    g_values = jax.lax.all_gather(values, axis, tiled=True)
    g_indices = jax.lax.all_gather(indices, axis, tiled=True)
    if op == Average:
        n = jax.lax.psum(jnp.ones((), jnp.int32), axis)
        if jnp.issubdtype(g_values.dtype, jnp.integer):
            g_values = g_values // n.astype(g_values.dtype)
        else:
            g_values = g_values / n.astype(g_values.dtype)
    return g_values, g_indices


def adasum(x, axis: str = MESH_AXIS):
    """Adasum combine across the replica axis inside SPMD code.

    Pairwise tree as in `adasum/adasum.h:185-331`: at level k, partners are
    distance 2^k apart; coefficients from psum'd dots/norms restricted to each
    pair. Implemented via all_gather + local tree (replica count is static).
    After the gather the tree is device-local math, so each pairwise combine
    runs as the fused Pallas dot+norm+apply kernel
    (`ops/pallas_kernels.adasum_combine`) when enabled — the TPU analogue of
    the reference's SSE/AVX fused loops (`adasum/adasum.h:98-131`) — with the
    vectorized-jnp tree as fallback (zero-padding to lane width is exact:
    zeros contribute nothing to dot or norms).
    """
    from .ops import pallas_kernels as _pk

    g = jax.lax.all_gather(x, axis)  # [n, ...]
    n = g.shape[0]
    if n & (n - 1):
        raise ValueError("Adasum requires a power-of-2 replica count "
                         "(parity: torch/mpi_ops.py:104-120)")
    flat = g.reshape(n, -1).astype(jnp.float32)
    if _pk.mode() != "off" and not _pk.vma_active(flat):
        pad = (-flat.shape[1]) % 128
        padded = jnp.pad(flat, ((0, 0), (0, pad))) if pad else flat
        while padded.shape[0] > 1:  # one batched launch per tree level
            padded = _pk.adasum_combine_pairs(padded[0::2], padded[1::2])
        return padded[0, :flat.shape[1]].reshape(x.shape).astype(x.dtype)
    while flat.shape[0] > 1:
        a, b = flat[0::2], flat[1::2]
        dot = jnp.sum(a * b, axis=1, keepdims=True)
        na = jnp.sum(a * a, axis=1, keepdims=True)
        nb = jnp.sum(b * b, axis=1, keepdims=True)
        ac = jnp.where(na == 0, 1.0, 1.0 - dot / (2 * jnp.where(na == 0, 1.0, na)))
        bc = jnp.where(nb == 0, 1.0, 1.0 - dot / (2 * jnp.where(nb == 0, 1.0, nb)))
        flat = ac * a + bc * b
    return flat[0].reshape(x.shape).astype(x.dtype)


# ------------------------------------------- quantized ring (GSPMD wire)
# The EQuARX move (PAPERS.md arXiv:2506.17615): quantized allreduce INSIDE
# the compiled program. The same ppermute ring as `matmul_reduce_scatter`
# above, but every hop ships the fused int8/int4 quantize+pack rows from
# `ops/pallas_kernels.py` instead of raw f32 — the PR 10 wire footprints
# (int4 = 50.8% of int8 bytes) finally applied to the GSPMD plane, which
# until now moved raw bf16/f32 while all the bandwidth wins sat on the
# coordinator path. See docs/gspmd.md.

_GSPMD_WIRES = ("int8", "int4")


def gspmd_wire(value: Optional[str] = None) -> str:
    """Resolve the compiled-path wire mode (``HOROVOD_GSPMD_WIRE``).

    Returns ``""`` (wire off — the exact GSPMD program), ``"int8"`` or
    ``"int4"``. ``value`` overrides the env var (the
    ``make_train_step(compression=...)`` argument). int4 must be admitted
    by the PR 10 ``ConvergenceGate`` first — a refused gate downgrades to
    int8 rather than risking the 4-bit grid on a model the deterministic
    A/B harness couldn't converge (`ops/adaptive.py`).
    """
    v = os.environ.get("HOROVOD_GSPMD_WIRE", "") if value is None else value
    v = (v or "").strip().lower()
    if v in ("", "0", "off", "none"):
        return ""
    if v not in _GSPMD_WIRES:
        raise ValueError(
            f"HOROVOD_GSPMD_WIRE must be int8|int4|off, got {v!r}")
    from .ops.adaptive import admit_wire

    return admit_wire(v)


def _wire_block(block: Optional[int]) -> int:
    from .ops import compression as comp

    return int(block or comp.block_size())


def _pack_fns(wire: str):
    from .ops import pallas_kernels as pk

    if wire == "int4":
        return pk.int4_quantize_pack, pk.int4_unpack
    return pk.int8_quantize_pack, pk.int8_unpack


def _ring_chunk(num_elements: int, world: int, block: int) -> int:
    """Per-rank chunk length: ceil(n/world) rounded up to whole blocks, so
    every hop's packed rows are [chunk//block, block+scale] with no ragged
    tail inside the ring."""
    per_rank = -(-num_elements // world)
    return -(-per_rank // block) * block


def _wire_eligible(num_elements: int, dtype, wire: str, block: int) -> bool:
    """Static (trace-time) gate for the quantized path: float payload, at
    least one quantization block (below that the scale overhead and ring
    latency beat the savings — the HOROVOD_COMPRESSION_MIN_SIZE rationale),
    and an even block for the int4 nibble split."""
    return (wire in _GSPMD_WIRES
            and jnp.issubdtype(dtype, jnp.floating)
            and num_elements >= block
            and not (wire == "int4" and block % 2))


def quantized_reduce_scatter(x, axis: str = MESH_AXIS, wire: str = "int8",
                             block: Optional[int] = None):
    """Ring reduce-scatter with a quantized wire; call inside shard_map.

    ``x`` is this rank's local contribution (any float shape; flattened and
    zero-padded to ``world * chunk`` with ``chunk = _ring_chunk(...)``).
    Returns the 1-D f32 chunk of the cross-rank sum this rank owns (global
    chunk ``p`` of the padded flat sum). Rank p seeds its accumulator with
    local chunk (p-1) mod m; each of the m-1 hops quantize+packs the
    accumulator ([rows, block] -> [rows, block+4] int8 rows, or the int4
    half-split nibble rows), rotates the packed bytes one rank forward via
    ppermute, dequantizes, and adds the local chunk (p-k-1) mod m — so
    after the last hop rank p holds chunk p summed over every rank, and
    every hop moved packed bytes instead of raw f32. ``wire`` values
    outside int8/int4 run the identical ring schedule with raw f32 hops
    (the exact-wire reference).
    """
    m = jax.lax.psum(1, axis)
    block = _wire_block(block)
    flat = jnp.ravel(x).astype(jnp.float32)
    num = flat.shape[0]
    if wire in _GSPMD_WIRES:
        chunk = _ring_chunk(num, m, block)
    else:
        chunk = -(-num // m)
    pad = m * chunk - num
    if pad:
        flat = jnp.pad(flat, (0, pad))
    if m == 1:
        return flat
    p = jax.lax.axis_index(axis)

    def local_chunk(k):
        idx = jnp.mod(p - k - 1, m)
        return jax.lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)

    perm = [(j, (j + 1) % m) for j in range(m)]
    acc = local_chunk(0)
    if wire not in _GSPMD_WIRES:
        for k in range(1, m):
            acc = jax.lax.ppermute(acc, axis, perm) + local_chunk(k)
        return acc
    pack, unpack = _pack_fns(wire)
    for k in range(1, m):
        wired = jax.lax.ppermute(pack(acc.reshape(-1, block)), axis, perm)
        q, scales = unpack(wired)
        acc = (q.astype(jnp.float32) * scales).reshape(-1) + local_chunk(k)
    return acc


def quantized_all_gather(chunk, axis: str = MESH_AXIS, wire: str = "int8",
                         block: Optional[int] = None):
    """Ring all-gather of per-rank 1-D chunks with a quantized wire.

    Each rank quantize+packs its own chunk ONCE and the packed bytes make
    m-1 hops around the ring; every rank — including the owner —
    reconstructs each chunk from the same packed rows, so the gathered
    [m * chunk] result is bit-identical on every rank (the property the
    replicated-params invariant rests on). ``wire`` outside int8/int4
    falls back to the exact tiled all_gather.
    """
    m = jax.lax.psum(1, axis)
    flat = jnp.ravel(chunk).astype(jnp.float32)
    if m == 1:
        return flat
    if wire not in _GSPMD_WIRES:
        return jax.lax.all_gather(flat, axis, tiled=True)
    block = _wire_block(block)
    num = flat.shape[0]
    pad = (-num) % block
    padded = jnp.pad(flat, (0, pad)) if pad else flat
    pack, unpack = _pack_fns(wire)
    p = jax.lax.axis_index(axis)
    perm = [(j, (j + 1) % m) for j in range(m)]
    cur = pack(padded.reshape(-1, block))
    out = jnp.zeros((m * num,), jnp.float32)
    for k in range(m):
        q, scales = unpack(cur)
        val = (q.astype(jnp.float32) * scales).reshape(-1)[:num]
        idx = jnp.mod(p - k, m)
        out = jax.lax.dynamic_update_slice_in_dim(out, val, idx * num, 0)
        if k + 1 < m:
            cur = jax.lax.ppermute(cur, axis, perm)
    return out


def quantized_allreduce(x, op: int = Average, axis: str = MESH_AXIS,
                        wire: Optional[str] = None,
                        block: Optional[int] = None):
    """Allreduce whose wire rides the quantized ring; call inside shard_map.

    Composition of :func:`quantized_reduce_scatter` and
    :func:`quantized_all_gather`: every hop of both phases moves int8/int4
    packed rows, so the whole reduction costs the PR 10 wire footprints
    inside the compiled program. The result is bit-identical on every rank
    (averaging divides the identical gathered sum). Falls back to the
    exact :func:`allreduce` when the wire is off, the payload is not
    floating-point, or the flat size is under one quantization block
    (non-lane-aligned / tiny tensors — see ``_wire_eligible``).

    ``wire=None`` resolves ``HOROVOD_GSPMD_WIRE`` at trace time
    (:func:`gspmd_wire`, including the int4 convergence-gate admission).
    """
    wire = gspmd_wire(wire)
    if op == Adasum:
        raise NotImplementedError(
            "the quantized GSPMD wire does not support Adasum; use "
            "spmd.adasum (exact) instead")
    block = _wire_block(block)
    if not _wire_eligible(x.size, x.dtype, wire, block):
        return allreduce(x, op, axis)
    m = jax.lax.psum(1, axis)
    chunk = quantized_reduce_scatter(x, axis, wire, block)
    flat = quantized_all_gather(chunk, axis, wire, block)[:x.size]
    if op == Average:
        flat = flat / m
    return flat.reshape(x.shape).astype(x.dtype)


# ------------------------------------------------ algorithm zoo (autotune v3)
# The flat bidirectional ring above is bandwidth-optimal but pays world-1
# latency rounds; the MPI characterization study (PAPERS.md arXiv:1810.11112)
# and the reference's hierarchical allreduce (operations.cc:440-454) both
# show the winning algorithm is a function of payload size x world size x
# topology. The zoo: "ring" (above), "tree" (recursive halving/doubling,
# O(log w) rounds — latency-optimal for small payloads), "hier" (intra-host
# reduce-scatter -> cross-host allreduce -> intra-host all-gather over a
# (host, chip) factorization). Every member rides the same packed int8/int4
# rows, the same EF-residual convention and the same _wire_eligible exact
# fallbacks as the ring. See docs/autotune.md.

_GSPMD_ALGOS = ("ring", "tree", "hier", "auto")

#: payloads at or under this many f32 elements (256 KB) are latency-bound
#: on the flat ring — the "auto" tree/ring crossover before any tuner
#: measurement arrives
_TREE_AUTO_MAX = 1 << 16


def gspmd_algo(value: Optional[str] = None) -> str:
    """Resolve the compiled-path collective algorithm (``HOROVOD_GSPMD_ALGO``).

    Returns ``"ring"`` (the default — byte-identical to the pre-zoo
    program), ``"tree"``, ``"hier"`` or ``"auto"``. ``value`` overrides the
    env var (the ``make_train_step(algorithm=...)`` argument)."""
    v = os.environ.get("HOROVOD_GSPMD_ALGO", "") if value is None else value
    v = (v or "").strip().lower()
    if v in ("", "0", "off", "none"):
        return "ring"
    if v not in _GSPMD_ALGOS:
        raise ValueError(
            f"HOROVOD_GSPMD_ALGO must be ring|tree|hier|auto, got {v!r}")
    return v


def mesh_hosts(world: int) -> int:
    """``(host, chip)`` factorization for the hierarchical allreduce.

    ``HOROVOD_MESH_HOSTS`` pins the host count (it must divide the world
    size — the launcher's host-major rank numbering is assumed, rank =
    host * chips + chip, matching the executor's ("dcn","ici") mesh).
    Unset auto-factorizes: the largest divisor of ``world`` at most
    sqrt(world), so 8 -> 2x4, 16 -> 4x4; 1 (no factorization, ring
    fallback) when ``world`` is prime."""
    v = os.environ.get("HOROVOD_MESH_HOSTS", "").strip()
    if v:
        hosts = int(v)
        if hosts < 1 or world % hosts:
            raise ValueError(
                f"HOROVOD_MESH_HOSTS={hosts} does not divide the world "
                f"size {world} (host-major rank numbering needs "
                f"world = hosts * chips)")
        return hosts
    hosts, d = 1, 2
    while d * d <= world:
        if world % d == 0:
            hosts = d
        d += 1
    return hosts


def resolve_algorithm(total: int, world: int,
                      algorithm: Optional[str] = None) -> str:
    """Effective zoo member for one payload of ``total`` f32 elements.

    Explicit choices pass through; ``"auto"`` follows the coordinator's
    tuned broadcast when one has arrived
    (`ops/adaptive.set_autotuned_algorithm`, shipped as the fourth tuned
    ``ResponseList`` field) and otherwise the static heuristic: small
    payloads ride the tree when the world is a power of two, multi-host
    factorizations ride the hierarchical schedule, everything else the
    ring."""
    a = gspmd_algo(algorithm)
    if a != "auto":
        return a
    from .ops.adaptive import autotuned_algorithm

    tuned = autotuned_algorithm()
    if tuned:
        return tuned
    if total <= _TREE_AUTO_MAX and world & (world - 1) == 0 and world > 1:
        return "tree"
    if mesh_hosts(world) > 1:
        return "hier"
    return "ring"


def _ring_reduce_scatter(flat, axis: str, wire: str, block: int,
                         size: int, pos, perm):
    """Ring reduce-scatter over a sub-ring of ``size`` members embedded in
    ``axis``: ``pos`` is this rank's (traced) position on its ring and
    ``perm`` the global ppermute rotating every sub-ring one step forward
    in parallel. ``flat`` is the 1-D f32 local contribution, already
    padded to ``size * chunk``; returns the summed chunk position ``pos``
    owns — the same schedule as :func:`quantized_reduce_scatter`, just
    with ring geometry supplied by the caller."""
    chunk = flat.shape[0] // size
    if size == 1:
        return flat

    def local_chunk(k):
        idx = jnp.mod(pos - k - 1, size)
        return jax.lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)

    acc = local_chunk(0)
    if wire not in _GSPMD_WIRES:
        for k in range(1, size):
            acc = jax.lax.ppermute(acc, axis, perm) + local_chunk(k)
        return acc
    pack, unpack = _pack_fns(wire)
    for k in range(1, size):
        wired = jax.lax.ppermute(pack(acc.reshape(-1, block)), axis, perm)
        q, scales = unpack(wired)
        acc = (q.astype(jnp.float32) * scales).reshape(-1) + local_chunk(k)
    return acc


def _ring_all_gather(chunk, axis: str, wire: str, block: int,
                     size: int, pos, perm):
    """Ring all-gather over a sub-ring (geometry as in
    :func:`_ring_reduce_scatter`). The owner packs its chunk once and the
    packed rows (raw f32 on an exact wire) make ``size - 1`` hops
    unchanged, so every ring member reconstructs each chunk from identical
    bytes — the bit-identity property of :func:`quantized_all_gather`."""
    num = chunk.shape[0]
    if size == 1:
        return chunk
    out = jnp.zeros((size * num,), jnp.float32)
    if wire not in _GSPMD_WIRES:
        cur = chunk
        for k in range(size):
            idx = jnp.mod(pos - k, size)
            out = jax.lax.dynamic_update_slice_in_dim(out, cur, idx * num, 0)
            if k + 1 < size:
                cur = jax.lax.ppermute(cur, axis, perm)
        return out
    pack, unpack = _pack_fns(wire)
    pad = (-num) % block
    padded = jnp.pad(chunk, (0, pad)) if pad else chunk
    cur = pack(padded.reshape(-1, block))
    for k in range(size):
        q, scales = unpack(cur)
        val = (q.astype(jnp.float32) * scales).reshape(-1)[:num]
        idx = jnp.mod(pos - k, size)
        out = jax.lax.dynamic_update_slice_in_dim(out, val, idx * num, 0)
        if k + 1 < size:
            cur = jax.lax.ppermute(cur, axis, perm)
    return out


def quantized_allreduce_tree(x, op: int = Average, axis: str = MESH_AXIS,
                             wire: Optional[str] = None,
                             block: Optional[int] = None):
    """Recursive-halving/doubling allreduce — O(log w) rounds, the
    latency-optimal zoo member for small payloads; call inside shard_map.

    Reduce phase: log2(w) recursive-halving exchanges at distances w/2,
    w/4, ..., 1. Each round partners ``p`` and ``p ^ d`` split the active
    window ("bit set keeps the upper half"), ship the half the partner
    keeps — packed int8/int4 rows on a quantized wire, raw f32 otherwise —
    and add; after the last round rank ``p`` owns the fully summed chunk
    ``p``, the same ownership convention as the ring. Gather phase: log2(w)
    recursive-doubling exchanges of *packed bytes*: each chunk is
    quantized once by its owner and forwarded verbatim, so every rank
    decodes identical bytes and the result is bit-identical everywhere
    (the :func:`quantized_all_gather` property).

    Falls back to the ring (:func:`quantized_allreduce`) on
    non-power-of-two worlds — the halving recursion needs 2^k members —
    and to the exact :func:`allreduce` for payloads the wire cannot carry
    (:func:`_wire_eligible`) or non-float dtypes.
    """
    wire = gspmd_wire(wire)
    if op == Adasum:
        raise NotImplementedError(
            "the GSPMD tree allreduce does not support Adasum; use "
            "spmd.adasum (exact) instead")
    block = _wire_block(block)
    m = jax.lax.psum(1, axis)
    if m & (m - 1) or m == 1:
        return quantized_allreduce(x, op, axis, wire, block)
    if wire in _GSPMD_WIRES and not _wire_eligible(x.size, x.dtype, wire,
                                                   block):
        return allreduce(x, op, axis)
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return allreduce(x, op, axis)
    num = x.size
    quant = wire in _GSPMD_WIRES
    chunk = _ring_chunk(num, m, block) if quant else -(-num // m)
    flat = jnp.ravel(x).astype(jnp.float32)
    pad = m * chunk - num
    if pad:
        flat = jnp.pad(flat, (0, pad))
    p = jax.lax.axis_index(axis)
    rounds = int(m).bit_length() - 1
    if quant:
        pack, unpack = _pack_fns(wire)
    # recursive halving: every window half is a whole number of chunks,
    # hence (quantized) a whole number of blocks — no ragged rows
    win = flat
    for k in range(rounds):
        d = m >> (k + 1)
        half = win.shape[0] // 2
        bit = jnp.equal((p // d) % 2, 1)
        lower, upper = win[:half], win[half:]
        keep = jnp.where(bit, upper, lower)
        send = jnp.where(bit, lower, upper)
        perm = [(j, j ^ d) for j in range(m)]
        if quant:
            wired = jax.lax.ppermute(pack(send.reshape(-1, block)), axis,
                                     perm)
            q, scales = unpack(wired)
            recv = (q.astype(jnp.float32) * scales).reshape(-1)
        else:
            recv = jax.lax.ppermute(send, axis, perm)
        win = keep + recv
    # recursive doubling: forward the owner-packed rows verbatim so every
    # rank decodes the same bytes (bit-identity)
    if quant:
        rows = chunk // block
        packed = pack(win.reshape(-1, block))
        buf = jnp.zeros((m * rows, packed.shape[1]), packed.dtype)
        buf = jax.lax.dynamic_update_slice_in_dim(buf, packed, p * rows, 0)
        for k in range(rounds):
            d = 1 << k
            lo = (p // d) * d
            seg = jax.lax.dynamic_slice_in_dim(buf, lo * rows, d * rows)
            perm = [(j, j ^ d) for j in range(m)]
            recv = jax.lax.ppermute(seg, axis, perm)
            buf = jax.lax.dynamic_update_slice_in_dim(buf, recv,
                                                      (lo ^ d) * rows, 0)
        q, scales = unpack(buf)
        out = (q.astype(jnp.float32) * scales).reshape(-1)[:num]
    else:
        buf = jnp.zeros((m * chunk,), jnp.float32)
        buf = jax.lax.dynamic_update_slice_in_dim(buf, win, p * chunk, 0)
        for k in range(rounds):
            d = 1 << k
            lo = (p // d) * d
            seg = jax.lax.dynamic_slice_in_dim(buf, lo * chunk, d * chunk)
            perm = [(j, j ^ d) for j in range(m)]
            recv = jax.lax.ppermute(seg, axis, perm)
            buf = jax.lax.dynamic_update_slice_in_dim(buf, recv,
                                                      (lo ^ d) * chunk, 0)
        out = buf[:num]
    if op == Average:
        out = out / m
    return out.reshape(x.shape).astype(x.dtype)


def quantized_allreduce_hier(x, op: int = Average, axis: str = MESH_AXIS,
                             wire: Optional[str] = None,
                             block: Optional[int] = None,
                             hosts: Optional[int] = None):
    """2-level hierarchical allreduce over a ``(host, chip)`` factorization
    of the replica axis; call inside shard_map.

    The reference's NCCLHierarchicalAllreduce decomposition
    (`operations.cc:440-454`) on the packed wire: intra-host ring
    reduce-scatter (chips on one host talk over ICI), cross-host allreduce
    of each owned chunk — every chip is the representative for the chunk
    it owns, riding a host-ring reduce-scatter + all-gather that only
    crosses hosts — then intra-host ring all-gather. Both gather phases
    forward owner-packed bytes verbatim and the phase-2 result is
    bit-identical across hosts, so the final result is bit-identical on
    every rank. Cross-host traffic shrinks from the flat ring's
    ``2(w-1)`` chunk exchanges per boundary edge to the phase-2 rows alone
    (`ops/compression.gspmd_cross_host_footprint`).

    ``hosts`` defaults to :func:`mesh_hosts` (``HOROVOD_MESH_HOSTS`` or the
    auto factorization); rank numbering is host-major (rank = host * chips
    + chip), matching the executor's ("dcn","ici") mesh. Falls back to the
    flat ring when the factorization is degenerate (hosts <= 1, hosts ==
    world, or world % hosts != 0) and to the exact :func:`allreduce` for
    payloads the wire cannot carry.
    """
    wire = gspmd_wire(wire)
    if op == Adasum:
        raise NotImplementedError(
            "the GSPMD hierarchical allreduce does not support Adasum; "
            "use spmd.adasum (exact) instead")
    block = _wire_block(block)
    m = jax.lax.psum(1, axis)
    h = mesh_hosts(m) if hosts is None else int(hosts)
    if h <= 1 or h >= m or m % h:
        return quantized_allreduce(x, op, axis, wire, block)
    if wire in _GSPMD_WIRES and not _wire_eligible(x.size, x.dtype, wire,
                                                   block):
        return allreduce(x, op, axis)
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return allreduce(x, op, axis)
    num = x.size
    c = m // h  # chips per host
    quant = wire in _GSPMD_WIRES
    chunk = _ring_chunk(num, c, block) if quant else -(-num // c)
    flat = jnp.ravel(x).astype(jnp.float32)
    pad = c * chunk - num
    if pad:
        flat = jnp.pad(flat, (0, pad))
    p = jax.lax.axis_index(axis)
    hp, l = p // c, p % c  # (host, chip) of this rank, host-major
    intra = [(j, (j // c) * c + ((j % c) + 1) % c) for j in range(m)]
    inter = [(j, (((j // c) + 1) % h) * c + (j % c)) for j in range(m)]
    # phase 1: intra-host reduce-scatter — chip l ends with chunk l of the
    # host-local sum
    chunk_l = _ring_reduce_scatter(flat, axis, wire, block, c, l, intra)
    # phase 2: cross-host allreduce of chunk l among the h chips sharing
    # local index l (RS + AG over the host ring — the only phase whose
    # bytes cross a host boundary)
    sub = _ring_chunk(chunk, h, block) if quant else -(-chunk // h)
    pad2 = h * sub - chunk
    if pad2:
        chunk_l = jnp.pad(chunk_l, (0, pad2))
    owned = _ring_reduce_scatter(chunk_l, axis, wire, block, h, hp, inter)
    chunk_g = _ring_all_gather(owned, axis, wire, block, h, hp,
                               inter)[:chunk]
    # phase 3: intra-host all-gather of the globally reduced chunks
    out = _ring_all_gather(chunk_g, axis, wire, block, c, l, intra)[:num]
    if op == Average:
        out = out / m
    return out.reshape(x.shape).astype(x.dtype)


def _wire_roundtrip(flat, wire: str, block: int):
    """The value one quantized hop delivers for a local contribution — the
    EF-SGD numerator, same absmax/qmax block math as
    ``ops/compression.py quantize_blocks`` (pure: no metric side effects,
    safe inside the traced step)."""
    from .ops import compression as comp

    num = flat.shape[0]
    pad = (-num) % block
    padded = jnp.pad(flat, (0, pad)) if pad else flat
    q, scales = comp.quantize_blocks(padded, block,
                                     bits=4 if wire == "int4" else 8)
    return comp.dequantize_blocks(q, scales, jnp.float32, block)[:num]


# --------------------------------------------------- quantized all_to_all
def _a2a_roundtrip(flat, wire: str, block: int):
    """EF numerator for one quantized all_to_all: the value the packed wire
    delivers for this rank's ``[m, per]`` payload, with the same per-peer
    padded block layout as the forward pack (each peer's segment pads to
    whole blocks independently, so no block ever mixes two peers' data).
    Pure ``comp.quantize_blocks`` math — safe inside the traced step."""
    from .ops import compression as comp

    m, per = flat.shape
    pad = (-per) % block
    padded = jnp.pad(flat, ((0, 0), (0, pad))) if pad else flat
    q, scales = comp.quantize_blocks(padded.reshape(-1), block,
                                     bits=4 if wire == "int4" else 8)
    out = comp.dequantize_blocks(q, scales, jnp.float32, block)
    return out.reshape(m, per + pad)[:, :per]


def _a2a_wired(x, axis: str, wire: str, block: int):
    """One quantized all_to_all exchange (forward value only): pad each
    destination peer's payload to whole blocks, quantize+pack through the
    fused kernels, move the packed int8 rows, unpack+dequantize on
    arrival. The packed rows keep their [rows, row_bytes] shape through
    the exchange because each peer's row count is identical."""
    m = jax.lax.psum(1, axis)
    per = x.size // m
    flat = x.reshape(m, per).astype(jnp.float32)
    pad = (-per) % block
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    pack, unpack = _pack_fns(wire)
    packed = pack(flat.reshape(-1, block))
    wired = jax.lax.all_to_all(packed, axis, 0, 0, tiled=True)
    q, scales = unpack(wired)
    vals = (q.astype(jnp.float32) * scales).reshape(m, per + pad)[:, :per]
    return vals.reshape(x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _st_all_to_all(x, axis, wire, block):
    return _a2a_wired(x, axis, wire, block)


def _st_fwd(x, axis, wire, block):
    return _a2a_wired(x, axis, wire, block), None


def _st_bwd(axis, wire, block, _res, g):
    # Straight-through: the quantizer is gradient-dead (jnp.round), so the
    # cotangent rides the exact wire. A dim-0 tiled all_to_all is its own
    # transpose, so this IS the true adjoint of the exchange itself — only
    # the quantization nonlinearity is bypassed.
    return (jax.lax.all_to_all(g, axis, 0, 0, tiled=True),)


_st_all_to_all.defvjp(_st_fwd, _st_bwd)


def quantized_all_to_all(x, axis: str = MESH_AXIS, wire: str = "int8",
                         block: Optional[int] = None, ef=None):
    """all_to_all over ``axis`` whose payload rides the packed wire; call
    inside shard_map (the MoE token exchange — docs/moe.md).

    ``x`` is the local ``[L, ...]`` operand with dim 0 indexing destination
    peers in ``L / world`` row groups (``jax.lax.all_to_all`` split/concat
    dim 0, tiled). Each peer's payload pads independently to whole
    quantization blocks and quantize+packs through the fused kernels into
    ``[payload | 4 f32-scale bytes]`` rows; only the packed int8 bytes
    cross the wire, and receivers dequantize. Eligibility mirrors the ring
    (:func:`_wire_eligible` on the per-peer element count): non-float
    payloads, payloads under one block, or an odd block under int4 ride
    the exact all_to_all instead.

    Gradients are straight-through: the backward pass ships the cotangent
    over an *exact* all_to_all, which is the true adjoint of the exchange
    (a dim-0 all_to_all is its own transpose); only the gradient-dead
    quantizer is bypassed.

    ``ef`` (f32, same shape as ``x``) engages EF-SGD error feedback: the
    residual from the previous exchange in this direction is added before
    quantization, and the new residual ``corrected - wire(corrected)``
    comes back to be banked — one leaf per exchange direction, like the
    PR 13 optimizer-state leaf. With ``ef`` given the return is
    ``(y, new_ef)``; otherwise just ``y``.
    """
    m = jax.lax.psum(1, axis)
    if x.shape[0] % m:
        raise ValueError(
            f"all_to_all dim 0 ({x.shape[0]}) not divisible by axis size "
            f"{m}")
    block = _wire_block(block)
    per = x.size // m
    if m == 1 or not _wire_eligible(per, x.dtype, wire, block):
        y = jax.lax.all_to_all(x, axis, 0, 0, tiled=True)
        return (y, jnp.zeros(x.shape, jnp.float32)) if ef is not None else y
    corrected = x.astype(jnp.float32)
    if ef is not None:
        corrected = corrected + jax.lax.stop_gradient(
            ef.astype(jnp.float32))
    y = _st_all_to_all(corrected, axis, wire, block).astype(x.dtype)
    if ef is None:
        return y
    flat = jax.lax.stop_gradient(corrected).reshape(m, per)
    new_ef = (flat - _a2a_roundtrip(flat, wire, block)).reshape(x.shape)
    return y, new_ef


# ------------------------------------------------------------ whole-step API
def replica_mesh() -> Mesh:
    return basics.mesh()


def batch_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Shard dim 0 (batch) across replicas."""
    return NamedSharding(mesh or basics.mesh(), P(MESH_AXIS))


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or basics.mesh(), P())


def shard_batch(batch, mesh: Optional[Mesh] = None):
    """Place a host batch onto the mesh, sharded along dim 0."""
    sh = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)


def replicate(tree, mesh: Optional[Mesh] = None):
    """Replicate params/optimizer state across the mesh (the SPMD analogue of
    `broadcast_parameters`: every replica holds identical values)."""
    sh = replicated_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)


def make_train_step(loss_fn: Callable, tx, mesh: Optional[Mesh] = None,
                    donate: bool = True, zero1: bool = False,
                    example_opt_state=None,
                    compression: Optional[str] = None,
                    algorithm: Optional[str] = None) -> Callable:
    """Build the jitted data-parallel train step (the bench hot loop).

    ``loss_fn(params, batch) -> scalar loss`` computed on the *local* shard;
    gradient averaging across replicas is inserted automatically by GSPMD
    because params are replicated while the batch is sharded. ``tx`` is an
    optax GradientTransformation. Returns
    ``step(params, opt_state, batch) -> (params, opt_state, loss)``.

    ``zero1=True`` shards the optimizer state 1/N over the replica axis
    (`optim/zero.py`): pass ``example_opt_state`` (an abstract or concrete
    ``tx.init(params)`` pytree) so the per-leaf shardings can be derived,
    and place the live state with :func:`optim.zero.shard_opt_state` before
    the first call.

    ``compression`` selects the quantized GSPMD wire (``"int8"``/``"int4"``;
    ``None`` resolves ``HOROVOD_GSPMD_WIRE``, ``"off"`` forces the exact
    wire). When a wire engages, the step runs as an explicit shard_map
    program whose gradient reduction rides the quantized ppermute ring with
    an error-feedback residual carried as an extra optimizer-state leaf —
    build the state with :func:`quantized_opt_state`, and see docs/gspmd.md.
    With the wire off, this function compiles the exact same program as
    before the knob existed (the cache-key pin tested in tests/test_gspmd.py).

    ``algorithm`` selects the collective schedule for the quantized wire
    (``"ring"``/``"tree"``/``"hier"``/``"auto"``; ``None`` resolves
    ``HOROVOD_GSPMD_ALGO``). Unset/``"ring"`` compiles the byte-identical
    pre-zoo ring program (pinned in tests); ``"auto"`` resolves per
    payload size and topology at trace time (:func:`resolve_algorithm`).
    With the wire off the partitioner inserts the psum itself and the
    algorithm knob is inert; ``zero1=True`` keeps the ring — its chunk
    layout IS the optimizer-state sharding.
    """
    import optax

    wire = gspmd_wire(compression)
    if wire:
        return _make_quantized_step(loss_fn, tx, mesh, donate, zero1, wire,
                                    algorithm=algorithm)

    mesh = mesh or basics.mesh()
    repl = NamedSharding(mesh, P())
    opt_sh: Any = repl
    if zero1:
        if example_opt_state is None:
            raise ValueError(
                "zero1=True needs example_opt_state (tx.init(params) or its "
                "jax.eval_shape) to derive per-leaf shardings")
        from .optim.zero import zero1_shardings

        opt_sh = zero1_shardings(example_opt_state, mesh)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(
        step,
        donate_argnums=donate_argnums,
        out_shardings=(repl, opt_sh, repl),
    )


# ------------------------------------------- quantized whole-step builder
def _shard_map(f, mesh, in_specs, out_specs):
    """shard_map with the vma/replication checker off (across jax API
    renames) so the fused quantize+pack kernels stay eligible inside the
    ring (`pallas_kernels.vma_active`)."""
    import inspect

    kw = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    params = inspect.signature(jax.shard_map).parameters
    for flag in ("check_vma", "check_rep"):
        if flag in params:
            kw[flag] = False
            break
    return jax.shard_map(f, **kw)


def quantized_opt_state(tx, params, mesh: Optional[Mesh] = None,
                        zero1: bool = False, block: Optional[int] = None):
    """Initial ``(inner_state, ef_residual)`` for a quantized train step.

    The error-feedback residual — ``corrected - quantize_roundtrip(
    corrected)``, the same EF-SGD math the coordinator wire uses
    (`ops/compression.py`) — is a per-rank quantity, so it rides as ONE
    extra optimizer-state leaf of global shape ``[world, total_params]``
    sharded 1/N over the mesh axis: inside the shard_map step each rank
    sees exactly its own row. The update is deterministic (no RNG, fixed
    reduction order), so re-running a step reproduces the residual
    bit-for-bit and the replicated params stay bit-identical across ranks.

    ``zero1=True`` builds the flat-space ZeRO-1 state instead
    (`optim/zero.flat_zero1_state`): the optimizer runs on each rank's
    ring chunk of the flattened parameter vector — valid for elementwise
    transforms (sgd/momentum/adam/adamw), where flat-space update equals
    tree-space update.
    """
    mesh = mesh or basics.mesh()
    n = mesh.shape[MESH_AXIS]
    total = sum(int(np.prod(np.shape(l) or (1,)))
                for l in jax.tree_util.tree_leaves(params))
    ef = jax.device_put(jnp.zeros((n, total), jnp.float32),
                        NamedSharding(mesh, P(MESH_AXIS)))
    if zero1:
        from .optim.zero import flat_zero1_state

        inner = flat_zero1_state(tx, total, mesh, _wire_block(block))
    else:
        inner = replicate(tx.init(params), mesh)
    return inner, ef


#: Running (wire, exact) byte accumulators behind hvd_quantization_ratio
#: for the compiled path — the engine keeps its own pair for the
#: coordinator wire (runtime/engine.py).
_gspmd_bytes = {"wire": 0.0, "exact": 0.0}


#: last algorithm recorded per payload-size class — K_ALGO events fire on
#: change only, so hvddoctor's algorithm_thrash signature counts real flips
_algo_last: dict = {}


def _note_algorithm(algorithm: str, total: int) -> None:
    """Gauge + flight-recorder trail for the compiled plane's algorithm
    choice: ``hvd_collective_algorithm{class}`` tracks the member in play
    per payload-size class, and a blackbox ``K_ALGO`` event records each
    change (`blackbox/signatures.detect_algorithm_thrash`)."""
    from . import blackbox as _blackbox
    from .metrics import instruments
    from .ops.adaptive import ALGO_CODES, size_class

    cls = size_class(total * 4)
    instruments.collective_algorithm().labels(**{"class": cls}).set(
        ALGO_CODES.get(algorithm, 0))
    prev = _algo_last.get(cls)
    if prev != algorithm:
        _algo_last[cls] = algorithm
        if prev is not None:
            _blackbox.record(_blackbox.K_ALGO, cls, f"{prev}->{algorithm}")


def _record_gspmd_wire(total: int, wire: str, world: int, block: int,
                       algorithm: str = "ring"):
    """Truthful byte accounting for one quantized collective round (eagerly,
    per step call — counters cannot tick inside the compiled program).
    Bytes come from the same catalog the three-way bench reads
    (`ops/compression.gspmd_wire_footprint`), per the algorithm actually
    traced."""
    from .metrics import instruments
    from .ops import compression as comp

    hosts = mesh_hosts(world) if algorithm == "hier" else None
    wire_b = comp.gspmd_wire_footprint(total, wire, world, block,
                                       algorithm=algorithm, hosts=hosts)
    exact_b = comp.gspmd_wire_footprint(total, "none", world, block,
                                        algorithm=algorithm, hosts=hosts)
    instruments.wire_bytes().labels(compression=f"gspmd-{wire}").inc(wire_b)
    instruments.wire_bytes_exact().inc(exact_b)
    _note_algorithm(algorithm, total)
    _gspmd_bytes["wire"] += wire_b
    _gspmd_bytes["exact"] += exact_b
    if _gspmd_bytes["exact"]:
        instruments.quantization_ratio().set(
            _gspmd_bytes["wire"] / _gspmd_bytes["exact"])


def _make_quantized_step(loss_fn: Callable, tx, mesh: Optional[Mesh],
                         donate: bool, zero1: bool, wire: str,
                         block: Optional[int] = None,
                         algorithm: Optional[str] = None) -> Callable:
    """The explicit-collective variant of make_train_step: gradients ride
    the quantized ppermute ring instead of GSPMD's inserted psum.

    Dataflow (docs/gspmd.md): local grads -> flatten to one f32 vector ->
    add this rank's EF residual -> quantized ring. ``zero1=False`` runs a
    full quantized allreduce and the optimizer on the whole (replicated)
    tree; ``zero1=True`` reduce-scatters the corrected gradients so the
    elementwise optimizer math runs on this rank's 1/N chunk only, then
    all-gathers the param delta over the same quantized ring — the ZeRO-1
    schedule with every collective on the packed wire.

    ``algorithm`` swaps the allreduce schedule for a zoo member
    (docs/autotune.md); ``"ring"``/unset traces the identical pre-zoo
    program, and ``zero1=True`` always keeps the ring (its chunk layout is
    the optimizer-state sharding). The EF residual convention is
    algorithm-independent: every member delivers the same one-hop
    quantization of the corrected gradient (``_wire_roundtrip``).
    """
    import optax

    mesh = mesh or basics.mesh()
    n = mesh.shape[MESH_AXIS]
    block = _wire_block(block)
    algo = gspmd_algo(algorithm)

    def _flatten_f32(leaves):
        parts = [jnp.ravel(l).astype(jnp.float32) for l in leaves]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def _split_like(flat, leaves):
        out, off = [], 0
        for l in leaves:
            out.append(flat[off:off + l.size].reshape(l.shape)
                       .astype(l.dtype))
            off += l.size
        return out

    def local_step(params, inner, ef, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        g_leaves, treedef = jax.tree_util.tree_flatten(grads)
        flat = _flatten_f32(g_leaves)
        total = flat.shape[0]
        corrected = flat + ef[0]
        use_ring = zero1 or _wire_eligible(total, corrected.dtype, wire,
                                           block)
        if use_ring:
            new_ef = (corrected
                      - _wire_roundtrip(corrected, wire, block))[None]
        else:
            new_ef = jnp.zeros_like(ef)
        if zero1:
            g_chunk = quantized_reduce_scatter(
                corrected, MESH_AXIS, wire, block) / n
            chunk = g_chunk.shape[0]
            p_flat = _flatten_f32(jax.tree_util.tree_leaves(params))
            pad = n * chunk - total
            if pad:
                p_flat = jnp.pad(p_flat, (0, pad))
            p = jax.lax.axis_index(MESH_AXIS)
            p_chunk = jax.lax.dynamic_slice_in_dim(p_flat, p * chunk, chunk)
            upd_chunk, inner = tx.update(g_chunk, inner, p_chunk)
            upd_flat = quantized_all_gather(
                upd_chunk, MESH_AXIS, wire, block)[:total]
            updates = jax.tree_util.tree_unflatten(
                treedef, _split_like(upd_flat, g_leaves))
            params = optax.apply_updates(params, updates)
        else:
            a = resolve_algorithm(total, n, algo)
            if a == "tree":
                reduced = quantized_allreduce_tree(
                    corrected, Average, MESH_AXIS, wire, block)
            elif a == "hier":
                reduced = quantized_allreduce_hier(
                    corrected, Average, MESH_AXIS, wire, block)
            else:
                reduced = quantized_allreduce(
                    corrected, Average, MESH_AXIS, wire, block)
            grads = jax.tree_util.tree_unflatten(
                treedef, _split_like(reduced, g_leaves))
            updates, inner = tx.update(grads, inner, params)
            params = optax.apply_updates(params, updates)
        loss = jax.lax.pmean(loss, MESH_AXIS)
        return params, inner, new_ef, loss

    def step(params, opt_state, batch):
        inner, ef = opt_state
        if zero1:
            inner_specs = jax.tree_util.tree_map(
                lambda l: P(MESH_AXIS) if (jnp.ndim(l) == 1 and l.shape[0]
                                           and l.shape[0] % n == 0) else P(),
                inner)
        else:
            inner_specs = jax.tree_util.tree_map(lambda l: P(), inner)
        fn = _shard_map(
            local_step, mesh,
            in_specs=(P(), inner_specs, P(MESH_AXIS), P(MESH_AXIS)),
            out_specs=(P(), inner_specs, P(MESH_AXIS), P()))
        params, inner, ef, loss = fn(params, inner, ef, batch)
        return params, (inner, ef), loss

    jitted = jax.jit(step, donate_argnums=(0, 1) if donate else ())

    # "auto" resolves at trace time (the first call); pin the same answer
    # for accounting so a later tuned broadcast can't make the byte
    # counters disagree with the program actually compiled
    resolved: dict = {}

    @functools.wraps(jitted)
    def instrumented(params, opt_state, batch):
        total = int(opt_state[1].shape[1])  # read before donation
        out = jitted(params, opt_state, batch)
        a = resolved.setdefault(
            total, "ring" if zero1 else resolve_algorithm(total, n, algo))
        _record_gspmd_wire(total, wire, n, block, a)
        return out

    instrumented.jitted = jitted  # .lower()/.compile() escape hatch
    return instrumented
