"""SPMD fast path: collectives *inside* a jitted step over the device mesh.

This is the performance path that replaces the reference's whole background
engine for training loops: where Horovod's `DistributedOptimizer` enqueues one
NCCL allreduce per gradient tensor with 64 MB fusion
(`horovod/torch/__init__.py:115-169`, `nccl_operations.cc:55-105`), here the
entire train step — forward, backward, gradient averaging, optimizer update —
is ONE compiled XLA program over the replica mesh. XLA schedules the gradient
all-reduces on ICI, overlaps them with the backward pass (latency-hiding
scheduler), and fuses the optimizer update; there is nothing left to negotiate
at runtime. This is the design stance from SURVEY.md §7: negotiation machinery
for the eager path, static scheduling for the hot path.

Two usage levels:

1. Collective primitives with the ``"hvd"`` axis for custom ``shard_map`` code:
   ``spmd.allreduce/allgather/alltoall/broadcast/...``
2. Whole-step builders: ``make_train_step(loss_fn, tx)`` returns a jitted
   data-parallel step with batch sharded over replicas and params replicated.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import basics
from .basics import MESH_AXIS, Adasum, Average, Sum


# --------------------------------------------------------- in-jit primitives
def allreduce(x, op: int = Average, axis: str = MESH_AXIS):
    """Collective reduce across the replica axis; call inside shard_map/pmap.

    TPU-native form of `EnqueueTensorAllreduce` (`operations.cc:783`) for code
    already running under SPMD.
    """
    if op == Adasum:
        return adasum(x, axis=axis)
    s = jax.lax.psum(x, axis)
    if op == Average:
        n = jax.lax.psum(jnp.ones((), jnp.int32), axis)
        if jnp.issubdtype(s.dtype, jnp.integer):
            s = s // n.astype(s.dtype)  # match eager engine int semantics
        else:
            s = s / n.astype(s.dtype)
    return s


def pmean(x, axis: str = MESH_AXIS):
    return jax.lax.pmean(x, axis)


def allgather(x, axis: str = MESH_AXIS):
    return jax.lax.all_gather(x, axis, tiled=True)


def alltoall(x, axis: str = MESH_AXIS, split_axis: int = 0, concat_axis: int = 0):
    return jax.lax.all_to_all(x, axis, split_axis, concat_axis, tiled=True)


def broadcast(x, root_rank: int, axis: str = MESH_AXIS):
    """Every replica receives replica ``root_rank``'s value."""
    idx = jax.lax.axis_index(axis)
    masked = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis)


def reduce_scatter(x, axis: str = MESH_AXIS, scatter_axis: int = 0):
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                                tiled=True)


def allreduce_sparse(values, indices, op: int = Average, axis: str = MESH_AXIS):
    """In-jit sparse allreduce (`tensorflow/__init__.py:75-91` rebuilt for
    SPMD): allgather rows + indices instead of reducing the dense tensor.

    Unlike the eager engine path (`ops.sparse.allreduce_sparse`, ragged dim0
    negotiated at runtime), XLA requires a static, equal per-device row count
    — pad with a sentinel row (e.g. index 0, zero values) to equalize.
    Returns ``(gathered_values [n*k, ...], gathered_indices [n*k])``; apply
    with scatter-add, duplicates accumulate.
    """
    if op == Adasum:
        raise NotImplementedError(
            "Adasum does not support sparse tensors; densify first")
    g_values = jax.lax.all_gather(values, axis, tiled=True)
    g_indices = jax.lax.all_gather(indices, axis, tiled=True)
    if op == Average:
        n = jax.lax.psum(jnp.ones((), jnp.int32), axis)
        if jnp.issubdtype(g_values.dtype, jnp.integer):
            g_values = g_values // n.astype(g_values.dtype)
        else:
            g_values = g_values / n.astype(g_values.dtype)
    return g_values, g_indices


def adasum(x, axis: str = MESH_AXIS):
    """Adasum combine across the replica axis inside SPMD code.

    Pairwise tree as in `adasum/adasum.h:185-331`: at level k, partners are
    distance 2^k apart; coefficients from psum'd dots/norms restricted to each
    pair. Implemented via all_gather + local tree (replica count is static).
    After the gather the tree is device-local math, so each pairwise combine
    runs as the fused Pallas dot+norm+apply kernel
    (`ops/pallas_kernels.adasum_combine`) when enabled — the TPU analogue of
    the reference's SSE/AVX fused loops (`adasum/adasum.h:98-131`) — with the
    vectorized-jnp tree as fallback (zero-padding to lane width is exact:
    zeros contribute nothing to dot or norms).
    """
    from .ops import pallas_kernels as _pk

    g = jax.lax.all_gather(x, axis)  # [n, ...]
    n = g.shape[0]
    if n & (n - 1):
        raise ValueError("Adasum requires a power-of-2 replica count "
                         "(parity: torch/mpi_ops.py:104-120)")
    flat = g.reshape(n, -1).astype(jnp.float32)
    if _pk.mode() != "off" and not _pk.vma_active(flat):
        pad = (-flat.shape[1]) % 128
        padded = jnp.pad(flat, ((0, 0), (0, pad))) if pad else flat
        while padded.shape[0] > 1:  # one batched launch per tree level
            padded = _pk.adasum_combine_pairs(padded[0::2], padded[1::2])
        return padded[0, :flat.shape[1]].reshape(x.shape).astype(x.dtype)
    while flat.shape[0] > 1:
        a, b = flat[0::2], flat[1::2]
        dot = jnp.sum(a * b, axis=1, keepdims=True)
        na = jnp.sum(a * a, axis=1, keepdims=True)
        nb = jnp.sum(b * b, axis=1, keepdims=True)
        ac = jnp.where(na == 0, 1.0, 1.0 - dot / (2 * jnp.where(na == 0, 1.0, na)))
        bc = jnp.where(nb == 0, 1.0, 1.0 - dot / (2 * jnp.where(nb == 0, 1.0, nb)))
        flat = ac * a + bc * b
    return flat[0].reshape(x.shape).astype(x.dtype)


# ------------------------------------------------------------ whole-step API
def replica_mesh() -> Mesh:
    return basics.mesh()


def batch_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Shard dim 0 (batch) across replicas."""
    return NamedSharding(mesh or basics.mesh(), P(MESH_AXIS))


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or basics.mesh(), P())


def shard_batch(batch, mesh: Optional[Mesh] = None):
    """Place a host batch onto the mesh, sharded along dim 0."""
    sh = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)


def replicate(tree, mesh: Optional[Mesh] = None):
    """Replicate params/optimizer state across the mesh (the SPMD analogue of
    `broadcast_parameters`: every replica holds identical values)."""
    sh = replicated_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)


def make_train_step(loss_fn: Callable, tx, mesh: Optional[Mesh] = None,
                    donate: bool = True, zero1: bool = False,
                    example_opt_state=None) -> Callable:
    """Build the jitted data-parallel train step (the bench hot loop).

    ``loss_fn(params, batch) -> scalar loss`` computed on the *local* shard;
    gradient averaging across replicas is inserted automatically by GSPMD
    because params are replicated while the batch is sharded. ``tx`` is an
    optax GradientTransformation. Returns
    ``step(params, opt_state, batch) -> (params, opt_state, loss)``.

    ``zero1=True`` shards the optimizer state 1/N over the replica axis
    (`optim/zero.py`): pass ``example_opt_state`` (an abstract or concrete
    ``tx.init(params)`` pytree) so the per-leaf shardings can be derived,
    and place the live state with :func:`optim.zero.shard_opt_state` before
    the first call.
    """
    import optax

    mesh = mesh or basics.mesh()
    repl = NamedSharding(mesh, P())
    opt_sh: Any = repl
    if zero1:
        if example_opt_state is None:
            raise ValueError(
                "zero1=True needs example_opt_state (tx.init(params) or its "
                "jax.eval_shape) to derive per-leaf shardings")
        from .optim.zero import zero1_shardings

        opt_sh = zero1_shardings(example_opt_state, mesh)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(
        step,
        donate_argnums=donate_argnums,
        out_shardings=(repl, opt_sh, repl),
    )
