"""Training-loop callbacks (Keras-surface parity, framework-agnostic).

Reference parity: `horovod/_keras/callbacks.py` —
  * BroadcastGlobalVariablesCallback (:20-43) — sync params+optimizer state
    from root at train start (the checkpoint/restore pattern).
  * MetricAverageCallback (:46-84) — allreduce epoch metrics across ranks.
  * LearningRateScheduleCallback (:87-134) and LearningRateWarmupCallback
    (:137-185) — multiplier schedules with the momentum-correction staircase.

JAX shape: callbacks operate on a mutable ``state`` dict the training loop
owns (``params``, ``opt_state``, ``lr`` keys by convention) via hooks named
like Keras': ``on_train_begin / on_epoch_begin / on_epoch_end / on_batch_end``.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

from . import basics
from .ops import collective_ops as ops
from .optim.broadcast import broadcast_optimizer_state, broadcast_parameters


class Callback:
    def on_train_begin(self, state: Dict[str, Any]) -> None: ...

    def on_epoch_begin(self, epoch: int, state: Dict[str, Any]) -> None: ...

    def on_batch_end(self, batch: int, state: Dict[str, Any]) -> None: ...

    def on_epoch_end(self, epoch: int, state: Dict[str, Any],
                     metrics: Optional[Dict[str, float]] = None) -> None: ...


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast params (+ optimizer state) from root at train start
    (`_keras/callbacks.py:20-43`)."""

    def __init__(self, root_rank: int = 0, broadcast_opt_state: bool = True):
        self.root_rank = root_rank
        self.broadcast_opt_state = broadcast_opt_state

    def on_train_begin(self, state):
        state["params"] = broadcast_parameters(state["params"],
                                               self.root_rank)
        if self.broadcast_opt_state and "opt_state" in state:
            state["opt_state"] = broadcast_optimizer_state(
                state["opt_state"], self.root_rank)


class MetricAverageCallback(Callback):
    """Average epoch metrics over ranks before reporting
    (`_keras/callbacks.py:46-84`)."""

    def on_epoch_end(self, epoch, state, metrics=None):
        if not metrics or basics.size() == 1:
            return
        import numpy as np

        for k in sorted(metrics):
            avg = ops.allreduce(np.asarray([metrics[k]], np.float64),
                                name=f"metric.{k}.e{epoch}", op=basics.Average)
            metrics[k] = float(np.asarray(avg)[0])


class LearningRateScheduleCallback(Callback):
    """Multiply the base LR by ``multiplier(epoch)`` within [start, end)
    (`_keras/callbacks.py:87-134`). ``staircase``/momentum-correction notes
    apply to the optimizer integration the loop owns."""

    def __init__(self, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 initial_lr: Optional[float] = None,
                 steps_per_epoch: Optional[int] = None):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.initial_lr = initial_lr
        self.steps_per_epoch = steps_per_epoch
        if not callable(multiplier):
            self._mult = lambda epoch: multiplier
        else:
            self._mult = multiplier
        self._current_epoch = 0
        self._batches_this_epoch = 0
        self._learned_steps: Optional[int] = None
        self._warned_no_steps = False

    def _in_range(self, epoch):
        return (epoch >= self.start_epoch
                and (self.end_epoch is None or epoch < self.end_epoch))

    def on_epoch_begin(self, epoch, state):
        if self._batches_this_epoch:
            # learn steps/epoch from the epoch just finished so smooth
            # schedules work even when the loop never declared it
            self._learned_steps = self._batches_this_epoch
        self._batches_this_epoch = 0
        self._current_epoch = epoch
        base = self.initial_lr if self.initial_lr is not None else \
            state.get("base_lr", state.get("lr"))
        if base is None:
            raise ValueError("state must carry 'lr' (or pass initial_lr)")
        state.setdefault("base_lr", base)
        if self.staircase and self._in_range(epoch):
            state["lr"] = state["base_lr"] * self._mult(epoch)

    def on_batch_end(self, batch, state):
        self._batches_this_epoch += 1
        if not self.staircase and self._in_range(self._current_epoch):
            # Smooth schedule needs a fractional epoch (reference reads
            # Keras `params['steps']`): declared steps_per_epoch wins;
            # otherwise use the count learned from the previous epoch.
            # During the very first epoch with neither, hold the
            # epoch-begin lr and warn once instead of crashing the loop.
            steps = (self.steps_per_epoch or state.get("steps_per_epoch")
                     or self._learned_steps)
            if not steps:
                if not self._warned_no_steps:
                    import warnings

                    warnings.warn(
                        "smooth LR schedule has no steps_per_epoch yet "
                        "(pass it to the callback or set "
                        "state['steps_per_epoch']); lr will move at epoch "
                        "granularity until one epoch has completed")
                    self._warned_no_steps = True
                return
            frac = self._current_epoch + min(1.0, (batch + 1) / float(steps))
            state["lr"] = state["base_lr"] * self._mult(frac)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from lr to lr*size over ``warmup_epochs``
    (`_keras/callbacks.py:137-185`, Goyal et al. linear scaling)."""

    def __init__(self, warmup_epochs: int = 5, momentum_correction: bool = True,
                 initial_lr: Optional[float] = None, verbose: bool = False,
                 steps_per_epoch: Optional[int] = None):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose
        size = basics.size() if basics.is_initialized() else 1

        def multiplier(epoch):
            if epoch >= warmup_epochs:
                return size
            # epoch may be fractional; reference formula:
            # lr = initial * (size * epoch / warmup + (1 - epoch / warmup))
            p = epoch / float(warmup_epochs)
            return size * p + (1 - p)

        # The smooth ramp only applies within [0, warmup_epochs); afterwards
        # on_epoch_begin pins lr at base*size (reference passes the same
        # end_epoch, `_keras/callbacks.py:137-185`).
        super().__init__(multiplier, start_epoch=0,
                         end_epoch=warmup_epochs, staircase=False,
                         initial_lr=initial_lr,
                         steps_per_epoch=steps_per_epoch)

    def on_epoch_begin(self, epoch, state):
        super().on_epoch_begin(epoch, state)
        state["lr"] = state["base_lr"] * self._mult(epoch)
        if self.verbose and epoch <= self.warmup_epochs:
            print(f"Epoch {epoch}: warmup lr = {state['lr']:.6f}")


class CommitStateCallback(Callback):
    """Commit an :class:`~.elastic.ElasticState` every N batches, bounding
    how much work a membership reset can roll back (reference
    `horovod/_keras/elastic.py` CommitStateCallback). Commit boundaries are
    also where waiting joiners are admitted, so smaller N means faster
    scale-up at the cost of more frequent snapshots."""

    def __init__(self, state, batches_per_commit: int = 1):
        self.state = state
        self.batches_per_commit = max(1, int(batches_per_commit))
        self._since_commit = 0

    def on_batch_end(self, batch, state):
        self._since_commit += 1
        if self._since_commit >= self.batches_per_commit:
            self._since_commit = 0
            self.state.commit()


class ConsistencyCheckCallback(Callback):
    """Run the cross-rank parameter consistency auditor
    (:class:`~.integrity.ConsistencyAuditor`, docs/fault-tolerance.md)
    every N batches. Collective: install it on EVERY rank, with the same
    interval, or the audit's broadcast/allreduce will desynchronize the
    ranks it exists to protect. With ``interval=None`` the
    ``HOROVOD_CONSISTENCY_INTERVAL`` knob decides (0 disables)."""

    def __init__(self, interval: Optional[int] = None,
                 policy: Optional[str] = None, root_rank: int = 0):
        from .integrity import ConsistencyAuditor

        self.auditor = ConsistencyAuditor(interval=interval, policy=policy,
                                          root_rank=root_rank)

    def on_batch_end(self, batch, state):
        state["params"] = self.auditor.maybe_audit(state["params"])


class MetricsCallback(Callback):
    """Dump the aggregated runtime-metrics snapshot (docs/metrics.md) as JSON
    at epoch boundaries, on the aggregating rank only. The file is rewritten
    atomically each time, so ``path`` always holds the latest complete
    snapshot; the written object is ``{"epoch": N, "metrics": snapshot}``."""

    def __init__(self, path: str, every_n_epochs: int = 1):
        self.path = path
        self.every_n_epochs = max(1, int(every_n_epochs))

    def on_epoch_end(self, epoch, state, metrics=None):
        if basics.is_initialized() and basics.rank() != 0:
            return
        if (epoch + 1) % self.every_n_epochs:
            return
        import json
        import os

        from .metrics import aggregate

        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch": int(epoch), "metrics": aggregate()}, f,
                      indent=2, sort_keys=True)
        os.replace(tmp, self.path)


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = list(callbacks)

    def on_train_begin(self, state):
        for c in self.callbacks:
            c.on_train_begin(state)

    def on_epoch_begin(self, epoch, state):
        for c in self.callbacks:
            c.on_epoch_begin(epoch, state)

    def on_batch_end(self, batch, state):
        for c in self.callbacks:
            c.on_batch_end(batch, state)

    def on_epoch_end(self, epoch, state, metrics=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, state, metrics)
