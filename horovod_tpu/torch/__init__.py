"""PyTorch binding surface — `horovod.torch` parity on the TPU-native engine.

Reference parity: `horovod/torch/__init__.py` + `torch/mpi_ops.py`:
  * ``allreduce[_async][_]``, ``allgather[_async]``, ``broadcast[_async][_]``,
    ``alltoall``, ``poll``, ``synchronize``, ``join`` (`torch/mpi_ops.py`).
  * ``DistributedOptimizer`` — per-parameter hooks fire async allreduce during
    backward; ``synchronize()`` drains before ``step()``;
    ``backward_passes_per_step`` accumulation; ``skip_synchronize``
    (`torch/__init__.py:115-209`).
  * ``broadcast_parameters`` (:437-466), ``broadcast_optimizer_state``
    (:469-585), ``Compression`` (`torch/compression.py`).

Torch tensors live on CPU (no CUDA in this build); the collective executes on
the TPU/device mesh through the shared engine — the torch<->engine boundary is
a zero-copy numpy view where possible, matching the reference's adapter layer
(`torch/adapter_v2.cc`) in role.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from .. import basics
from ..basics import (  # noqa: F401  (re-exported API surface; probe set
    # mirrors reference torch/mpi_ops.py:60-77)
    Adasum,
    Average,
    Sum,
    cross_rank,
    cross_size,
    ddl_built,
    gloo_built,
    gloo_enabled,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mlsl_built,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    shutdown,
    size,
)
from ..exceptions import HorovodInternalError  # noqa: F401
from ..ops import collective_ops as _ops
from .compression import Compression  # noqa: F401


def _require_torch():
    import torch

    return torch


def _to_numpy(tensor) -> np.ndarray:
    torch = _require_torch()
    if tensor.dtype == torch.bfloat16:
        # numpy has no native bf16; reinterpret the bits through ml_dtypes so
        # the wire dtype stays 16-bit (the point of Compression.bf16)
        import ml_dtypes

        t = tensor.detach().cpu().contiguous()
        if hasattr(torch, "uint16"):  # torch >= 2.3
            return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
        return t.float().numpy().astype(ml_dtypes.bfloat16)
    return tensor.detach().cpu().numpy()


def _result_to_torch(result, dtype):
    torch = _require_torch()
    arr = np.asarray(result)
    if arr.dtype.name == "bfloat16":
        t = torch.from_numpy(arr.view(np.uint16).copy()).view(torch.bfloat16)
    else:
        t = torch.from_numpy(arr.copy())
    return t if dtype is None else t.to(dtype)


def _from_result(result, like):
    return _result_to_torch(result, like.dtype)


# ------------------------------------------------------------- collectives
def allreduce_async(tensor, average: Optional[bool] = None,
                    name: Optional[str] = None, op: Optional[int] = None) -> int:
    op = _resolve_op(average, op)
    h = _ops.allreduce_async(_to_numpy(tensor), name=name, op=op)
    _HANDLE_DTYPES[h] = tensor.dtype
    return h


class _HorovodAllreduce:
    """Differentiable allreduce (`torch/mpi_ops.py:159-171` HorovodAllreduce):
    the adjoint of a sum/average over ranks is the same reduction of the
    incoming gradient (each rank's output feeds every rank's loss). Defined
    lazily because torch is an optional dependency of this package."""

    _cls = None

    @classmethod
    def apply(cls, tensor, op, name):
        if cls._cls is None:
            torch = _require_torch()

            class Fn(torch.autograd.Function):
                @staticmethod
                def forward(ctx, x, op_, name_):
                    ctx.op = op_
                    return synchronize(allreduce_async(x, name=name_,
                                                       op=op_))

                @staticmethod
                def backward(ctx, dy):
                    # Adasum keeps the reference's registered sum-allreduce
                    # gradient (its combine rule has no closed-form adjoint)
                    op_ = ctx.op if ctx.op in (Sum, Average) else Sum
                    return allreduce(dy, op=op_), None, None

            cls._cls = Fn
        return cls._cls.apply(tensor, op, name)


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None, compression=Compression.none,
              op: Optional[int] = None):
    """Returns a NEW tensor with the averaged/summed value
    (`torch/mpi_ops.py:133-168`). Differentiable: an input that requires
    grad yields the reference-formula gradient (allreduce of the incoming
    gradient with the same op); compression casts are torch ops, so the
    gradient flows through them too."""
    op_ = _resolve_op(average, op)
    comp, ctx = compression.compress(tensor)
    out = _HorovodAllreduce.apply(comp, op_, name)
    return compression.decompress(out, ctx)


def allreduce_async_(tensor, average: Optional[bool] = None,
                     name: Optional[str] = None,
                     op: Optional[int] = None) -> int:
    """In-place async allreduce: the completion callback copies the result
    into ``tensor`` before the handle unblocks (`torch/mpi_ops.py:170-205`
    in-place semantics; copy-at-completion like `mpi_ops_v2.cc:53-79`, so
    temporary wrappers over shared storage — ``p.data``, views — work)."""
    op_ = _resolve_op(average, op)
    h = _ops.allreduce_async(_to_numpy(tensor), name=name, op=op_,
                             callback=_make_inplace_callback(tensor))
    _HANDLE_DTYPES[h] = tensor.dtype
    _remember_inplace(h, tensor)
    return h


def allreduce_(tensor, average: Optional[bool] = None,
               name: Optional[str] = None, op: Optional[int] = None):
    return synchronize(allreduce_async_(tensor, average=average, name=name,
                                        op=op))


def allgather_async(tensor, name: Optional[str] = None) -> int:
    if tensor.dim() == 0:
        raise ValueError(
            "hvd.allgather requires a tensor with at least one dimension "
            "(got a 0-dim scalar); reshape with tensor.reshape(1) first")
    h = _ops.allgather_async(_to_numpy(tensor), name=name)
    _HANDLE_DTYPES[h] = tensor.dtype
    return h


class _HorovodAllgather:
    """Differentiable allgather (`torch/mpi_ops.py:290-309`): the adjoint of
    concatenation over ranks is sum-allreduce of the incoming gradient, then
    slicing out this rank's segment at the offset given by the gathered
    per-rank dim0s (ragged inputs allowed — the dims are allgathered too)."""

    _cls = None

    @classmethod
    def apply(cls, tensor, name):
        if tensor.dim() == 0:
            # the backward narrows dim 0 of the gathered gradient; a 0-dim
            # input has no dim 0 and autograd would fail much later with an
            # opaque 'invalid gradient' shape error — reject up front
            raise ValueError(
                "hvd.allgather requires a tensor with at least one "
                "dimension (got a 0-dim scalar); reshape with "
                "tensor.reshape(1) first")
        if cls._cls is None:
            torch = _require_torch()

            class Fn(torch.autograd.Function):
                @staticmethod
                def forward(ctx, x, name_):
                    ctx.dim0 = int(x.shape[0]) if x.dim() else 1
                    return synchronize(allgather_async(x, name=name_))

                @staticmethod
                def backward(ctx, dy):
                    torch = _require_torch()
                    g = allreduce(dy, op=Sum)
                    dims = allgather(torch.tensor([ctx.dim0],
                                                  dtype=torch.int64))
                    r = rank()
                    offset = int(dims[:r].sum().item()) if r else 0
                    return g.narrow(0, offset, ctx.dim0), None

            cls._cls = Fn
        return cls._cls.apply(tensor, name)


def allgather(tensor, name: Optional[str] = None):
    """Concatenates over ranks along dim 0; differentiable
    (`torch/mpi_ops.py:312-336`)."""
    return _HorovodAllgather.apply(tensor, name)


def broadcast_async(tensor, root_rank: int, name: Optional[str] = None) -> int:
    h = _ops.broadcast_async(_to_numpy(tensor), root_rank, name=name)
    _HANDLE_DTYPES[h] = tensor.dtype
    return h


class _HorovodBroadcast:
    """Differentiable broadcast (`torch/mpi_ops.py:372-386`): every rank's
    output is root's input, so root's gradient is the sum of all ranks'
    incoming gradients and non-root inputs get zero."""

    _cls = None

    @classmethod
    def apply(cls, tensor, root_rank, name):
        if cls._cls is None:
            torch = _require_torch()

            class Fn(torch.autograd.Function):
                @staticmethod
                def forward(ctx, x, root_, name_):
                    ctx.root_rank = root_
                    return synchronize(broadcast_async(x, root_, name=name_))

                @staticmethod
                def backward(ctx, dy):
                    g = allreduce(dy, op=Sum)
                    if rank() != ctx.root_rank:
                        g = g * 0
                    return g, None, None

            cls._cls = Fn
        return cls._cls.apply(tensor, root_rank, name)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Out-of-place broadcast; differentiable (`torch/mpi_ops.py:389-412`)."""
    return _HorovodBroadcast.apply(tensor, root_rank, name)


def broadcast_async_(tensor, root_rank: int,
                     name: Optional[str] = None) -> int:
    h = _ops.broadcast_async(_to_numpy(tensor), root_rank, name=name,
                             callback=_make_inplace_callback(tensor))
    _HANDLE_DTYPES[h] = tensor.dtype
    _remember_inplace(h, tensor)
    return h


def broadcast_(tensor, root_rank: int, name: Optional[str] = None):
    return synchronize(broadcast_async_(tensor, root_rank, name=name))


class _HorovodAlltoall:
    """Differentiable alltoall. Equal-split alltoall is self-adjoint (the
    exchange is a permutation of blocks); the ragged form's adjoint is an
    alltoall of the gradient with splits = the forward's received splits,
    which routes each gradient chunk back to the rank that sent the
    corresponding rows (later-horovod HorovodAlltoall)."""

    _cls = None

    @classmethod
    def apply(cls, tensor, splits, name):
        if cls._cls is None:
            torch = _require_torch()

            class Fn(torch.autograd.Function):
                @staticmethod
                def forward(ctx, x, splits_, name_):
                    res = _ops.synchronize(
                        _ops.alltoall_async(_to_numpy(x), splits=splits_,
                                            name=name_))
                    from ..runtime.messages import AlltoallvResult

                    if isinstance(res, AlltoallvResult):
                        ctx.recv_splits = tuple(
                            int(s) for s in res.received_splits)
                        out = _from_result(res.output, x)
                        rs = torch.tensor(ctx.recv_splits,
                                          dtype=torch.int32)
                        ctx.mark_non_differentiable(rs)
                        return out, rs
                    ctx.recv_splits = None
                    return _from_result(res, x)

                @staticmethod
                def backward(ctx, dy, *unused_rs_grad):
                    if ctx.recv_splits is not None:
                        dx, _ = alltoall(dy, splits=ctx.recv_splits)
                        return dx, None, None
                    return alltoall(dy), None, None

            cls._cls = Fn
        return cls._cls.apply(tensor, splits, name)


def alltoall(tensor, splits=None, name: Optional[str] = None):
    """Alltoall; with ``splits`` (length-world, summing to dim 0) the
    ragged alltoallv form — the later-horovod torch surface shape,
    returning ``(output, received_splits)``. Any int iterable works
    (torch tensor, numpy array, list); the engine normalizes.
    Differentiable in both forms."""
    if splits is not None:
        splits = tuple(int(s) for s in splits)
    return _HorovodAlltoall.apply(tensor, splits, name)


# Per-handle metadata. The in-place copy-back happens in the engine's
# completion callback (which holds the tensor only until the collective
# finishes, like the reference's done-callback in `mpi_ops_v2.cc:53-79`) —
# these maps only shape synchronize()'s RETURN value, so the target entry is
# a weak reference: a caller that drops both the handle and the tensor
# without synchronizing must not pin the tensor forever (round-1 review:
# these maps grew without bound). All entries clear on engine shutdown.
_INPLACE_TARGETS: Dict[int, Any] = {}
_HANDLE_DTYPES: Dict[int, Any] = {}


def _reset_handle_maps() -> None:
    _INPLACE_TARGETS.clear()
    _HANDLE_DTYPES.clear()


basics.register_shutdown_hook(_reset_handle_maps)


def _remember_inplace(handle: int, tensor) -> None:
    import weakref

    try:
        _INPLACE_TARGETS[handle] = weakref.ref(tensor)
    except TypeError:  # tensor subclass without weakref support
        _INPLACE_TARGETS[handle] = lambda t=tensor: t


def _make_inplace_callback(tensor):
    """Completion callback writing the collective result into ``tensor``.
    The closure's strong reference lives only until the op completes, so
    temporary wrappers over shared storage (``p.data``, views) are updated
    correctly without pinning anything past the collective."""
    torch = _require_torch()

    def cb(ok, result):
        if ok:
            with torch.no_grad():
                tensor.copy_(_result_to_torch(result, tensor.dtype))

    return cb


def poll(handle: int) -> bool:
    return _ops.poll(handle)


def synchronize(handle: int):
    """Blocks and returns a torch tensor in the submitted tensor's dtype
    (`torch/mpi_ops.py:476-492`); for in-place ops the copy-back has already
    happened in the completion callback — the original tensor is returned
    (or a fresh tensor if the caller's wrapper was dropped)."""
    try:
        result = _ops.synchronize(handle)
    finally:
        # pop even when the op failed, or failed handles leak map entries
        dtype = _HANDLE_DTYPES.pop(handle, None)
        target_ref = _INPLACE_TARGETS.pop(handle, None)
    target = target_ref() if target_ref is not None else None
    if target is not None:
        return target
    return _result_to_torch(result, dtype)


def join() -> int:
    return _ops.join()


def _resolve_op(average: Optional[bool], op: Optional[int]) -> int:
    # reference deprecation dance (torch/mpi_ops.py:90-130): average kw wins
    # if given; default Average
    if average is not None:
        return Average if average else Sum
    return Average if op is None else op


# ------------------------------------------------------- parameter broadcast
def broadcast_parameters(params, root_rank: int = 0) -> None:
    """In-place broadcast of a ``model.state_dict()`` or named-parameter
    iterable (`torch/__init__.py:437-466`)."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    for name, p in items:
        if p is None:
            continue
        handles.append(broadcast_async_(p.data if hasattr(p, "data") else p,
                                        root_rank, name=f"bp.{name}"))
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer, root_rank: int = 0) -> None:
    """In-place broadcast of optimizer state incl. scalar hyper-state and
    param_groups hyperparameters (lr, momentum, ...) wrapped into a pickled
    object broadcast (`torch/__init__.py:469-585`)."""
    torch = _require_torch()

    # Checkpoint-resume pattern: rank 0 restored state, workers constructed a
    # fresh optimizer with empty state. Materialize state on every rank with a
    # zero-grad dummy step first (the reference's flow, torch/__init__.py:
    # 477-493) so all ranks submit the same broadcast set — otherwise the
    # name negotiation would wait forever on tensors only root enqueued.
    if not optimizer.state_dict().get("state"):
        restore = []
        for group in optimizer.param_groups:
            for p in group["params"]:
                # snapshot: step() with zero grads still mutates params when
                # weight_decay/momentum hyperparameters are active
                restore.append((p, p.grad, p.detach().clone()))
                p.grad = torch.zeros_like(p)
        optimizer.step()
        with torch.no_grad():
            for p, g, snap in restore:
                p.copy_(snap)
                p.grad = g
    state_dict = optimizer.state_dict()

    # scalar-wrapping: non-tensor leaves are broadcast as objects and written
    # back (the reference's _create_callback machinery, :497-560)
    scalars: List[Tuple[str, Any]] = []
    tensors: List[Tuple[str, Any]] = []
    for pid, pstate in state_dict.get("state", {}).items():
        for k, v in sorted(pstate.items()):
            key = f"opt.{pid}.{k}"
            if torch.is_tensor(v):
                tensors.append((key, v))
            else:
                scalars.append((key, v))
    handles = [broadcast_async_(t, root_rank, name=n) for n, t in tensors]
    for h in handles:
        synchronize(h)

    # param_groups hyperparameters (lr, momentum, weight_decay, ...) sync too
    # (`torch/__init__.py:560-582`); the rank-local 'params' index lists stay
    hypers = [{k: v for k, v in g.items() if k != "params"}
              for g in state_dict.get("param_groups", [])]
    from ..optim.broadcast import broadcast_object

    synced_scalars, synced_hypers = broadcast_object(
        ([v for _, v in scalars], hypers), root_rank, name="opt.scalars")
    for (key, _), new in zip(scalars, synced_scalars):
        pid_s, k = key.split(".")[1:]
        state_dict["state"][int(pid_s) if pid_s.isdigit() else pid_s][k] = new
    for group, new_hyper in zip(state_dict.get("param_groups", []),
                                synced_hypers):
        group.update(new_hyper)
    optimizer.load_state_dict(state_dict)


# ----------------------------------------------------- DistributedOptimizer
def _validate_named_parameters(optimizer, named_parameters):
    """Default naming + duplicate rejection shared by both optimizer wraps
    (`torch/__init__.py:93-105`)."""
    if named_parameters is not None:
        named = list(named_parameters)
    else:
        named = [(f"param.{i}.{j}", p)
                 for i, g in enumerate(optimizer.param_groups)
                 for j, p in enumerate(g["params"])]
    import collections

    counts = collections.Counter(n for n, _ in named)
    dups = {n for n, c in counts.items() if c > 1}
    if dups:
        raise ValueError(f"duplicate parameter names: {sorted(dups)} "
                         "(named_parameters must be unique, "
                         "torch/__init__.py:93-105)")
    return named


class _DistributedOptimizer:
    """Wraps a torch optimizer: per-parameter backward hooks fire async
    allreduce; ``step()`` drains handles first (`torch/__init__.py:115-209`)."""

    def __init__(self, optimizer, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1, op: int = Average):
        torch = _require_torch()
        self._opt = optimizer
        self._compression = compression
        self._op = op
        self.backward_passes_per_step = backward_passes_per_step
        self._counts: Dict[str, int] = {}
        self._handles: Dict[str, int] = {}
        self._ctxs: Dict[str, Any] = {}
        self._should_sync = True

        named = _validate_named_parameters(optimizer, named_parameters)
        self._named = named
        if basics.size() > 1:
            for name, p in named:
                if p.requires_grad:
                    self._register_hook(name, p)

    def _register_hook(self, name, p):
        # post-accumulate hook = the grad-accumulator hook of the reference
        # (`torch/__init__.py:115-150`)
        def hook(param):
            self._counts[name] = self._counts.get(name, 0) + 1
            if self._counts[name] == self.backward_passes_per_step:
                self._counts[name] = 0
                # the raw ACCUMULATED gradient goes on the wire — the
                # reference does not divide by the pass count
                # (`torch/__init__.py:115-150`); users scale their loss
                comp, ctx = self._compression.compress(param.grad)
                self._handles[name] = _ops.allreduce_async(
                    _to_numpy(comp), name=f"grad.{name}", op=self._op)
                self._ctxs[name] = (ctx, param)

        p.register_post_accumulate_grad_hook(hook)

    def synchronize(self) -> None:
        """Drain outstanding gradient allreduces into .grad
        (`torch/__init__.py:152-169`)."""
        torch = _require_torch()
        for name, h in list(self._handles.items()):
            out = _ops.synchronize(h)
            ctx, param = self._ctxs.pop(name)
            t = self._compression.decompress(_result_to_torch(out, None), ctx)
            with torch.no_grad():
                param.grad.copy_(t.to(param.grad.dtype))
        self._handles.clear()

    @contextlib.contextmanager
    def skip_synchronize(self):
        """(`torch/__init__.py:171-189`) — use after a manual synchronize()
        (e.g. for gradient clipping) so step() doesn't re-drain."""
        self._should_sync = False
        try:
            yield
        finally:
            self._should_sync = True

    def step(self, closure=None):
        if self._should_sync and basics.size() > 1:
            self.synchronize()
        return self._opt.step(closure)

    def zero_grad(self, *a, **k):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step() or optimizer.synchronize()")
        return self._opt.zero_grad(*a, **k)

    def __getattr__(self, item):
        return getattr(self._opt, item)


class _DistributedAdasumOptimizer:
    """Delta-flow Adasum (`torch/__init__.py:211-379`): each backward pass
    hook runs the *inner* optimizer step for just that parameter, producing
    the local delta ``-α·f(g)``; the delta — not the gradient — is combined
    across ranks with op=Adasum, and ``step()`` applies the combined delta.

    Deviation from the reference mechanics (same math): the reference
    leaves ``p`` holding the raw delta between hook and ``step()``
    (`torch/__init__.py:296-312`); here ``p`` is restored to its pre-step
    value immediately, so the model is never observably corrupted mid-step.
    """

    def __init__(self, optimizer, named_parameters=None,
                 compression=Compression.none,
                 backward_passes_per_step: int = 1):
        torch = _require_torch()
        self._opt = optimizer
        self._compression = compression
        self.backward_passes_per_step = backward_passes_per_step
        self._counts: Dict[str, int] = {}
        self._handles: Dict[str, int] = {}
        self._ctxs: Dict[str, Any] = {}

        named = _validate_named_parameters(optimizer, named_parameters)
        self._named = named
        for name, p in named:
            if p.requires_grad:
                self._register_hook(name, p)

    def _allreduce_delta_async(self, name, p):
        torch = _require_torch()
        start = p.detach().clone()
        # run the inner optimizer on just this parameter (reference stashes
        # param_groups the same way, `torch/__init__.py:299-309`)
        stash = [g["params"] for g in self._opt.param_groups]
        for g in self._opt.param_groups:
            g["params"] = [v for v in g["params"] if v is p]
        self._opt.step()
        for g, s in zip(self._opt.param_groups, stash):
            g["params"] = s
        with torch.no_grad():
            delta = p.detach() - start
            p.copy_(start)
        comp, ctx = self._compression.compress(delta)
        self._handles[name] = _ops.allreduce_async(
            _to_numpy(comp), name=f"adasum.{name}", op=Adasum)
        self._ctxs[name] = (ctx, p)

    def _register_hook(self, name, p):
        def hook(param):
            self._counts[name] = self._counts.get(name, 0) + 1
            if self._counts[name] == self.backward_passes_per_step:
                self._counts[name] = 0
                self._allreduce_delta_async(name, param)

        p.register_post_accumulate_grad_hook(hook)

    def synchronize(self) -> None:
        # parity: a no-op — draining happens in step()
        # (`torch/__init__.py:345-347`)
        pass

    @contextlib.contextmanager
    def skip_synchronize(self):
        raise AssertionError("Skipping synchronization is not supported "
                             "when using Adasum optimizer.")

    def step(self, closure=None):
        torch = _require_torch()
        loss = closure() if closure is not None else None
        # Fire for every hook-registered param missing a handle — even ones
        # whose grad is None (inner step skips them, producing a zero delta
        # that is still submitted). Submission must not depend on rank-local
        # gradient presence or ranks diverge on the negotiated name set and
        # deadlock (reference fires all of _requires_update,
        # `torch/__init__.py:352-355`).
        for name, p in self._named:
            if p.requires_grad and name not in self._handles:
                self._counts[name] = 0
                self._allreduce_delta_async(name, p)
        for name, h in list(self._handles.items()):
            ctx, p = self._ctxs.pop(name)
            combined = self._compression.decompress(
                _result_to_torch(_ops.synchronize(h), None), ctx)
            with torch.no_grad():
                p.add_(combined.to(p.dtype))
        self._handles.clear()
        return loss

    def zero_grad(self, *a, **k):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step()")
        return self._opt.zero_grad(*a, **k)

    def __getattr__(self, item):
        return getattr(self._opt, item)


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1,
                         op: int = Average):
    """op=Adasum routes to the delta-flow optimizer when communicating
    (`torch/__init__.py:428-435`)."""
    if op == Adasum and basics.size() > 1:
        return _DistributedAdasumOptimizer(optimizer, named_parameters,
                                           compression,
                                           backward_passes_per_step)
    return _DistributedOptimizer(optimizer, named_parameters, compression,
                                 backward_passes_per_step, op)
