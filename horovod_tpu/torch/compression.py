"""Torch-native gradient compression (`horovod/torch/compression.py` parity).

The shared :mod:`horovod_tpu.ops.compression` operates on JAX arrays; torch
tensors carry torch dtypes, so the torch surface gets its own compressor pair
exactly as the reference splits `tensorflow/compression.py` /
`torch/compression.py`.
"""

from __future__ import annotations


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point:
            return tensor.half(), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.to(ctx)


class BF16Compressor(Compressor):
    """TPU-native 16-bit wire format (fp32 exponent range)."""

    @staticmethod
    def compress(tensor):
        import torch

        if tensor.dtype.is_floating_point:
            return tensor.to(torch.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor if ctx is None else tensor.to(ctx)


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
