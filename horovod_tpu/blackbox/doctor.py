"""``hvddoctor`` — postmortem diagnosis of a blackbox bundle.

Ingests a dump directory (``rank_*.json`` + optional ``bundle.json``), a
bundle manifest, or a single rank dump, then:

* matches the known failure signatures (:mod:`.signatures`) — collective
  deadlock with the stalled tensor and missing ranks, parameter-desync
  origin step, NaN-first rank, dead workers, stragglers, reconnect
  storms, heartbeat flaps;
* prints a cross-rank merged timeline of the final seconds;
* reports the first divergence — the earliest event where one rank's
  stream stops matching its peers.

Exit codes: 0 diagnosis produced, 1 unreadable/empty bundle, 2 usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict

from . import signatures as sigs


def load_bundle(path: str) -> Dict[int, dict]:
    """{rank: dump doc} out of a directory, bundle manifest, or one dump.
    Raises ValueError when nothing diagnosable is found."""
    docs: Dict[int, dict] = {}
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            if name.startswith("rank_") and name.endswith(".json"):
                _ingest(os.path.join(path, name), docs)
        if not docs:  # a bare bundle.json with its rank files cleaned up
            manifest = os.path.join(path, "bundle.json")
            if os.path.exists(manifest):
                _ingest(manifest, docs)
    else:
        _ingest(path, docs)
    if not docs:
        raise ValueError("no rank dumps found in %r (expected rank_N.json "
                         "files or a bundle.json manifest)" % path)
    return docs


def _ingest(path: str, docs: Dict[int, dict]) -> None:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as exc:
        raise ValueError("unreadable dump %s: %s" % (path, exc))
    if isinstance(doc, dict) and "ranks" in doc and "blackbox_bundle" in doc:
        for rank, rdoc in doc["ranks"].items():
            docs[int(rank)] = rdoc
    elif isinstance(doc, dict) and "rank" in doc:
        docs[int(doc["rank"])] = doc
    else:
        raise ValueError("%s is not a blackbox dump or bundle" % path)


def diagnose(bundle: Dict[int, dict], window_s: float = 30.0,
             timeline_limit: int = 200) -> dict:
    world = max([d.get("world_size") or 0 for d in bundle.values()]
                + [max(bundle) + 1])
    present = sorted(bundle)
    return {
        "ranks": present,
        "world_size": world,
        "missing_ranks": [r for r in range(world) if r not in bundle],
        "stub_ranks": [r for r in present if bundle[r].get("stub")],
        "reasons": {r: bundle[r].get("reason") or "" for r in present},
        "signatures": sigs.match_signatures(bundle),
        "first_divergence": sigs.first_divergence(bundle),
        "timeline": sigs.merged_timeline(bundle, window_s, timeline_limit),
    }


def format_report(diag: dict, bundle_path: str) -> str:
    lines = ["hvddoctor: %s" % bundle_path,
             "  ranks: %s of world %d%s" % (
                 diag["ranks"], diag["world_size"],
                 " (MISSING: %s)" % diag["missing_ranks"]
                 if diag["missing_ranks"] else "")]
    for r in diag["ranks"]:
        stub = " [coordinator stub]" if r in diag["stub_ranks"] else ""
        lines.append("  rank %d%s: %s" % (r, stub, diag["reasons"][r]))
    lines.append("")
    if diag["signatures"]:
        lines.append("DIAGNOSIS")
        for sig in diag["signatures"]:
            lines.append("  [%s] %s" % (sig["severity"].upper(),
                                        sig["summary"]))
    else:
        lines.append("DIAGNOSIS\n  no known failure signature matched; "
                     "inspect the timeline below")
    div = diag["first_divergence"]
    if div is not None:
        lines.append("")
        lines.append("FIRST DIVERGENCE")
        lines.append("  %s %r at %s: present on rank(s) %s, absent on "
                     "rank(s) %s" % (div["kind"], div["name"],
                                     _fmt_t(div["t"]), div["present_ranks"],
                                     div["absent_ranks"]))
    if diag["timeline"]:
        t_end = diag["timeline"][-1]["t"]
        lines.append("")
        lines.append("TIMELINE (final %d events)" % len(diag["timeline"]))
        for ev in diag["timeline"]:
            lines.append("  %+9.3fs rank %s %-10s %s %s" % (
                float(ev["t"]) - float(t_end), ev.get("rank", "?"),
                ev.get("kind", "?"), ev.get("name", ""),
                ev.get("detail", "")))
    return "\n".join(lines)


def _fmt_t(t) -> str:
    try:
        return time.strftime("%H:%M:%S", time.localtime(float(t)))
    except (ValueError, OverflowError, OSError):
        return str(t)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvddoctor",
        description="Diagnose a horovod_tpu blackbox postmortem bundle "
                    "(HOROVOD_BLACKBOX; see docs/observability.md).")
    parser.add_argument("bundle",
                        help="dump directory, bundle.json, or rank_N.json")
    parser.add_argument("--json", action="store_true",
                        help="emit the diagnosis as JSON")
    parser.add_argument("--window", type=float, default=30.0,
                        help="timeline window before the last event "
                             "(seconds, default 30)")
    parser.add_argument("--timeline-limit", type=int, default=200,
                        help="max merged-timeline events (default 200)")
    args = parser.parse_args(argv)

    try:
        bundle = load_bundle(args.bundle)
    except ValueError as exc:
        print("invalid bundle: %s" % exc, file=sys.stderr)
        return 1
    diag = diagnose(bundle, args.window, args.timeline_limit)
    try:
        if args.json:
            print(json.dumps(diag, indent=1))
        else:
            print(format_report(diag, args.bundle))
        sys.stdout.flush()
    except BrokenPipeError:
        # reader (head, less) closed the pipe mid-report: not an error, but
        # the interpreter would complain again flushing stdout at exit
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0
