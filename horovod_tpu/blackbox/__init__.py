"""Per-rank black-box flight recorder (``HOROVOD_BLACKBOX``).

A bounded in-memory ring of recent structured events — control frames,
collective lifecycle transitions, integrity verdicts, heartbeat state,
metric deltas, elastic epoch changes — recorded on every rank at
near-zero cost. On abnormal exit (enforced collective timeout,
``NonFiniteError``/``ParameterDesyncError``, ``ShutdownError``, an
unhandled exception, SIGTERM/SIGABRT, or a coordinator-declared dead
worker) every reachable rank dumps its ring plus a final metrics
snapshot and open-span table to ``HOROVOD_BLACKBOX_DIR/rank_N.json``;
rank 0 assembles the per-rank dumps — writing coordinator-knowledge
stubs for ranks that died silently — into one postmortem bundle that
``bin/hvddoctor`` diagnoses.

The whole subsystem is a no-op unless ``HOROVOD_BLACKBOX`` is set:
``active()`` returns ``None`` and every instrumentation site is a single
attribute read, allocating nothing (same discipline as tracing, asserted
the same way via :func:`allocation_count`).
"""

from __future__ import annotations

import json
import logging
import os
import signal as _signal
import socket as _socket
import sys
import threading
import time

from .recorder import (  # noqa: F401  (re-exported for callers)
    K_ALGO, K_ANOMALY, K_BITWIDTH, K_CKPT, K_COLLECTIVE, K_EPOCH, K_ERROR,
    K_EXCLUDED, K_FAILOVER, K_FAULT, K_FENCE, K_FRAME_RX, K_FRAME_TX,
    K_HEARTBEAT, K_METRICS, K_RANK_LOST, K_RECONNECT, K_SIGNAL, K_STALL,
    K_TIMEOUT, K_VERDICT, Event, FlightRecorder, allocation_count,
    ring_capacity,
)

logger = logging.getLogger("horovod_tpu")

BLACKBOX_VERSION = 1
DEFAULT_DIR = "hvd_blackbox"

_lock = threading.Lock()
_recorder = None            # FlightRecorder when HOROVOD_BLACKBOX is set
_dir = None                 # dump directory (resolved at activation)
_rank = 0                   # this process's rank (set_identity)
_world = 1
_dumped = False             # one dump per abnormal exit, not one per symptom
_shipper = None             # callable(doc_json) shipping a dump to rank 0
_dead = {}                  # rank -> (wall time, reason): coordinator view
_hooks_installed = False
_prev_excepthook = None
_prev_handlers = {}         # signum -> previous handler


def _enabled_env() -> bool:
    raw = os.environ.get("HOROVOD_BLACKBOX", "").strip()
    return raw not in ("", "0", "false", "False", "off")


def blackbox_dir() -> str:
    return _dir if _dir else (
        os.environ.get("HOROVOD_BLACKBOX_DIR", "").strip() or DEFAULT_DIR)


def active():
    """The process recorder, or None when the blackbox is off (fast path)."""
    return _recorder


def enabled() -> bool:
    return _recorder is not None


def maybe_activate():
    """Install the recorder iff ``HOROVOD_BLACKBOX`` is set. Idempotent."""
    global _recorder, _dir
    if not _enabled_env():
        return None
    with _lock:
        if _recorder is None:
            _dir = (os.environ.get("HOROVOD_BLACKBOX_DIR", "").strip()
                    or DEFAULT_DIR)
            _recorder = FlightRecorder()
            _install_hooks()
        return _recorder


def set_identity(rank: int, world_size: int) -> None:
    """Learned at init: names this process's dump file and stamps events
    recorded without an explicit rank."""
    global _rank, _world
    _rank = int(rank)
    _world = int(world_size)


def set_shipper(fn) -> None:
    """How a worker's dump reaches rank 0 (a ``push_blackbox`` bound to
    the coordinated controller); None on rank 0 / uncoordinated modes."""
    global _shipper
    _shipper = fn


def record(kind, name="", detail="", rank=None, t=None) -> None:
    """Record one event if the blackbox is on; no-op (one global read +
    one compare) otherwise. Non-hot-path convenience — tight loops should
    hold ``active()`` themselves, exactly like tracing sites do."""
    rec = _recorder
    if rec is None:
        return
    rec.record(kind, name, detail, _rank if rank is None else rank, t)


def note_dead_rank(rank: int, reason: str) -> None:
    """Coordinator side: remember a declared-dead worker so rank 0's dump
    can write a stub for it (its own dump will never arrive)."""
    rec = _recorder
    if rec is None:
        return
    rank = int(rank)
    _dead[rank] = (time.time(), reason)
    rec.record(K_RANK_LOST, "rank_%d" % rank, reason, rank)


# ------------------------------------------------------------------- dumps

def _open_span_table():
    """The tracing recorder's in-flight collectives — what each rank was
    still waiting on when it died."""
    from .. import tracing
    tr = tracing.active()
    if tr is None:
        return []
    try:
        return [{"rank": r, "name": n, "ts": ts}
                for r, n, ts in tr.open_spans()]
    except Exception:
        return []


def _build_doc(reason: str) -> dict:
    rec = _recorder
    doc = {
        "blackbox": BLACKBOX_VERSION,
        "rank": _rank,
        "world_size": _world,
        "reason": reason,
        "hostname": _socket.gethostname(),
        "pid": os.getpid(),
        "dumped_at": time.time(),
        "events": rec.event_dicts() if rec is not None else [],
        "dropped_events": rec.dropped if rec is not None else 0,
    }
    try:
        from ..metrics import local_snapshot
        doc["metrics"] = local_snapshot()
    except Exception:
        doc["metrics"] = {}
    doc["open_spans"] = _open_span_table()
    if _rank == 0 and _dead:
        doc["coordinator"] = {
            "dead_ranks": {str(r): {"at": t, "reason": why}
                           for r, (t, why) in sorted(_dead.items())}}
    return doc


def _write_doc(path: str, doc: dict) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


def dump(reason: str, force: bool = False):
    """Write this rank's postmortem dump. Idempotent per process (the
    first abnormal symptom wins; later ones are usually cascade). Never
    raises — this runs from excepthooks and signal handlers. Returns the
    written path, or None when the blackbox is off or already dumped."""
    global _dumped
    rec = _recorder
    if rec is None:
        return None
    with _lock:
        if _dumped and not force:
            return None
        _dumped = True
    try:
        doc = _build_doc(reason)
        path = os.path.join(blackbox_dir(), "rank_%d.json" % _rank)
        _write_doc(path, doc)
        try:
            from ..metrics import instruments
            instruments.blackbox_dumps().inc()
        except Exception:
            pass
        logger.warning("blackbox: rank %d dumped %d events to %s (%s)",
                       _rank, len(doc["events"]), path, reason)
        shipper = _shipper
        if shipper is not None and _rank != 0:
            try:
                shipper(json.dumps(doc))
            except Exception:
                pass
        if _rank == 0:
            _write_dead_stubs(reason)
            assemble(blackbox_dir(), reason=reason)
        return path
    except Exception as exc:  # must never take down the dying process
        logger.error("blackbox: dump failed: %s", exc)
        return None


def _write_dead_stubs(reason: str) -> None:
    """Rank 0 speaks for ranks that died without dumping: a stub carrying
    the coordinator's knowledge (declared-dead reason and when)."""
    for rank, (t, why) in sorted(_dead.items()):
        path = os.path.join(blackbox_dir(), "rank_%d.json" % rank)
        if os.path.exists(path):
            continue
        try:
            _write_doc(path, {
                "blackbox": BLACKBOX_VERSION, "rank": rank,
                "world_size": _world, "stub": True, "assembled_by": _rank,
                "reason": "no dump received; coordinator declared the rank "
                          "dead: %s" % why,
                "declared_dead_at": t, "dumped_at": time.time(),
                "events": [], "metrics": {}, "open_spans": [],
            })
        except Exception:
            pass


def store_dump(rank: int, doc_json: str) -> None:
    """Rank 0: persist a worker's dump arriving over ``MSG_BLACKBOX``.
    Re-assembles the bundle if rank 0 already dumped, so late worker
    dumps still make it into ``bundle.json``."""
    try:
        doc = json.loads(doc_json)
        rank = int(rank)
        path = os.path.join(blackbox_dir(), "rank_%d.json" % rank)
        _write_doc(path, doc)
        record(K_ERROR, "rank_%d" % rank,
               "received postmortem dump (%s)" % (doc.get("reason") or "?"),
               rank=rank)
        if _dumped:
            assemble(blackbox_dir())
        logger.warning("blackbox: stored rank %d dump at %s", rank, path)
    except Exception as exc:
        logger.debug("blackbox: dropping bad dump from rank %s: %s",
                     rank, exc)


def assemble(dir_path=None, reason=None):
    """Collect every ``rank_*.json`` in the dump directory into one
    ``bundle.json`` manifest. Safe to call repeatedly (late dumps) and
    from the driver for runs whose rank 0 itself died."""
    dir_path = dir_path or blackbox_dir()
    ranks = {}
    try:
        names = sorted(os.listdir(dir_path))
    except OSError:
        return None
    for name in names:
        if not (name.startswith("rank_") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(dir_path, name)) as f:
                doc = json.load(f)
            ranks[str(int(doc.get("rank", name[5:-5])))] = doc
        except (OSError, ValueError):
            continue
    if not ranks:
        return None
    bundle = {"blackbox_bundle": BLACKBOX_VERSION,
              "assembled_at": time.time(),
              "reason": reason, "ranks": ranks}
    path = os.path.join(dir_path, "bundle.json")
    try:
        _write_doc(path, bundle)
    except OSError as exc:
        logger.error("blackbox: bundle assembly failed: %s", exc)
        return None
    return path


# ------------------------------------------------- process-level triggers

def _on_unhandled(exc_type, exc, tb):
    try:
        record(K_ERROR, exc_type.__name__, str(exc))
        dump("unhandled exception: %s: %s" % (exc_type.__name__, exc))
    except Exception:
        pass
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def _on_signal(signum, frame):
    try:
        name = _signal.Signals(signum).name
    except ValueError:
        name = str(signum)
    try:
        record(K_SIGNAL, name, "process received %s" % name)
        dump("signal %s" % name)
    except Exception:
        pass
    prev = _prev_handlers.get(signum)
    if callable(prev):
        prev(signum, frame)
    else:
        # restore default disposition and re-deliver so the exit status
        # still says "killed by signal"
        _signal.signal(signum, _signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def _install_hooks() -> None:
    """sys.excepthook chain + SIGTERM/SIGABRT handlers. Signal handlers
    only install from the main thread (signal.signal raises elsewhere —
    in-process thread clusters simply skip them)."""
    global _hooks_installed, _prev_excepthook
    if _hooks_installed:
        return
    _hooks_installed = True
    _prev_excepthook = sys.excepthook
    sys.excepthook = _on_unhandled
    for signum in (_signal.SIGTERM, getattr(_signal, "SIGABRT", None)):
        if signum is None:
            continue
        try:
            _prev_handlers[signum] = _signal.signal(signum, _on_signal)
        except (ValueError, OSError, RuntimeError):
            pass


def _uninstall_hooks() -> None:
    global _hooks_installed, _prev_excepthook
    if not _hooks_installed:
        return
    _hooks_installed = False
    if sys.excepthook is _on_unhandled:
        sys.excepthook = _prev_excepthook or sys.__excepthook__
    _prev_excepthook = None
    for signum, prev in list(_prev_handlers.items()):
        try:
            if _signal.getsignal(signum) is _on_signal:
                _signal.signal(signum, prev)
        except (ValueError, OSError, RuntimeError, TypeError):
            pass
    _prev_handlers.clear()


# --------------------------------------------------------------- lifecycle

def finalize() -> None:
    """Normal-shutdown teardown (basics.shutdown): no dump — the black
    box only speaks on abnormal exit — just reset module state."""
    global _recorder, _dir, _dumped, _shipper, _rank, _world
    with _lock:
        _recorder = None
        _dir = None
        _dumped = False
        _shipper = None
        _rank = 0
        _world = 1
        _dead.clear()
    _uninstall_hooks()


def reset_for_tests() -> None:
    """Hard reset of all module state (unit tests only)."""
    finalize()
