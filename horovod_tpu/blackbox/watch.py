"""Live anomaly watch (``HOROVOD_ANOMALY_WATCH``).

A daemon thread on the aggregating process (rank 0) sampling the
already-merged ``hvd_*`` registry on a fixed cadence and holding a
:class:`~.signatures.RollingBaseline` per tracked signal:

* ``step_seconds`` — mean allreduce latency over the sample interval
* ``exposed_comm_seconds`` — blocked-in-synchronize time per interval
* ``straggler_skew_seconds`` — the arrival-skew gauge as-is
* ``wire_bytes_rate`` — collective payload bytes/second on the wire

When a window deviates past the configured factor the watch raises the
``hvd_anomaly_active{signal=...}`` gauge, logs a structured warning, and
records a flight-recorder event — the hook the autotuner and quantization
gating consume, and extra forensics if the job later dies. Knobs:
``HOROVOD_ANOMALY_INTERVAL`` (seconds, default 5), ``HOROVOD_ANOMALY_WINDOW``
(samples, default 12), ``HOROVOD_ANOMALY_FACTOR`` (default 3.0).
"""

from __future__ import annotations

import logging
import os
import threading

from ..utils.env import env_float as _env_float
from . import K_ANOMALY, record as _record
from .signatures import RollingBaseline, SEV_WARNING, make_signature

logger = logging.getLogger("horovod_tpu")

#: (signal name, noise floor) — floors keep idle jobs from alarming
SIGNALS = (
    ("step_seconds", 1e-3),
    ("exposed_comm_seconds", 1e-3),
    ("straggler_skew_seconds", 0.05),
    ("wire_bytes_rate", 1024.0),
    # serving mode (serving/engine.py gauges): per-interval request-latency
    # p99 out of histogram bucket deltas, plus the admission queue depth.
    # Only sampled when the serving families exist in the snapshot, so
    # training-only jobs keep clean baselines.
    ("serving_p99_seconds", 1e-3),
    ("serving_queue_depth", 1.0),
    # overload-shed rate (serving/server.py brownout/shed path): sheds per
    # second out of hvd_serving_shed_total deltas. Maps to the doctor's
    # serving_overload signature, not latency_regression — shedding is the
    # mitigation working, and the response is capacity, not profiling.
    ("serving_shed_rate", 0.5),
    # MoE capacity dispatch (parallel/expert.py gauges): sustained expert-
    # load imbalance is the router going degenerate — same live-signal
    # treatment as straggler skew. Only sampled when the MoE family
    # exists in the snapshot, so non-MoE jobs keep clean baselines.
    ("moe_load_imbalance", 1.0),
)

#: the checkpoint bundle-age signal is THRESHOLD-based, not baselined: a
#: rolling baseline would learn a steadily growing age as normal, which is
#: exactly the failure (bundles that stopped finalizing). It fires when
#: ``hvd_ckpt_bundle_age_steps`` exceeds this factor times
#: HOROVOD_CKPT_INTERVAL, presence-gated so jobs without checkpointing
#: never sample it.
CKPT_AGE_FACTOR = 2.0

_watch = None
_watch_lock = threading.Lock()


def _series_total(snapshot, name, field="value"):
    metric = snapshot.get(name)
    if not metric:
        return 0.0
    total = 0.0
    for series in metric.get("series") or []:
        total += float(series.get(field, 0.0) or 0.0)
    return total


def _hist_totals(snapshot, name):
    metric = snapshot.get(name)
    if not metric:
        return 0.0, 0.0
    s = c = 0.0
    for series in metric.get("series") or []:
        s += float(series.get("sum", 0.0) or 0.0)
        c += float(series.get("count", 0.0) or 0.0)
    return s, c


class AnomalyWatch:
    """Rolling-baseline watcher over aggregated snapshots.

    ``observe_snapshot`` is the whole algorithm and takes a plain
    snapshot dict, so tests drive it synchronously without the thread."""

    def __init__(self, interval=None, window=None, factor=None,
                 min_samples=None, slo_engine=None):
        self.interval = (interval if interval is not None
                         else _env_float("HOROVOD_ANOMALY_INTERVAL", 5.0))
        window = (int(window) if window is not None
                  else int(_env_float("HOROVOD_ANOMALY_WINDOW", 12)))
        factor = (factor if factor is not None
                  else _env_float("HOROVOD_ANOMALY_FACTOR", 3.0))
        min_samples = int(min_samples) if min_samples is not None else 4
        self._baselines = {
            name: RollingBaseline(window=window, factor=factor,
                                  min_samples=min_samples, floor=floor)
            for name, floor in SIGNALS}
        self._active = {name: False for name, _ in SIGNALS}
        self._ckpt_active = False
        if slo_engine is None:
            from ..goodput.slo import SLOEngine

            slo_engine = SLOEngine.from_env()
        self._slo = slo_engine
        self._prev = {}          # cumulative-counter memory between samples
        self._samples = 0
        self._signatures = []    # most recent detections (healthz surface)
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------- signals
    def _delta(self, key, current):
        prev = self._prev.get(key)
        self._prev[key] = current
        if prev is None or current < prev:  # first sample or registry reset
            return None
        return current - prev

    def extract(self, snapshot) -> dict:
        """Per-interval signal values out of one aggregated snapshot.
        Cumulative series become deltas; their first sample is skipped."""
        out = {}
        hsum, hcount = _hist_totals(snapshot, "hvd_allreduce_latency_seconds")
        dsum, dcount = self._delta("lat_sum", hsum), self._delta(
            "lat_count", hcount)
        if dsum is not None and dcount:
            out["step_seconds"] = dsum / dcount
        dexp = self._delta("exposed", _series_total(
            snapshot, "hvd_exposed_comm_seconds"))
        if dexp is not None:
            out["exposed_comm_seconds"] = dexp
        out["straggler_skew_seconds"] = _series_total(
            snapshot, "hvd_straggler_skew_seconds")
        dwire = self._delta("wire", _series_total(
            snapshot, "hvd_wire_bytes_total"))
        if dwire is not None:
            out["wire_bytes_rate"] = dwire / max(self.interval, 1e-6)
        p99 = self._serving_p99(snapshot)
        if p99 is not None:
            out["serving_p99_seconds"] = p99
        if "hvd_serving_queue_depth" in snapshot:
            out["serving_queue_depth"] = _series_total(
                snapshot, "hvd_serving_queue_depth")
        if "hvd_serving_shed_total" in snapshot:
            dshed = self._delta("shed", _series_total(
                snapshot, "hvd_serving_shed_total"))
            if dshed is not None:
                out["serving_shed_rate"] = dshed / max(self.interval, 1e-6)
        if "hvd_moe_load_imbalance" in snapshot:
            out["moe_load_imbalance"] = _series_total(
                snapshot, "hvd_moe_load_imbalance")
        return out

    def _serving_p99(self, snapshot):
        """This interval's request-latency p99: the bucket-count DELTAS of
        ``hvd_serving_request_latency_seconds{stage="total"}`` between
        samples (counts are per-bucket, last slot = +Inf overflow), read at
        the 99th percentile — so the signal tracks the latency of requests
        finished in this window, not the lifetime distribution."""
        metric = snapshot.get("hvd_serving_request_latency_seconds")
        if not metric:
            return None
        buckets = metric.get("buckets") or []
        counts = None
        for series in metric.get("series") or []:
            if (series.get("labels") or {}).get("stage") != "total":
                continue
            c = [float(x) for x in series.get("counts") or []]
            if counts is None:
                counts = c
            elif len(c) == len(counts):
                counts = [a + b for a, b in zip(counts, c)]
        if not counts:
            return None
        prev = self._prev.get("serving_lat_counts")
        self._prev["serving_lat_counts"] = counts
        if (prev is None or len(prev) != len(counts)
                or sum(counts) < sum(prev)):  # first sample / reset
            return None
        from ..metrics import quantile_from_buckets

        delta = [max(0.0, a - b) for a, b in zip(counts, prev)]
        return quantile_from_buckets(buckets, delta, 0.99)

    # ------------------------------------------------------------ decision
    def observe_snapshot(self, snapshot) -> list:
        """Feed one aggregated snapshot; returns this sample's new
        anomaly signatures (empty on a healthy sample)."""
        from ..metrics import instruments

        self._samples += 1
        fired = []
        for name, value in self.extract(snapshot).items():
            baseline = self._baselines[name]
            base = baseline.baseline()
            anomalous = baseline.observe(value)
            if anomalous and not self._active[name]:
                # serving signals map to the doctor's vocabulary: the shed
                # rate is overload (capacity story), the rest is latency
                # regression; everything else keeps the generic id
                if name == "serving_shed_rate":
                    sig_id = "serving_overload"
                elif name.startswith("serving_"):
                    sig_id = "latency_regression"
                else:
                    sig_id = "anomaly:%s" % name
                evidence = {"signal": name, "value": value,
                            "baseline": base}
                if name == "straggler_skew_seconds":
                    # a skew anomaly and a repeat-excluded rank are the
                    # same machine seen live vs postmortem; point the
                    # operator at the doctor signature that names it
                    evidence["related"] = "chronic_straggler"
                sig = make_signature(
                    sig_id, SEV_WARNING,
                    "anomaly: %s=%.6g deviates from rolling baseline %.6g "
                    "(factor %g over %d samples)"
                    % (name, value, base, baseline.factor, len(baseline)),
                    **evidence)
                fired.append(sig)
                logger.warning("anomaly watch: %s", sig["summary"])
                _record(K_ANOMALY, name, sig["summary"])
            if anomalous != self._active[name]:
                self._active[name] = anomalous
                instruments.anomaly_active().labels(signal=name).set(
                    1 if anomalous else 0)
        fired.extend(self._check_ckpt_age(snapshot))
        fired.extend(self._check_slo(snapshot))
        if fired:
            self._signatures = (self._signatures + fired)[-16:]
        return fired

    def _check_slo(self, snapshot) -> list:
        """Multi-window burn-rate evaluation of the declarative HOROVOD_SLO
        objectives (docs/goodput.md): the SLO engine turns each sample into
        per-objective bad-fractions; fire/clear edges become signatures and
        ``hvd_anomaly_active{signal="slo:<name>"}`` transitions here."""
        from ..metrics import instruments

        if self._slo is None:
            return []
        fired = []
        for ev in self._slo.observe(snapshot):
            signal = "slo:%s" % ev["slo"]
            if ev["event"] == "fire":
                sig = make_signature(
                    "slo_burn_rate", SEV_WARNING,
                    "SLO %s burning error budget %.1fx too fast "
                    "(slow window %.1fx, objective %s%s%g) — see "
                    "hvddoctor budget_exhausted for the dominant cause"
                    % (ev["slo"], ev["burn_fast"], ev["burn_slow"],
                       ev["slo"], ev.get("op", ""), ev["bound"]),
                    slo=ev["slo"], burn_fast=ev["burn_fast"],
                    burn_slow=ev["burn_slow"], bound=ev["bound"],
                    related="budget_exhausted")
                fired.append(sig)
                logger.warning("anomaly watch: %s", sig["summary"])
                _record(K_ANOMALY, signal, sig["summary"])
                instruments.anomaly_active().labels(signal=signal).set(1)
            else:
                logger.info("anomaly watch: SLO %s burn recovered "
                            "(%.2fx)", ev["slo"], ev["burn_fast"])
                _record(K_ANOMALY, signal,
                        "slo %s burn recovered" % ev["slo"])
                instruments.anomaly_active().labels(signal=signal).set(0)
        return fired

    def _check_ckpt_age(self, snapshot) -> list:
        """Threshold check on ``hvd_ckpt_bundle_age_steps`` (see
        CKPT_AGE_FACTOR above): fires once per episode when the age
        exceeds CKPT_AGE_FACTOR x HOROVOD_CKPT_INTERVAL, clears when a
        bundle finalizes and the gauge drops back."""
        from ..metrics import instruments

        if "hvd_ckpt_bundle_age_steps" not in snapshot:
            return []
        try:
            interval = max(1, int(os.environ.get("HOROVOD_CKPT_INTERVAL",
                                                 "10")))
        except ValueError:
            interval = 10
        age = _series_total(snapshot, "hvd_ckpt_bundle_age_steps")
        threshold = CKPT_AGE_FACTOR * interval
        anomalous = age > threshold
        fired = []
        if anomalous and not self._ckpt_active:
            sig = make_signature(
                "anomaly:ckpt_bundle_age_steps", SEV_WARNING,
                "anomaly: checkpoint bundle age %d steps exceeds %.0f "
                "(%gx HOROVOD_CKPT_INTERVAL=%d) — shards are landing but "
                "bundles never finalize; see hvddoctor stale_checkpoint "
                "for the lagging rank"
                % (age, threshold, CKPT_AGE_FACTOR, interval),
                signal="ckpt_bundle_age_steps", value=age,
                threshold=threshold, related="stale_checkpoint")
            fired.append(sig)
            logger.warning("anomaly watch: %s", sig["summary"])
            _record(K_ANOMALY, "ckpt_bundle_age_steps", sig["summary"])
        if anomalous != self._ckpt_active:
            self._ckpt_active = anomalous
            instruments.anomaly_active().labels(
                signal="ckpt_bundle_age_steps").set(1 if anomalous else 0)
        return fired

    def state(self) -> dict:
        """Healthz surface: which signals are currently anomalous."""
        doc = {"running": self._thread is not None
               and self._thread.is_alive(),
               "samples": self._samples,
               "active": {k: v for k, v in self._active.items() if v},
               "recent": [s["summary"] for s in self._signatures[-4:]]}
        if self._slo is not None:
            doc["slo"] = self._slo.state()
        return doc

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run,
                                        name="hvd-anomaly-watch", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        from ..metrics import aggregate, instruments

        for name, _ in SIGNALS:  # pre-touch so /metrics renders zeros
            instruments.anomaly_active().labels(signal=name).set(0)
        while not self._stop.wait(self.interval):
            try:
                self.observe_snapshot(aggregate())
            except Exception as exc:  # the watch must never kill the job
                logger.debug("anomaly watch: sample failed: %s", exc)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)


# -------------------------------------------------------- module lifecycle

def _enabled_env() -> bool:
    raw = os.environ.get("HOROVOD_ANOMALY_WATCH", "").strip()
    return raw not in ("", "0", "false", "False", "off")


def maybe_start_watch(force: bool = False):
    """Start the watch thread if ``HOROVOD_ANOMALY_WATCH`` is set (or
    ``force``). Idempotent; returns the watch or None. Called from
    ``hvd.init()`` on the aggregating process only — the signals it
    consumes exist merged on rank 0."""
    global _watch
    if not _enabled_env() and not force:
        return None
    with _watch_lock:
        if _watch is None:
            _watch = AnomalyWatch()
            _watch.start()
        return _watch


def stop_watch() -> None:
    global _watch
    with _watch_lock:
        w, _watch = _watch, None
    if w is not None:
        w.stop()


def watch_state():
    """The running watch's state dict, or None when the watch is off."""
    with _watch_lock:
        return None if _watch is None else _watch.state()
