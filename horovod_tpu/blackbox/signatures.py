"""Known-failure signature engine, shared postmortem and live.

Two consumers, one vocabulary:

* ``bin/hvddoctor`` runs the event-based detectors over a postmortem
  bundle (every rank's flight-recorder dump) and reports which known
  failure shapes match.
* The rank-0 anomaly watch (:mod:`.watch`) runs the metric-based
  :class:`RollingBaseline` live over the aggregated ``hvd_*`` registry
  and emits the same :func:`make_signature` records when a window
  deviates.

A signature is a plain dict — ``id``, ``severity``, ``summary`` and an
``evidence`` mapping — so both paths serialize identically and the
doctor's JSON output is stable for scripting.
"""

from __future__ import annotations

import re
from collections import deque
from typing import Dict, List, Optional

from . import recorder as rec

SEV_INFO = "info"
SEV_WARNING = "warning"
SEV_CRITICAL = "critical"

_SEV_ORDER = {SEV_CRITICAL: 0, SEV_WARNING: 1, SEV_INFO: 2}

#: threshold (seconds) past which the final straggler-skew gauge alone is
#: considered diagnostic, even without coordinator stall events
STRAGGLER_SKEW_S = 1.0
#: reconnects by one rank that constitute a storm
RECONNECT_STORM_COUNT = 3
#: sub-coordinator upstream reconnects at ONE tier that constitute a flap
TIER_FLAP_COUNT = 3
#: ok->miss heartbeat transitions that constitute a flap
HEARTBEAT_FLAP_TRANSITIONS = 2
#: bitwidth decision changes for ONE bucket that constitute thrash
BITWIDTH_THRASH_FLIPS = 4
#: collective-algorithm changes for ONE payload-size class that constitute
#: thrash (the zoo recompiles the step program on every switch)
ALGO_THRASH_FLIPS = 4
#: exclusion episodes for one rank past which it is chronic, not noise
CHRONIC_STRAGGLER_EPISODES = 3
#: final fast-window SLO burn rate past which the error budget is being
#: spent too fast to last the horizon (matches slo.FAST_BURN_THRESHOLD)
SLO_BURN_EXHAUSTED = 2.0


def make_signature(sig_id: str, severity: str, summary: str,
                   **evidence) -> dict:
    return {"id": sig_id, "severity": severity, "summary": summary,
            "evidence": evidence}


def sort_signatures(sigs: List[dict]) -> List[dict]:
    return sorted(sigs, key=lambda s: (_SEV_ORDER.get(s["severity"], 9),
                                       s["id"]))


# ------------------------------------------------------------------ parsing

def parse_ranks(text: str) -> List[int]:
    """Rank list out of a coordinator/integrity detail string: matches the
    ``ranks [1, 2]`` / ``rank(s) ['0']`` phrasings those sites emit."""
    m = re.search(r"ranks?(?:\(s\))? \[([^\]]*)\]", text)
    if not m:
        return []
    return [int(n) for n in re.findall(r"\d+", m.group(1))]


def parse_step(text: str) -> Optional[int]:
    m = re.search(r"\(step (\d+)\)", text)
    return int(m.group(1)) if m else None


def _iter_events(bundle: Dict[int, dict]):
    for rank in sorted(bundle):
        for ev in bundle[rank].get("events") or []:
            yield rank, ev


def _metric_value(doc: dict, name: str) -> float:
    """Sum of a metric's series values in one dump's final snapshot."""
    metric = (doc.get("metrics") or {}).get(name)
    if not metric:
        return 0.0
    total = 0.0
    for series in metric.get("series") or []:
        total += float(series.get("value", series.get("sum", 0.0)) or 0.0)
    return total


# ---------------------------------------------------------------- detectors

def detect_collective_deadlock(bundle) -> List[dict]:
    """Enforced-watchdog timeouts, or stall warnings that never resolved:
    name the tensor and the ranks it was waiting on."""
    sigs = []
    seen = set()
    stalls: Dict[str, dict] = {}
    for src, ev in _iter_events(bundle):
        if ev.get("kind") == rec.K_TIMEOUT:
            tensor = ev.get("name") or "?"
            missing = parse_ranks(ev.get("detail") or "")
            key = (tensor, tuple(missing))
            if key in seen:
                continue
            seen.add(key)
            sigs.append(make_signature(
                "collective_deadlock", SEV_CRITICAL,
                "collective deadlock: tensor %r timed out waiting on "
                "rank(s) %s" % (tensor, missing or "?"),
                tensor=tensor, missing_ranks=missing, reported_by=src,
                detail=ev.get("detail") or ""))
        elif ev.get("kind") == rec.K_STALL:
            tensor = ev.get("name") or "?"
            stalls[tensor] = {"missing": parse_ranks(ev.get("detail") or ""),
                              "detail": ev.get("detail") or "", "src": src,
                              "count": stalls.get(tensor, {}).get(
                                  "count", 0) + 1}
    if not sigs:
        for tensor, info in stalls.items():
            sigs.append(make_signature(
                "collective_deadlock", SEV_CRITICAL,
                "collective deadlock: tensor %r stalled waiting on "
                "rank(s) %s (never resolved)" % (tensor,
                                                 info["missing"] or "?"),
                tensor=tensor, missing_ranks=info["missing"],
                reported_by=info["src"], stall_warnings=info["count"],
                detail=info["detail"]))
    return sigs


def detect_straggler(bundle) -> List[dict]:
    """A single rank repeatedly the one everybody waits on, or a final
    arrival-skew gauge big enough to explain the slowdown on its own."""
    waited_on: Dict[int, int] = {}
    for _, ev in _iter_events(bundle):
        if ev.get("kind") in (rec.K_STALL, rec.K_TIMEOUT):
            for r in parse_ranks(ev.get("detail") or ""):
                waited_on[r] = waited_on.get(r, 0) + 1
    skew = max((_metric_value(doc, "hvd_straggler_skew_seconds")
                for doc in bundle.values()), default=0.0)
    sigs = []
    repeat = [(n, r) for r, n in waited_on.items() if n >= 2]
    if repeat:
        n, r = max(repeat)
        sigs.append(make_signature(
            "straggler", SEV_WARNING,
            "straggler: rank %d was the missing rank in %d stall/timeout "
            "events (final arrival skew %.3fs)" % (r, n, skew),
            rank=r, events=n, skew_seconds=skew))
    elif skew >= STRAGGLER_SKEW_S:
        sigs.append(make_signature(
            "straggler", SEV_WARNING,
            "straggler: final enqueue-time skew %.3fs between fastest and "
            "slowest rank" % skew, skew_seconds=skew))
    return sigs


def detect_param_desync(bundle) -> List[dict]:
    """Consistency-auditor divergence: report the earliest origin step."""
    first = None
    for src, ev in _iter_events(bundle):
        if (ev.get("kind") == rec.K_VERDICT
                and "parameter desync" in (ev.get("detail") or "")):
            step = parse_step(ev["detail"])
            if first is None or (step or 0) < (first[0] or 1 << 60):
                first = (step, src, ev)
    if first is None:
        return []
    step, src, ev = first
    offenders = parse_ranks(ev.get("detail") or "")
    return [make_signature(
        "param_desync", SEV_CRITICAL,
        "parameter desync first detected at step %s on rank(s) %s"
        % (step if step is not None else "?", offenders or "?"),
        origin_step=step, ranks=offenders, reported_by=src,
        detail=ev.get("detail") or "")]


def detect_nan_first(bundle) -> List[dict]:
    """Non-finite gradients: the earliest event across ranks names the
    rank where NaN/Inf entered the job."""
    first = None
    for src, ev in _iter_events(bundle):
        if (ev.get("kind") == rec.K_VERDICT
                and "non-finite" in (ev.get("detail") or "")):
            if first is None or float(ev.get("t") or 0) < float(
                    first[1].get("t") or 0):
                first = (src, ev)
    if first is None:
        return []
    src, ev = first
    offenders = parse_ranks(ev.get("detail") or "")
    origin = offenders[0] if offenders else src
    return [make_signature(
        "nan_first", SEV_CRITICAL,
        "non-finite gradients entered first on rank %s (step %s)"
        % (origin, parse_step(ev.get("detail") or "") or "?"),
        rank=origin, ranks=offenders, reported_by=src,
        detail=ev.get("detail") or "")]


def detect_reconnect_storm(bundle) -> List[dict]:
    counts: Dict[int, int] = {}
    for _, ev in _iter_events(bundle):
        if ev.get("kind") == rec.K_RECONNECT:
            r = int(ev.get("rank") or 0)
            counts[r] = counts.get(r, 0) + 1
    sigs = []
    for r, n in sorted(counts.items()):
        if n >= RECONNECT_STORM_COUNT:
            sigs.append(make_signature(
                "reconnect_storm", SEV_WARNING,
                "reconnect storm: rank %d reconnected its control-plane "
                "connection %d times" % (r, n), rank=r, reconnects=n))
    return sigs


def detect_tier_aggregator_flap(bundle) -> List[dict]:
    """Repeated sub-coordinator upstream reconnects concentrated at one
    aggregation tier (events named ``tier_N``): the tier's parent slot is
    unstable — a flapping mid-tier aggregator, a half-dead standby, or a
    network partition along that tier's links — distinct from one rank's
    reconnect storm (docs/control-plane.md)."""
    per_tier: Dict[int, int] = {}
    for _, ev in _iter_events(bundle):
        if ev.get("kind") != rec.K_RECONNECT:
            continue
        name = str(ev.get("name") or "")
        if not name.startswith("tier_"):
            continue
        try:
            tier = int(name[5:])
        except ValueError:
            continue
        per_tier[tier] = per_tier.get(tier, 0) + 1
    sigs = []
    for tier, n in sorted(per_tier.items()):
        if n >= TIER_FLAP_COUNT:
            sigs.append(make_signature(
                "tier_aggregator_flap", SEV_WARNING,
                "tier aggregator flap: sub-coordinators at tier %d "
                "reconnected upstream %d times — the tier-%d parent slot "
                "is unstable" % (tier, n, tier + 1),
                tier=tier, reconnects=n))
    return sigs


def detect_heartbeat_flap(bundle) -> List[dict]:
    """A rank repeatedly missing heartbeats and recovering — a flapping
    network or an overloaded host, not a clean death."""
    streams: Dict[int, List[str]] = {}
    for _, ev in _iter_events(bundle):
        if ev.get("kind") != rec.K_HEARTBEAT:
            continue
        subject = int(ev.get("rank") or 0)
        state = "miss" if "miss" in (ev.get("detail") or "") else "ok"
        streams.setdefault(subject, []).append(state)
    sigs = []
    for r, states in sorted(streams.items()):
        transitions = sum(1 for a, b in zip(states, states[1:])
                          if a == "ok" and b == "miss")
        if states and states[0] == "miss":
            transitions += 1
        if transitions >= HEARTBEAT_FLAP_TRANSITIONS:
            sigs.append(make_signature(
                "heartbeat_flap", SEV_WARNING,
                "heartbeat flap: rank %d went silent %d separate times"
                % (r, transitions), rank=r, flaps=transitions))
    return sigs


def detect_dead_worker(bundle) -> List[dict]:
    sigs = []
    seen = set()
    for src, ev in _iter_events(bundle):
        if ev.get("kind") == rec.K_RANK_LOST:
            r = int(ev.get("rank") or 0)
            if r in seen:
                continue
            seen.add(r)
            sigs.append(make_signature(
                "dead_worker", SEV_CRITICAL,
                "worker lost: rank %d (%s)" % (r, ev.get("detail") or
                                               "no reason recorded"),
                rank=r, reason=ev.get("detail") or "", reported_by=src))
    return sigs


def detect_coordinator_failover(bundle) -> List[dict]:
    """A K_FAILOVER event means the warm standby promoted itself (or a
    worker redialed the promoted standby) after rank 0's coordinator died
    (HOROVOD_STANDBY_COORD, docs/control-plane.md)."""
    sigs = []
    for src, ev in _iter_events(bundle):
        if ev.get("kind") != rec.K_FAILOVER:
            continue
        detail = ev.get("detail") or ""
        if "promoted" not in detail and "standby" not in detail:
            continue
        if "serving" in detail:
            continue  # the serving plane's failover has its own signature
        sigs.append(make_signature(
            "coordinator_failover", SEV_WARNING,
            "coordinator failover: %s" % (detail or "standby promoted"),
            rank=int(ev.get("rank") or 0), reported_by=src))
        break  # one promotion event is the story; redials are echoes
    return sigs


def detect_serving_failover(bundle) -> List[dict]:
    """The serving frontend died and its warm standby promoted itself
    (serving/standby.py, docs/inference.md failure matrix): one
    K_FAILOVER event with a ``serving standby promoted`` detail. The
    request ledger survives by replication, so this is a WARNING — loss
    or duplication would surface as jepsen violations, not here."""
    sigs = []
    for src, ev in _iter_events(bundle):
        if ev.get("kind") != rec.K_FAILOVER:
            continue
        detail = ev.get("detail") or ""
        if "serving" not in detail or "promoted" not in detail:
            continue
        sigs.append(make_signature(
            "serving_failover", SEV_WARNING,
            "serving frontend failover: %s" % detail,
            rank=int(ev.get("rank") or 0), reported_by=src))
        break  # one promotion is the story
    return sigs


_SHED_RE = re.compile(r"class=(\S+)")
_RESOURCE_RE = re.compile(r"resource=(\S+)")


def detect_serving_overload(bundle) -> List[dict]:
    """The serving plane shed load or saturated (docs/inference.md):
    the frontend records K_ANOMALY ``serving_shed`` events naming the
    shedding class (``brownout`` = best-effort generations clamped,
    ``best_effort`` = hard sheds) and workers record
    ``serving_saturation`` naming the scarce resource (``queue`` vs
    ``kv_blocks`` vs ``decode_slots``). One signature summarizing both:
    what was shed, and which resource actually ran out."""
    classes: List[str] = []
    resources: List[str] = []
    first_detail = ""
    reported_by = None
    for src, ev in _iter_events(bundle):
        if ev.get("kind") != rec.K_ANOMALY:
            continue
        name = ev.get("name") or ""
        if name not in ("serving_shed", "serving_saturation",
                        "serving_shed_rate"):
            continue
        detail = ev.get("detail") or ""
        if not first_detail:
            first_detail = detail
            reported_by = src
        m = _SHED_RE.search(detail)
        if m and m.group(1) not in classes:
            classes.append(m.group(1))
        m = _RESOURCE_RE.search(detail)
        if m and m.group(1) not in resources:
            resources.append(m.group(1))
    if not first_detail:
        return []
    # hard sheds outrank brownout in the headline; saturation evidence
    # from workers names the scarce resource even when the frontend only
    # browned out
    klass = ("best_effort" if "best_effort" in classes
             else (classes[0] if classes else "none"))
    # a worker naming the scarce resource (kv_blocks / decode_slots)
    # beats the frontend's generic queue evidence
    specific = [r for r in resources if r != "queue"]
    resource = specific[0] if specific else (
        resources[0] if resources else "queue")
    return [make_signature(
        "serving_overload", SEV_WARNING,
        "serving overload: shedding class=%s, saturated resource=%s "
        "(first: %s)" % (klass, resource, first_detail),
        shed_classes=classes, resources=resources,
        reported_by=reported_by)]


def detect_split_brain(bundle) -> List[dict]:
    """Fenced-leadership safety violation: the jepsen-lite history checker
    (faultinject/jepsen.py) found two coordinators whose attested
    leadership intervals — reconstructed from K_FENCE lease events —
    overlap in time, an epoch with two holders, or an epoch regression.
    By design this must NEVER fire: the lease CAS plus self-fencing
    guarantees a single writer per instant, so any match is a bug in the
    fencing machinery itself, not an operational hiccup."""
    from ..faultinject import jepsen  # lazy: keeps import order acyclic

    verdict = jepsen.check_history(bundle)
    if verdict["single_writer"]:
        return []
    return [make_signature(
        "split_brain", SEV_CRITICAL,
        "split-brain leadership: %s" % "; ".join(verdict["violations"]),
        violations=verdict["violations"],
        intervals=verdict["intervals"],
        fenced_frames=verdict["fenced_frames"])]


def detect_bitwidth_thrash(bundle) -> List[dict]:
    """An adaptive-wire bucket whose bitwidth selector keeps flipping
    (many K_BITWIDTH decision changes for one bucket name) is thrashing:
    its gradient statistics sit on a decision boundary, and every flip
    recompiles the bucket's wire program. Raise HOROVOD_ADAPTIVE_TOL or
    HOROVOD_ADAPTIVE_INTERVAL, or pin the mode with
    HOROVOD_COMPRESSION=int8."""
    flips: Dict[str, int] = {}
    last: Dict[str, str] = {}
    for src, ev in _iter_events(bundle):
        if ev.get("kind") != rec.K_BITWIDTH:
            continue
        name = ev.get("name") or "?"
        detail = ev.get("detail") or ""
        # count real flips only once per rank-interleaved stream: every
        # rank records the same decision sequence, so dedupe on transition
        if detail == last.get(name):
            continue
        last[name] = detail
        flips[name] = flips.get(name, 0) + 1
    sigs = []
    for name, n in sorted(flips.items()):
        if n >= BITWIDTH_THRASH_FLIPS:
            sigs.append(make_signature(
                "bitwidth_thrash", SEV_WARNING,
                "adaptive wire thrashing: bucket '%s' changed bitwidth "
                "%d times (raise HOROVOD_ADAPTIVE_TOL / "
                "HOROVOD_ADAPTIVE_INTERVAL or pin HOROVOD_COMPRESSION)"
                % (name, n),
                bucket=name, flips=n))
    return sigs


def detect_algorithm_thrash(bundle) -> List[dict]:
    """A payload-size class whose collective algorithm keeps flipping
    (many K_ALGO decision changes for one class) is thrashing: its payload
    profile sits on a zoo decision boundary, and every flip retraces and
    recompiles the step program. Pin the schedule with
    HOROVOD_GSPMD_ALGO=ring|tree|hier, or let the joint tuner settle
    (HOROVOD_AUTOTUNE_ALGO) instead of flipping by hand."""
    flips: Dict[str, int] = {}
    last: Dict[str, str] = {}
    for src, ev in _iter_events(bundle):
        if ev.get("kind") != rec.K_ALGO:
            continue
        name = ev.get("name") or "?"
        detail = ev.get("detail") or ""
        # settle events are terminal decisions, not flips
        if detail.startswith("settled"):
            continue
        # dedupe rank-interleaved streams on transition, as bitwidth does
        if detail == last.get(name):
            continue
        last[name] = detail
        flips[name] = flips.get(name, 0) + 1
    sigs = []
    for name, n in sorted(flips.items()):
        if n >= ALGO_THRASH_FLIPS:
            sigs.append(make_signature(
                "algorithm_thrash", SEV_WARNING,
                "collective algorithm thrashing: size class '%s' changed "
                "algorithm %d times (pin HOROVOD_GSPMD_ALGO or let the "
                "joint tuner settle)" % (name, n),
                size_class=name, flips=n))
    return sigs


def detect_chronic_straggler(bundle) -> List[dict]:
    """A rank the straggler policy (runtime/straggler.py) excluded over
    and over. Each exclusion records a K_EXCLUDED event carrying a
    cumulative ``episode=N`` counter and the rank's host, so a rank whose
    episodes reach CHRONIC_STRAGGLER_EPISODES — or that was escalated to
    rank_lost outright — points at the MACHINE, not the step: name the
    host so the operator can drain or replace it."""
    episodes: Dict[int, int] = {}
    hosts: Dict[int, str] = {}
    escalated: Dict[int, str] = {}
    for src, ev in _iter_events(bundle):
        if ev.get("kind") != rec.K_EXCLUDED:
            continue
        m = re.match(r"rank_(\d+)$", ev.get("name") or "")
        if not m:
            continue
        r = int(m.group(1))
        detail = ev.get("detail") or ""
        hm = re.search(r"host=(\S+)", detail)
        if hm and hm.group(1) not in ("", "?"):
            hosts[r] = hm.group(1)
        em = re.search(r"episode=(\d+)", detail)
        if detail.startswith("excluded") and "self" not in detail:
            # the episode counter is cumulative per policy lifetime, so
            # its max IS the count — robust to rank-interleaved streams
            # that replay the same episode from several recorders
            n = int(em.group(1)) if em else episodes.get(r, 0) + 1
            episodes[r] = max(episodes.get(r, 0), n)
        elif detail.startswith("escalated"):
            escalated[r] = detail
    sigs = []
    for r in sorted(set(episodes) | set(escalated)):
        n = episodes.get(r, 0)
        if r not in escalated and n < CHRONIC_STRAGGLER_EPISODES:
            continue
        host = hosts.get(r, "?")
        tail = (" and was escalated to rank_lost" if r in escalated else "")
        sigs.append(make_signature(
            "chronic_straggler",
            SEV_CRITICAL if r in escalated else SEV_WARNING,
            "chronic straggler: rank %d (host %s) was excluded from "
            "%d collective round group(s)%s — suspect the machine, "
            "not the workload" % (r, host, n, tail),
            rank=r, host=host, episodes=n, escalated=r in escalated))
    return sigs


def detect_latency_regression(bundle) -> List[dict]:
    """Serving-mode latency regression: the live anomaly watch flagged a
    serving signal (request-latency p99 or admission queue depth) deviating
    from its rolling baseline and recorded the K_ANOMALY event this
    detector resurfaces postmortem (serving/engine.py gauges,
    docs/inference.md). One signature per signal: the first firing is the
    story, later ones are the same regression still burning."""
    sigs = []
    seen = set()
    for src, ev in _iter_events(bundle):
        if ev.get("kind") != rec.K_ANOMALY:
            continue
        name = ev.get("name") or ""
        if not name.startswith("serving_") or name in seen:
            continue
        if name in ("serving_shed", "serving_saturation",
                    "serving_shed_rate"):
            continue  # overload evidence — detect_serving_overload's story
        seen.add(name)
        sigs.append(make_signature(
            "latency_regression", SEV_WARNING,
            "serving latency regression: %s" % (ev.get("detail") or name),
            signal=name, reported_by=src))
    return sigs


def detect_stale_checkpoint(bundle) -> List[dict]:
    """Checkpoint bundles that stopped finalizing, and the member holding
    them back. Every shard write records a K_CKPT ``snapshot`` event with
    ``step=N ... index=I``; rank 0 records ``finalize`` when a manifest
    lands (ckpt/manager.py). Shards advancing past the last finalized
    bundle with one member's snapshot head trailing the rest means that
    rank's writer is wedged or starved — name it, since the bundle can
    only finalize when EVERY member's shard of the same step lands. A
    ``restore`` whose detail shows ``journal_head > step`` is the same
    disease seen from the recovery side: the replacement restored an old
    disk bundle while a peer held fresher state it could not reach."""
    heads: Dict[int, int] = {}        # reporting rank -> latest snap step
    last_final = -1
    stale_restores = []
    for src, ev in _iter_events(bundle):
        if ev.get("kind") != rec.K_CKPT:
            continue
        name = ev.get("name") or ""
        detail = ev.get("detail") or ""
        sm = re.search(r"step=(-?\d+)", detail)
        step = int(sm.group(1)) if sm else -1
        if name == "snapshot":
            r = ev.get("rank", src)
            heads[r] = max(heads.get(r, -1), step)
        elif name == "finalize":
            last_final = max(last_final, step)
        elif name in ("restore", "peer_restore"):
            jm = re.search(r"journal_head=(-?\d+)", detail)
            jhead = int(jm.group(1)) if jm else -1
            if jhead > step >= 0:
                stale_restores.append((ev.get("rank", src), step, jhead))
    sigs = []
    if len(heads) >= 2:
        lead = max(heads.values())
        lagger = min(heads, key=lambda r: heads[r])
        lag = lead - heads[lagger]
        if lead > last_final and lag >= 2:
            sigs.append(make_signature(
                "stale_checkpoint", SEV_WARNING,
                "checkpoint bundles are not finalizing: shards reached "
                "step %d but the last complete bundle is step %d — rank "
                "%d's snapshots stop at step %d, holding every newer "
                "bundle open (wedged writer thread or starved disk on "
                "that rank)" % (lead, last_final, lagger, heads[lagger]),
                rank=lagger, head=heads[lagger], lead=lead,
                last_finalized=last_final))
    for r, step, jhead in stale_restores:
        sigs.append(make_signature(
            "stale_checkpoint", SEV_WARNING,
            "stale checkpoint restore: rank %d restored step %d from the "
            "disk bundle while a buddy journal already held step %d — "
            "the peer restore path was unreachable, so the resumed "
            "trajectory lost %d committed step(s)"
            % (r, step, jhead, jhead - step),
            rank=r, restored_step=step, journal_head=jhead))
    return sigs


def detect_budget_exhausted(bundle) -> List[dict]:
    """SLO error budget burning at an unsustainable rate at dump time:
    read the final ``hvd_slo_burn_rate{slo}`` gauges, and when one is at
    or past the fire threshold, NAME the dominant badput cause (the
    largest ``hvd_badput_seconds_total{cause}`` bucket, idle excluded
    unless it is all there is) and the ranks driving it — the doctor's
    answer to "the SLO alert fired, now what do I fix?"."""
    burns = {}     # slo -> max burn across ranks' dumps
    by_cause = {}  # cause -> total seconds
    by_rank = {}   # (cause, rank) -> seconds
    for doc in bundle.values():
        metrics = doc.get("metrics") or {}
        for series in (metrics.get("hvd_slo_burn_rate") or {}).get(
                "series") or []:
            slo = (series.get("labels") or {}).get("slo", "?")
            v = float(series.get("value", 0.0) or 0.0)
            burns[slo] = max(burns.get(slo, 0.0), v)
        for series in (metrics.get("hvd_badput_seconds_total") or {}).get(
                "series") or []:
            labels = series.get("labels") or {}
            cause = labels.get("cause", "?")
            v = float(series.get("value", 0.0) or 0.0)
            by_cause[cause] = by_cause.get(cause, 0.0) + v
            key = (cause, labels.get("rank", "?"))
            by_rank[key] = by_rank.get(key, 0.0) + v
    hot = {s: b for s, b in burns.items() if b >= SLO_BURN_EXHAUSTED}
    if not hot:
        return []
    named = {c: v for c, v in by_cause.items()
             if c != "idle" and v > 0} or by_cause
    sigs = []
    for slo in sorted(hot):
        burn = hot[slo]
        if named:
            cause = max(named, key=named.get)
            ranks = sorted(
                (r for (c, r) in by_rank if c == cause),
                key=lambda r: -by_rank[(cause, r)])[:4]
            detail = (", dominated by %s (%.1fs, rank(s) %s)"
                      % (cause, named[cause], ranks))
        else:
            cause, ranks, detail = None, [], ""
        sigs.append(make_signature(
            "budget_exhausted", SEV_WARNING,
            "SLO %s error budget burning %.1fx faster than sustainable "
            "at dump time%s" % (slo, burn, detail),
            slo=slo, burn_rate=burn, dominant_cause=cause,
            driving_ranks=ranks,
            badput_seconds={c: round(v, 3) for c, v in by_cause.items()}))
    return sigs


#: every event-based detector the doctor runs, in reporting order
DETECTORS = (
    detect_collective_deadlock,
    detect_param_desync,
    detect_nan_first,
    detect_dead_worker,
    detect_coordinator_failover,
    detect_serving_failover,
    detect_serving_overload,
    detect_split_brain,
    detect_straggler,
    detect_chronic_straggler,
    detect_latency_regression,
    detect_reconnect_storm,
    detect_tier_aggregator_flap,
    detect_heartbeat_flap,
    detect_bitwidth_thrash,
    detect_algorithm_thrash,
    detect_stale_checkpoint,
    detect_budget_exhausted,
)


def match_signatures(bundle: Dict[int, dict]) -> List[dict]:
    sigs: List[dict] = []
    for detect in DETECTORS:
        sigs.extend(detect(bundle))
    return sort_signatures(sigs)


# ----------------------------------------------------- cross-rank analysis

#: kinds every rank emits — the only sound basis for divergence analysis
#: (coordinator-side kinds exist on rank 0 alone by construction)
_DIVERGENCE_KINDS = (rec.K_COLLECTIVE, rec.K_VERDICT)


def first_divergence(bundle: Dict[int, dict]) -> Optional[dict]:
    """Earliest (kind, name) that appears in some ranks' streams but not
    all of them — where one rank's recent history stops matching its
    peers (e.g. the tensor a hung rank never enqueued)."""
    ranks = sorted(bundle)
    if len(ranks) < 2:
        return None
    keysets = {}
    first_seen = {}
    for r in ranks:
        keys = set()
        for ev in bundle[r].get("events") or []:
            if ev.get("kind") not in _DIVERGENCE_KINDS:
                continue
            key = (ev["kind"], ev.get("name") or "")
            keys.add(key)
            t = float(ev.get("t") or 0)
            if key not in first_seen or t < first_seen[key]:
                first_seen[key] = t
        keysets[r] = keys
    divergent = []
    for key, t in first_seen.items():
        present = [r for r in ranks if key in keysets[r]]
        if len(present) != len(ranks):
            divergent.append((t, key, present))
    if not divergent:
        return None
    t, (kind, name), present = min(divergent)
    return {"t": t, "kind": kind, "name": name, "present_ranks": present,
            "absent_ranks": [r for r in ranks if r not in present]}


def merged_timeline(bundle: Dict[int, dict], window_s: float = 30.0,
                    limit: int = 200) -> List[dict]:
    """All ranks' events interleaved by wall time, clipped to the final
    ``window_s`` seconds before the last recorded event."""
    events = []
    for src, ev in _iter_events(bundle):
        d = dict(ev)
        d.setdefault("rank", src)
        events.append(d)
    if not events:
        return []
    events.sort(key=lambda e: float(e.get("t") or 0))
    t_end = float(events[-1].get("t") or 0)
    clipped = [e for e in events if float(e.get("t") or 0) >= t_end - window_s]
    return clipped[-limit:]


# ------------------------------------------------------------- live metrics

class RollingBaseline:
    """Rolling-median baseline for one live metric signal.

    ``observe(value)`` returns True when the window holds enough history
    and the new value exceeds ``factor`` times the baseline median (with
    a per-signal noise floor so idle jobs never alarm)."""

    def __init__(self, window: int = 12, factor: float = 3.0,
                 min_samples: int = 4, floor: float = 1e-3):
        self.window = max(2, int(window))
        self.factor = float(factor)
        self.min_samples = max(2, int(min_samples))
        self.floor = float(floor)
        self._values = deque(maxlen=self.window)

    def baseline(self) -> Optional[float]:
        if len(self._values) < self.min_samples:
            return None
        ordered = sorted(self._values)
        mid = len(ordered) // 2
        if len(ordered) % 2:
            return ordered[mid]
        return (ordered[mid - 1] + ordered[mid]) / 2.0

    def observe(self, value: float) -> bool:
        base = self.baseline()
        anomalous = (base is not None
                     and value > self.factor * max(base, self.floor))
        self._values.append(float(value))
        return anomalous

    def __len__(self):
        return len(self._values)
