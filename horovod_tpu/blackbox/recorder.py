"""Flight-recorder event records and the bounded per-process event ring.

An event is one row of the black box: a wall-clock timestamp, the rank it
happened on, a short ``kind`` tag (one of the ``K_*`` constants), a
``name`` (tensor / peer / signal, kind-dependent) and a free-form
``detail`` string. Events land in a ring capped by
``HOROVOD_BLACKBOX_EVENTS``; overflow drops the oldest event — the whole
point of a flight recorder is the *recent* past, so the ring never grows
without bound and never blocks the paths it instruments.

The module mirrors the tracing discipline exactly: with
``HOROVOD_BLACKBOX`` unset nothing here is ever constructed, and the
``_allocations`` counter lets tests assert the engine's hot path
allocates zero blackbox objects in that state.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

# Event kinds. Strings, not ints: dumps are JSON for humans and hvddoctor
# both, and the ring is small enough that tag size is irrelevant.
K_FRAME_TX = "frame_tx"        # control-plane frame sent
K_FRAME_RX = "frame_rx"        # control-plane frame received
K_COLLECTIVE = "collective"    # collective lifecycle transition
K_STALL = "stall"              # coordinator stall warning for a tensor
K_TIMEOUT = "timeout"          # enforced collective watchdog fired
K_VERDICT = "verdict"          # GradGuard / ConsistencyAuditor verdict
K_HEARTBEAT = "heartbeat"      # heartbeat state change (miss / recovery)
K_METRICS = "metrics"          # periodic metric-registry delta
K_EPOCH = "epoch"              # elastic membership epoch change
K_RANK_LOST = "rank_lost"      # coordinator declared a worker lost/dead
K_RECONNECT = "reconnect"      # worker control-plane reconnect
K_FAULT = "fault"              # fault-injection rule fired
K_ERROR = "error"              # exception / abnormal condition
K_SIGNAL = "signal"            # process signal received
K_ANOMALY = "anomaly"          # live anomaly-watch detection
K_FAILOVER = "failover"        # coordinator failover (standby promotion or
                               # a worker redialing the promoted standby)
K_BITWIDTH = "bitwidth"        # adaptive-wire bitwidth decision change
K_ALGO = "algorithm"           # collective-algorithm decision change or
                               # joint-tuner settle (name = size class)
K_EXCLUDED = "excluded"        # straggler policy excluded/readmitted/
                               # escalated a rank (detail names the host)
K_CKPT = "checkpoint"          # checkpoint lifecycle: shard snapshot
                               # landed, bundle finalized, peer restore
K_FENCE = "fence"              # fenced-leadership event: lease acquired /
                               # renewed, a coordinator self-fenced, or a
                               # stale-epoch frame was rejected

DEFAULT_EVENTS = 4096

# Tracks every event-record allocation so the no-op fast path can be
# asserted: with the blackbox disabled this must not move.
_allocations = 0


def allocation_count() -> int:
    return _allocations


class Event:
    __slots__ = ("t", "rank", "kind", "name", "detail")

    def __init__(self, t, rank, kind, name="", detail=""):
        self.t = t
        self.rank = rank
        self.kind = kind
        self.name = name
        self.detail = detail

    def as_dict(self) -> dict:
        return {"t": self.t, "rank": self.rank, "kind": self.kind,
                "name": self.name, "detail": self.detail}

    def __repr__(self):
        return ("Event(t=%r, rank=%r, kind=%r, name=%r, detail=%r)"
                % (self.t, self.rank, self.kind, self.name, self.detail))


def ring_capacity() -> int:
    try:
        cap = int(os.environ.get("HOROVOD_BLACKBOX_EVENTS", DEFAULT_EVENTS))
    except ValueError:
        cap = DEFAULT_EVENTS
    return max(1, cap)


class FlightRecorder:
    """Per-process bounded ring of recent structured events.

    Thread-safe; every controller/engine/coordinator thread funnels
    through the one process-wide instance installed by
    :mod:`horovod_tpu.blackbox`. Recording never raises and never blocks
    beyond the ring lock — a crashing process must still be able to
    record its way down.
    """

    def __init__(self, capacity=None):
        self._cap = capacity if capacity is not None else ring_capacity()
        self._ring = deque()
        self._lock = threading.Lock()
        self._dropped = 0

    def record(self, kind, name="", detail="", rank=0, t=None):
        global _allocations
        if t is None:
            t = time.time()
        with self._lock:
            _allocations += 1
            if len(self._ring) >= self._cap:
                self._ring.popleft()
                self._dropped += 1
            self._ring.append(Event(t, rank, kind, name, detail))

    def events(self):
        """A stable copy of the ring, oldest first."""
        with self._lock:
            return list(self._ring)

    def event_dicts(self):
        return [e.as_dict() for e in self.events()]

    def __len__(self):
        with self._lock:
            return len(self._ring)

    @property
    def capacity(self) -> int:
        return self._cap

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0
