"""``hvdrun`` — the launcher CLI (horovodrun equivalent).

Reference parity: `horovod/run/run.py:395-616` (arg surface), `gloo_run.py`
(per-rank env injection + fan-out). TPU-native: instead of Gloo rendezvous,
each worker gets ``HVD_COORDINATOR_ADDR``/``HVD_NUM_PROCS``/``HVD_PROCESS_ID``
for `jax.distributed.initialize` (the coordinator service replaces the MPI/
Gloo control plane, SURVEY §5) plus ``HVD_KV_ADDR``/``HVD_SECRET`` for the
launcher's KV store (run-func shipping, future control plane).

Usage::

    hvdrun -np 4 python train.py
    hvdrun -np 8 -H host1:4,host2:4 python train.py
    hvdrun -np 4 --timeline-filename /tmp/tl.json python train.py
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List, Optional

import logging

from . import config_parser, hosts as hosts_mod, rendezvous
from .exec_utils import RankProcess, wait_all

logger = logging.getLogger("horovod_tpu.run")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu distributed job.")
    # not required=True: --check-build/--version must work without it
    # (validated in run_commandline)
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="number of ranks")
    p.add_argument("-H", "--hosts", default=None,
                   help='host:slots list, e.g. "h1:4,h2:4" (default: '
                        "localhost:np)")
    p.add_argument("-hostfile", "--hostfile", dest="hostfile", default=None,
                   help="file with one 'host slots=N' per line")
    p.add_argument("-p", "--ssh-port", dest="ssh_port", type=int, default=22)
    p.add_argument("--no-ssh-check", action="store_true",
                   help="skip the ssh reachability pre-flight")
    p.add_argument("--no-nic-discovery", action="store_true",
                   help="skip driver/task NIC discovery; guess one address")
    p.add_argument("--nics", "--network-interface", dest="nics", default=None,
                   help="comma-separated interface allowlist (skips "
                        "discovery), e.g. eth0,eth1")
    p.add_argument("--disable-cache", action="store_true",
                   help="do not memoize ssh checks on disk")
    p.add_argument("--output-filename", default=None,
                   help="per-rank output file prefix (rank appended)")
    # elastic mode (docs/elastic.md): any of these flags routes the launch
    # through the elastic driver (run/elastic_driver.py)
    p.add_argument("--min-np", "--min-num-proc", dest="min_np", type=int,
                   default=None,
                   help="elastic: minimum surviving workers before the job "
                        "aborts (default: 1)")
    p.add_argument("--max-np", "--max-num-proc", dest="max_np", type=int,
                   default=None,
                   help="elastic: maximum workers to scale up to "
                        "(default: -np)")
    p.add_argument("--host-discovery-script", default=None,
                   help="elastic: executable printing one 'host[:slots]' "
                        "per line, re-run periodically to find "
                        "arriving/departing hosts")
    p.add_argument("--blacklist-cooldown", type=float, default=0.0,
                   help="elastic: seconds before a failed host may be "
                        "retried (0 = blacklist forever)")
    p.add_argument("--start-timeout", type=float, default=600.0)
    p.add_argument("--verbose", action="store_true")
    # knob flags (run.py:395-616)
    p.add_argument("--fusion-threshold-mb", type=float, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--timeline-filename", default=None)
    tmc = p.add_mutually_exclusive_group()
    tmc.add_argument("--timeline-mark-cycles", dest="timeline_mark_cycles",
                     action="store_true", default=None)
    tmc.add_argument("--no-timeline-mark-cycles", dest="timeline_mark_cycles",
                     action="store_false")
    at = p.add_mutually_exclusive_group()
    at.add_argument("--autotune", dest="autotune", action="store_true",
                    default=None)
    at.add_argument("--no-autotune", dest="autotune", action="store_false")
    p.add_argument("--autotune-log", "--autotune-log-file",
                   dest="autotune_log", default=None)
    # the four GP-tuner cadence knobs (run.py:502-521, parameter_manager.cc)
    p.add_argument("--autotune-warmup-samples", type=int, default=None)
    p.add_argument("--autotune-steps-per-sample", type=int, default=None)
    p.add_argument("--autotune-bayes-opt-max-samples", type=int, default=None)
    p.add_argument("--autotune-gaussian-process-noise", type=float,
                   default=None)
    # two-level collectives (run.py:433-447): tri-state — unset leaves the
    # workers' own HOROVOD_HIERARCHICAL_* env/default in force
    hier_ar = p.add_mutually_exclusive_group()
    hier_ar.add_argument("--hierarchical-allreduce",
                         dest="hierarchical_allreduce", action="store_true",
                         default=None)
    hier_ar.add_argument("--no-hierarchical-allreduce",
                         dest="hierarchical_allreduce", action="store_false")
    hier_ag = p.add_mutually_exclusive_group()
    hier_ag.add_argument("--hierarchical-allgather",
                         dest="hierarchical_allgather", action="store_true",
                         default=None)
    hier_ag.add_argument("--no-hierarchical-allgather",
                         dest="hierarchical_allgather", action="store_false")
    stall = p.add_mutually_exclusive_group()
    stall.add_argument("--stall-check", dest="stall_check",
                       action="store_true", default=None)
    stall.add_argument("--no-stall-check", dest="stall_check",
                       action="store_false",
                       help="disable the stall inspector entirely "
                            "(HOROVOD_STALL_CHECK_DISABLE)")
    p.add_argument("--stall-check-time", "--stall-check-warning-time-seconds",
                   dest="stall_check_time", type=float, default=None)
    p.add_argument("--stall-shutdown-time",
                   "--stall-check-shutdown-time-seconds",
                   dest="stall_shutdown_time", type=float, default=None)
    p.add_argument("--log-level", default=None, type=str.upper,
                   choices=["TRACE", "DEBUG", "INFO", "WARNING", "ERROR",
                            "FATAL"],
                   help="worker HOROVOD_LOG_LEVEL (reference level names)")
    lht = p.add_mutually_exclusive_group()
    lht.add_argument("--log-hide-timestamp", dest="log_hide_timestamp",
                     action="store_true", default=None)
    lht.add_argument("--no-log-hide-timestamp", dest="log_hide_timestamp",
                     action="store_false")
    p.add_argument("--config-file", default=None, help="YAML config file")
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="print available frameworks/controllers/ops and exit")
    from .. import __version__

    p.add_argument("-v", "--version", action="version", version=__version__,
                   help="show the horovod_tpu version")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program and args to launch per rank")
    return p


def make_rank_envs(ranks, coordinator_addr: str, kv_addr: str, secret: str,
                   knob_env: Dict[str, str]) -> List[Dict[str, str]]:
    # hierarchical collectives require every rank to compile the IDENTICAL
    # program; the grouping env must therefore be a GLOBAL fact, exported
    # identically everywhere — 0 when hosts hold unequal rank counts
    # (heterogeneous hostfile tails), which disables the two-level path
    local_sizes = {r.local_size for r in ranks}
    uniform = ranks[0].local_size if len(local_sizes) == 1 else 0
    envs = []
    for r in ranks:
        env = dict(knob_env)
        env.update({
            "HVD_NUM_PROCS": str(r.size),
            "HVD_PROCESS_ID": str(r.rank),
            "HVD_COORDINATOR_ADDR": coordinator_addr,
            "HVD_LOCAL_RANK": str(r.local_rank),
            "HVD_LOCAL_SIZE": str(r.local_size),
            "HVD_CROSS_RANK": str(r.cross_rank),
            "HVD_CROSS_SIZE": str(r.cross_size),
            "HVD_UNIFORM_LOCAL_SIZE": str(uniform),
            "HVD_KV_ADDR": kv_addr,
            "HVD_SECRET": secret,
        })
        envs.append(env)
    return envs


def _discover_nics(hostnames: List[str], ssh_port: int, secret: str,
                   local_host: str):
    """Driver/task ring NIC discovery (`run/run.py:199-269` redesigned on
    the authenticated service layer): start a task server on every host via
    ssh (locally for this host), register, ring-probe, intersect.

    Returns ``(nic, driver_ip, per_host_ip)`` — the chosen common
    interface, the launcher's address on it, and each host's address on it
    — or None if discovery failed (caller falls back to the one-NIC guess).
    """
    import subprocess

    from . import network as net
    from .service import DriverService, TaskClient

    driver = DriverService(len(hostnames), secret)
    procs = []
    clients = []
    try:
        driver_ifaces = net.filter_routed(net.get_local_interfaces())
        driver_ip_guess = rendezvous.local_ip()
        # give tasks EVERY driver interface address to try — bootstrapping
        # registration through the same single route guess that discovery
        # exists to replace would be circular (the reference ships all
        # driver addresses too, `run.py:222-228`)
        driver_addrs = list(dict.fromkeys(
            [f"{a}:{driver.port}" for a in driver_ifaces.values()]
            + [f"{driver_ip_guess}:{driver.port}"]))
        module = [sys.executable, "-m", "horovod_tpu.run.task_server"]
        for i, host in enumerate(hostnames):
            args = ["--index", str(i),
                    "--driver", ",".join(driver_addrs)]
            if host == local_host:
                env = dict(os.environ, HVD_SECRET=secret)
                local_args = list(args)
                local_args[3] = f"127.0.0.1:{driver.port}"
                procs.append(subprocess.Popen(module + local_args, env=env))
            else:
                import shlex

                # the secret travels over ssh STDIN — an env assignment in
                # the remote command would be visible in `ps` on that host
                remote = (f"cd {shlex.quote(os.getcwd())} && "
                          + " ".join(shlex.quote(c)
                                     for c in module + args
                                     + ["--secret-stdin"]))
                p = subprocess.Popen(
                    ["ssh", "-p", str(ssh_port),
                     "-o", "StrictHostKeyChecking=no", host, remote],
                    stdin=subprocess.PIPE)
                p.stdin.write((secret + "\n").encode())
                p.stdin.flush()
                procs.append(p)
        driver.wait_for_registration(timeout=60.0)
        clients = [TaskClient((hostnames[i], driver.task_addresses(i)
                               [next(iter(driver.task_addresses(i)))][1]),
                   secret) for i in range(len(hostnames))]
        common = driver.ring_probe(clients)
        nic = common[0]
        per_host = {h: driver.task_addresses(i).get(nic, (None,))[0]
                    for i, h in enumerate(hostnames)}
        driver_ip = driver_ifaces.get(nic, driver_ip_guess)
        return nic, driver_ip, per_host
    except Exception as exc:
        logger.warning("NIC discovery failed (%s); falling back to "
                       "single-address guess", exc)
        return None
    finally:
        # ask remote task servers to exit — terminating the local ssh
        # client alone would leave them lingering (no pty, no signal)
        for c in clients:
            try:
                c.shutdown()
            except Exception:
                pass
        for p in procs:
            p.terminate()
        driver.stop()


def launch(np: int, command: List[str], hosts: Optional[str] = None,
           hostfile: Optional[str] = None, ssh_port: int = 22,
           knob_env: Optional[Dict[str, str]] = None,
           output_filename: Optional[str] = None,
           start_timeout: float = 600.0,
           extra_env: Optional[Dict[str, str]] = None,
           check_ssh: Optional[bool] = None,
           discover_nics: Optional[bool] = None,
           nics: Optional[List[str]] = None,
           use_cache: bool = True) -> int:
    """Core fan-out; returns worst exit code."""
    if hostfile:
        hostlist = hosts_mod.parse_hostfile(hostfile)
    elif hosts:
        hostlist = hosts_mod.parse_hosts(hosts)
    else:
        hostlist = [hosts_mod.HostSlots("localhost", np)]
    ranks = hosts_mod.allocate(hostlist, np)

    secret = rendezvous.make_secret()
    kv = rendezvous.KVStoreServer(secret).start()
    # locality by resolution, not string match: hostfiles commonly name the
    # driver's own machine by real hostname (`run/run.py` local set)
    from .network import resolves_local

    local = {h: resolves_local(h)
             for h in dict.fromkeys(r.hostname for r in ranks)}
    multi_host = any(not local[r.hostname] for r in ranks)

    remote_hosts = sorted({r.hostname for r in ranks
                           if not local[r.hostname]})
    if (check_ssh if check_ssh is not None else multi_host) and remote_hosts:
        from .cache import DiskCache
        from .ssh import check_all_hosts_ssh

        check_all_hosts_ssh(remote_hosts, ssh_port,
                            cache=DiskCache() if use_cache else None)

    ip = rendezvous.local_ip() if multi_host else "127.0.0.1"
    host_ips: Dict[str, str] = {}
    iface_env: Dict[str, str] = {}
    if nics:
        iface_env["HVD_NICS"] = ",".join(nics)
        if multi_host:
            # pin the launcher's own advertised address (kv/coordinator
            # fallback) to the requested NIC too, not just the ranks'
            from .network import get_local_interfaces

            ifaces = get_local_interfaces()
            for n in nics:
                if n in ifaces:
                    ip = ifaces[n]
                    break
    elif (discover_nics if discover_nics is not None else multi_host):
        hostnames = list(dict.fromkeys(r.hostname for r in ranks))
        local_names = [h for h in hostnames if local[h]]
        found = _discover_nics(hostnames, ssh_port, secret,
                               local_names[0] if local_names else "")
        if found:
            nic, driver_ip, per_host = found
            iface_env["HVD_NICS"] = nic
            ip = driver_ip
            host_ips = {h: a for h, a in per_host.items() if a}

    kv_addr = f"{ip}:{kv.port}"
    coord_port = rendezvous.find_free_port()
    coord_host = host_ips.get(ranks[0].hostname, ranks[0].hostname)
    if local.get(coord_host, False) or coord_host in ("localhost",
                                                      "127.0.0.1"):
        # a loopback/local coordinator address is unreachable from remote
        # ranks — advertise the routable launcher address instead
        coord_host = "127.0.0.1" if not multi_host else ip
    coordinator_addr = f"{coord_host}:{coord_port}"

    merged_knobs = dict(knob_env or {})
    merged_knobs.update(iface_env)
    envs = make_rank_envs(ranks, coordinator_addr, kv_addr, secret,
                          merged_knobs)
    if extra_env:
        for e in envs:
            e.update(extra_env)
    procs = []
    try:
        for r, env in zip(ranks, envs):
            out = (f"{output_filename}.{r.rank}" if output_filename else None)
            procs.append(RankProcess(r.rank, command, env,
                                     hostname=r.hostname, ssh_port=ssh_port,
                                     output_file=out,
                                     is_local=local[r.hostname]))
        return wait_all(procs, timeout=start_timeout if start_timeout > 0
                        else None)
    finally:
        for p in procs:
            p.terminate()
        kv.stop()


def check_build() -> str:
    """``--check-build`` report (`run/run.py:289-332` parity): which
    frameworks, controllers and tensor-op paths this install can use."""
    import importlib

    from .. import __version__

    def probe(mod: str) -> bool:
        try:
            importlib.import_module(mod)
            return True
        except Exception:
            return False

    try:
        from ..runtime.native import load_library

        load_library()
        have_native = True
    except Exception:
        have_native = False

    def x(v: bool) -> str:
        return "X" if v else " "

    return (
        f"horovod_tpu v{__version__}:\n"
        f"\n"
        f"Available Frameworks:\n"
        f"    [{x(probe('jax'))}] JAX / flax (native surface)\n"
        f"    [{x(probe('tensorflow'))}] TensorFlow (eager + tf.function)\n"
        f"    [{x(probe('torch'))}] PyTorch\n"
        f"    [{x(probe('mxnet'))}] MXNet\n"
        f"\n"
        f"Available Controllers:\n"
        f"    [{x(have_native)}] native C++ core\n"
        f"    [X] python fallback\n"
        f"    [X] coordinated (cross-process)\n"
        f"\n"
        f"Available Tensor Operations:\n"
        f"    [{x(probe('jax'))}] XLA collectives (SPMD + eager engine)\n"
        f"    [{x(probe('jax.experimental.pallas'))}] Pallas kernels\n"
        f"    [X] Adasum\n")


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    # argparse stops flag-parsing at the command remainder, so a
    # --check-build belonging to the USER program is never consumed here
    # (reference handles this with a custom action, `run/run.py:327-332`)
    if args.check_build:
        print(check_build())
        return 0
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("hvdrun: no command given", file=sys.stderr)
        return 2
    if args.num_proc is None or args.num_proc < 1:
        print("hvdrun: -np/--num-proc is required", file=sys.stderr)
        return 2
    knob_env = config_parser.env_from_config(args.config_file, args)
    elastic = (args.min_np is not None or args.max_np is not None
               or args.host_discovery_script is not None)
    if elastic:
        if args.min_np is not None and args.min_np > args.num_proc:
            print("hvdrun: --min-np cannot exceed -np", file=sys.stderr)
            return 2
        if args.max_np is not None and args.max_np < args.num_proc:
            print("hvdrun: --max-np cannot be below -np", file=sys.stderr)
            return 2
        from .elastic_driver import launch_elastic

        if args.verbose:
            print(f"hvdrun: elastic launch, {args.num_proc} ranks "
                  f"(min {args.min_np or 1}, max "
                  f"{args.max_np or args.num_proc}): {cmd}", file=sys.stderr)
        return launch_elastic(
            args.num_proc, cmd, min_np=args.min_np, max_np=args.max_np,
            hosts=args.hosts, hostfile=args.hostfile,
            host_discovery_script=args.host_discovery_script,
            blacklist_cooldown=args.blacklist_cooldown,
            ssh_port=args.ssh_port, knob_env=knob_env,
            output_filename=args.output_filename)
    if args.verbose:
        print(f"hvdrun: launching {args.num_proc} ranks: {cmd}",
              file=sys.stderr)
    return launch(args.num_proc, cmd, hosts=args.hosts,
                  hostfile=args.hostfile, ssh_port=args.ssh_port,
                  knob_env=knob_env, output_filename=args.output_filename,
                  start_timeout=args.start_timeout,
                  check_ssh=False if args.no_ssh_check else None,
                  discover_nics=False if args.no_nic_discovery else None,
                  nics=args.nics.split(",") if args.nics else None,
                  use_cache=not args.disable_cache)


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
