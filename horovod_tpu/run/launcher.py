"""``hvdrun`` — the launcher CLI (horovodrun equivalent).

Reference parity: `horovod/run/run.py:395-616` (arg surface), `gloo_run.py`
(per-rank env injection + fan-out). TPU-native: instead of Gloo rendezvous,
each worker gets ``HVD_COORDINATOR_ADDR``/``HVD_NUM_PROCS``/``HVD_PROCESS_ID``
for `jax.distributed.initialize` (the coordinator service replaces the MPI/
Gloo control plane, SURVEY §5) plus ``HVD_KV_ADDR``/``HVD_SECRET`` for the
launcher's KV store (run-func shipping, future control plane).

Usage::

    hvdrun -np 4 python train.py
    hvdrun -np 8 -H host1:4,host2:4 python train.py
    hvdrun -np 4 --timeline-filename /tmp/tl.json python train.py
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List, Optional

from . import config_parser, hosts as hosts_mod, rendezvous
from .exec_utils import RankProcess, wait_all


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu distributed job.")
    p.add_argument("-np", "--num-proc", type=int, required=True,
                   help="number of ranks")
    p.add_argument("-H", "--hosts", default=None,
                   help='host:slots list, e.g. "h1:4,h2:4" (default: '
                        "localhost:np)")
    p.add_argument("--hostfile", default=None,
                   help="file with one 'host slots=N' per line")
    p.add_argument("--ssh-port", type=int, default=22)
    p.add_argument("--output-filename", default=None,
                   help="per-rank output file prefix (rank appended)")
    p.add_argument("--start-timeout", type=float, default=600.0)
    p.add_argument("--verbose", action="store_true")
    # knob flags (run.py:395-616)
    p.add_argument("--fusion-threshold-mb", type=float, default=None)
    p.add_argument("--cycle-time-ms", type=float, default=None)
    p.add_argument("--cache-capacity", type=int, default=None)
    p.add_argument("--timeline-filename", default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true")
    p.add_argument("--autotune", action="store_true")
    p.add_argument("--autotune-log", default=None)
    p.add_argument("--stall-check-time", type=float, default=None)
    p.add_argument("--stall-shutdown-time", type=float, default=None)
    p.add_argument("--log-level", default=None)
    p.add_argument("--config-file", default=None, help="YAML config file")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program and args to launch per rank")
    return p


def make_rank_envs(ranks, coordinator_addr: str, kv_addr: str, secret: str,
                   knob_env: Dict[str, str]) -> List[Dict[str, str]]:
    envs = []
    for r in ranks:
        env = dict(knob_env)
        env.update({
            "HVD_NUM_PROCS": str(r.size),
            "HVD_PROCESS_ID": str(r.rank),
            "HVD_COORDINATOR_ADDR": coordinator_addr,
            "HVD_LOCAL_RANK": str(r.local_rank),
            "HVD_LOCAL_SIZE": str(r.local_size),
            "HVD_CROSS_RANK": str(r.cross_rank),
            "HVD_CROSS_SIZE": str(r.cross_size),
            "HVD_KV_ADDR": kv_addr,
            "HVD_SECRET": secret,
        })
        envs.append(env)
    return envs


def launch(np: int, command: List[str], hosts: Optional[str] = None,
           hostfile: Optional[str] = None, ssh_port: int = 22,
           knob_env: Optional[Dict[str, str]] = None,
           output_filename: Optional[str] = None,
           start_timeout: float = 600.0,
           extra_env: Optional[Dict[str, str]] = None) -> int:
    """Core fan-out; returns worst exit code."""
    if hostfile:
        hostlist = hosts_mod.parse_hostfile(hostfile)
    elif hosts:
        hostlist = hosts_mod.parse_hosts(hosts)
    else:
        hostlist = [hosts_mod.HostSlots("localhost", np)]
    ranks = hosts_mod.allocate(hostlist, np)

    secret = rendezvous.make_secret()
    kv = rendezvous.KVStoreServer(secret).start()
    multi_host = any(r.hostname not in ("localhost", "127.0.0.1")
                     for r in ranks)
    ip = rendezvous.local_ip() if multi_host else "127.0.0.1"
    kv_addr = f"{ip}:{kv.port}"
    coord_port = rendezvous.find_free_port()
    coord_host = ranks[0].hostname
    if coord_host in ("localhost",):
        coord_host = "127.0.0.1" if not multi_host else ip
    coordinator_addr = f"{coord_host}:{coord_port}"

    envs = make_rank_envs(ranks, coordinator_addr, kv_addr, secret,
                          knob_env or {})
    if extra_env:
        for e in envs:
            e.update(extra_env)
    procs = []
    try:
        for r, env in zip(ranks, envs):
            out = (f"{output_filename}.{r.rank}" if output_filename else None)
            procs.append(RankProcess(r.rank, command, env,
                                     hostname=r.hostname, ssh_port=ssh_port,
                                     output_file=out))
        return wait_all(procs, timeout=start_timeout if start_timeout > 0
                        else None)
    finally:
        for p in procs:
            p.terminate()
        kv.stop()


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("hvdrun: no command given", file=sys.stderr)
        return 2
    knob_env = config_parser.env_from_config(args.config_file, args)
    if args.verbose:
        print(f"hvdrun: launching {args.num_proc} ranks: {cmd}",
              file=sys.stderr)
    return launch(args.num_proc, cmd, hosts=args.hosts,
                  hostfile=args.hostfile, ssh_port=args.ssh_port,
                  knob_env=knob_env, output_filename=args.output_filename,
                  start_timeout=args.start_timeout)


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
