"""Task-server bootstrap: run on each job host (via ssh) before launch so
the driver can discover common NICs and reach the host for command exec.

Reference parity: `horovod/run/run_task.py` + `run/task/task_service.py` —
the worker registers its per-interface addresses with the driver service,
then serves probe/exec requests. The shared secret comes from the
``HVD_SECRET`` environment variable (never the command line, where it would
be visible in ``ps``).

Usage (what the launcher execs over ssh)::

    HVD_SECRET=... python -m horovod_tpu.run.task_server \
        --index 1 --driver 10.0.0.1:43211 [--linger 300]
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--index", type=int, required=True)
    ap.add_argument("--driver", required=True,
                    help="driver addresses, comma-separated ip:port — each "
                         "is tried in turn (multi-homed drivers)")
    ap.add_argument("--linger", type=float, default=300.0,
                    help="seconds to keep serving before exiting")
    ap.add_argument("--include-lo", action="store_true",
                    help="report loopback too (single-host testing)")
    ap.add_argument("--secret-stdin", action="store_true",
                    help="read the secret from stdin (the ssh path: an env "
                         "assignment in the remote command would appear in "
                         "ps output)")
    args = ap.parse_args(argv)

    if args.secret_stdin:
        secret = sys.stdin.readline().strip()
    else:
        secret = os.environ.get("HVD_SECRET")
    if not secret:
        print("task_server: no secret provided", file=sys.stderr)
        return 2

    from .network import host_hash
    from .service import DriverClient, TaskService

    svc = TaskService(args.index, secret, include_lo=args.include_lo)
    try:
        last_err = None
        registered = False
        for addr in args.driver.split(","):
            ip, port_s = addr.rsplit(":", 1)
            try:
                # short per-address timeout: blackholed interfaces must not
                # eat the driver's registration window one 10s apiece
                DriverClient((ip, int(port_s)), secret).register(
                    args.index, svc.addresses(), host_hash(), timeout=5.0)
                registered = True
                break
            except OSError as exc:
                last_err = exc
        if not registered:
            # NOTE: a secret mismatch looks identical to unreachability
            # from here (the driver drops unauthenticated connections
            # without replying), hence the hint
            print(f"task_server: could not reach the driver at any of "
                  f"{args.driver}: {last_err} (check network routes AND "
                  "that HVD_SECRET matches the launcher's)",
                  file=sys.stderr)
            return 1
        deadline = time.monotonic() + args.linger
        while time.monotonic() < deadline and not svc.shutdown_requested():
            time.sleep(0.2)
    finally:
        svc.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
