"""Elastic launch driver: monitor workers, blacklist failed hosts, spawn
joiners discovered at runtime.

Reference parity: `horovod/run/elastic/driver.py` — the driver keeps the job
alive while at least ``min_np`` workers survive, periodically re-runs host
discovery, and assigns newly discovered slots fresh (monotonic) ranks up to
``max_np``. Unlike the reference's Gloo rendezvous rebuild, workers here
re-rendezvous *in-band*: the rank-0 coordinator admits a joiner at the next
commit boundary and bumps the membership epoch (runtime/coordinator.py), so
the driver's only jobs are process supervision, blacklisting, and spawning.

Rank-0 loss is fatal by design: rank 0 hosts the coordinator (and the KV
server lives with the launcher), so its death takes the control plane with
it — the reference has the same asymmetry around the rendezvous server.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional

from . import hosts as hosts_mod, rendezvous
from .discovery import Blacklist, HostDiscovery
from .exec_utils import RankProcess
from .launcher import make_rank_envs
from .service import DriverService

logger = logging.getLogger("horovod_tpu.run.elastic")


class ElasticDriver:
    """Supervises an elastic job. ``run()`` blocks until rank 0 exits (its
    code is the job's code) or membership falls below ``min_np``."""

    def __init__(self, np: int, min_np: int, max_np: int,
                 command: List[str], discovery: HostDiscovery,
                 blacklist: Optional[Blacklist] = None,
                 ssh_port: int = 22,
                 knob_env: Optional[Dict[str, str]] = None,
                 extra_env: Optional[Dict[str, str]] = None,
                 output_filename: Optional[str] = None,
                 discovery_interval: float = 5.0,
                 poll_interval: float = 0.5):
        self.np = np
        self.min_np = min_np
        self.max_np = max_np
        self.command = list(command)
        self.discovery = discovery
        self.blacklist = blacklist or Blacklist()
        self.ssh_port = ssh_port
        self.knob_env = dict(knob_env or {})
        self.extra_env = dict(extra_env or {})
        self.output_filename = output_filename
        self.discovery_interval = discovery_interval
        self.poll_interval = poll_interval

        self._procs: Dict[int, RankProcess] = {}  # rank → live process
        self._rank_host: Dict[int, str] = {}
        self._next_rank = 0
        # hot-spare respawn budget per rank (HOROVOD_ELASTIC_RESPAWN): a
        # failed worker is relaunched under its ORIGINAL rank id so it
        # reclaims the same shard slot and restores from its checkpoint
        # buddy in O(shard) (docs/checkpoint.md). Defaults to 1 when
        # checkpointing is on and 0 otherwise — without a restore source a
        # respawn is just the old scale-up with extra steps, and knobs-
        # unset jobs must behave exactly as before.
        default_respawn = "1" if os.environ.get("HOROVOD_CKPT_DIR") else "0"
        try:
            self.respawn_limit = int(
                os.environ.get("HOROVOD_ELASTIC_RESPAWN",
                               default_respawn))
        except ValueError:
            self.respawn_limit = 0
        self._respawns: Dict[int, int] = {}
        self._secret = rendezvous.make_secret()
        self._kv: Optional[rendezvous.KVStoreServer] = None
        self._driver_svc: Optional[DriverService] = None
        self._base_env: Dict[str, str] = {}

    # -------------------------------------------------------------- spawning
    def _is_local(self, hostname: str) -> bool:
        from .network import resolves_local

        return resolves_local(hostname)

    def _spawn(self, rank: int, host: str, local_rank: int = 0,
               local_size: int = 1) -> None:
        info = hosts_mod.RankInfo(
            rank=rank, size=self.np, hostname=host,
            local_rank=local_rank, local_size=local_size,
            # cross placement is informational in elastic mode (no
            # hierarchical collectives on the host wire)
            cross_rank=0, cross_size=1)
        env = make_rank_envs([info], self._base_env["coord"],
                             self._base_env["kv"], self._secret,
                             self.knob_env)[0]
        env.update(self.extra_env)
        env["HVD_ELASTIC"] = "1"
        # checkpoint knobs ride through to every worker (a respawned
        # replacement must see the same bundle dir/buddy config)
        for k, v in os.environ.items():
            if k.startswith("HOROVOD_CKPT_"):
                env.setdefault(k, v)
        if self._driver_svc is not None:
            env["HVD_DRIVER_ADDR"] = self._base_env["driver"]
        out = (f"{self.output_filename}.{rank}"
               if self.output_filename else None)
        logger.info("spawning rank %d on %s", rank, host)
        self._procs[rank] = RankProcess(
            rank, self.command, env, hostname=host, ssh_port=self.ssh_port,
            output_file=out, is_local=self._is_local(host))
        self._rank_host[rank] = host

    def _host_load(self) -> Dict[str, int]:
        load: Dict[str, int] = {}
        for r in self._procs:
            h = self._rank_host[r]
            load[h] = load.get(h, 0) + 1
        return load

    def _scale_up(self, available: List[hosts_mod.HostSlots]) -> None:
        """Fill free slots on non-blacklisted hosts with fresh ranks until
        max_np. New ranks re-rendezvous in-band (coordinator admission)."""
        load = self._host_load()
        for h in available:
            while (len(self._procs) < self.max_np
                   and load.get(h.hostname, 0) < h.slots):
                rank = self._next_rank
                self._next_rank += 1
                self._spawn(rank, h.hostname,
                            local_rank=load.get(h.hostname, 0),
                            local_size=h.slots)
                load[h.hostname] = load.get(h.hostname, 0) + 1

    def _try_respawn(self, rank: int, failed_host: str) -> bool:
        """Hot-spare replacement: relaunch a failed worker under its
        ORIGINAL rank id. The coordinator admits it at the next commit
        boundary like any joiner, but because the rank (and so its
        position in the sorted member list) is the same, the replacement
        reclaims the dead worker's shard slot and restores it from the
        buddy journal in O(shard) — resuming the job's bit-identical
        trajectory mid-epoch instead of forcing an O(model) rebuild
        (ckpt/manager.py, docs/checkpoint.md)."""
        done = self._respawns.get(rank, 0)
        if done >= self.respawn_limit:
            return False
        self._respawns[rank] = done + 1
        try:
            available = self.blacklist.filter(self.discovery.discover())
        except Exception as exc:
            logger.warning("host discovery failed during respawn: %s", exc)
            available = []
        load = self._host_load()
        host = None
        for h in available:
            if load.get(h.hostname, 0) < h.slots:
                host = h.hostname
                break
        if host is None:
            # no clean host free: the process died but the machine may be
            # fine (workload crash, OOM kill) — retry in place
            host = failed_host
        logger.warning("respawning rank %d on %s (attempt %d/%d)",
                       rank, host, self._respawns[rank],
                       self.respawn_limit)
        try:
            self._spawn(rank, host)
            return True
        except Exception as exc:
            logger.error("respawn of rank %d on %s failed: %s",
                         rank, host, exc)
            return False

    # -------------------------------------------------------------- monitor
    def _merge_reported_failures(self) -> None:
        """Hosts reported dead via DriverClient.notify_host_failure join the
        blacklist (the monitor's own poll() only sees local/ssh exit codes;
        a task can report an unreachable *neighbour* this way)."""
        if self._driver_svc is None:
            return
        for host, (_, reason) in self._driver_svc.failed_hosts().items():
            if not self.blacklist.blacklisted(host):
                logger.warning("host %s reported failed: %s", host, reason)
                self.blacklist.fail(host)

    def run(self) -> int:
        self._kv = rendezvous.KVStoreServer(self._secret).start()
        initial = self.blacklist.filter(self.discovery.discover())
        if not initial:
            raise RuntimeError("host discovery returned no usable hosts")
        total_slots = sum(h.slots for h in initial)
        start_np = max(self.min_np, min(self.np, total_slots, self.max_np))
        ranks = hosts_mod.allocate(initial, start_np)

        multi_host = any(not self._is_local(r.hostname) for r in ranks)
        ip = rendezvous.local_ip() if multi_host else "127.0.0.1"
        self._driver_svc = DriverService(len(initial), self._secret)
        self._base_env = {
            "kv": f"{ip}:{self._kv.port}",
            # elastic workers resolve the coordinator via the KV store;
            # exported for parity with the static launcher env
            "coord": f"{ip}:{rendezvous.find_free_port()}",
            "driver": f"{ip}:{self._driver_svc.port}",
        }
        try:
            for r in ranks:
                self._spawn(r.rank, r.hostname, r.local_rank, r.local_size)
                self._next_rank = max(self._next_rank, r.rank + 1)
            return self._monitor()
        finally:
            for p in self._procs.values():
                p.terminate()
            self._driver_svc.stop()
            self._kv.stop()

    def _monitor(self) -> int:
        last_discovery = time.monotonic()
        while True:
            for rank in sorted(self._procs):
                rc = self._procs[rank].poll()
                if rc is None:
                    continue
                host = self._rank_host[rank]
                del self._procs[rank]
                if rank == 0:
                    # rank 0 hosts the coordinator: its exit — clean or
                    # not — ends the job
                    logger.info("rank 0 exited with code %d; job %s",
                                rc, "complete" if rc == 0 else "failed")
                    return rc
                if rc == 0:
                    logger.info("rank %d on %s finished cleanly", rank, host)
                    continue
                logger.warning("rank %d on %s exited with code %d; "
                               "continuing with %d workers",
                               rank, host, rc, len(self._procs))
                self.blacklist.fail(host)
                if self._try_respawn(rank, host):
                    continue
                if len(self._procs) < self.min_np:
                    logger.error(
                        "alive workers (%d) fell below --min-np (%d); "
                        "aborting", len(self._procs), self.min_np)
                    return rc
            if not self._procs:
                return 0
            now = time.monotonic()
            if now - last_discovery >= self.discovery_interval:
                last_discovery = now
                self._merge_reported_failures()
                try:
                    available = self.blacklist.filter(
                        self.discovery.discover())
                except Exception as exc:
                    logger.warning("host discovery failed: %s", exc)
                    available = []
                if len(self._procs) < self.max_np:
                    self._scale_up(available)
            time.sleep(self.poll_interval)


def launch_elastic(np: int, command: List[str],
                   min_np: Optional[int] = None,
                   max_np: Optional[int] = None,
                   hosts: Optional[str] = None,
                   hostfile: Optional[str] = None,
                   host_discovery_script: Optional[str] = None,
                   blacklist_cooldown: float = 0.0,
                   ssh_port: int = 22,
                   knob_env: Optional[Dict[str, str]] = None,
                   extra_env: Optional[Dict[str, str]] = None,
                   output_filename: Optional[str] = None) -> int:
    """Entry point the launcher routes to when any elastic flag is present
    (``--min-np`` / ``--max-np`` / ``--host-discovery-script``)."""
    from .discovery import (FixedHostDiscovery, HostDiscoveryScript)

    if host_discovery_script:
        discovery: HostDiscovery = HostDiscoveryScript(host_discovery_script)
    elif hostfile:
        discovery = FixedHostDiscovery(hosts_mod.parse_hostfile(hostfile))
    elif hosts:
        discovery = FixedHostDiscovery(hosts_mod.parse_hosts(hosts))
    else:
        discovery = FixedHostDiscovery(
            [hosts_mod.HostSlots("localhost", max_np or np)])
    driver = ElasticDriver(
        np=np,
        min_np=min_np if min_np is not None else 1,
        max_np=max_np if max_np is not None else np,
        command=command,
        discovery=discovery,
        blacklist=Blacklist(cooldown=blacklist_cooldown),
        ssh_port=ssh_port,
        knob_env=knob_env,
        extra_env=extra_env,
        output_filename=output_filename)
    return driver.run()
