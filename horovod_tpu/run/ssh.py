"""ssh pre-flight reachability checks.

Reference parity: `horovod/run/run.py:63-115`
(`_check_all_hosts_ssh_successful`): every remote host gets
``ssh -o StrictHostKeyChecking=no <host> date``, retried up to 5 times,
threaded across hosts; any failure prints the output and exits. Results are
memoized on disk (`run/util/cache.py`) so repeated launches skip the probe.
"""

from __future__ import annotations

import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterable, Optional, Tuple

from .cache import DiskCache

SSH_RETRIES = 5


def _default_exec(host: str, ssh_port: int) -> Tuple[int, str]:
    cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port and ssh_port != 22:
        cmd += ["-p", str(ssh_port)]
    cmd += [host, "date"]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=30)
    return r.returncode, r.stdout + r.stderr


def check_all_hosts_ssh(hosts: Iterable[str], ssh_port: int = 22,
                        retries: int = SSH_RETRIES,
                        cache: Optional[DiskCache] = None,
                        exec_fn=_default_exec,
                        exit_on_failure: bool = True) -> Dict[str, bool]:
    """Probe every host concurrently; returns host → ok. With
    ``exit_on_failure`` (the CLI path) a failure prints the ssh output for
    each bad host and raises SystemExit(1), as the reference does."""
    hosts = list(dict.fromkeys(hosts))
    results: Dict[str, bool] = {}
    outputs: Dict[str, str] = {}

    def probe(host: str) -> bool:
        key = f"ssh:{host}:{ssh_port}"
        if cache is not None and cache.get(key):
            return True
        out = ""
        for _ in range(retries):
            try:
                rc, out = exec_fn(host, ssh_port)
            except Exception as exc:  # timeout, missing binary...
                rc, out = 255, str(exc)
            if rc == 0:
                if cache is not None:
                    cache.put(key, True)
                return True
        outputs[host] = out
        return False

    with ThreadPoolExecutor(max_workers=min(32, max(1, len(hosts)))) as ex:
        for host, ok in zip(hosts, ex.map(probe, hosts)):
            results[host] = ok

    if exit_on_failure and not all(results.values()):
        for host, ok in results.items():
            if not ok:
                print(f"ssh not successful for host {host}:\n"
                      f"{outputs.get(host, '')}", file=sys.stderr)
        raise SystemExit(1)
    return results
