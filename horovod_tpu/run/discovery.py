"""Host discovery + failure blacklisting for elastic launches.

Reference parity: `horovod/run/elastic/discovery.py` — ``HostDiscovery``
(fixed list or a user script re-run periodically, one ``host[:slots]`` per
line) and the blacklist that keeps a failed host out of the candidate set.
Extension: the blacklist has a cooldown (``--blacklist-cooldown``) after
which a host becomes eligible again — preempted TPU hosts routinely come
back with the same name, and a permanent blacklist would strand them.
"""

from __future__ import annotations

import logging
import subprocess
import time
from typing import Dict, List, Optional

from .hosts import HostSlots, parse_hosts

logger = logging.getLogger("horovod_tpu.run.discovery")


class HostDiscovery:
    """Interface: ``discover()`` returns the currently available hosts."""

    def discover(self) -> List[HostSlots]:
        raise NotImplementedError


class FixedHostDiscovery(HostDiscovery):
    """Static ``-H host:slots,...`` set (elastic within a fixed pool: lost
    hosts are blacklisted, recovered ones rejoin after cooldown)."""

    def __init__(self, hosts: List[HostSlots]):
        self._hosts = list(hosts)

    def discover(self) -> List[HostSlots]:
        return list(self._hosts)


class HostDiscoveryScript(HostDiscovery):
    """Runs a user script that prints one ``host`` or ``host:slots`` per
    line (the reference's ``--host-discovery-script`` contract; see
    docs/elastic.md for the exact format). A failing or hanging script
    yields the previous snapshot rather than killing the job."""

    def __init__(self, script: str, timeout: float = 30.0,
                 default_slots: int = 1):
        self._script = script
        self._timeout = timeout
        self._default_slots = default_slots
        self._last: List[HostSlots] = []

    def discover(self) -> List[HostSlots]:
        try:
            out = subprocess.run(
                [self._script], capture_output=True, text=True,
                timeout=self._timeout, check=True).stdout
        except (OSError, subprocess.SubprocessError) as exc:
            logger.warning("host discovery script %s failed (%s); keeping "
                           "previous host set", self._script, exc)
            return list(self._last)
        hosts: List[HostSlots] = []
        for line in out.splitlines():
            line = line.split("#")[0].strip()
            if not line:
                continue
            parsed = parse_hosts(line)
            for h in parsed:
                if ":" not in line:
                    h.slots = self._default_slots
            hosts.extend(parsed)
        self._last = hosts
        return hosts


class Blacklist:
    """Failed-host registry with cooldown. ``fail(host)`` records a failure;
    ``blacklisted(host)`` is True until ``cooldown`` seconds have passed
    (cooldown <= 0 means permanent, the reference behaviour)."""

    def __init__(self, cooldown: float = 0.0):
        self.cooldown = cooldown
        self._failed: Dict[str, float] = {}

    def fail(self, host: str) -> None:
        self._failed[host] = time.monotonic()
        logger.warning("blacklisting host %s%s", host,
                       f" for {self.cooldown:.0f}s" if self.cooldown > 0
                       else " permanently")

    def blacklisted(self, host: str) -> bool:
        ts = self._failed.get(host)
        if ts is None:
            return False
        if self.cooldown > 0 and time.monotonic() - ts >= self.cooldown:
            del self._failed[host]
            logger.info("host %s cooldown expired; eligible again", host)
            return False
        return True

    def filter(self, hosts: List[HostSlots]) -> List[HostSlots]:
        return [h for h in hosts if not self.blacklisted(h.hostname)]

    def hosts(self) -> List[str]:
        return sorted(self._failed)
