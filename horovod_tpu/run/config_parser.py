"""YAML/flag → env knob mapping.

Reference parity: `horovod/run/common/util/config_parser.py` (YAML config file
mapped onto HOROVOD_* envs) and the knob flags of `run/run.py:395-616`
(``--fusion-threshold-mb`` → HOROVOD_FUSION_THRESHOLD etc.)."""

from __future__ import annotations

from typing import Dict, Optional

# flag name -> (env var, converter)
_KNOBS = {
    "fusion_threshold_mb": ("HOROVOD_FUSION_THRESHOLD",
                            lambda v: str(int(float(v) * 1024 * 1024))),
    "cycle_time_ms": ("HOROVOD_CYCLE_TIME", str),
    "cache_capacity": ("HOROVOD_CACHE_CAPACITY", str),
    "timeline_filename": ("HOROVOD_TIMELINE", str),
    "autotune_log": ("HOROVOD_AUTOTUNE_LOG", str),
    "autotune_warmup_samples": ("HOROVOD_AUTOTUNE_WARMUP_SAMPLES",
                                lambda v: str(int(v))),
    "autotune_steps_per_sample": ("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE",
                                  lambda v: str(int(v))),
    "autotune_bayes_opt_max_samples": (
        "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", lambda v: str(int(v))),
    "autotune_gaussian_process_noise": (
        "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", lambda v: str(float(v))),
    "stall_check_time": ("HOROVOD_STALL_CHECK_TIME_SECONDS", str),
    "stall_shutdown_time": ("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", str),
    "log_level": ("HOROVOD_LOG_LEVEL", str),
}

# tri-state booleans: True and False both export (the reference maps
# --no-hierarchical-allreduce to HOROVOD_HIERARCHICAL_ALLREDUCE=0, and
# --no-stall-check to HOROVOD_STALL_CHECK_DISABLE=1 —
# `run/common/util/config_parser.py:140-180`); None leaves the env alone
_TRISTATE = {
    "hierarchical_allreduce": ("HOROVOD_HIERARCHICAL_ALLREDUCE",
                               lambda v: "1" if v else "0"),
    "hierarchical_allgather": ("HOROVOD_HIERARCHICAL_ALLGATHER",
                               lambda v: "1" if v else "0"),
    "stall_check": ("HOROVOD_STALL_CHECK_DISABLE",
                    lambda v: "0" if v else "1"),
    "autotune": ("HOROVOD_AUTOTUNE", lambda v: "1" if v else "0"),
    "timeline_mark_cycles": ("HOROVOD_TIMELINE_MARK_CYCLES",
                             lambda v: "1" if v else "0"),
    "log_hide_timestamp": ("HOROVOD_LOG_HIDE_TIME",
                           lambda v: "1" if v else "0"),
}


def args_to_env(args) -> Dict[str, str]:
    """Map parsed CLI args (argparse Namespace or dict) to env vars."""
    d = vars(args) if not isinstance(args, dict) else args
    env = {}
    for flag, (var, conv) in _KNOBS.items():
        v = d.get(flag)
        if v is not None and v is not False:
            env[var] = conv(v)
    for flag, (var, conv) in _TRISTATE.items():
        v = d.get(flag)
        if v is not None:
            env[var] = conv(v)
    return env


def parse_config_file(path: str) -> Dict[str, object]:
    """Parse the YAML config file into flag values (reference layout:
    top-level params + nested ``timeline:``/``autotune:`` sections)."""
    import yaml

    with open(path) as f:
        data = yaml.safe_load(f) or {}
    out: Dict[str, object] = {}
    for k in ("fusion_threshold_mb", "cycle_time_ms", "cache_capacity",
              "log_level"):
        if k.replace("_", "-") in data:
            out[k] = data[k.replace("_", "-")]
        elif k in data:
            out[k] = data[k]
    tl = data.get("timeline") or {}
    if "filename" in tl:
        out["timeline_filename"] = tl["filename"]
    if "mark-cycles" in tl:
        out["timeline_mark_cycles"] = tl["mark-cycles"]
    # reference layout nests the two-level knobs under ``params:``
    # (`run/common/util/config_parser.py:60-66`); accept them top-level and
    # in underscore spelling too, like every other knob in this file
    params = data.get("params") or {}
    for k in ("hierarchical-allreduce", "hierarchical-allgather"):
        ku = k.replace("-", "_")
        for src in (data, params):  # params: section wins when both given
            if k in src:
                out[ku] = bool(src[k])
            elif ku in src:
                out[ku] = bool(src[ku])
    at = data.get("autotune") or {}
    if "enabled" in at:
        out["autotune"] = bool(at["enabled"])
    if "log-file" in at:
        out["autotune_log"] = at["log-file"]
    for k in ("warmup-samples", "steps-per-sample", "bayes-opt-max-samples",
              "gaussian-process-noise"):
        if k in at:
            out["autotune_" + k.replace("-", "_")] = at[k]
    # ``logging:`` section (`config_parser.py:103-107` there)
    lg = data.get("logging") or {}
    if "level" in lg:
        out["log_level"] = lg["level"]
    if "hide-timestamp" in lg:
        out["log_hide_timestamp"] = bool(lg["hide-timestamp"])
    # ``stall-check:`` section (`config_parser.py:86-92` there)
    sc = data.get("stall-check") or data.get("stall_check") or {}
    if "enabled" in sc:
        out["stall_check"] = bool(sc["enabled"])
    if "warning-time-seconds" in sc:
        out["stall_check_time"] = sc["warning-time-seconds"]
    if "shutdown-time-seconds" in sc:
        out["stall_shutdown_time"] = sc["shutdown-time-seconds"]
    return out


def env_from_config(path: Optional[str], args=None) -> Dict[str, str]:
    merged: Dict[str, object] = {}
    if path:
        merged.update(parse_config_file(path))
    if args is not None:
        d = vars(args) if not isinstance(args, dict) else dict(args)
        for k, v in d.items():
            if v is None:
                continue
            # tri-states: an explicit False (--no-*) must override the
            # config file, not vanish
            if v is False and k not in _TRISTATE:
                continue
            merged[k] = v
    return args_to_env(merged)
