"""YAML/flag → env knob mapping.

Reference parity: `horovod/run/common/util/config_parser.py` (YAML config file
mapped onto HOROVOD_* envs) and the knob flags of `run/run.py:395-616`
(``--fusion-threshold-mb`` → HOROVOD_FUSION_THRESHOLD etc.)."""

from __future__ import annotations

from typing import Dict, Optional

# flag name -> (env var, converter)
_KNOBS = {
    "fusion_threshold_mb": ("HOROVOD_FUSION_THRESHOLD",
                            lambda v: str(int(float(v) * 1024 * 1024))),
    "cycle_time_ms": ("HOROVOD_CYCLE_TIME", str),
    "cache_capacity": ("HOROVOD_CACHE_CAPACITY", str),
    "timeline_filename": ("HOROVOD_TIMELINE", str),
    "timeline_mark_cycles": ("HOROVOD_TIMELINE_MARK_CYCLES",
                             lambda v: "1" if v else "0"),
    "autotune": ("HOROVOD_AUTOTUNE", lambda v: "1" if v else "0"),
    "autotune_log": ("HOROVOD_AUTOTUNE_LOG", str),
    "stall_check_time": ("HOROVOD_STALL_CHECK_TIME_SECONDS", str),
    "stall_shutdown_time": ("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", str),
    "log_level": ("HOROVOD_LOG_LEVEL", str),
}


def args_to_env(args) -> Dict[str, str]:
    """Map parsed CLI args (argparse Namespace or dict) to env vars."""
    d = vars(args) if not isinstance(args, dict) else args
    env = {}
    for flag, (var, conv) in _KNOBS.items():
        v = d.get(flag)
        if v is not None and v is not False:
            env[var] = conv(v)
    return env


def parse_config_file(path: str) -> Dict[str, object]:
    """Parse the YAML config file into flag values (reference layout:
    top-level params + nested ``timeline:``/``autotune:`` sections)."""
    import yaml

    with open(path) as f:
        data = yaml.safe_load(f) or {}
    out: Dict[str, object] = {}
    for k in ("fusion_threshold_mb", "cycle_time_ms", "cache_capacity",
              "log_level"):
        if k.replace("_", "-") in data:
            out[k] = data[k.replace("_", "-")]
        elif k in data:
            out[k] = data[k]
    tl = data.get("timeline") or {}
    if "filename" in tl:
        out["timeline_filename"] = tl["filename"]
    if "mark-cycles" in tl:
        out["timeline_mark_cycles"] = tl["mark-cycles"]
    at = data.get("autotune") or {}
    if at.get("enabled"):
        out["autotune"] = True
    if "log-file" in at:
        out["autotune_log"] = at["log-file"]
    return out


def env_from_config(path: Optional[str], args=None) -> Dict[str, str]:
    merged: Dict[str, object] = {}
    if path:
        merged.update(parse_config_file(path))
    if args is not None:
        d = vars(args) if not isinstance(args, dict) else dict(args)
        for k, v in d.items():
            if v is not None and v is not False:
                merged[k] = v
    return args_to_env(merged)
