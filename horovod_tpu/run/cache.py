"""On-disk memoization of expensive launcher checks (ssh reachability, NIC
sets) with a TTL.

Reference parity: `horovod/run/util/cache.py` — a pickled dict under
``~/.horovod`` keyed by parameters, entries expire after
``--disable-cache``-able timeout. Here: JSON under ``~/.horovod_tpu`` (no
pickle needed for plain values), same TTL semantics.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Optional


class DiskCache:
    def __init__(self, path: Optional[str] = None, ttl_s: float = 1200.0,
                 clock: Callable[[], float] = time.time):
        self._path = path or os.path.join(
            os.path.expanduser("~"), ".horovod_tpu", "cache.json")
        self._ttl = ttl_s
        self._clock = clock
        self._lock = threading.Lock()
        self._data = {}
        try:
            with open(self._path) as f:
                self._data = json.load(f)
        except (OSError, ValueError):
            self._data = {}

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                return None
            ts, value = ent
            if self._clock() - ts > self._ttl:
                del self._data[key]
                return None
            return value

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            self._data[key] = [self._clock(), value]
            try:
                os.makedirs(os.path.dirname(self._path), exist_ok=True)
                tmp = self._path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(self._data, f)
                os.replace(tmp, self._path)
            except OSError:
                pass  # cache is best-effort
