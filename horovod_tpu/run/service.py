"""Secret-authenticated driver/task TCP services for launch-time
coordination: task registration, ring NIC probing, remote command execution
and termination.

Reference parity: `horovod/run/common/service/driver_service.py` (driver
collects task registrations + per-task routed interfaces, intersects),
`task_service.py` (remote command exec + wait), `common/network.py` (secret-
authenticated pickled-message TCP services). Wire format here:
``len(4B big-endian) | hmac_sha256(32B) | pickle`` — the HMAC over the
pickle bytes is verified BEFORE unpickling, so unauthenticated peers cannot
reach the deserializer (same property as the reference's `secret.py`
wrapping).
"""

from __future__ import annotations

import hmac
import hashlib
import os
import pickle
import signal
import socket
import struct
import subprocess
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from . import network as net

_LEN = struct.Struct(">I")
_DIGEST = 32
# Control messages are small; reject bigger frames BEFORE buffering so an
# unauthenticated peer cannot exhaust memory (HMAC is only checkable after
# the full frame arrives).
_MAX_FRAME = 16 * 1024 * 1024


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def _send_msg(sock: socket.socket, secret: str, msg: Any) -> None:
    payload = pickle.dumps(msg)
    digest = hmac.new(secret.encode(), payload, hashlib.sha256).digest()
    sock.sendall(_LEN.pack(len(payload) + _DIGEST) + digest + payload)


def _recv_msg(sock: socket.socket, secret: str) -> Any:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_FRAME:
        raise PermissionError(f"frame of {n} bytes exceeds limit")
    frame = _recv_exact(sock, n)
    digest, payload = frame[:_DIGEST], frame[_DIGEST:]
    want = hmac.new(secret.encode(), payload, hashlib.sha256).digest()
    if not hmac.compare_digest(digest, want):
        raise PermissionError("message failed HMAC authentication")
    return pickle.loads(payload)


class _Service:
    """Threaded request/response TCP server; one message per connection."""

    def __init__(self, secret: str, handler: Callable[[dict], Any],
                 port: int = 0):
        self._secret = secret
        self._handler = handler
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _serve_one(self, conn: socket.socket) -> None:
        with conn:
            try:
                conn.settimeout(30.0)
                msg = _recv_msg(conn, self._secret)
                reply = self._handler(msg)
                _send_msg(conn, self._secret, reply)
            except PermissionError:
                return  # unauthenticated: drop silently
            except Exception as exc:
                try:
                    _send_msg(conn, self._secret,
                              {"error": f"{type(exc).__name__}: {exc}"})
                except OSError:
                    pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def call(addr: Tuple[str, int], secret: str, msg: dict,
         timeout: float = 30.0) -> Any:
    with socket.create_connection(addr, timeout=timeout) as sock:
        _send_msg(sock, secret, msg)
        reply = _recv_msg(sock, secret)
    if isinstance(reply, dict) and "error" in reply:
        raise RuntimeError(reply["error"])
    return reply


# -------------------------------------------------------------- task service
class TaskService:
    """Per-host service started before the job: answers interface probes and
    executes/terminates commands (`task_service.py` parity)."""

    def __init__(self, index: int, secret: str, include_lo: bool = False):
        self.index = index
        self._secret = secret
        self._include_lo = include_lo
        self._proc: Optional[subprocess.Popen] = None
        self._shutdown = threading.Event()
        self._svc = _Service(secret, self._handle)
        self.port = self._svc.port

    def shutdown_requested(self) -> bool:
        return self._shutdown.is_set()

    def addresses(self) -> Dict[str, Tuple[str, int]]:
        """nic → (ip, port) for every (routed) local interface; the single
        listener binds 0.0.0.0 so each address reaches it."""
        ifaces = net.get_local_interfaces()
        if not self._include_lo:
            ifaces = net.filter_routed(ifaces) or ifaces
        return {nic: (ip, self.port) for nic, ip in ifaces.items()}

    def _handle(self, msg: dict) -> Any:
        op = msg.get("op")
        if op == "ping":
            return {"ok": True, "index": self.index}
        if op == "addresses":
            return self.addresses()
        if op == "probe":
            return {"reachable":
                    sorted(net.probe_reachable(msg["addresses"]))}
        if op == "run":
            if self._proc is not None and self._proc.poll() is None:
                raise RuntimeError("a command is already running")
            env = dict(os.environ)
            env.update(msg.get("env") or {})
            self._proc = subprocess.Popen(
                msg["cmd"], env=env, start_new_session=True)
            return {"pid": self._proc.pid}
        if op == "wait":
            if self._proc is None:
                raise RuntimeError("no command started")
            return {"rc": self._proc.wait(msg.get("timeout"))}
        if op == "terminate":
            if self._proc is not None and self._proc.poll() is None:
                try:
                    os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
                except OSError:
                    self._proc.terminate()
            return {"ok": True}
        if op == "shutdown":
            # stop lingering: the driver is done with this task server
            # (killing the local ssh client alone would NOT stop the
            # remote process — no pty, no signal)
            self._shutdown.set()
            return {"ok": True}
        raise ValueError(f"unknown op: {op}")

    def stop(self) -> None:
        self._handle({"op": "terminate"})
        self._svc.stop()


class TaskClient:
    def __init__(self, addr: Tuple[str, int], secret: str):
        self._addr = addr
        self._secret = secret

    def _call(self, msg: dict, timeout: float = 30.0) -> Any:
        return call(self._addr, self._secret, msg, timeout=timeout)

    def ping(self):
        return self._call({"op": "ping"})

    def addresses(self) -> Dict[str, Tuple[str, int]]:
        return self._call({"op": "addresses"})

    def probe(self, addresses: Dict[str, Tuple[str, int]]) -> List[str]:
        return self._call({"op": "probe", "addresses": addresses})["reachable"]

    def run_command(self, cmd: List[str],
                    env: Optional[Dict[str, str]] = None) -> int:
        return self._call({"op": "run", "cmd": cmd, "env": env})["pid"]

    def wait(self, timeout: Optional[float] = None) -> int:
        # timeout=None means wait forever — the socket must block forever
        # too, not cap at the default call() timeout
        return self._call({"op": "wait", "timeout": timeout},
                          timeout=None if timeout is None
                          else timeout + 5.0)["rc"]

    def terminate(self) -> None:
        self._call({"op": "terminate"})

    def shutdown(self) -> None:
        self._call({"op": "shutdown"})


# ------------------------------------------------------------ driver service
class DriverService:
    """Launcher-side registry: tasks register their per-NIC addresses +
    host hash; after the ring probe the driver knows the common routed
    interface set (`driver_service.py` + `run.py:199-269`)."""

    def __init__(self, num_hosts: int, secret: str):
        self.num_hosts = num_hosts
        self._secret = secret
        self._cv = threading.Condition()
        self._registered: Dict[int, Dict[str, Tuple[str, int]]] = {}
        self._host_hashes: Dict[int, str] = {}
        self._routed: Dict[int, Set[str]] = {}
        # elastic: hosts reported dead (by the monitor loop or by a task
        # observing its neighbour), hostname → (monotonic ts, reason); the
        # discovery loop consults this before re-offering a host
        self._failed_hosts: Dict[str, Tuple[float, str]] = {}
        self._svc = _Service(secret, self._handle)
        self.port = self._svc.port

    def _handle(self, msg: dict) -> Any:
        op = msg.get("op")
        if op == "register":
            with self._cv:
                self._registered[msg["index"]] = msg["addresses"]
                self._host_hashes[msg["index"]] = msg.get("host_hash", "")
                self._cv.notify_all()
            return {"ok": True}
        if op == "host_failed":
            with self._cv:
                self._failed_hosts[msg["host"]] = (
                    time.monotonic(), msg.get("reason", ""))
                self._cv.notify_all()
            return {"ok": True}
        raise ValueError(f"unknown op: {op}")

    def failed_hosts(self) -> Dict[str, Tuple[float, str]]:
        """hostname → (monotonic timestamp, reason) of reported failures."""
        with self._cv:
            return dict(self._failed_hosts)

    def wait_for_registration(self, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        with self._cv:
            while len(self._registered) < self.num_hosts:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cv.wait(timeout=remaining):
                    missing = sorted(set(range(self.num_hosts))
                                     - set(self._registered))
                    raise TimeoutError(
                        f"task services {missing} never registered within "
                        f"{timeout}s")

    def task_addresses(self, index: int) -> Dict[str, Tuple[str, int]]:
        with self._cv:
            return dict(self._registered[index])

    def host_hashes(self) -> Dict[int, str]:
        with self._cv:
            return dict(self._host_hashes)

    def ring_probe(self, clients: List[TaskClient]) -> List[str]:
        """Each task probes the NEXT task's addresses (ring), all hosts in
        parallel; the common reachable interface set is the intersection
        (`run.py:246-266`)."""
        from concurrent.futures import ThreadPoolExecutor

        def probe_one(i):
            return set(clients[i].probe(
                self.task_addresses((i + 1) % self.num_hosts)))

        with ThreadPoolExecutor(max_workers=min(32, self.num_hosts)) as ex:
            for i, routed in enumerate(ex.map(probe_one,
                                              range(self.num_hosts))):
                self._routed[i] = routed
        common: Optional[Set[str]] = None
        for i in range(self.num_hosts):
            common = self._routed[i] if common is None \
                else (common & self._routed[i])
        if not common:
            raise RuntimeError(
                "Unable to find a set of common task-to-task communication "
                f"interfaces: {sorted((i, sorted(r)) for i, r in self._routed.items())}")
        return sorted(common)

    def stop(self) -> None:
        self._svc.stop()


class DriverClient:
    def __init__(self, addr: Tuple[str, int], secret: str):
        self._addr = addr
        self._secret = secret

    def register(self, index: int, addresses: Dict[str, Tuple[str, int]],
                 host_hash: str = "", timeout: float = 10.0) -> None:
        call(self._addr, self._secret,
             {"op": "register", "index": index, "addresses": addresses,
              "host_hash": host_hash}, timeout=timeout)

    def notify_host_failure(self, host: str, reason: str = "",
                            timeout: float = 10.0) -> None:
        """Report a dead/unreachable host so the elastic driver blacklists
        it instead of rescheduling onto it."""
        call(self._addr, self._secret,
             {"op": "host_failed", "host": host, "reason": reason},
             timeout=timeout)
