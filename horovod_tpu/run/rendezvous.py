"""HTTP KV rendezvous server + client.

Reference parity: `horovod/run/http/http_server.py` (scoped PUT/GET KV store
used by Gloo rendezvous and the run-func result channel) and
`http/http_client.py`. Here the KV store distributes the `jax.distributed`
coordinator address and ships cloudpickled functions/results for ``run()``
(`run/run.py:769-828`), and will carry the cross-process control-plane
request lists (wire format) in a later milestone.

Security: requests carry an HMAC of the body with a per-job secret
(`run/common/util/secret.py` parity).
"""

from __future__ import annotations

import hashlib
import hmac
import http.server
import os
import secrets as pysecrets
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, Optional, Tuple


def make_secret() -> str:
    return pysecrets.token_hex(16)


def _sign(secret: str, payload: bytes) -> str:
    return hmac.new(secret.encode(), payload, hashlib.sha256).hexdigest()


class KVStoreServer:
    """Threaded HTTP server: PUT /scope/key, GET /scope/key (404 if absent)."""

    def __init__(self, secret: str, host: str = "0.0.0.0", port: int = 0):
        self._secret = secret
        store: Dict[Tuple[str, str], bytes] = {}
        lock = threading.Lock()
        secret_ = secret

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence
                pass

            def _path(self):
                parts = self.path.strip("/").split("/", 1)
                if len(parts) != 2:
                    return None
                return parts[0], parts[1]

            def do_PUT(self):
                key = self._path()
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                sig = self.headers.get("X-HVD-Sig", "")
                if not hmac.compare_digest(sig, _sign(secret_, body)):
                    self.send_response(403)
                    self.end_headers()
                    return
                if key is None:
                    self.send_response(400)
                    self.end_headers()
                    return
                # compare-and-swap: X-HVD-If-Match carries the expected
                # current value hex-encoded, or "absent" for "key must not
                # exist yet". 412 on mismatch — the whole check-and-write is
                # atomic under the store lock, which is what closes the
                # lost-update race two blind writers would have.
                expect = self.headers.get("X-HVD-If-Match")
                with lock:
                    if expect is not None:
                        cur = store.get(key)
                        if expect == "absent":
                            ok = cur is None
                        else:
                            try:
                                ok = cur == bytes.fromhex(expect)
                            except ValueError:
                                ok = False
                        if not ok:
                            self.send_response(412)
                            self.end_headers()
                            return
                    store[key] = body
                self.send_response(200)
                self.end_headers()

            def do_GET(self):
                key = self._path()
                with lock:
                    val = store.get(key) if key else None
                if val is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(val)))
                self.end_headers()
                self.wfile.write(val)

            def do_DELETE(self):  # finalize scope (RendezvousHandler parity)
                key = self._path()
                with lock:
                    if key:
                        store.pop(key, None)
                self.send_response(200)
                self.end_headers()

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


class KVStoreClient:
    # transient-failure policy: the KV server rides on rank 0's host, and an
    # elastic reset (or plain startup ordering) can leave brief windows where
    # connections are refused; retry with bounded exponential backoff instead
    # of failing the whole job on one dropped packet
    RETRIES = 5
    BACKOFF = 0.1  # seconds, doubles per attempt

    def __init__(self, addr: str, secret: str, timeout: float = 30.0):
        self._base = f"http://{addr}"
        self._secret = secret
        self._timeout = timeout

    def _open(self, req):
        delay = self.BACKOFF
        for attempt in range(self.RETRIES):
            try:
                return urllib.request.urlopen(req, timeout=self._timeout)
            except urllib.error.HTTPError:
                # a real server answer (403/404/...) — not transient; note
                # HTTPError subclasses URLError/OSError, so this must come
                # first
                raise
            except (urllib.error.URLError, ConnectionError, OSError,
                    socket.timeout):
                if attempt == self.RETRIES - 1:
                    raise
                time.sleep(delay)
                delay *= 2

    def put(self, scope: str, key: str, value: bytes) -> None:
        req = urllib.request.Request(
            f"{self._base}/{scope}/{key}", data=value, method="PUT",
            headers={"X-HVD-Sig": _sign(self._secret, value)})
        self._open(req).read()

    def put_if(self, scope: str, key: str, value: bytes,
               expected: Optional[bytes]) -> bool:
        """Compare-and-swap: write ``value`` only if the key's current value
        equals ``expected`` (``None`` = key must not exist). Returns whether
        the swap won; ``False`` means another writer got there first."""
        headers = {
            "X-HVD-Sig": _sign(self._secret, value),
            "X-HVD-If-Match":
                "absent" if expected is None else expected.hex(),
        }
        req = urllib.request.Request(
            f"{self._base}/{scope}/{key}", data=value, method="PUT",
            headers=headers)
        try:
            self._open(req).read()
            return True
        except urllib.error.HTTPError as e:
            if e.code == 412:
                return False
            raise

    def get(self, scope: str, key: str) -> Optional[bytes]:
        try:
            req = urllib.request.Request(f"{self._base}/{scope}/{key}")
            return self._open(req).read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def wait(self, scope: str, key: str, timeout: float = 60.0) -> bytes:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = self.get(scope, key)
            if v is not None:
                return v
            time.sleep(0.1)
        raise TimeoutError(f"KV key {scope}/{key} not available "
                           f"after {timeout}s")


def find_free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def local_ip() -> str:
    """Local address to advertise. ``HVD_NICS`` (set by ``hvdrun --nics`` or
    NIC discovery) pins it to a named interface; otherwise a best-effort
    route-based guess (reference NIC discovery is the full driver/task
    probe, `run/run.py:199-269`; single-NIC hosts need only the guess)."""
    import os

    nics = os.environ.get("HVD_NICS")
    if nics:
        from .network import get_local_interfaces

        ifaces = get_local_interfaces()
        for nic in nics.split(","):
            if nic in ifaces:
                return ifaces[nic]
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
