"""Network introspection for the launcher: interface enumeration, routed-
interface probing, and host hashing.

Reference parity: `horovod/run/run.py:199-269` (NIC discovery — every worker
probes the next worker's interfaces in a ring and the driver intersects the
routed sets), `horovod/run/common/util/host_hash.py` (host identity for
colocation), `horovod/run/util/network.py` (interface filtering).
"""

from __future__ import annotations

import hashlib
import os
import socket
import struct
from typing import Dict, Set, Tuple


def get_local_interfaces() -> Dict[str, str]:
    """Interface name → IPv4 address for every UP interface with an
    address (Linux ioctl SIOCGIFADDR; the reference uses psutil)."""
    import fcntl

    out: Dict[str, str] = {}
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        for _, name in socket.if_nameindex():
            try:
                packed = fcntl.ioctl(
                    s.fileno(), 0x8915,  # SIOCGIFADDR
                    struct.pack("256s", name[:15].encode()))
                out[name] = socket.inet_ntoa(packed[20:24])
            except OSError:
                continue  # interface without an IPv4 address
    return out


def filter_routed(ifaces: Dict[str, str]) -> Dict[str, str]:
    """Drop loopback — interfaces 'not really connected to any external
    networks such as lo0 with address 127.0.0.1' (`run/run.py:248-251`)."""
    return {n: a for n, a in ifaces.items()
            if not a.startswith("127.") and n != "lo"}


def probe_reachable(addresses: Dict[str, Tuple[str, int]],
                    timeout: float = 2.0) -> Set[str]:
    """Which of the peer's per-NIC (ip, port) listeners can THIS host reach?
    The ring-probe step of NIC discovery (`run/run.py:246-253`). Probes run
    concurrently so unreachable NICs cost one connect-timeout total, not
    one each."""
    from concurrent.futures import ThreadPoolExecutor

    def try_one(item):
        nic, (ip, port) = item
        try:
            with socket.create_connection((ip, port), timeout=timeout):
                return nic
        except OSError:
            return None

    if not addresses:
        return set()
    with ThreadPoolExecutor(max_workers=min(16, len(addresses))) as ex:
        return {nic for nic in ex.map(try_one, addresses.items())
                if nic is not None}


def host_hash(salt: str = "") -> str:
    """Stable identity of THIS host, for colocating ranks launched through
    indirection (Spark task hosts, containers) where hostname strings may
    not match (`host_hash.py`). ``HOROVOD_HOSTNAME`` overrides."""
    hostname = os.environ.get("HOROVOD_HOSTNAME", socket.gethostname())
    # containers of the same job on one machine share no hostname; CONTAINER
    # ids make them distinct hosts, as in the reference
    container = os.environ.get("CONTAINER_ID", "")
    return hashlib.sha1(
        f"{hostname}-{container}-{salt}".encode()).hexdigest()[:16]


def resolves_local(hostname: str) -> bool:
    """Does this name refer to the local machine? (`run/run.py` local set)"""
    if hostname in ("localhost", "127.0.0.1", socket.gethostname()):
        return True
    try:
        addrs = {ai[4][0] for ai in socket.getaddrinfo(hostname, None)}
    except OSError:
        return False
    local = set(get_local_interfaces().values()) | {"127.0.0.1", "::1"}
    return bool(addrs & local)
