"""Process fan-out: local subprocesses or ssh, with per-rank stream prefixing
and first-failure kill.

Reference parity: `horovod/run/common/util/safe_shell_exec.py` (middleman fork
killing the process tree on parent death, stream prefixing ``[rank]<stdout>``)
and `horovod/run/gloo_run.py:142-259` (threaded ssh fan-out, first-failure
termination). Local processes run in their own process group so the whole tree
can be killed."""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence


# Env vars whose values must never appear on a remote command line (argv is
# world-readable via `ps` on the remote host) — they travel over ssh stdin.
SENSITIVE_ENV = ("HVD_SECRET",)


class RankProcess:
    def __init__(self, rank: int, cmd: Sequence[str], env: Dict[str, str],
                 hostname: Optional[str] = None, ssh_port: int = 22,
                 output_file: Optional[str] = None,
                 is_local: Optional[bool] = None):
        self.rank = rank
        self.returncode: Optional[int] = None
        self._output_file = output_file
        if is_local is None:
            # fallback when the caller didn't already classify the host
            # (launch() passes its resolves_local verdict so both layers
            # agree on what counts as local)
            is_local = hostname in (None, "localhost", "127.0.0.1")
        if is_local:
            full_env = dict(os.environ)
            full_env.update(env)
            self._proc = subprocess.Popen(
                list(cmd), env=full_env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, start_new_session=True)
        else:
            # ssh fan-out: env inlined into the remote command
            # (gloo_run.py:207-237) — except secrets, which are read from
            # stdin so they never show up in `ps` output
            secret_vars = [k for k in SENSITIVE_ENV if k in env]
            envstr = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items()
                              if k not in secret_vars)
            prefix = "".join(f"IFS= read -r {k} && export {k} && "
                             for k in secret_vars)
            remote = f"{prefix}cd {shlex.quote(os.getcwd())} && " \
                f"env {envstr} " + " ".join(shlex.quote(c) for c in cmd)
            self._proc = subprocess.Popen(
                ["ssh", "-p", str(ssh_port),
                 "-o", "StrictHostKeyChecking=no", hostname, remote],
                stdin=subprocess.PIPE if secret_vars else None,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                start_new_session=True)
            if secret_vars:
                for k in secret_vars:
                    self._proc.stdin.write((env[k] + "\n").encode())
                self._proc.stdin.flush()
                # deliver EOF: commands that drain stdin must not block on
                # the launcher holding the pipe open
                self._proc.stdin.close()
        self._pump = threading.Thread(target=self._pump_output, daemon=True)
        self._pump.start()

    def _pump_output(self):
        f = open(self._output_file, "w") if self._output_file else None
        try:
            for raw in self._proc.stdout:
                line = raw.decode("utf-8", "replace")
                sys.stdout.write(f"[{self.rank}]<stdout>:{line}")
                sys.stdout.flush()
                if f:
                    f.write(line)
        finally:
            if f:
                f.close()

    def poll(self) -> Optional[int]:
        self.returncode = self._proc.poll()
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        self.returncode = self._proc.wait(timeout)
        return self.returncode

    def terminate(self):
        try:
            os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass


def wait_all(procs: List[RankProcess], timeout: Optional[float] = None) -> int:
    """Wait for all ranks; on first nonzero exit, kill the rest
    (first-failure semantics, `gloo_run.py:253-259`). Returns worst code."""
    deadline = time.monotonic() + timeout if timeout else None
    pending = list(procs)
    worst = 0
    while pending:
        for p in list(pending):
            rc = p.poll()
            if rc is not None:
                pending.remove(p)
                if rc != 0:
                    worst = worst or rc
                    for q in pending:
                        q.terminate()
        if deadline and time.monotonic() > deadline:
            for q in pending:
                q.terminate()
            raise TimeoutError("ranks did not finish before timeout")
        time.sleep(0.05)
    return worst
