"""Per-rank worker for the run-func API (`horovod/run/run_task.py` parity):
pull the pickled function from the launcher's KV store, init the framework,
execute, post the result."""

from __future__ import annotations

import os
import pickle
import sys
import traceback


def main() -> int:
    addr = os.environ["HVD_KV_ADDR"]
    secret = os.environ["HVD_SECRET"]
    rank = int(os.environ.get("HVD_PROCESS_ID", "0"))

    from .rendezvous import KVStoreClient

    client = KVStoreClient(addr, secret)
    blob = client.wait("runfunc", "fn", timeout=60.0)

    try:
        # unpickle inside the guard: a function that can't deserialize
        # (e.g. __main__-defined without cloudpickle) must report its
        # traceback, not silently "produce no result"
        fn, args, kwargs = pickle.loads(blob)
        import horovod_tpu as hvd

        hvd.init()
        result = fn(*args, **kwargs)
        payload = pickle.dumps((True, result))
    except BaseException:
        payload = pickle.dumps((False, traceback.format_exc()))
        client.put("result", str(rank), payload)
        return 1
    client.put("result", str(rank), payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
