"""Launcher package: CLI (`hvdrun`, `launcher.py`) and the programmatic
func API, re-exported so ``from horovod_tpu.run import run`` mirrors the
reference's `from horovod.run import run` (`run/run.py:863-947`)."""

from .api import run  # noqa: F401
