"""``horovod_tpu.run.run(fn, ...)`` — programmatic launch of a function.

Reference parity: `horovod/run/run.py:769-828, 863-947` — the function is
cloudpickled, shipped through the launcher's KV store, executed by every rank
(`run_task.py`), and per-rank results are returned in rank order."""

from __future__ import annotations

import pickle
import sys
from typing import Any, Callable, List, Optional

from . import launcher, rendezvous


def _dumps(obj) -> bytes:
    try:
        import cloudpickle

        return cloudpickle.dumps(obj)
    except ImportError:  # stdlib pickle handles module-level functions
        return pickle.dumps(obj)


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        np: int = 1, hosts: Optional[str] = None,
        hostfile: Optional[str] = None, ssh_port: int = 22,
        env: Optional[dict] = None, start_timeout: float = 600.0,
        verbose: bool = False) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``np`` ranks; returns per-rank results."""
    payload = _dumps((fn, tuple(args), dict(kwargs or {})))

    secret = rendezvous.make_secret()
    kv = rendezvous.KVStoreServer(secret).start()
    ip = rendezvous.local_ip() if hosts or hostfile else "127.0.0.1"
    kv_addr = f"{ip}:{kv.port}"
    client = rendezvous.KVStoreClient(kv_addr, secret)
    client.put("runfunc", "fn", payload)

    cmd = [sys.executable, "-m", "horovod_tpu.run.task"]
    try:
        rc = launcher.launch(
            np, cmd, hosts=hosts, hostfile=hostfile, ssh_port=ssh_port,
            knob_env=dict(env or {}), start_timeout=start_timeout,
            extra_env={"HVD_KV_ADDR": kv_addr, "HVD_SECRET": secret})
        results = []
        for r in range(np):
            blob = client.get("result", str(r))
            if blob is None:
                raise RuntimeError(
                    f"rank {r} produced no result (exit code {rc})")
            ok, value = pickle.loads(blob)
            if not ok:
                raise RuntimeError(f"rank {r} failed: {value}")
            results.append(value)
        return results
    finally:
        kv.stop()
