"""Host parsing and rank allocation.

Reference parity: `horovod/run/run.py:694-709` (``host:slots`` parsing,
hostfile) and `horovod/run/gloo_run.py:53-111` (``_allocate``: global rank,
LOCAL rank within a host, CROSS rank across hosts). The LOCAL/CROSS split maps
to ICI/DCN domains on TPU (SURVEY §5)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List


@dataclass
class HostSlots:
    hostname: str
    slots: int


@dataclass
class RankInfo:
    rank: int
    size: int
    hostname: str
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int


def parse_hosts(hosts: str) -> List[HostSlots]:
    """``"h1:4,h2:4"`` → [HostSlots]; bare hostname means 1 slot."""
    out = []
    for part in hosts.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            out.append(HostSlots(name, int(slots)))
        else:
            out.append(HostSlots(part, 1))
    return out


def parse_hostfile(path: str) -> List[HostSlots]:
    """One ``host slots=N`` (mpirun style) or ``host:N`` per line."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            if "slots=" in line:
                name, _, rest = line.partition(" ")
                slots = int(rest.split("slots=")[1].split()[0])
                out.append(HostSlots(name.strip(), slots))
            else:
                out.extend(parse_hosts(line))
    return out


def diff_hosts(old: List[HostSlots], new: List[HostSlots]):
    """Membership delta between two discovery snapshots: hostnames added and
    removed (slot-count changes on a surviving host count as neither — the
    elastic driver re-reads slots when it spawns there). Used by the elastic
    driver's discovery loop (reference `run/elastic/discovery.py`
    HostManager.update_available_hosts)."""
    old_names = {h.hostname for h in old}
    new_names = {h.hostname for h in new}
    added = [h.hostname for h in new if h.hostname not in old_names]
    removed = [h.hostname for h in old if h.hostname not in new_names]
    return added, removed


def allocate(hosts: List[HostSlots], np: int) -> List[RankInfo]:
    """Assign np ranks to hosts in declaration order (gloo_run._allocate)."""
    total = sum(h.slots for h in hosts)
    if np > total:
        raise ValueError(
            f"requested -np {np} exceeds total available slots {total} "
            f"on hosts {[f'{h.hostname}:{h.slots}' for h in hosts]}")
    ranks: List[RankInfo] = []
    rank = 0
    used_hosts = []
    for h in hosts:
        if rank >= np:
            break
        take = min(h.slots, np - rank)
        used_hosts.append((h.hostname, take))
        rank += take
    # cross set for local_rank j = ranks with local_rank j across hosts
    # (exact reference semantics, gloo_run.py:87-111)
    rank = 0
    for host_idx, (hostname, take) in enumerate(used_hosts):
        for local_rank in range(take):
            cross_rank = sum(1 for hh, tt in used_hosts[:host_idx]
                             if tt > local_rank)
            cross_size = sum(1 for hh, tt in used_hosts if tt > local_rank)
            ranks.append(RankInfo(
                rank=rank, size=np, hostname=hostname,
                local_rank=local_rank, local_size=take,
                cross_rank=cross_rank, cross_size=cross_size))
            rank += 1
    return ranks
