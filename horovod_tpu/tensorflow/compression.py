"""TF-surface gradient compression (`horovod/tensorflow/compression.py`
parity): ``Compression.none`` / ``Compression.fp16`` compressor pairs, plus a
TPU-native ``bf16``."""

from __future__ import annotations


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    _wire_dtype_name = None

    @classmethod
    def compress(cls, tensor):
        import tensorflow as tf

        if tensor.dtype.is_floating:
            wire = getattr(tf, cls._wire_dtype_name)
            return tf.cast(tensor, wire), tensor.dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is None:
            return tensor
        import tensorflow as tf

        return tf.cast(tensor, ctx)


class FP16Compressor(_CastCompressor):
    _wire_dtype_name = "float16"


class BF16Compressor(_CastCompressor):
    """TPU-native 16-bit wire format (fp32 exponent range)."""

    _wire_dtype_name = "bfloat16"


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
