"""Shared collective gradient formulas — ONE implementation for both the
eager surface (`tensorflow/__init__.py`, wrapped in ``tf.custom_gradient``)
and the graph surface (`tensorflow/graph.py`).

Reference parity: the gradient registrations in
`horovod/tensorflow/mpi_ops.py:107-198` —
  allreduce  → allreduce of the upstream gradient with the same op (:107-118)
  allgather  → sum-allreduce of the upstream gradient, then slice this rank's
               segment at the offset given by the gathered per-rank dim0
               sizes (:140-163)
  broadcast  → sum-allreduce, zeroed on non-root ranks (:183-198)
  alltoall   → alltoall of the upstream gradient (self-adjoint equal-split;
               the ragged form re-exchanges with splits = received_splits)

Each formula takes the collective *callables* to use — the eager caller
passes its engine-bridge functions, the graph caller passes its py_function
node builders — so the math lives in exactly one place while each mode keeps
its own transport.
"""

from __future__ import annotations

import tensorflow as tf

from ..basics import Average, Sum


def allreduce_grad(dy, op, allreduce_fn):
    """d(allreduce_op(x))/dx applied to dy: the same reduction of dy.
    Adasum keeps the reference's registered sum-allreduce gradient (its
    combine rule has no closed-form adjoint)."""
    return allreduce_fn(dy, op if op in (Sum, Average) else Sum)


def allgather_grad(dy, x, rank, allreduce_fn, allgather_fn):
    """d(allgather(x))/dx applied to dy: sum-allreduce dy, slice this rank's
    rows back out. ``x`` is the forward input (its dim0 sets the slice
    length; per-rank dim0s may be ragged, so they are allgathered)."""
    g = allreduce_fn(dy, Sum)
    d0 = tf.shape(x)[0]
    sizes = tf.stop_gradient(allgather_fn(tf.reshape(d0, [1])))
    offset = tf.reduce_sum(sizes[:rank])
    begin = tf.concat([[offset], tf.zeros([tf.rank(x) - 1], tf.int32)],
                      axis=0)
    return tf.slice(g, begin, tf.shape(x))


def broadcast_grad(dy, root_rank, rank, allreduce_fn):
    """d(broadcast(x, root))/dx applied to dy: every rank's output is root's
    input, so root receives the cross-rank gradient sum and everyone else
    zero."""
    g = allreduce_fn(dy, Sum)
    return g if rank == root_rank else g * 0


def alltoall_grad(dy, alltoall_fn):
    """Equal-split alltoall is its own adjoint (a permutation of blocks)."""
    return alltoall_fn(dy)


def alltoallv_grad(dy, received_splits, alltoallv_fn):
    """Ragged adjoint: re-exchange dy with splits = the forward's received
    splits, returning each gradient chunk to the rank that sent the
    corresponding rows. ``alltoallv_fn(t, splits)`` must return
    ``(output, received_splits)``; only the output is the gradient."""
    dx, _ = alltoallv_fn(dy, received_splits)
    return dx
