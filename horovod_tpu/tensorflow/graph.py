"""Graph-mode (``tf.function``) collectives on the shared engine.

Reference parity: the graph path of `horovod/tensorflow/mpi_ops.cc` — the
`HorovodAllreduceOp` / `HorovodAllgatherOp` / `HorovodBroadcastOp`
AsyncOpKernels (:286-484) — plus the gradient registrations in
`horovod/tensorflow/mpi_ops.py:107-198`.

Design (TPU-native): instead of custom C++ kernels compiled against TF's ABI,
each collective lowers to a pair of ``tf.py_function`` nodes driving the
shared background engine — a *start* node that enqueues the named tensor and
returns the async handle, and a *sync* node that blocks on the handle and
yields the negotiated result. This keeps the reference's async overlap (all
starts can execute before any sync completes; TF dataflow schedules them the
way the AsyncOpKernel enqueues interleave) and the engine-side semantics:
negotiation, fusion, response cache, stall detection and timeline spans all
apply to graph ops exactly as to eager ones.

Cross-rank submission order: start nodes carry a control-dependency chain in
trace order. Two data-independent py_function nodes may otherwise execute in
any order, and ranks must submit tensors in a consistent order for
program-order negotiation (the reference gets this from its single tensor
queue; the coordinated controller doesn't need it, but the chain makes the
uncoordinated SPMD mode safe too). Sync nodes are NOT chained — each depends
only on its own start, so collectives still overlap and fuse.

Gradients (`tensorflow/mpi_ops.py`):
  allreduce  → allreduce of the upstream gradient (:107-118)
  allgather  → sum-allreduce, then slice this rank's segment using gathered
               dim0 sizes (:140-163)
  broadcast  → sum-allreduce, zeroed on non-root ranks (:183-198)
  alltoall   → alltoall of the upstream gradient (engine extension; the
               equal-split exchange is its own adjoint)

Rank binding: the engine rank is resolved at TRACE time and re-bound inside
each py_function body — bodies run on TF executor threads, not the thread
that called the function, so the in-process cluster rig's thread-local rank
would otherwise be lost. One-rank-per-process deployments are unaffected; the
in-process rig must trace per-rank ``tf.function`` objects (define the
function inside the per-rank body, as the tests do).

Thread-pool sizing: sync nodes BLOCK a TF inter-op thread until the
collective completes. Per process this cannot deadlock — by the time any
sync runs, its start (and, via the chain, every earlier start) has executed,
so the tensor is already submitted on every rank and will complete. But the
in-process cluster rig shares ONE TF runtime between ranks: rank A's blocked
syncs can starve rank B's starts if the inter-op pool is too small (e.g. a
single-core box defaults to 1 thread). The test conftest sets
``TF_NUM_INTEROP_THREADS`` accordingly; real deployments (one rank per
process) need nothing.
"""

from __future__ import annotations

import re

import numpy as np
import tensorflow as tf

from .. import basics
from ..basics import Adasum, Average, Sum
from ..ops import collective_ops as _ops
from . import _grads
from .compression import Compression


def _sanitize(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_.]", "_", name)


def _unserialize_pyfunc_nodes() -> None:
    """Let engine nodes overlap: exempt py_function ops from tf.function's
    automatic control-dependency serialization.

    tf.function chains every stateful op in creation order, which would
    serialize sync(A) → start(B) — collective B could not even *submit*
    until A completed, destroying the negotiation overlap the reference's
    AsyncOpKernels provide (`tensorflow/mpi_ops.cc:286-345`). TF's own
    collectives escape via the same mechanism used here
    (`auto_control_deps.MUST_RUN_ORDER_INSENSITIVE_STATEFUL_OPS`, the list
    holding CrossReplicaSum/CollectivePermute): ops on it still always run
    (no pruning) but are not serialized against other stateful ops.

    Cross-rank submission determinism does not depend on ACD — the start
    halves are explicitly chained per graph (`_start`). Consequence for
    users: two of THEIR py_functions inside one compiled step are no longer
    implicitly ordered against each other; order-critical side effects need
    an explicit ``tf.control_dependencies`` (set ``HVD_TF_SERIALIZE_PYFUNC=1``
    to restore stock serialization and give up collective overlap)."""
    from ..utils.env import env_on

    if env_on("HVD_TF_SERIALIZE_PYFUNC"):
        return
    try:
        from tensorflow.python.framework import auto_control_deps as _acd

        # list in some TF versions, frozenset in others — rebind either way
        _acd.MUST_RUN_ORDER_INSENSITIVE_STATEFUL_OPS = frozenset(
            set(_acd.MUST_RUN_ORDER_INSENSITIVE_STATEFUL_OPS)
            | {"EagerPyFunc", "PyFunc", "PyFuncStateless"})
    except Exception:  # private module moved: keep correctness, lose overlap
        pass


_unserialize_pyfunc_nodes()


def _next_trace_index() -> int:
    """Per-graph trace-order counter. All ranks trace the same program, so
    counter order — and every name derived from it — is rank-deterministic."""
    g = tf.compat.v1.get_default_graph()
    n = getattr(g, "_hvd_tpu_name_counter", 0)
    g._hvd_tpu_name_counter = n + 1
    return n


def _graph_name(prefix: str, tensor) -> str:
    """Engine name for an unnamed graph collective: the symbolic tensor name
    (deterministic given the same program, like the reference's
    `tensorflow/mpi_ops.py:102-103`) plus the trace-order counter — two
    unnamed collectives on the SAME tensor in one step must not collide on
    the engine's in-flight duplicate-name check."""
    try:
        tn = tensor.name
    except Exception:
        tn = None
    base = f"{prefix}.{_sanitize(tn)}" if tn else f"{prefix}.graph"
    return f"{base}.{_next_trace_index()}"


def _derived_name(name: str, kind: str) -> str:
    """Engine name for a collective derived from another node's gradient:
    tracing one forward collective's gradient twice (two ``tape.gradient``
    calls over a shared forward, or grad-of-grad) must yield distinct engine
    names, or the in-flight duplicate-name check rejects the second at
    runtime."""
    return f"{name}.{kind}.{_next_trace_index()}"


def _start(py_start, *tensors):
    """Engine-start node: ``py_start(*np_arrays) -> handle``. Ordered after
    the previous start in this graph via a control dependency (trace order =
    submission order on every rank)."""
    r = basics.rank()

    def body(*xs):
        basics.set_thread_rank(r)
        return np.int64(py_start(*[x.numpy() for x in xs]))

    g = tf.compat.v1.get_default_graph()
    prev = getattr(g, "_hvd_tpu_last_start", None)
    with tf.control_dependencies([prev] if prev is not None else []):
        h = tf.py_function(body, list(tensors), Tout=tf.int64)
    g._hvd_tpu_last_start = h
    return h


def _sync(handle, dtype, shape):
    """Engine-sync node: blocks on the handle, yields the result. Raises
    HorovodInternalError through the py_function on negotiation/execution
    failure (surfaced by TF as an op error, like the AsyncOpKernel's
    non-OK done status)."""
    r = basics.rank()

    def body(h):
        basics.set_thread_rank(r)
        return np.asarray(_ops.synchronize(int(h.numpy())))

    out = tf.py_function(body, [handle], Tout=dtype)
    out.set_shape(shape)
    return out


def _allreduce_raw(tensor, name, op=Sum, prescale=1.0, postscale=1.0):
    """Raw engine allreduce node (no in-framework division — Average division
    happens in the public wrapper, `tensorflow/__init__.py:117`)."""

    @tf.custom_gradient
    def fwd(x):
        h = _start(lambda a: _ops.allreduce_async(
            a, name=name, op=op, prescale_factor=prescale,
            postscale_factor=postscale), x)
        y = _sync(h, x.dtype, x.shape)

        def grad(dy):
            # adjoint of y = post*reduce(pre*x) is the same scaled reduction
            # of dy (scalars commute into the sum); formula shared with the
            # eager surface (`_grads.allreduce_grad`)
            return _grads.allreduce_grad(
                dy, op,
                lambda d, o: _allreduce_raw(d, _derived_name(name, "grad"),
                                            op=o, prescale=prescale,
                                            postscale=postscale))

        return y, grad

    return fwd(tensor)


def _divide_by_size(t):
    """Average division matching the engine's eager kernel: floor-division
    for integer dtypes (`runtime/executor.py` integer Average), true
    division otherwise — graph and eager must return the same dtype."""
    div = tf.cast(basics.size(), t.dtype)
    return t // div if t.dtype.is_integer else t / div


def allreduce(tensor, name=None, op=Average, compression=Compression.none,
              prescale_factor=1.0, postscale_factor=1.0):
    """Graph-mode allreduce; IndexedSlices take the two-allgather sparse path
    (`tensorflow/__init__.py:75-91`)."""
    if isinstance(tensor, tf.IndexedSlices):
        if op == Adasum:
            raise NotImplementedError(
                "The Adasum reduction does not currently support sparse "
                "tensors. As a workaround please pass sparse_as_dense=True "
                "to DistributedOptimizer")
        name = _graph_name("sparse_allreduce", tensor.values) \
            if name is None else name
        values = allgather(tensor.values, name=f"{name}.values")
        indices = allgather(tensor.indices, name=f"{name}.indices")
        if op == Average:
            values = _divide_by_size(values)
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    name = _graph_name("allreduce", tensor) if name is None else name
    comp, ctx = compression.compress(tensor)
    raw = _allreduce_raw(comp, name, op=Sum if op == Average else op,
                         prescale=prescale_factor, postscale=postscale_factor)
    out = compression.decompress(raw, ctx)
    if op == Average:
        out = _divide_by_size(out)
    return out


def allgather(tensor, name=None):
    """Graph-mode allgather (ragged dim0 negotiated by the engine). Gradient
    per `mpi_ops.py:140-163`: sum-allreduce dy, slice this rank's segment at
    the offset given by the gathered per-rank dim0 sizes."""
    name = _graph_name("allgather", tensor) if name is None else name

    @tf.custom_gradient
    def fwd(x):
        h = _start(lambda a: _ops.allgather_async(a, name=name), x)
        y = _sync(h, x.dtype, tf.TensorShape([None]).concatenate(x.shape[1:]))

        def grad(dy):
            # formula shared with the eager surface (`_grads.allgather_grad`)
            return _grads.allgather_grad(
                dy, x, basics.rank(),
                lambda d, o: _allreduce_raw(d, _derived_name(name, "grad"),
                                            op=o),
                lambda d: allgather(d,
                                    name=_derived_name(name, "grad_sizes")))

        return y, grad

    return fwd(tensor)


def broadcast(tensor, root_rank, name=None):
    """Graph-mode broadcast. Gradient per `mpi_ops.py:183-198`: sum-allreduce,
    zeroed on non-root ranks."""
    name = _graph_name("broadcast", tensor) if name is None else name

    @tf.custom_gradient
    def fwd(x):
        h = _start(lambda a: _ops.broadcast_async(a, root_rank, name=name), x)
        y = _sync(h, x.dtype, x.shape)

        def grad(dy):
            # formula shared with the eager surface (`_grads.broadcast_grad`)
            return _grads.broadcast_grad(
                dy, root_rank, basics.rank(),
                lambda d, o: _allreduce_raw(d, _derived_name(name, "grad"),
                                            op=o))

        return y, grad

    return fwd(tensor)


def alltoall(tensor, splits=None, name=None):
    """Graph-mode alltoall. Equal-split (``splits=None``) is
    shape-preserving and self-adjoint, so the gradient is an alltoall of dy.

    With ``splits`` the ragged alltoallv form works under ``tf.function``
    too: the coordinator negotiates the full world×world send matrix
    (`runtime/coordinator.py`), so at RUN time the sync node knows exactly
    how many rows arrived — the traced output carries a dynamic dim 0 plus
    a concrete ``received_splits`` tensor (later-horovod's
    ``(output, received_splits)`` return shape). ``splits`` may be a Python
    sequence or a traced int tensor; values are consumed host-side inside
    the start node. Gradient: re-exchange dy with ``received_splits``
    (`_grads.alltoallv_grad`)."""
    name = _graph_name("alltoall", tensor) if name is None else name

    if splits is None:
        @tf.custom_gradient
        def fwd(x):
            h = _start(lambda a: _ops.alltoall_async(a, name=name), x)
            y = _sync(h, x.dtype, x.shape)

            def grad(dy):
                return _grads.alltoall_grad(
                    dy, lambda d: alltoall(d,
                                           name=_derived_name(name, "grad")))

            return y, grad

        return fwd(tensor)

    world = basics.size()
    r = basics.rank()

    @tf.custom_gradient
    def fwdv(x, sp):
        h = _start(
            lambda xx, ss: _ops.alltoall_async(
                xx, splits=[int(v) for v in ss.reshape(-1)], name=name),
            x, sp)

        def sync_body(hh):
            basics.set_thread_rank(r)
            res = _ops.synchronize(int(hh.numpy()))
            return (np.asarray(res.output),
                    np.asarray(res.received_splits, np.int32))

        y, rs = tf.py_function(sync_body, [h], Tout=[x.dtype, tf.int32])
        y.set_shape(tf.TensorShape([None]).concatenate(x.shape[1:]))
        rs.set_shape([world])

        def grad(dy, unused_drs):
            dx = _grads.alltoallv_grad(
                dy, rs,
                lambda d, s: alltoall(d, splits=s,
                                      name=_derived_name(name, "grad")))
            return dx, None

        return (y, rs), grad

    sp_t = tf.convert_to_tensor(splits)
    if sp_t.dtype != tf.int32:  # accept int64 splits tensors like the
        sp_t = tf.cast(sp_t, tf.int32)  # eager/torch surfaces do
    return fwdv(tensor, sp_t)
