"""``horovod_tpu.tensorflow.keras`` — `horovod/tensorflow/keras` parity.

Re-exports the eager TF surface (DistributedOptimizer, collectives,
basics) plus the tf.keras ``model.fit`` callbacks, so a reference script's

    import horovod.tensorflow.keras as hvd

port is an import-line change.
"""

from .. import (  # noqa: F401
    Adasum,
    Average,
    Compression,
    DistributedAdasumOptimizer,
    Sum,
    allgather,
    allreduce,
    broadcast,
    broadcast_variables,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from .. import _reduce_grads_and_vars
from . import callbacks  # noqa: F401


def _make_distributed_class(base_cls, compression, op, sparse_as_dense):
    """Dynamic subclass of a Keras optimizer class whose ``apply`` reduces
    gradients first (the reference's `_keras/__init__.py:20-33` technique).
    Shared by the wrap factory and ``load_model``'s custom_objects."""
    if not hasattr(base_cls, "apply"):
        # Keras 2 optimizers have no apply() funnel — the override below
        # would be dead code and training would silently run unsynchronized
        raise RuntimeError(
            "the distributed tf.keras optimizer requires Keras 3 "
            "(tf >= 2.16); on older TF use horovod_tpu.tensorflow."
            "DistributedOptimizer with a manual train loop "
            f"(got {base_cls.__name__} without an apply() method)")
    hvd_kw = dict(compression=compression, op=op,
                  sparse_as_dense=sparse_as_dense)

    class _Distributed(base_cls):
        def apply(self, grads, trainable_variables=None, **kwargs):
            # cover BOTH call shapes: explicit variables and the stored-
            # variables form (opt.apply(grads)) — skipping reduction for
            # the latter would silently diverge the replicas
            tvars = trainable_variables
            if tvars is None:
                tvars = list(getattr(self, "_trainable_variables", None)
                             or [])
                if not tvars:
                    raise RuntimeError(
                        "optimizer.apply(grads) before build(): no "
                        "variables to reduce against")
            reduced = _reduce_grads_and_vars(
                list(zip(grads, tvars)), **hvd_kw)
            grads2 = [g for g, _ in reduced]
            if trainable_variables is None:
                return super().apply(grads2, **kwargs)
            return super().apply(grads2, trainable_variables, **kwargs)

    _Distributed.__name__ = "Distributed" + base_cls.__name__
    return _Distributed


def _unconstructible_stub(name, err):
    """Placeholder for a Distributed<Name> class that could not be built
    (Keras-2 optimizers without the apply() funnel): deserializing a model
    that actually references it re-raises the ORIGINAL, actionable error."""
    def _raise(cls, *a, **k):
        raise RuntimeError(
            f"the saved model references Distributed{name}, which cannot be "
            f"reconstructed here: {err}") from err
    return type("Distributed" + name, (),
                {"__init__": _raise, "from_config": classmethod(_raise)})


def load_model(path, custom_optimizers=None, custom_objects=None,
               compression=Compression.none, op: int = Average,
               sparse_as_dense: bool = False):
    """Load a tf.keras model saved with a DistributedOptimizer, re-wrapping
    the deserialized optimizer (`keras/__init__.py:111-127` parity): the
    saved config references the dynamic ``Distributed<Name>`` class, which
    is re-created here for every standard Keras optimizer — plus any
    user-defined classes passed via ``custom_optimizers`` (the reference's
    parameter) — and passed as custom_objects.

    The wrap settings (``compression``/``op``/``sparse_as_dense``) are NOT
    stored in the saved config (it is the base optimizer's config, as in
    the reference); a model trained with non-default settings must re-pass
    them here or training resumes with Average/no-compression."""
    import tensorflow as tf

    customs = dict(custom_objects or {})
    # user classes FIRST: setdefault is first-write-wins, and a custom
    # subclass shadowing a builtin name must take precedence (reference
    # custom_optimizers semantics)
    bases = list(custom_optimizers or [])
    bases += [getattr(tf.keras.optimizers, name)
              for name in dir(tf.keras.optimizers)]
    for base in bases:
        if isinstance(base, type) and issubclass(
                base, tf.keras.optimizers.Optimizer) \
                and base.__name__[:1].isupper():
            try:
                dist = _make_distributed_class(base, compression, op,
                                               sparse_as_dense)
            except Exception as e:
                # Only classes the saved model actually references must be
                # constructible: on Keras 2 some builtin optimizers lack the
                # apply() funnel and _make_distributed_class refuses them —
                # that must not break load_model for models that never used
                # them. An explicitly passed custom class still raises, and
                # a model that DOES reference the broken class gets the
                # original error (not Keras's opaque "Unknown optimizer")
                # via a stub that re-raises on construction.
                if base in (custom_optimizers or ()):
                    raise
                dist = _unconstructible_stub(base.__name__, e)
            customs.setdefault("Distributed" + base.__name__, dist)
    return tf.keras.models.load_model(path, custom_objects=customs)


def DistributedOptimizer(optimizer, compression=Compression.none,
                         op: int = Average, sparse_as_dense: bool = False):
    """Keras-compatible distributed optimizer: a dynamic SUBCLASS of the
    wrapped optimizer's class (the reference's `_keras/__init__.py:20-33`
    technique), so ``model.compile(optimizer=...)`` accepts it and
    ``model.fit`` routes every update through the gradient allreduce.

    Gradient reduction happens in ``apply`` (Keras 3's single funnel —
    ``apply_gradients`` delegates to it), so both direct calls and the
    fit() train step are covered — including compiled fit (no
    ``run_eagerly``), where the reduction lowers to the graph-mode engine
    path. Pass ``jit_compile=False`` to ``model.compile`` explicitly:
    engine collectives are host ops and cannot be XLA-compiled (the same
    constraint the reference's custom C++ ops have), and Keras's default
    ``jit_compile="auto"`` resolves to True on machines with a non-CPU
    device.
    """
    if op == Adasum:
        raise NotImplementedError(
            "op=Adasum inside model.compile is not supported; use the "
            "eager DistributedAdasumOptimizer with a manual train loop")
    cls = _make_distributed_class(optimizer.__class__, compression, op,
                                  sparse_as_dense)
    return cls.from_config(optimizer.get_config())
