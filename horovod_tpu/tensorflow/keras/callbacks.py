"""tf.keras callbacks for ``model.fit`` — `horovod/tensorflow/keras/
callbacks.py` parity on the eager TF surface.

The flax-side training-loop callbacks live in ``horovod_tpu.callbacks``;
these subclasses adapt the same behaviors to the Keras callback protocol so
a reference ``model.fit(callbacks=[hvd.callbacks.* ...])`` script ports
directly.
"""

from __future__ import annotations

import numbers
from typing import Optional

import numpy as np

from .. import (Average, _require_tf, allreduce, broadcast_variables, rank,
                size)

try:
    import tensorflow as _tf

    _Base = _tf.keras.callbacks.Callback
except ImportError:  # keep the parent package's import-without-TF promise
    _Base = object


class BroadcastGlobalVariablesCallback(_Base):
    """Broadcast model + optimizer variables from ``root_rank`` after the
    first batch (so optimizer slot variables exist,
    `_keras/callbacks.py:20-43`)."""

    def __init__(self, root_rank: int = 0):
        _require_tf()
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_train_begin(self, logs=None):
        # fail early and clearly instead of XLA's "unsupported operation
        # EagerPyFunc" mid-fit: engine collectives are host ops, so the fit
        # train step must not be XLA-jitted (same constraint as the
        # reference's custom C++ ops)
        if getattr(self.model, "jit_compile", False) is True:
            raise RuntimeError(
                "this model's train step is XLA-jitted (jit_compile resolved "
                "to True — Keras's default 'auto' enables XLA when a non-CPU "
                "device is visible), which is incompatible with horovod_tpu's "
                "engine collectives (host ops are not XLA-compilable); pass "
                "jit_compile=False to model.compile")

    def on_batch_end(self, batch, logs=None):
        if self._done:
            return
        broadcast_variables(self.model.variables, root_rank=self.root_rank)
        opt_vars = getattr(self.model.optimizer, "variables", None)
        if opt_vars is not None:
            opt_vars = opt_vars() if callable(opt_vars) else opt_vars
            broadcast_variables(list(opt_vars), root_rank=self.root_rank)
        self._done = True


class MetricAverageCallback(_Base):
    """Average epoch metrics over ranks before they reach other callbacks
    (checkpointers, early stopping — `_keras/callbacks.py:46-84`)."""

    def __init__(self):
        _require_tf()
        super().__init__()

    def on_epoch_end(self, epoch, logs=None):
        if logs and size() > 1:
            for k, v in list(logs.items()):
                # numbers.Real covers python floats AND numpy scalars
                # (np.float32 is not an int/float subclass)
                if isinstance(v, numbers.Real):
                    logs[k] = float(allreduce(np.float64(v),
                                              name=f"metric.{k}",
                                              op=Average))


class LearningRateScheduleCallback(_Base):
    """Multiply the optimizer LR by ``multiplier(epoch)`` within
    [start_epoch, end_epoch) (`_keras/callbacks.py:87-134`). With
    ``staircase=False`` the multiplier sees fractional epochs computed from
    Keras ``params['steps']``."""

    def __init__(self, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 initial_lr: Optional[float] = None):
        _require_tf()
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.initial_lr = initial_lr
        self._mult = multiplier if callable(multiplier) \
            else (lambda epoch: multiplier)
        self._current_epoch = 0

    def _in_range(self, epoch):
        return (epoch >= self.start_epoch
                and (self.end_epoch is None or epoch < self.end_epoch))

    def _lr_var(self):
        opt = self.model.optimizer
        var = getattr(opt, "learning_rate", None)
        return opt.lr if var is None else var

    def _set_lr(self, value):
        import tensorflow as tf

        var = self._lr_var()
        if isinstance(var, tf.Variable):
            var.assign(value)
        else:  # plain attribute / Keras 3 property
            self.model.optimizer.learning_rate = value

    def on_train_begin(self, logs=None):
        if self.initial_lr is None:
            var = self._lr_var()
            try:
                self.initial_lr = float(var)
            except (TypeError, ValueError):
                raise ValueError(
                    "the optimizer's learning_rate is a schedule object "
                    f"({type(var).__name__}); LR schedule callbacks need a "
                    "scalar learning rate — pass the base value directly "
                    "to the optimizer") from None

    def on_epoch_begin(self, epoch, logs=None):
        self._current_epoch = epoch
        if self._in_range(epoch):
            # epoch-granularity set for BOTH modes: when Keras doesn't
            # report params['steps'] (unknown-cardinality datasets) a
            # smooth schedule must still move per epoch, not silently
            # hold the base LR
            self._set_lr(self.initial_lr * self._mult(epoch))

    def on_batch_begin(self, batch, logs=None):
        if self.staircase or not self._in_range(self._current_epoch):
            return
        steps = (self.params or {}).get("steps")
        if not steps:
            return  # epoch granularity (set at epoch begin) until known
        frac = self._current_epoch + min(1.0, (batch + 1) / float(steps))
        self._set_lr(self.initial_lr * self._mult(frac))

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = float(self._lr_var())


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual warmup from lr to lr*size over ``warmup_epochs``
    (`_keras/callbacks.py:137-185`): multiplier ramps 1/size → 1 applied on
    top of the size-scaled base LR."""

    def __init__(self, warmup_epochs: int = 5, verbose: bool = False,
                 initial_lr: Optional[float] = None):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

        def multiplier(epoch):
            n = size()
            return 1.0 / n + epoch * (1.0 - 1.0 / n) / max(warmup_epochs, 1)

        super().__init__(multiplier, start_epoch=0,
                         end_epoch=warmup_epochs, staircase=False,
                         initial_lr=initial_lr)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if (epoch == self.warmup_epochs - 1 and self.verbose
                and rank() == 0):
            print(f"Epoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {float(self._lr_var()):.6g}.")
