"""TensorFlow binding surface — `horovod.tensorflow` parity on the TPU engine.

Reference parity: `horovod/tensorflow/__init__.py` (530 LoC) +
`tensorflow/mpi_ops.py`: eager-mode ``allreduce`` (Average division in
framework, `__init__.py:117`), ``allgather``, ``broadcast``,
``broadcast_variables`` (:139-171), ``DistributedGradientTape`` (:473-530),
``DistributedOptimizer`` via ``compute_gradients`` wrap (:281-295), and
``Compression`` (`tensorflow/compression.py`).

TensorFlow is NOT part of the TPU image — JAX is the native surface
(`horovod_tpu.spmd` / `horovod_tpu.optim`). This module exists for users
porting TF2 scripts: it requires an environment with tensorflow installed
and routes TF tensors through the shared engine (numpy at the boundary,
like the reference's `TFTensor` adapter in role,
`tensorflow/mpi_ops.cc:78-250`). Inside ``tf.function`` the same calls
lower to the graph-mode path (`graph.py`) — py_function engine nodes with
the reference's registered gradients — so compiled train steps and
``model.fit`` without ``run_eagerly`` work too.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from .. import basics
from ..basics import (  # noqa: F401  (re-exported API surface; probe set
    # mirrors reference tensorflow/__init__.py:30-43)
    Adasum,
    Average,
    Sum,
    cross_rank,
    cross_size,
    ddl_built,
    gloo_built,
    gloo_enabled,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mlsl_built,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    shutdown,
    size,
)
from ..exceptions import HorovodInternalError  # noqa: F401
from ..ops import collective_ops as _ops
from .compression import Compression  # noqa: F401

try:
    import tensorflow as tf

    _HAVE_TF = True
except ImportError:  # pragma: no cover - exercised only without tensorflow
    tf = None
    _HAVE_TF = False


def _gpu_available() -> bool:
    if not _HAVE_TF:
        return False
    try:
        return bool(tf.config.list_physical_devices("GPU"))
    except Exception:  # pragma: no cover - defensive against TF quirks
        return False


#: reference parity (`tensorflow/__init__.py:43`): True when TF sees a GPU.
#: Always False on the TPU-native platform — kept so ported scripts that
#: branch on it (e.g. Adasum GPU scaling) take their CPU/TPU path.
has_gpu = _gpu_available()


def _require_tf():
    if not _HAVE_TF:
        raise ImportError(
            "horovod_tpu.tensorflow requires the 'tensorflow' package, which "
            "is not installed. The TPU-native training surface is JAX "
            "(horovod_tpu / horovod_tpu.spmd); install tensorflow only if "
            "you are porting a TF2 eager script.")
    return tf


def _to_numpy(tensor) -> np.ndarray:
    _require_tf()
    return tensor.numpy() if hasattr(tensor, "numpy") else np.asarray(tensor)


def _from_result(result, like):
    t = _require_tf()
    return t.convert_to_tensor(np.asarray(result), dtype=like.dtype)


def _eager_allreduce(tensor, op, name):
    """Differentiable eager engine allreduce: ``tf.custom_gradient`` attaches
    the shared reference-formula gradient (`_grads.allreduce_grad`,
    reference `tensorflow/mpi_ops.py:107-118`) so eager ``tf.GradientTape``
    through a mid-graph collective matches the reference."""
    t = _require_tf()
    from . import _grads

    @t.custom_gradient
    def fwd(x):
        y = _from_result(
            _ops.synchronize(_ops.allreduce_async(_to_numpy(x), name=name,
                                                  op=op)), x)

        def grad(dy):
            return _grads.allreduce_grad(
                dy, op, lambda d, o: _eager_allreduce(d, o, None))

        return y, grad

    return fwd(tensor)


def _eager_allgather(tensor, name):
    t = _require_tf()
    from . import _grads

    @t.custom_gradient
    def fwd(x):
        y = _from_result(
            _ops.synchronize(_ops.allgather_async(_to_numpy(x), name=name)),
            x)

        def grad(dy):
            return _grads.allgather_grad(
                dy, x, rank(),
                lambda d, o: _eager_allreduce(d, o, None),
                lambda d: _from_result(
                    _ops.synchronize(_ops.allgather_async(_to_numpy(d))), d))

        return y, grad

    return fwd(tensor)


def _eager_broadcast(tensor, root_rank, name):
    t = _require_tf()
    from . import _grads

    @t.custom_gradient
    def fwd(x):
        y = _from_result(
            _ops.synchronize(_ops.broadcast_async(_to_numpy(x), root_rank,
                                                  name=name)), x)

        def grad(dy):
            return _grads.broadcast_grad(
                dy, root_rank, rank(),
                lambda d, o: _eager_allreduce(d, o, None))

        return y, grad

    return fwd(tensor)


def _eager_alltoall(tensor, splits, name):
    t = _require_tf()
    from . import _grads

    if splits is None:
        @t.custom_gradient
        def fwd(x):
            y = _from_result(
                _ops.synchronize(_ops.alltoall_async(_to_numpy(x),
                                                     name=name)), x)

            def grad(dy):
                return _grads.alltoall_grad(
                    dy, lambda d: _eager_alltoall(d, None, None))

            return y, grad

        return fwd(tensor)

    # a symbolic (graph-mode) splits tensor has no concrete values to read
    # here; np.asarray on it fails with an opaque NotImplementedError deep
    # in numpy — catch it and say what to do instead
    try:
        sp = tuple(int(s) for s in np.asarray(splits).reshape(-1))
    except (TypeError, NotImplementedError, ValueError) as e:
        raise ValueError(
            "alltoall splits must be concrete in eager mode; use "
            "tf.function for traced splits (got symbolic "
            f"{type(splits).__name__})") from e

    @t.custom_gradient
    def fwdv(x):
        res = _ops.synchronize(
            _ops.alltoall_async(_to_numpy(x), splits=sp, name=name))
        y = _from_result(res.output, x)
        rs = t.constant(res.received_splits, dtype=t.int32)

        def grad(dy, unused_drs):
            return _grads.alltoallv_grad(
                dy, rs, lambda d, s: _eager_alltoall(d, s, None))

        return (y, rs), grad

    return fwdv(tensor)


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None, compression=Compression.none,
              op: Optional[int] = None):
    """Eager allreduce (`tensorflow/__init__.py:44-118`): compress → engine →
    decompress; Average division happens in-framework (:117). Passing both
    ``average`` and ``op`` is rejected, as in the reference (:51-55).
    Differentiable under eager ``tf.GradientTape`` with the reference's
    registered gradient (`tensorflow/mpi_ops.py:107-118`); the compression
    casts are tf ops, so the gradient flows through them too.

    A ``tf.IndexedSlices`` input takes the sparse path (:75-91): two
    allgathers (values + indices) instead of a dense reduce, Average divides
    gathered values by world size, Adasum is rejected. Per-rank slice counts
    may differ — ragged dim0 is negotiated like any allgather.
    """
    if average is not None and op is not None:
        raise ValueError("The op parameter supersedes average; please provide "
                         "only one of them.")
    op_ = Average if op is None and average is None else (
        (Average if average else Sum) if average is not None else op)
    t = _require_tf()
    if not t.executing_eagerly():
        from . import graph as _graph
        return _graph.allreduce(tensor, name=name, op=op_,
                                compression=compression)
    if isinstance(tensor, t.IndexedSlices):
        if op_ == Adasum:
            raise NotImplementedError(
                "The Adasum reduction does not currently support sparse "
                "tensors. As a workaround please pass sparse_as_dense=True "
                "to DistributedOptimizer")
        name = _ops._auto_name("sparse_allreduce", name)
        return _finish_grad(
            *_start_grad(tensor, name, compression, op_, False),
            compression, op_)
    comp, ctx = compression.compress(tensor)
    out = _eager_allreduce(comp, op_, name)
    return compression.decompress(out, ctx)


def allgather(tensor, name: Optional[str] = None):
    """Differentiable allgather (`tensorflow/mpi_ops.py:140-163` gradient)."""
    t = _require_tf()
    if not t.executing_eagerly():
        from . import graph as _graph
        return _graph.allgather(tensor, name=name)
    return _eager_allgather(tensor, name)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    """Differentiable broadcast (`tensorflow/mpi_ops.py:183-198` gradient)."""
    t = _require_tf()
    if not t.executing_eagerly():
        from . import graph as _graph
        return _graph.broadcast(tensor, root_rank, name=name)
    return _eager_broadcast(tensor, root_rank, name)


def alltoall(tensor, splits=None, name: Optional[str] = None):
    """Alltoall (engine extension beyond the 0.18.2 op set — the reference
    gained tf alltoall in 0.20). Without ``splits``: equal split, dim 0
    divisible by world size, rank r receives segment r from every rank.
    With ``splits`` (length-world, summing to dim 0): ragged alltoallv,
    returning ``(output, received_splits)`` (later-horovod's API shape).
    Works in both eager and graph mode — graph mode negotiates the recv
    splits through the coordinator's send matrix, so the traced output has
    a dynamic dim 0 and a concrete ``received_splits`` tensor.
    Differentiable in both forms (the ragged adjoint re-exchanges with
    ``received_splits``)."""
    t = _require_tf()
    if not t.executing_eagerly():
        from . import graph as _graph
        return _graph.alltoall(tensor, splits=splits, name=name)
    return _eager_alltoall(tensor, splits, name)


def join() -> int:
    return _ops.join()


def _var_name(v, i: int) -> str:
    """Rank-consistent UNIQUE name for a variable's collectives: eager
    ``tf.Variable.name`` is "Variable:0" for every unnamed variable, so the
    position qualifies it (two unnamed variables must not collide on the
    engine's duplicate-name check)."""
    return f"{i}.{getattr(v, 'name', None) or 'var'}"


def broadcast_variables(variables: List[Any], root_rank: int = 0) -> None:
    """Assign every tf.Variable its root-rank value
    (`tensorflow/__init__.py:139-171`). Handles both tf.Variable
    (``value`` is a method) and Keras 3 variables (``value`` is a
    property)."""
    _require_tf()
    for i, v in enumerate(variables):
        raw = getattr(v, "value", None)
        val = raw() if callable(raw) else (v if raw is None else raw)
        v.assign(broadcast(val, root_rank, name=f"bv.{_var_name(v, i)}"))


def _start_grad(g, name, compression, op, sparse_as_dense):
    """Start the async reduction for one gradient; returns (kind, handles,
    meta). IndexedSlices take the two-allgather path unless sparse_as_dense
    (`_keras/__init__.py:50-53` densify; `tensorflow/__init__.py:83-91`)."""
    t = _require_tf()
    if not t.executing_eagerly():
        # graph mode: the engine nodes are dataflow ops, so TF schedules all
        # starts before blocking syncs itself — no two-phase bookkeeping
        from . import graph as _graph
        if isinstance(g, t.IndexedSlices) and sparse_as_dense:
            g = t.convert_to_tensor(g)
        return "graph", None, _graph.allreduce(g, name=name, op=op,
                                               compression=compression)
    if isinstance(g, t.IndexedSlices):
        if sparse_as_dense:
            g = t.convert_to_tensor(g)
        else:
            hv = _ops.allgather_async(_to_numpy(g.values),
                                      name=f"{name}.values")
            hi = _ops.allgather_async(_to_numpy(g.indices),
                                      name=f"{name}.indices")
            return "sparse", (hv, hi), g
    comp, ctx = compression.compress(g)
    return "dense", _ops.allreduce_async(_to_numpy(comp), name=name, op=op), \
        (ctx, comp)


def _finish_grad(kind, handles, meta, compression, op):
    t = _require_tf()
    if kind == "graph":
        return meta
    if kind == "sparse":
        g = meta
        values = _from_result(_ops.synchronize(handles[0]), g.values)
        indices = t.convert_to_tensor(np.asarray(_ops.synchronize(handles[1])),
                                      dtype=g.indices.dtype)
        if op == Average:
            values = values / t.cast(size(), values.dtype)
        return t.IndexedSlices(values, indices, dense_shape=g.dense_shape)
    ctx, comp = meta
    out = _from_result(_ops.synchronize(handles), comp)
    return compression.decompress(out, ctx)


def _reduce_grads_and_vars(grads_and_vars, compression, op,
                           sparse_as_dense):
    """Allreduce every gradient in a (grad, var) list — all collectives in
    flight before any drain (the hook-overlap pattern). Shared by the
    plain wrapper and the keras-subclass optimizer."""
    started = []
    for i, (g, v) in enumerate(grads_and_vars):
        if g is None:
            started.append((None, v))
            continue
        started.append((_start_grad(g, f"grad.{_var_name(v, i)}",
                                    compression, op, sparse_as_dense), v))
    return [(None if s is None else _finish_grad(*s, compression, op), v)
            for s, v in started]


class DistributedGradientTape:
    """Wraps ``tf.GradientTape`` so ``gradient()`` returns rank-averaged
    gradients (`tensorflow/__init__.py:473-530`); IndexedSlices gradients
    (embedding lookups) go through the sparse allgather path."""

    def __init__(self, tape, compression=Compression.none, op: int = Average,
                 sparse_as_dense: bool = False):
        _require_tf()
        self._tape = tape
        self._compression = compression
        self._op = op
        self._sparse_as_dense = sparse_as_dense

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources,
                                    output_gradients=output_gradients)
        flat = grads if isinstance(grads, (list, tuple)) else [grads]
        started = [None if g is None else
                   _start_grad(g, f"tape.{i}", self._compression, self._op,
                               self._sparse_as_dense)
                   for i, g in enumerate(flat)]
        outs = [None if s is None else
                _finish_grad(*s, self._compression, self._op)
                for s in started]
        if isinstance(grads, tuple):
            return tuple(outs)
        return outs if isinstance(grads, list) else outs[0]

    def __getattr__(self, item):
        return getattr(self._tape, item)


class DistributedOptimizer:
    """Keras-optimizer wrapper: gradients are allreduced before ``apply_
    gradients`` (`tensorflow/__init__.py:281-295` compute_gradients wrap);
    ``sparse_as_dense`` densifies IndexedSlices first
    (`_keras/__init__.py:50-53`). ``op=Adasum`` on a multi-rank world
    constructs the delta-flow ``DistributedAdasumOptimizer`` instead, like
    the reference factory."""

    def __new__(cls, optimizer=None, compression=Compression.none,
                op: int = Average, sparse_as_dense: bool = False):
        if op == Adasum and size() > 1:
            return DistributedAdasumOptimizer(optimizer,
                                              compression=compression)
        return super().__new__(cls)

    def __init__(self, optimizer, compression=Compression.none,
                 op: int = Average, sparse_as_dense: bool = False):
        _require_tf()
        self._opt = optimizer
        self._compression = compression
        self._op = op
        self._sparse_as_dense = sparse_as_dense

    def apply_gradients(self, grads_and_vars, **kwargs):
        reduced = _reduce_grads_and_vars(
            list(grads_and_vars), self._compression, self._op,
            self._sparse_as_dense)
        return self._opt.apply_gradients(reduced, **kwargs)

    def __getattr__(self, item):
        return getattr(self._opt, item)


class DistributedAdasumOptimizer:
    """Delta-flow Adasum for eager Keras optimizers
    (`tensorflow/__init__.py:313-407` rebuilt without graph slots/conds):
    the inner optimizer updates locally every step; on each communication
    step (every ``backward_passes_per_step``-th call) the cumulative delta
    from the per-variable ``start`` snapshot is Adasum-combined across
    ranks and ``var = start = start + combined_delta``.
    """

    def __init__(self, optimizer, compression=Compression.none,
                 backward_passes_per_step: int = 1):
        _require_tf()
        self._opt = optimizer
        self._compression = compression
        self._k = backward_passes_per_step
        self._step_count = 0
        self._starts = {}  # var.ref() -> tf.Variable snapshot

    def apply_gradients(self, grads_and_vars, **kwargs):
        t = _require_tf()
        if not t.executing_eagerly():
            raise NotImplementedError(
                "DistributedAdasumOptimizer keeps Python-side delta "
                "snapshots and cannot run inside tf.function; use an eager "
                "train loop (the reference's delta optimizer is likewise a "
                "stateful graph construct, tensorflow/__init__.py:313-407)")
        # Keep the FULL variable list for communication: submission must not
        # depend on rank-local gradient presence (a var whose grad is None on
        # this rank still contributes its — zero — delta), or ranks diverge
        # on the negotiated name set and deadlock; names index the full list
        # so differing None patterns can't pair different variables.
        all_gv = list(grads_and_vars)
        gv = [(g, v) for g, v in all_gv if g is not None]
        for _, v in all_gv:
            if v.ref() not in self._starts:
                self._starts[v.ref()] = t.Variable(v.read_value(),
                                                   trainable=False)
        result = self._opt.apply_gradients(gv, **kwargs) if gv else None
        self._step_count += 1
        if self._step_count % self._k != 0:
            return result
        started = []
        for i, (_, v) in enumerate(all_gv):
            start = self._starts[v.ref()]
            delta = v.read_value() - start.read_value()
            comp, ctx = self._compression.compress(delta)
            started.append((v, start, ctx, comp, _ops.allreduce_async(
                _to_numpy(comp), name=f"adasum.{_var_name(v, i)}",
                op=Adasum)))
        for v, start, ctx, comp, h in started:
            combined = self._compression.decompress(
                _from_result(_ops.synchronize(h), comp), ctx)
            start.assign_add(t.cast(combined, start.dtype))
            v.assign(start.read_value())
        return result

    def __getattr__(self, item):
        return getattr(self._opt, item)


class BroadcastGlobalVariablesHook:
    """tf.estimator-style hook parity (`tensorflow/__init__.py:173-227`):
    call ``after_create_session`` (or just ``broadcast_variables``) once."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def after_create_session(self, session=None, coord=None):
        t = _require_tf()
        broadcast_variables(
            [v for v in t.compat.v1.global_variables()], self.root_rank)
