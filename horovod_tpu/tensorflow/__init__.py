"""TensorFlow binding surface — `horovod.tensorflow` parity on the TPU engine.

Reference parity: `horovod/tensorflow/__init__.py` (530 LoC) +
`tensorflow/mpi_ops.py`: eager-mode ``allreduce`` (Average division in
framework, `__init__.py:117`), ``allgather``, ``broadcast``,
``broadcast_variables`` (:139-171), ``DistributedGradientTape`` (:473-530),
``DistributedOptimizer`` via ``compute_gradients`` wrap (:281-295), and
``Compression`` (`tensorflow/compression.py`).

TensorFlow is NOT part of the TPU image — JAX is the native surface
(`horovod_tpu.spmd` / `horovod_tpu.optim`). This module exists for users
porting TF2 eager scripts: it requires an environment with tensorflow
installed and routes TF eager tensors through the shared engine (numpy at
the boundary, like the reference's `TFTensor` adapter in role,
`tensorflow/mpi_ops.cc:78-250`). Graph-mode/tf.function custom ops are out
of scope — XLA-jitted training belongs on the JAX path.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from .. import basics
from ..basics import (  # noqa: F401  (re-exported API surface)
    Adasum,
    Average,
    Sum,
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from ..exceptions import HorovodInternalError  # noqa: F401
from ..ops import collective_ops as _ops
from .compression import Compression  # noqa: F401

try:
    import tensorflow as tf

    _HAVE_TF = True
except ImportError:  # pragma: no cover - exercised only without tensorflow
    tf = None
    _HAVE_TF = False


def _require_tf():
    if not _HAVE_TF:
        raise ImportError(
            "horovod_tpu.tensorflow requires the 'tensorflow' package, which "
            "is not installed. The TPU-native training surface is JAX "
            "(horovod_tpu / horovod_tpu.spmd); install tensorflow only if "
            "you are porting a TF2 eager script.")
    return tf


def _to_numpy(tensor) -> np.ndarray:
    _require_tf()
    return tensor.numpy() if hasattr(tensor, "numpy") else np.asarray(tensor)


def _from_result(result, like):
    t = _require_tf()
    return t.convert_to_tensor(np.asarray(result), dtype=like.dtype)


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None, compression=Compression.none,
              op: Optional[int] = None):
    """Eager allreduce (`tensorflow/__init__.py:44-118`): compress → engine →
    decompress; Average division happens in-framework (:117). Passing both
    ``average`` and ``op`` is rejected, as in the reference (:51-55)."""
    if average is not None and op is not None:
        raise ValueError("The op parameter supersedes average; please provide "
                         "only one of them.")
    op_ = Average if op is None and average is None else (
        (Average if average else Sum) if average is not None else op)
    comp, ctx = compression.compress(tensor)
    out = _from_result(
        _ops.synchronize(_ops.allreduce_async(_to_numpy(comp), name=name,
                                              op=op_)), comp)
    return compression.decompress(out, ctx)


def allgather(tensor, name: Optional[str] = None):
    return _from_result(
        _ops.synchronize(_ops.allgather_async(_to_numpy(tensor), name=name)),
        tensor)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    return _from_result(
        _ops.synchronize(_ops.broadcast_async(_to_numpy(tensor), root_rank,
                                              name=name)), tensor)


def join() -> int:
    return _ops.join()


def broadcast_variables(variables: List[Any], root_rank: int = 0) -> None:
    """Assign every tf.Variable its root-rank value
    (`tensorflow/__init__.py:139-171`)."""
    _require_tf()
    for i, v in enumerate(variables):
        name = getattr(v, "name", None) or f"var.{i}"
        v.assign(broadcast(v.value() if hasattr(v, "value") else v,
                           root_rank, name=f"bv.{name}"))


class DistributedGradientTape:
    """Wraps ``tf.GradientTape`` so ``gradient()`` returns rank-averaged
    gradients (`tensorflow/__init__.py:473-530`)."""

    def __init__(self, tape, compression=Compression.none, op: int = Average):
        _require_tf()
        self._tape = tape
        self._compression = compression
        self._op = op

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources,
                                    output_gradients=output_gradients)
        flat = grads if isinstance(grads, (list, tuple)) else [grads]
        handles, ctxs = [], []
        for i, g in enumerate(flat):
            if g is None:
                handles.append(None)
                ctxs.append((None, None))
                continue
            comp, ctx = self._compression.compress(g)
            handles.append(_ops.allreduce_async(_to_numpy(comp),
                                                name=f"tape.{i}", op=self._op))
            ctxs.append((ctx, comp))
        outs = []
        for h, (ctx, comp) in zip(handles, ctxs):
            if h is None:
                outs.append(None)
                continue
            out = _from_result(_ops.synchronize(h), comp)
            outs.append(self._compression.decompress(out, ctx))
        if isinstance(grads, tuple):
            return tuple(outs)
        return outs if isinstance(grads, list) else outs[0]

    def __getattr__(self, item):
        return getattr(self._tape, item)


class DistributedOptimizer:
    """Keras-optimizer wrapper: gradients are allreduced before ``apply_
    gradients`` (`tensorflow/__init__.py:281-295` compute_gradients wrap)."""

    def __init__(self, optimizer, compression=Compression.none,
                 op: int = Average):
        _require_tf()
        self._opt = optimizer
        self._compression = compression
        self._op = op

    def apply_gradients(self, grads_and_vars, **kwargs):
        grads_and_vars = list(grads_and_vars)
        reduced = []
        handles, metas = [], []
        for i, (g, v) in enumerate(grads_and_vars):
            if g is None:
                handles.append(None)
                metas.append((None, None, v))
                continue
            comp, ctx = self._compression.compress(g)
            name = getattr(v, "name", None) or f"opt.{i}"
            handles.append(_ops.allreduce_async(_to_numpy(comp),
                                                name=f"grad.{name}",
                                                op=self._op))
            metas.append((ctx, comp, v))
        for h, (ctx, comp, v) in zip(handles, metas):
            if h is None:
                reduced.append((None, v))
                continue
            out = _from_result(_ops.synchronize(h), comp)
            reduced.append((self._compression.decompress(out, ctx), v))
        return self._opt.apply_gradients(reduced, **kwargs)

    def __getattr__(self, item):
        return getattr(self._opt, item)


class BroadcastGlobalVariablesHook:
    """tf.estimator-style hook parity (`tensorflow/__init__.py:173-227`):
    call ``after_create_session`` (or just ``broadcast_variables``) once."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def after_create_session(self, session=None, coord=None):
        t = _require_tf()
        broadcast_variables(
            [v for v in t.compat.v1.global_variables()], self.root_rank)
