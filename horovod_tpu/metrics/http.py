"""Prometheus-text /metrics endpoint (``HOROVOD_METRICS_PORT``).

A daemon-threaded stdlib HTTP server started on the aggregating process
(rank 0, or any standalone/local-cluster process).  Port 0 binds an
ephemeral port; the bound port is exposed as ``server.port`` and logged,
which is how tests and the CI smoke scrape without a fixed allocation.
``HOROVOD_METRICS_ADDR`` selects the bind address (default ``0.0.0.0``;
``127.0.0.1`` keeps the endpoint loopback-only).

Besides ``/metrics`` the server answers ``/healthz`` with a JSON liveness
summary — rank count, last-negotiation age, heartbeat status, anomaly-
watch state (docs/observability.md).
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger("horovod_tpu")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
HEALTH_CONTENT_TYPE = "application/json; charset=utf-8"


class MetricsHTTPServer:
    """Serves ``render_fn()`` at /metrics and ``health_fn()`` as JSON at
    /healthz; everything else is 404."""

    def __init__(self, port: int, render_fn, addr: str = "0.0.0.0",
                 health_fn=None):
        self._render = render_fn
        self._health = health_fn
        self._requested_port = int(port)
        # the wildcard spelling callers use maps to the stdlib's "" bind
        self._addr = "" if addr in ("", "0.0.0.0") else addr
        self._display_addr = addr or "0.0.0.0"
        self._httpd = None
        self._thread = None
        self.port = None  # bound port, set by start()

    def start(self) -> int:
        render = self._render
        health = self._health

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    try:
                        body = json.dumps(
                            health() if health is not None else {},
                            indent=1).encode("utf-8")
                    except Exception as exc:  # pragma: no cover - source bug
                        self.send_error(500, str(exc))
                        return
                    self._reply(body, HEALTH_CONTENT_TYPE)
                    return
                if path not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = render().encode("utf-8")
                except Exception as exc:  # pragma: no cover - render bug
                    self.send_error(500, str(exc))
                    return
                self._reply(body, CONTENT_TYPE)

            def _reply(self, body, content_type):
                self.send_response(200)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                log.debug("metrics http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((self._addr, self._requested_port),
                                          Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.25},
            name="hvd-metrics-http", daemon=True)
        self._thread.start()
        log.info("metrics endpoint on http://%s:%d/metrics (+/healthz)",
                 self._display_addr, self.port)
        return self.port

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
