"""Prometheus-text /metrics endpoint (``HOROVOD_METRICS_PORT``).

A daemon-threaded stdlib HTTP server started on the aggregating process
(rank 0, or any standalone/local-cluster process).  Port 0 binds an
ephemeral port; the bound port is exposed as ``server.port`` and logged,
which is how tests and the CI smoke scrape without a fixed allocation.
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger("horovod_tpu")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsHTTPServer:
    """Serves ``render_fn()`` at /metrics; everything else is 404."""

    def __init__(self, port: int, render_fn):
        self._render = render_fn
        self._requested_port = int(port)
        self._httpd = None
        self._thread = None
        self.port = None  # bound port, set by start()

    def start(self) -> int:
        render = self._render

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = render().encode("utf-8")
                except Exception as exc:  # pragma: no cover - render bug
                    self.send_error(500, str(exc))
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                log.debug("metrics http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer(("", self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.25},
            name="hvd-metrics-http", daemon=True)
        self._thread.start()
        log.info("metrics endpoint on http://0.0.0.0:%d/metrics", self.port)
        return self.port

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
