"""The standard horovod_tpu metric catalog (docs/metrics.md).

Each accessor returns the live metric from the process-global registry,
creating it on first touch.  Accessors re-resolve through the registry on
every call (a dict lookup under a lock) so handles never go stale across
``reset_metrics()`` — instrumentation sites may still cache the returned
object locally when they sit in a tight loop.
"""

from __future__ import annotations

from .registry import exponential_buckets, get_registry

#: Fused-batch fill: tensors per executed response.
FUSION_TENSOR_BUCKETS = [1, 2, 4, 8, 16, 32, 64, 128, 256, 512]
#: Fused-batch fill: bytes per executed response (1 KiB .. 1 GiB).
FUSION_BYTE_BUCKETS = exponential_buckets(1024.0, 4.0, 10)


def engine_ticks():
    return get_registry().counter(
        "hvd_engine_ticks_total", "Background engine loop iterations.")


def allreduce_latency():
    return get_registry().histogram(
        "hvd_allreduce_latency_seconds",
        "Wall time of one executed allreduce/adasum response (fused "
        "bucket), submit-batch to results-ready.",
        labels=("dtype", "compression"))


def collective_latency():
    return get_registry().histogram(
        "hvd_collective_latency_seconds",
        "Wall time of one executed response, any collective op.",
        labels=("op",))


def fusion_tensors():
    return get_registry().histogram(
        "hvd_fusion_tensors",
        "Tensors fused into one executed response.",
        buckets=FUSION_TENSOR_BUCKETS)


def fusion_bytes():
    return get_registry().histogram(
        "hvd_fusion_bytes",
        "Payload bytes of one executed response (pre-compression).",
        buckets=FUSION_BYTE_BUCKETS)


def response_cache_hits():
    return get_registry().counter(
        "hvd_response_cache_hits_total",
        "Negotiations answered from the response cache.")


def response_cache_misses():
    return get_registry().counter(
        "hvd_response_cache_misses_total",
        "Negotiations that required a full metadata exchange.")


def negotiations():
    return get_registry().counter(
        "hvd_negotiations_total",
        "Coordinator negotiation rounds that produced responses (rank 0).")


def wire_bytes():
    return get_registry().counter(
        "hvd_wire_bytes_total",
        "Collective payload bytes this rank put on the wire, after "
        "compression — both data planes: the coordinator wire (engine "
        "path) and the compiled GSPMD ring "
        "(compression=\"gspmd-int8\"/\"gspmd-int4\", spmd.py; "
        "docs/gspmd.md).", labels=("compression",))


def wire_bytes_exact():
    return get_registry().counter(
        "hvd_wire_bytes_exact_total",
        "Collective payload bytes the same traffic would have cost "
        "uncompressed (ratio denominator; covers the coordinator wire "
        "and the GSPMD ring).")


def quantization_ratio():
    return get_registry().gauge(
        "hvd_quantization_ratio",
        "Running wire-bytes / exact-bytes ratio (1.0 = no compression "
        "win), over both the coordinator wire and the GSPMD ring.",
        agg="max")


def expert_load():
    return get_registry().gauge(
        "hvd_expert_load",
        "Tokens routed to each expert in the latest capacity-dispatch MoE "
        "step (parallel/expert.py; global count, identical on every "
        "rank).", labels=("expert",), agg="max")


def moe_load_imbalance():
    return get_registry().gauge(
        "hvd_moe_load_imbalance",
        "max/mean expert load of the latest MoE step (1.0 = perfectly "
        "balanced router; sustained high values mean dropped tokens and "
        "idle experts — the anomaly watch tracks this like straggler "
        "skew).", agg="max")


def moe_dropped_tokens():
    return get_registry().counter(
        "hvd_moe_dropped_tokens_total",
        "Tokens dropped by capacity-factor MoE dispatch (routed past "
        "their expert's buffer; they contribute zero to the MoE output "
        "— docs/moe.md).")


def moe_capacity_factor():
    return get_registry().gauge(
        "hvd_moe_capacity_factor",
        "Capacity factor of the running MoE train step (buffer slots = "
        "ceil(CF * tokens / experts)).", agg="max")


def bitwidth_decisions():
    return get_registry().counter(
        "hvd_bitwidth_decisions_total",
        "Adaptive-wire bitwidth decision changes, labelled by the grid "
        "switched to (ops/adaptive.py BitwidthSelector).",
        labels=("wire",))


def adaptive_bitwidth():
    return get_registry().gauge(
        "hvd_adaptive_bitwidth",
        "Most recently selected adaptive-wire grid, in bits "
        "(4 = int4, 8 = int8, 16 = bf16 fallback).")


def collective_algorithm():
    return get_registry().gauge(
        "hvd_collective_algorithm",
        "Collective algorithm in play per payload-size class "
        "(0 = ring, 1 = tree, 2 = hierarchical — ops/adaptive.ALGO_CODES).",
        labels=("class",))


def error_feedback_roundtrips():
    return get_registry().counter(
        "hvd_error_feedback_roundtrips_total",
        "Eager quantize/dequantize round trips with EF-SGD residual "
        "accumulation (ops/compression.py quantize_roundtrip).")


def control_bytes():
    return get_registry().counter(
        "hvd_control_bytes_total",
        "Control-plane (coordinator TCP) frame bytes.",
        labels=("direction",))


def elastic_epoch():
    return get_registry().gauge(
        "hvd_elastic_epoch",
        "Current membership epoch (0 for non-elastic jobs).", agg="max")


def elastic_rank_lost():
    return get_registry().counter(
        "hvd_elastic_rank_lost_total",
        "Workers declared lost by the coordinator (elastic membership).")


def stalled_tensors():
    return get_registry().gauge(
        "hvd_stalled_tensors",
        "Tensors currently past the stall-check deadline with ranks "
        "missing.", agg="max")


def control_reconnects():
    return get_registry().counter(
        "hvd_control_reconnects_total",
        "Successful worker-side control-plane reconnects (transparent "
        "recovery from a dropped coordinator connection).")


def heartbeat_misses():
    return get_registry().counter(
        "hvd_heartbeat_misses_total",
        "Worker heartbeat intervals the coordinator observed as missed "
        "(HOROVOD_HEARTBEAT_INTERVAL elapsed with no frame from a rank).")


def frames_rejected():
    return get_registry().counter(
        "hvd_frames_rejected_total",
        "Control-plane frames rejected for integrity violations "
        "(CRC32/HMAC mismatch or an over-bound length prefix).")


def grad_nonfinite():
    return get_registry().counter(
        "hvd_grad_nonfinite_total",
        "Gradient tensors this rank observed with NaN/Inf values before "
        "allreduce (HOROVOD_GRAD_GUARD detection, any policy but off).")


def steps_skipped():
    return get_registry().counter(
        "hvd_steps_skipped_total",
        "Optimizer steps dropped globally because some rank's gradients "
        "were non-finite (HOROVOD_GRAD_GUARD=skip).")


def param_desync():
    return get_registry().counter(
        "hvd_param_desync_total",
        "Parameter tensors whose cross-rank digest diverged from the "
        "root's (consistency auditor, HOROVOD_CONSISTENCY_INTERVAL).")


def integrity_heals():
    return get_registry().counter(
        "hvd_integrity_heals_total",
        "Self-heal re-broadcasts of the full parameter set from the root "
        "after a digest divergence (HOROVOD_CONSISTENCY_POLICY=heal).")


def collective_timeouts():
    return get_registry().counter(
        "hvd_collective_timeouts_total",
        "Collectives forcibly failed after stalling past "
        "HOROVOD_COLLECTIVE_TIMEOUT (enforced watchdog; each firing also "
        "names the missing ranks in the CollectiveTimeoutError).")


def exposed_comm_seconds():
    return get_registry().gauge(
        "hvd_exposed_comm_seconds",
        "Cumulative wall time this rank spent blocked in synchronize() "
        "waiting on collective results — communication NOT hidden behind "
        "compute (the hvdprof exposed-communication headline).", agg="sum")


def straggler_skew_seconds():
    return get_registry().gauge(
        "hvd_straggler_skew_seconds",
        "Enqueue-time spread (slowest minus fastest rank) observed at the "
        "most recent negotiation a tensor became ready — how long fast "
        "ranks waited for the straggler.", agg="max")


def partial_collectives():
    return get_registry().counter(
        "hvd_partial_collectives_total",
        "Collectives completed over a straggler-excluded subgroup instead "
        "of the full member set (rank 0 straggler policy).")


def excluded_rank():
    return get_registry().gauge(
        "hvd_excluded_rank",
        "Highest rank currently excluded by the straggler policy, or -1 "
        "when every member is participating.", agg="max")


def straggler_promotions():
    return get_registry().counter(
        "hvd_straggler_promotions_total",
        "Chronically slow ranks escalated to rank_lost / hot-spare "
        "promotion after trailing excluded past "
        "HOROVOD_STRAGGLER_MAX_SKIP rounds.")


def trace_dropped_events():
    return get_registry().counter(
        "hvd_trace_dropped_events_total",
        "Trace spans dropped because the HOROVOD_TRACE_BUFFER ring (or "
        "rank 0's merge store) was full.")


def anomaly_active():
    return get_registry().gauge(
        "hvd_anomaly_active",
        "Live anomaly-watch verdict per tracked signal (1 = the current "
        "window deviates from its rolling baseline; HOROVOD_ANOMALY_WATCH, "
        "docs/observability.md).", labels=("signal",), agg="max")


def blackbox_dumps():
    return get_registry().counter(
        "hvd_blackbox_dumps_total",
        "Flight-recorder postmortem dumps written by this process on "
        "abnormal exit (HOROVOD_BLACKBOX).")


def coord_batch_ranks():
    return get_registry().histogram(
        "hvd_coord_batch_ranks",
        "Ranks carried per batched negotiation frame received by the "
        "coordinator, labeled by the sending tier ('host' for legacy "
        "MSG_BATCH host frames, the tier number for grouped MSG_TBATCH "
        "frames; HOROVOD_HIERARCHICAL_COORD, HOROVOD_HIERARCHY_TIERS; "
        "docs/control-plane.md).", labels=("tier",),
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096,
                 16384, 65536, 262144))


def coord_tier_depth():
    return get_registry().gauge(
        "hvd_coord_tier_depth",
        "Configured aggregation-tree depth of the hierarchical control "
        "plane (1 = the single host tier; HOROVOD_HIERARCHY_TIERS; "
        "docs/control-plane.md).", agg="max")


def coord_failovers():
    return get_registry().counter(
        "hvd_coord_failovers_total",
        "Coordinator failovers: the warm standby promoted itself after "
        "losing its replication stream to rank 0 "
        "(HOROVOD_STANDBY_COORD; docs/control-plane.md).")


def fencing_epoch():
    return get_registry().gauge(
        "hvd_fencing_epoch",
        "Highest coordinator fencing epoch this process has observed "
        "(0 until lease-based leadership is enabled or seen; "
        "HOROVOD_LEASE_TTL; docs/fault-tolerance.md).", agg="max")


def lease_renewals():
    return get_registry().counter(
        "hvd_lease_renewals_total",
        "Successful coordinator-lease renewals by the active leader "
        "(HOROVOD_LEASE_TTL/HOROVOD_LEASE_RENEW; a stalling rate here "
        "predicts a self-fence; docs/fault-tolerance.md).")


def frames_fenced():
    return get_registry().counter(
        "hvd_frames_fenced_total",
        "Control frames rejected for carrying a stale fencing epoch — a "
        "deposed-but-still-running coordinator's traffic being ignored "
        "(docs/fault-tolerance.md).")


def epoch_coalesced_joins():
    return get_registry().counter(
        "hvd_epoch_coalesced_joins_total",
        "Extra joiners folded into an already-pending membership epoch "
        "bump by admission batching (HOROVOD_ADMISSION_BATCH_MS) — each "
        "one is an epoch reset the job did NOT pay for.")


def standby_journal_lag():
    return get_registry().gauge(
        "hvd_standby_journal_lag",
        "Journal records queued at rank 0 but not yet shipped to a warm "
        "standby, labeled by the standby's tier ('root' for the global "
        "rank-0 standby, the tier number for subtree-scoped streams; "
        "docs/control-plane.md).", labels=("tier",), agg="max")


# --------------------------------------------------------------- serving
# The inference-serving catalog (serving/, docs/inference.md). Request
# latencies use the default LATENCY_BUCKETS, whose bucket-count deltas are
# also what the anomaly watch derives its live p99 from.

def serving_requests():
    return get_registry().counter(
        "hvd_serving_requests_total",
        "Serving requests by terminal disposition (submitted / completed / "
        "failed / rejected / readmitted).", labels=("status",))


def serving_request_latency():
    return get_registry().histogram(
        "hvd_serving_request_latency_seconds",
        "Request latency: submit-to-done (stage=total) and submit-to-first-"
        "token (stage=first_token). p50/p99 derive from bucket counts.",
        labels=("stage",))


def serving_phase_seconds():
    return get_registry().histogram(
        "hvd_serving_phase_seconds",
        "Engine phase wall time per step (phase=prefill|decode).",
        labels=("phase",))


def serving_tokens():
    return get_registry().counter(
        "hvd_serving_tokens_total",
        "Tokens processed: prompt tokens prefilled (phase=prefill) and "
        "tokens generated (phase=decode). rate(phase=decode) is the "
        "tokens/s headline.", labels=("phase",))


def serving_decode_batch():
    return get_registry().histogram(
        "hvd_serving_decode_batch",
        "In-flight requests per batched decode step (continuous-batching "
        "fill; max is the HOROVOD_SERVING_MAX_BATCH width).",
        buckets=(1, 2, 4, 8, 16, 32, 64, 128))


def serving_queue_depth():
    return get_registry().gauge(
        "hvd_serving_queue_depth",
        "Requests waiting in the admission queue (bounded by "
        "HOROVOD_SERVING_MAX_QUEUE; sustained depth = saturation).",
        agg="max")


def serving_active_requests():
    return get_registry().gauge(
        "hvd_serving_active_requests",
        "Requests currently in the decode batch.", agg="max")


def serving_kv_occupancy():
    return get_registry().gauge(
        "hvd_serving_kv_occupancy",
        "Fraction of KV-cache blocks allocated (the admission-control "
        "currency; 1.0 = no new request can be admitted).", agg="max")


def serving_kv_tokens():
    return get_registry().gauge(
        "hvd_serving_kv_tokens",
        "Token slots actually written in the KV cache (live context "
        "payload, vs the block-granular hvd_serving_kv_occupancy).",
        agg="max")


def serving_shed():
    return get_registry().counter(
        "hvd_serving_shed_total",
        "Requests degraded by overload admission control: class=best_effort "
        "counts hard sheds (SERVE_SHED answered without dispatch), "
        "class=brownout counts best-effort requests whose max_new was "
        "clamped. High-priority traffic is never shed.",
        labels=("class",))


def serving_hedges():
    return get_registry().counter(
        "hvd_serving_hedges_total",
        "Tail-latency hedges: outcome=launched (second replica engaged "
        "after the p95-derived delay), outcome=won (hedge answered first; "
        "original cancelled), outcome=lost (original answered first; hedge "
        "cancelled).", labels=("outcome",))


def serving_cancels():
    return get_registry().counter(
        "hvd_serving_cancels_total",
        "Request cancellations by reason: client (explicit / disconnect), "
        "deadline (wire budget expired), ttl (orphan sweep), propagated "
        "(frontend-to-worker MSG_SERVE_CANCEL applied), hedge (losing "
        "duplicate).", labels=("reason",))


def serving_frontend_failovers():
    return get_registry().counter(
        "hvd_serving_frontend_failovers_total",
        "Serving-frontend standby promotions (lease takeover or replication "
        "stream loss). Paired with a K_FAILOVER blackbox event naming the "
        "promoted address.")


def checkpoint_stall_seconds():
    return get_registry().counter(
        "hvd_checkpoint_stall_seconds",
        "Seconds the training step path spent handing snapshots to the "
        "async checkpoint writer (ckpt/writer.py). The write-behind design "
        "keeps this ~0; growth means the step path is blocking on "
        "checkpoint I/O.")


def checkpoint_bytes():
    return get_registry().counter(
        "hvd_checkpoint_bytes_total",
        "Checkpoint bytes shipped, by destination: kind=disk (shard + "
        "replica files landed in HOROVOD_CKPT_DIR) and kind=peer (buddy "
        "journal payloads to the ring successor).", labels=("kind",))


def ckpt_bundle_age_steps():
    return get_registry().gauge(
        "hvd_ckpt_bundle_age_steps",
        "Steps since the last FINALIZED checkpoint bundle (0 right after a "
        "manifest lands). Sustained age above ~2x HOROVOD_CKPT_INTERVAL "
        "means shards are being written but bundles never complete — a "
        "lagging or wedged member (hvddoctor: stale_checkpoint).",
        agg="max")


# --------------------------------------------------------------- goodput
# The time-attribution ledger (goodput/, docs/goodput.md). Counters carry
# a rank label so per-rank attribution survives the cross-rank merge
# (counters sum, but label sets stay disjoint per rank).

def goodput_seconds():
    return get_registry().counter(
        "hvd_goodput_seconds_total",
        "Wall-clock seconds attributed to useful compute by the goodput "
        "ledger, per rank (goodput/ledger.py; docs/goodput.md).",
        labels=("rank",))


def badput_seconds():
    return get_registry().counter(
        "hvd_badput_seconds_total",
        "Wall-clock seconds NOT spent computing, by cause (exposed_comm / "
        "stall / checkpoint / recovery / excluded / idle) and rank — the "
        "goodput ledger's badput breakdown (docs/goodput.md).",
        labels=("cause", "rank"))


def goodput_ratio():
    return get_registry().gauge(
        "hvd_goodput_ratio",
        "Fraction of this rank's wall-clock attributed to compute since "
        "init (merge takes the min: the fleet is only as good as its "
        "worst rank; the fleet-weighted ratio derives from the seconds "
        "counters).", labels=("rank",), agg="min")


def goodput_wall_seconds():
    return get_registry().gauge(
        "hvd_goodput_wall_seconds",
        "Wall-clock seconds the goodput ledger has been attributing on "
        "each rank (the completeness denominator: the per-rank state sums "
        "should cover >= 99% of this).", labels=("rank",), agg="max")


def slo_burn_rate():
    return get_registry().gauge(
        "hvd_slo_burn_rate",
        "Error-budget burn rate per declared SLO (HOROVOD_SLO): the "
        "fast-window bad-fraction divided by the objective's allowance. "
        "1.0 = burning exactly the budget; sustained >1 exhausts it "
        "(goodput/slo.py; docs/goodput.md).", labels=("slo",), agg="max")


def up():
    return get_registry().gauge(
        "hvd_up",
        "1 while the engine loop is alive (set at init, refreshed every "
        "metrics push, 0 at shutdown). Scrape alongside "
        "hvd_snapshot_unix_seconds to tell a wedged-but-listening rank "
        "from a healthy one.", agg="min")


def snapshot_unix_seconds():
    return get_registry().gauge(
        "hvd_snapshot_unix_seconds",
        "Unix time the engine loop last refreshed this registry (NOT the "
        "scrape time — a stale value under a live /metrics endpoint means "
        "the process is wedged).", agg="max")
