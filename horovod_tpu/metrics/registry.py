"""Dependency-free metrics primitives: Counter / Gauge / Histogram.

The reference stack leans on the Chrome-trace timeline for post-mortem
analysis; this module is the live-signals counterpart.  Everything here
is plain Python on purpose — no prometheus_client, no numpy — so the
registry can run inside the engine tick loop and inside the coordinator
server thread without adding imports to the hot path.

Design points:

* Metrics are created through a ``MetricsRegistry`` and identified by
  name.  Creating the same name twice returns the same object (so
  instrumentation sites don't need to coordinate import order).
* Labels follow the Prometheus child model: ``c.labels(op="allreduce")``
  returns a per-label-set child sharing the parent's storage.
* ``snapshot()`` produces a plain-dict representation that survives the
  wire codec (runtime/wire.py) and merges across ranks with
  ``merge_snapshots``: counters and histograms sum; gauges combine per
  their declared ``agg`` mode (``max`` / ``min`` / ``sum`` / ``last``).
* ``render_prometheus`` turns one (possibly merged) snapshot into the
  Prometheus text exposition format.
"""

from __future__ import annotations

import math
import threading


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def _fmt_value(v) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_labels(items) -> str:
    if not items:
        return ""
    parts = []
    for k, v in items:
        s = str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        parts.append(f'{k}="{s}"')
    return "{" + ",".join(parts) + "}"


def exponential_buckets(start: float, factor: float, count: int):
    """Prometheus-style exponential bucket bounds (upper edges, no +Inf)."""
    assert start > 0 and factor > 1 and count >= 1
    return [start * factor ** i for i in range(count)]


#: Default latency buckets: 20 exponential buckets from 50us to ~26s.
LATENCY_BUCKETS = exponential_buckets(50e-6, 2.0, 20)


def quantile_from_buckets(buckets, counts, q):
    """Estimate the q-quantile from per-bucket (non-cumulative) counts.

    ``buckets`` are the upper bounds (no +Inf); ``counts`` has one extra
    trailing slot for the implicit +Inf overflow bucket, matching the
    Histogram snapshot layout.  Returns the upper bound of the bucket the
    quantile falls in, ``2 * buckets[-1]`` when it lands in the overflow
    bucket, or ``None`` when there are no observations.  Shared by the
    anomaly watch (serving p99), the SLO engine, hvdtop and the serving
    bench so every consumer agrees on the estimate.
    """
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    acc = 0
    for i, b in enumerate(buckets):
        acc += counts[i] if i < len(counts) else 0
        if acc >= target:
            return b
    return buckets[-1] * 2.0 if buckets else None


class _Child:
    """One label-set instance of a metric."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric, key):
        self._metric = metric
        self._key = key

    def inc(self, amount=1.0):
        self._metric._inc(self._key, amount)

    def set(self, value):
        self._metric._set(self._key, value)

    def observe(self, value):
        self._metric._observe(self._key, value)

    @property
    def value(self):
        return self._metric._get(self._key)


class _Metric:
    kind = "untyped"

    def __init__(self, name, help, label_names=(), **kw):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children = {}

    def labels(self, **labels):
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got "
                f"{tuple(labels)}")
        key = _label_key(labels)
        with self._lock:
            if key not in self._children:
                self._children[key] = self._zero()
        return _Child(self, key)

    def _default_child(self):
        if self.label_names:
            raise ValueError(f"{self.name}: labeled metric needs .labels()")
        return self.labels()

    # -- storage ops, overridden per kind ---------------------------------
    def _zero(self):
        return 0.0

    def _inc(self, key, amount):
        raise NotImplementedError

    def _set(self, key, value):
        raise NotImplementedError

    def _observe(self, key, value):
        raise NotImplementedError

    def _get(self, key):
        with self._lock:
            return self._children.get(key)

    def snapshot_values(self):
        with self._lock:
            return {k: self._copy_value(v) for k, v in self._children.items()}

    @staticmethod
    def _copy_value(v):
        return v


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount=1.0):
        self._default_child().inc(amount)

    @property
    def value(self):
        return self._default_child().value

    def _inc(self, key, amount):
        if amount < 0:
            raise ValueError(f"{self.name}: counters only go up")
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def _set(self, key, value):
        raise TypeError(f"{self.name}: counters have no set()")

    def _observe(self, key, value):
        raise TypeError(f"{self.name}: counters have no observe()")


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help, label_names=(), agg="last"):
        super().__init__(name, help, label_names)
        if agg not in ("last", "max", "min", "sum"):
            raise ValueError(f"{name}: unknown gauge agg {agg!r}")
        self.agg = agg

    def set(self, value):
        self._default_child().set(value)

    def inc(self, amount=1.0):
        self._default_child().inc(amount)

    @property
    def value(self):
        return self._default_child().value

    def _inc(self, key, amount):
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def _set(self, key, value):
        with self._lock:
            self._children[key] = float(value)

    def _observe(self, key, value):
        raise TypeError(f"{self.name}: gauges have no observe()")


class _HistValue:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, nbuckets):
        self.counts = [0] * nbuckets  # per-bucket (non-cumulative) counts
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, label_names=(), buckets=None):
        super().__init__(name, help, label_names)
        bounds = list(buckets if buckets is not None else LATENCY_BUCKETS)
        if sorted(bounds) != bounds:
            raise ValueError(f"{name}: bucket bounds must be sorted")
        if bounds and bounds[-1] == float("inf"):
            bounds = bounds[:-1]
        self.buckets = bounds  # upper bounds, +Inf implicit

    def observe(self, value):
        self._default_child().observe(value)

    def _zero(self):
        return _HistValue(len(self.buckets) + 1)

    def _inc(self, key, amount):
        raise TypeError(f"{self.name}: histograms have no inc()")

    def _set(self, key, value):
        raise TypeError(f"{self.name}: histograms have no set()")

    def _observe(self, key, value):
        v = float(value)
        idx = len(self.buckets)
        for i, b in enumerate(self.buckets):
            if v <= b:
                idx = i
                break
        with self._lock:
            h = self._children.get(key)
            if h is None:
                h = self._children[key] = self._zero()
            h.counts[idx] += 1
            h.sum += v
            h.count += 1

    @staticmethod
    def _copy_value(v):
        c = _HistValue(len(v.counts))
        c.counts = list(v.counts)
        c.sum = v.sum
        c.count = v.count
        return c


class MetricsRegistry:
    """Holds every metric of one process; snapshot/merge/render live here."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    # -- factories --------------------------------------------------------
    def counter(self, name, help="", labels=()):
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name, help="", labels=(), agg="last"):
        return self._get_or_create(Gauge, name, help, labels, agg=agg)

    def histogram(self, name, help="", labels=(), buckets=None):
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def _get_or_create(self, cls, name, help, labels, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise TypeError(
                        f"{name} already registered as {m.kind}, not "
                        f"{cls.kind}")
                return m
            m = cls(name, help, labels, **kw)
            self._metrics[name] = m
            return m

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    # -- snapshot ---------------------------------------------------------
    def snapshot(self) -> dict:
        """Plain-dict snapshot: wire-codec friendly and merge-ready.

        ``{name: {"kind", "help", "agg"?, "buckets"?, "series":
        [{"labels": {...}, ...value fields...}]}}``
        """
        out = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            entry = {"kind": m.kind, "help": m.help, "series": []}
            if m.kind == "gauge":
                entry["agg"] = m.agg
            if m.kind == "histogram":
                entry["buckets"] = list(m.buckets)
            for key, val in sorted(m.snapshot_values().items()):
                series = {"labels": dict(key)}
                if m.kind == "histogram":
                    series["counts"] = list(val.counts)
                    series["sum"] = val.sum
                    series["count"] = val.count
                else:
                    series["value"] = float(val)
                entry["series"].append(series)
            out[m.name] = entry
        return out


# -- process-global registry ----------------------------------------------

_GLOBAL = MetricsRegistry()
_GLOBAL_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-global registry every instrumentation site writes to.
    One per process (threads of a local cluster share it — their counters
    sum naturally, matching the cross-process merge semantics)."""
    return _GLOBAL


def reset_registry() -> MetricsRegistry:
    """Swap in a fresh registry (tests).  Instrument accessors re-resolve
    on every call, so no handle goes stale."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = MetricsRegistry()
        return _GLOBAL


# -- cross-rank merge ------------------------------------------------------

def merge_snapshots(snapshots) -> dict:
    """Merge per-rank snapshots: counters/histograms sum, gauges use their
    declared ``agg`` mode.  Later snapshots win for ``last`` gauges."""
    merged = {}
    for snap in snapshots:
        if not snap:
            continue
        for name, entry in snap.items():
            dst = merged.get(name)
            if dst is None:
                dst = merged[name] = {
                    "kind": entry["kind"],
                    "help": entry.get("help", ""),
                    "series": [],
                    "_index": {},
                }
                if "agg" in entry:
                    dst["agg"] = entry["agg"]
                if "buckets" in entry:
                    dst["buckets"] = list(entry["buckets"])
            index = dst["_index"]
            for series in entry.get("series", []):
                key = _label_key(series.get("labels", {}))
                cur = index.get(key)
                if cur is None:
                    cur = {"labels": dict(series.get("labels", {}))}
                    if entry["kind"] == "histogram":
                        cur["counts"] = [0] * len(series.get("counts", []))
                        cur["sum"] = 0.0
                        cur["count"] = 0
                    index[key] = cur
                    dst["series"].append(cur)
                if entry["kind"] == "histogram":
                    counts = series.get("counts", [])
                    if len(cur["counts"]) < len(counts):
                        cur["counts"] += [0] * (len(counts) - len(cur["counts"]))
                    for i, c in enumerate(counts):
                        cur["counts"][i] += c
                    cur["sum"] += series.get("sum", 0.0)
                    cur["count"] += series.get("count", 0)
                elif entry["kind"] == "counter":
                    cur["value"] = cur.get("value", 0.0) + series.get("value", 0.0)
                else:  # gauge
                    agg = dst.get("agg", "last")
                    v = series.get("value", 0.0)
                    if "value" not in cur:
                        cur["value"] = v
                    elif agg == "max":
                        cur["value"] = max(cur["value"], v)
                    elif agg == "min":
                        cur["value"] = min(cur["value"], v)
                    elif agg == "sum":
                        cur["value"] += v
                    else:
                        cur["value"] = v
    for entry in merged.values():
        entry.pop("_index", None)
    return merged


# -- Prometheus text exposition --------------------------------------------

def render_prometheus(snapshot: dict) -> str:
    """Render one (merged) snapshot in the Prometheus text format."""
    lines = []
    for name in sorted(snapshot):
        entry = snapshot[name]
        kind = entry["kind"]
        if entry.get("help"):
            lines.append(f"# HELP {name} {entry['help']}")
        lines.append(f"# TYPE {name} {kind}")
        for series in entry.get("series", []):
            items = sorted(series.get("labels", {}).items())
            if kind == "histogram":
                bounds = entry.get("buckets", [])
                cum = 0
                counts = series.get("counts", [])
                for i, b in enumerate(bounds):
                    cum += counts[i] if i < len(counts) else 0
                    lbl = _fmt_labels(items + [("le", _fmt_value(b))])
                    lines.append(f"{name}_bucket{lbl} {cum}")
                total = series.get("count", 0)
                lbl = _fmt_labels(items + [("le", "+Inf")])
                lines.append(f"{name}_bucket{lbl} {total}")
                lines.append(
                    f"{name}_sum{_fmt_labels(items)} "
                    f"{_fmt_value(series.get('sum', 0.0))}")
                lines.append(f"{name}_count{_fmt_labels(items)} {total}")
            else:
                lines.append(
                    f"{name}{_fmt_labels(items)} "
                    f"{_fmt_value(series.get('value', 0.0))}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> dict:
    """Tiny parser for the text format: ``{sample_name: {label_tuple:
    value}}``.  Used by tests and the CI smoke check — intentionally
    strict: raises ValueError on lines it can't parse."""
    out = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            lbl_str, _, val_str = rest.rpartition("}")
            labels = []
            for part in _split_labels(lbl_str):
                if not part:
                    continue
                k, _, v = part.partition("=")
                if not v.startswith('"') or not v.endswith('"'):
                    raise ValueError(f"bad label in line: {raw!r}")
                labels.append((k.strip(), _unescape_label(v[1:-1])))
            key = tuple(sorted(labels))
        else:
            name, _, val_str = line.partition(" ")
            key = ()
        val_str = val_str.strip()
        if not name or not val_str:
            raise ValueError(f"bad sample line: {raw!r}")
        try:
            value = float(val_str.replace("+Inf", "inf"))
        except ValueError:
            raise ValueError(f"bad value in line: {raw!r}")
        out.setdefault(name.strip(), {})[key] = value
    return out


def _unescape_label(s: str) -> str:
    """Inverse of the ``_fmt_labels`` escaping.  Walks escape sequences
    left to right — chained ``str.replace`` would corrupt ``\\\\n`` (an
    escaped backslash followed by 'n') into a newline."""
    if "\\" not in s:
        return s
    out, i, n = [], 0, len(s)
    while i < n:
        ch = s[i]
        if ch == "\\" and i + 1 < n:
            nxt = s[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _split_labels(s: str):
    """Split 'a="x",b="y,z"' on commas outside quotes."""
    parts, cur, inq, esc = [], [], False, False
    for ch in s:
        if esc:
            cur.append(ch)
            esc = False
            continue
        if ch == "\\":
            cur.append(ch)
            esc = True
            continue
        if ch == '"':
            inq = not inq
            cur.append(ch)
            continue
        if ch == "," and not inq:
            parts.append("".join(cur).strip())
            cur = []
            continue
        cur.append(ch)
    if cur:
        parts.append("".join(cur).strip())
    return parts
