"""Runtime telemetry: registry, cross-rank aggregation, /metrics endpoint.

Layout (docs/metrics.md):

* :mod:`.registry` — dependency-free Counter / Gauge / Histogram, the
  process-global registry, snapshot/merge, Prometheus text rendering.
* :mod:`.instruments` — the standard ``hvd_*`` metric catalog.
* :mod:`.http` — the stdlib HTTP server behind ``HOROVOD_METRICS_PORT``.

This module owns the aggregation state: every rank periodically ships its
registry snapshot over the coordinator control channel (``MSG_METRICS``
frames, runtime/coordinator.py); the coordinator process stores them here
via :func:`store_report` and the endpoint / ``hvd.metrics()`` render the
merge of the local registry with every stored report.
"""

from __future__ import annotations

import os
import threading

from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       exponential_buckets, get_registry, merge_snapshots,
                       parse_prometheus, render_prometheus, reset_registry)
from . import instruments

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "instruments",
    "exponential_buckets", "get_registry", "merge_snapshots",
    "parse_prometheus", "render_prometheus", "reset_registry",
    "local_snapshot", "store_report", "clear_reports", "aggregate",
    "metrics_text", "metrics", "maybe_start_server", "stop_server",
    "server_port",
]

# Per-rank snapshots received over the control channel, keyed by rank.
# Only populated on the aggregating (coordinator) process.
_reports = {}
_reports_lock = threading.Lock()

_server = None
_server_lock = threading.Lock()


def local_snapshot() -> dict:
    """This process's registry as a plain dict (wire- and merge-ready)."""
    return get_registry().snapshot()


def store_report(rank: int, snapshot: dict, timestamp: float = 0.0) -> None:
    """Record one rank's shipped snapshot (coordinator side)."""
    with _reports_lock:
        _reports[int(rank)] = (float(timestamp), snapshot)


def clear_reports() -> None:
    with _reports_lock:
        _reports.clear()


def report_ranks():
    with _reports_lock:
        return sorted(_reports)


def aggregate() -> dict:
    """Merge the local registry with every stored per-rank report.

    The local registry is this process's own telemetry (on rank 0 that
    includes the coordinator-side counters); remote ranks never store a
    report for rank 0's registry, so nothing is double counted.
    """
    with _reports_lock:
        remote = [snap for _, (_, snap) in sorted(_reports.items())]
    return merge_snapshots([local_snapshot()] + remote)


def metrics_text() -> str:
    """The aggregated snapshot in Prometheus text format."""
    return render_prometheus(aggregate())


def metrics(prometheus: bool = False):
    """Public API (``hvd.metrics()``): the aggregated metrics snapshot.

    Returns the merged plain-dict snapshot — on the coordinator process the
    whole job, on other ranks just the local registry.  With
    ``prometheus=True`` returns the text exposition instead.
    """
    return metrics_text() if prometheus else aggregate()


# -- endpoint lifecycle (called from basics.init / basics.shutdown) ---------

def maybe_start_server(force: bool = False):
    """Start the /metrics endpoint if ``HOROVOD_METRICS_PORT`` is set (or
    ``force``).  Idempotent; port 0 binds an ephemeral port.  Returns the
    server or None."""
    global _server
    from .http import MetricsHTTPServer

    with _server_lock:
        if _server is not None:
            return _server
        raw = os.environ.get("HOROVOD_METRICS_PORT", "")
        if not raw.strip() and not force:
            return None
        port = int(raw) if raw.strip() else 0
        srv = MetricsHTTPServer(port, metrics_text)
        srv.start()
        _server = srv
        return srv


def stop_server() -> None:
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is not None:
        srv.stop()


def server_port():
    """Bound port of the running endpoint, or None."""
    with _server_lock:
        return None if _server is None else _server.port
