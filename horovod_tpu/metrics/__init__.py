"""Runtime telemetry: registry, cross-rank aggregation, /metrics endpoint.

Layout (docs/metrics.md):

* :mod:`.registry` — dependency-free Counter / Gauge / Histogram, the
  process-global registry, snapshot/merge, Prometheus text rendering.
* :mod:`.instruments` — the standard ``hvd_*`` metric catalog.
* :mod:`.http` — the stdlib HTTP server behind ``HOROVOD_METRICS_PORT``.

This module owns the aggregation state: every rank periodically ships its
registry snapshot over the coordinator control channel (``MSG_METRICS``
frames, runtime/coordinator.py); the coordinator process stores them here
via :func:`store_report` and the endpoint / ``hvd.metrics()`` render the
merge of the local registry with every stored report.
"""

from __future__ import annotations

import os
import threading

from .registry import (Counter, Gauge, Histogram, LATENCY_BUCKETS,
                       MetricsRegistry, exponential_buckets, get_registry,
                       merge_snapshots, parse_prometheus,
                       quantile_from_buckets, render_prometheus,
                       reset_registry)
from . import instruments

__all__ = [
    "Counter", "Gauge", "Histogram", "LATENCY_BUCKETS", "MetricsRegistry",
    "instruments",
    "exponential_buckets", "get_registry", "merge_snapshots",
    "parse_prometheus", "quantile_from_buckets", "render_prometheus",
    "reset_registry",
    "local_snapshot", "store_report", "drop_report", "readmit_report",
    "clear_reports", "aggregate", "metrics_text", "metrics",
    "maybe_start_server", "stop_server", "server_port",
    "set_health_source", "health_summary",
]

# Per-rank snapshots received over the control channel, keyed by rank.
# Only populated on the aggregating (coordinator) process.
_reports = {}
# Ranks declared dead by the coordinator: their in-flight MSG_METRICS
# frames may still land after rank_lost, and must not resurrect the dead
# rank's gauges in aggregate(). Cleared per rank on elastic re-admission.
_dropped = set()
_reports_lock = threading.Lock()

_server = None
_server_lock = threading.Lock()


def local_snapshot() -> dict:
    """This process's registry as a plain dict (wire- and merge-ready).
    Flushes the goodput ledger first (lazy import: goodput imports from
    this package) so snapshots always carry up-to-date attribution."""
    try:
        from ..goodput import ledger as _ledger
        led = _ledger.active()
        if led is not None:
            led.flush()
    except Exception:
        pass
    return get_registry().snapshot()


def store_report(rank: int, snapshot: dict, timestamp: float = 0.0) -> None:
    """Record one rank's shipped snapshot (coordinator side). Snapshots
    from ranks dropped via :func:`drop_report` are discarded — a stale
    frame racing the death must not resurrect the rank."""
    with _reports_lock:
        rank = int(rank)
        if rank in _dropped:
            return
        _reports[rank] = (float(timestamp), snapshot)


def drop_report(rank: int) -> None:
    """Forget a rank's stored snapshot and refuse later ones (coordinator
    ``rank_lost``), so a stale MSG_METRICS arriving after the death never
    resurrects the dead rank's gauges in :func:`aggregate`."""
    with _reports_lock:
        _reports.pop(int(rank), None)
        _dropped.add(int(rank))


def readmit_report(rank: int) -> None:
    """A previously-lost rank rejoined (elastic admission): accept its
    snapshots again."""
    with _reports_lock:
        _dropped.discard(int(rank))


def clear_reports() -> None:
    with _reports_lock:
        _reports.clear()
        _dropped.clear()


def report_ranks():
    with _reports_lock:
        return sorted(_reports)


def aggregate() -> dict:
    """Merge the local registry with every stored per-rank report.

    The local registry is this process's own telemetry (on rank 0 that
    includes the coordinator-side counters); remote ranks never store a
    report for rank 0's registry, so nothing is double counted.
    """
    with _reports_lock:
        remote = [snap for _, (_, snap) in sorted(_reports.items())]
    return merge_snapshots([local_snapshot()] + remote)


def metrics_text() -> str:
    """The aggregated snapshot in Prometheus text format."""
    return render_prometheus(aggregate())


def metrics(prometheus: bool = False):
    """Public API (``hvd.metrics()``): the aggregated metrics snapshot.

    Returns the merged plain-dict snapshot — on the coordinator process the
    whole job, on other ranks just the local registry.  With
    ``prometheus=True`` returns the text exposition instead.
    """
    return metrics_text() if prometheus else aggregate()


# -- /healthz (docs/observability.md) ----------------------------------------

# Control-plane liveness provider: the CoordinatorServer registers the
# CoordState.health_summary bound method; None outside coordinated mode.
_health_source = None


def set_health_source(fn) -> None:
    global _health_source
    _health_source = fn


def health_summary() -> dict:
    """The /healthz JSON body: reporting ranks, the coordinator's
    control-plane view (last-negotiation age, heartbeat ledger, members)
    and the live anomaly-watch state."""
    doc = {"status": "ok", "reporting_ranks": report_ranks()}
    up = get_registry().get("hvd_snapshot_unix_seconds")
    if up is not None:
        vals = up.snapshot_values().values()
        if vals:
            doc["snapshot_unix_seconds"] = max(vals)
    try:
        from ..goodput import ledger as _ledger
        led = _ledger.active()
        if led is not None:
            doc["goodput"] = led.summary()
    except Exception:
        pass
    src = _health_source
    if src is not None:
        try:
            cp = src()
        except Exception as exc:
            cp = {"error": str(exc)}
        doc["control_plane"] = cp
        if cp.get("shutting_down") or cp.get("disconnected") \
                or cp.get("silent_ranks"):
            doc["status"] = "degraded"
    try:
        from ..blackbox.watch import watch_state
        ws = watch_state()
        doc["anomaly_watch"] = ws if ws is not None else {"running": False}
        if (ws or {}).get("active"):
            doc["status"] = "degraded"
    except Exception:
        pass
    return doc


# -- endpoint lifecycle (called from basics.init / basics.shutdown) ---------

def maybe_start_server(force: bool = False):
    """Start the /metrics endpoint if ``HOROVOD_METRICS_PORT`` is set (or
    ``force``).  Idempotent; port 0 binds an ephemeral port.  Returns the
    server or None."""
    global _server
    from .http import MetricsHTTPServer

    with _server_lock:
        if _server is not None:
            return _server
        raw = os.environ.get("HOROVOD_METRICS_PORT", "")
        if not raw.strip() and not force:
            return None
        port = int(raw) if raw.strip() else 0
        addr = os.environ.get("HOROVOD_METRICS_ADDR", "").strip() or "0.0.0.0"
        srv = MetricsHTTPServer(port, metrics_text, addr=addr,
                                health_fn=health_summary)
        srv.start()
        _server = srv
        return srv


def stop_server() -> None:
    global _server
    with _server_lock:
        srv, _server = _server, None
    if srv is not None:
        srv.stop()


def server_port():
    """Bound port of the running endpoint, or None."""
    with _server_lock:
        return None if _server is None else _server.port
