"""Serving frontend: request routing over the hardened control plane.

The frontend is the serving pod's coordinator-analog: one TCP listener
speaking the ``runtime/wire.py`` framing (CRC32 + optional HMAC, bounded
frames) to two kinds of peers that both introduce themselves with
``MSG_SERVE_HELLO`` — *workers* (model replicas running a
:class:`~.engine.ServingEngine`, ``serving/worker.py``) and *clients*
(``serving/client.py``). Clients submit ``MSG_SERVE_SUBMIT`` frames; the
dispatcher routes each to the least-loaded live worker and relays the
worker's ``MSG_SERVE_RESULT`` back to whichever client owns the request.

Fault tolerance is the PR-2/PR-4 recipe applied to requests instead of
gradients:

* **Liveness** — workers heartbeat (``MSG_HEARTBEAT``) every
  ``HOROVOD_HEARTBEAT_INTERVAL``; a worker silent past the grace window
  (or whose socket drops) is declared dead.
* **Elastic re-admission** — a dead worker's in-flight requests do NOT
  error: they re-enter the dispatch queue and land on surviving replicas
  (counted by ``hvd_serving_requests_total{status="readmitted"}``). A
  rejoining worker just HELLOs again and starts taking load.
* **Exactly-once for clients** — request ids are client-chosen; the
  frontend keeps an LRU of finished results and answers duplicate submits
  from it, so a client that reconnects and blindly resubmits everything
  unresolved (the ``client.py`` recovery move) never double-generates.
* **Observability** — worker ``MSG_METRICS`` reports merge into the
  frontend's ``/metrics`` endpoint via the PR-3 dead-rank ledger
  (``store_report``/``drop_report``), so pod-level serving dashboards
  survive replica churn.

The frontend itself stopped being the single point of failure with the
survivable-serving work (docs/inference.md failure matrix):

* **Warm-standby failover** — a :class:`~.standby.ServingStandby` dials in
  with ``MSG_REPL_HELLO`` payload ``b"serve"`` and mirrors the durable
  request state (the result LRU + every open submit) over the same
  MSG_SNAPSHOT/MSG_JOURNAL framing the coordinator standby uses. With
  ``HOROVOD_SERVING_STANDBY`` + the rendezvous lease, the active frontend
  holds ``serve.lease.{gen}`` and stamps its fencing epoch on every
  outgoing ``MSG_SERVE_*`` frame; a deposed frontend's traffic is
  fence-rejected by workers, clients and the promoted standby alike.
* **Deadlines + cancellation** — submits may carry a deadline budget; the
  liveness loop cancels expired requests end to end (client tombstone,
  ``MSG_SERVE_CANCEL`` to the worker, KV blocks freed there). Clients
  propagate their own timeouts/disconnects the same way.
* **Overload brownout/shedding** (``HOROVOD_SERVING_SHED``) — best-effort
  traffic gets its ``max_new`` clamped once the backlog crosses half the
  shed threshold and is answered ``SERVE_SHED`` beyond it; high-priority
  traffic only ever sees the hard ``max_backlog`` backpressure.
* **Hedged decode** (``HOROVOD_SERVING_HEDGE``) — a request idle past a
  p95-derived delay is resubmitted to a second replica; first terminal
  result wins, the loser is cancelled (the pending-pop is the dedupe).
* **Per-replica circuit breaker** — heartbeat gaps or an error-rate burst
  open a breaker that keeps dispatch away from a sick replica until it
  cools down (unless every replica is sick — degraded beats down).
* **Graceful drain** — :meth:`ServingFrontend.drain_worker` sends
  ``MSG_SERVE_DRAIN``: the replica finishes in-flight work, hands queued
  work back (readmitted elsewhere) and refuses new, so rolling restarts
  are zero-loss by construction.
"""

from __future__ import annotations

import argparse
import collections
import logging
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import blackbox as _blackbox
from ..metrics import (drop_report, instruments, maybe_start_server,
                       readmit_report, store_report)
from ..runtime import wire
from ..runtime.coordinator import (MSG_BYE, MSG_HEARTBEAT, MSG_JOURNAL,
                                   MSG_METRICS, MSG_REPL_HELLO, MSG_SNAPSHOT,
                                   _publish_key)

logger = logging.getLogger("horovod_tpu")

#: completed results kept for duplicate-submit answers
RESULT_CACHE = 4096

#: brownout begins at this fraction of the shed threshold
BROWNOUT_FRACTION = 0.5

#: latency samples the hedge delay derives its p95 from
HEDGE_RING = 128


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    return float(raw) if raw else default


def standby_enabled() -> bool:
    raw = os.environ.get("HOROVOD_SERVING_STANDBY", "").strip()
    return raw not in ("", "0", "false", "False", "off")


class _Peer:
    """One connected socket (worker or client) with a write lock — results
    and relays are sent from multiple threads."""

    def __init__(self, sock: socket.socket, name: str):
        self.sock = sock
        self.name = name
        self.send_lock = threading.Lock()
        self.alive = True
        self.last_seen = time.monotonic()

    def send(self, secret: str, msg_type: int, seq: int,
             payload: bytes, fence: int = 0) -> bool:
        try:
            with self.send_lock:
                wire.send_frame(self.sock, secret, msg_type, seq, -1,
                                payload, fence=fence)
            return True
        except OSError:
            self.alive = False
            return False

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


class _Worker(_Peer):
    def __init__(self, sock: socket.socket, name: str, capacity: int):
        super().__init__(sock, name)
        self.capacity = max(1, capacity)
        self.inflight = 0  # guarded by the frontend lock
        self.metrics_rank: Optional[int] = None
        self.draining = False
        # circuit breaker: error-rate over a rolling outcome window plus
        # heartbeat-gap trips from the liveness loop. Open = excluded from
        # dispatch until ``breaker_until`` (half-open by expiry).
        self.fails = 0
        self.oks = 0
        self.breaker_until = 0.0

    def breaker_open(self, now: float) -> bool:
        return now < self.breaker_until

    def record_outcome(self, ok: bool, now: float, hold: float) -> bool:
        """Feed one terminal result into the breaker; True if it tripped."""
        tripped = False
        if ok:
            self.oks += 1
        else:
            self.fails += 1
            if self.fails >= 3 and self.fails > self.oks:
                self.breaker_until = now + hold
                tripped = True
        if self.fails + self.oks >= 64:  # rolling window reset
            self.fails = self.oks = 0
        return tripped


class _Pending:
    """One request the frontend has accepted but not answered."""

    __slots__ = ("request_id", "payload", "client", "worker", "submitted_t",
                 "deadline_t", "priority", "dispatched_t", "hedge_worker")

    def __init__(self, request_id: str, payload: bytes,
                 client: Optional[_Peer], deadline: float = 0.0,
                 priority: int = wire.SERVE_PRIO_HIGH):
        self.request_id = request_id
        self.payload = payload           # the SUBMIT payload, relay-ready
        self.client = client
        self.worker: Optional[str] = None
        self.submitted_t = time.monotonic()
        # the wire deadline is a relative budget re-anchored on THIS
        # host's monotonic clock (no cross-host clock comparison)
        self.deadline_t = (self.submitted_t + deadline if deadline > 0
                           else None)
        self.priority = priority
        self.dispatched_t: Optional[float] = None
        self.hedge_worker: Optional[str] = None


class ServingFrontend:
    """Accepts workers and clients; routes requests; survives worker loss.

    ``max_backlog`` bounds requests waiting for worker capacity — beyond
    it, submits answer ``SERVE_REJECTED`` (clients back off and retry).

    ``rank``/``gen`` identify this frontend in the blackbox/lease planes
    (the primary is conventionally rank 0, a standby rank 1);
    ``fence_epoch`` is stamped on every outgoing ``MSG_SERVE_*`` frame
    when non-zero — a promoted standby seeds it from the lease it won.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 secret: Optional[str] = None, max_backlog: int = 1024,
                 heartbeat_grace: Optional[float] = None, rank: int = 0,
                 gen: int = 0, fence_epoch: int = 0):
        self.secret = (secret if secret is not None
                       else os.environ.get("HVD_SECRET", ""))
        hb = _env_float("HOROVOD_HEARTBEAT_INTERVAL", 5.0)
        self.heartbeat_grace = (heartbeat_grace if heartbeat_grace
                                is not None else 3.0 * hb)
        self.breaker_hold = 2.0 * min(hb, self.heartbeat_grace / 3.0)
        self.max_backlog = int(max_backlog)
        self.rank = int(rank)
        self.gen = int(gen)
        self.fence_epoch = int(fence_epoch)
        self.fenced = False
        self.guard = wire.FenceGuard(rank=self.rank)
        if self.fence_epoch:
            self.guard.observe(self.fence_epoch)
        # overload shedding: fraction of max_backlog past which best-effort
        # submits are answered SERVE_SHED (0 = disabled); brownout (max_new
        # clamp) starts at BROWNOUT_FRACTION of that point
        self.shed_frac = _env_float("HOROVOD_SERVING_SHED", 0.0)
        # hedging: multiplier on the live p95 (0 = disabled)
        self.hedge_mult = _env_float("HOROVOD_SERVING_HEDGE", 0.0)
        self.hedge_floor = 0.05
        self.hedge_delay_override: Optional[float] = None
        self._lat_ring: collections.deque = collections.deque(
            maxlen=HEDGE_RING)
        self._stop = threading.Event()
        self.lock = threading.RLock()
        self.workers: Dict[str, _Worker] = {}
        self.pending: Dict[str, _Pending] = {}
        self.backlog: collections.deque = collections.deque()  # request ids
        self.results: "collections.OrderedDict[str, Tuple[int, List[int], str, float]]" = \
            collections.OrderedDict()
        self.readmitted = 0
        self.completed = 0
        self.cancelled = 0
        self.shed = 0
        self.hedged = 0
        self._repl_sinks: List[_Peer] = []
        self._lease = None
        self._last_shed_event = 0.0
        self._seq = 0
        self._threads: List[threading.Thread] = []
        self.listener = socket.create_server((host, port))
        self.listener.settimeout(0.2)
        self.addr = self.listener.getsockname()

    # ----------------------------------------------------------- lifecycle
    def attach_lease(self, lease) -> None:
        """Adopt an already-acquired :class:`~..runtime.lease.LeaseManager`
        (the promoted standby passes the one it won) and start renewing.
        Losing it later self-fences this frontend."""
        self._lease = lease
        self.fence_epoch = lease.epoch
        self.guard.observe(lease.epoch)
        lease.start_renewing(self._on_lease_fence)

    def _maybe_acquire_lease(self) -> None:
        from ..runtime import lease as _lease_mod

        if (self._lease is not None or not standby_enabled()
                or not _lease_mod.lease_enabled()):
            return
        mgr = _lease_mod.LeaseManager(self.gen, self.rank,
                                      key=f"serve.lease.{self.gen}")
        mgr.acquire_initial()
        self.attach_lease(mgr)
        logger.info("serving frontend holds lease serve.lease.%d "
                    "epoch=%d", self.gen, mgr.epoch)

    def start(self) -> "ServingFrontend":
        _blackbox.maybe_activate()
        self._maybe_acquire_lease()
        loops = [(self._accept_loop, "hvd-serve-accept"),
                 (self._liveness_loop, "hvd-serve-liveness")]
        if self.hedge_mult > 0:
            loops.append((self._hedge_loop, "hvd-serve-hedge"))
        for fn, name in loops:
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        maybe_start_server()
        logger.info("serving frontend listening on %s:%d", *self.addr[:2])
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._lease is not None:
            self._lease.stop()
        with self.lock:
            peers = list(self.workers.values())
            sinks, self._repl_sinks = list(self._repl_sinks), []
        for s in sinks:
            # a clean BYE tells the standby to stand down, not promote
            s.send(self.secret, MSG_BYE, self._next_seq(), b"",
                   fence=self.fence_epoch)
            s.close()
        for p in peers:
            p.close()
        try:
            self.listener.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=5)

    def _on_lease_fence(self, reason: str) -> None:
        """The lease moved under us: stop serving NOW. Peers are cut so
        workers/clients reconnect, probe the failover key and land on the
        promoted standby; any frame this deposed frontend still emits is
        stamped with the stale epoch and fence-rejected remotely."""
        self.fenced = True
        logger.error("serving frontend self-fenced: %s", reason)
        with self.lock:
            peers = (list(self.workers.values())
                     + [p.client for p in self.pending.values()
                        if p.client is not None])
            sinks, self._repl_sinks = list(self._repl_sinks), []
        for p in peers + sinks:
            p.close()

    def _next_seq(self) -> int:
        with self.lock:
            self._seq += 1
            return self._seq

    # ------------------------------------------------------------- accept
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self.listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sock.settimeout(1.0)
            threading.Thread(target=self._handshake, args=(sock,),
                             name="hvd-serve-peer", daemon=True).start()

    def _handshake(self, sock: socket.socket) -> None:
        try:
            frame = wire.recv_frame(sock, self.secret, self._stop,
                                    guard=self.guard)
            if (frame.msg_type == MSG_REPL_HELLO
                    and frame.payload.startswith(b"serve")):
                self._run_repl_sink(_Peer(sock, "serve-standby"))
                return
            if frame.msg_type != wire.MSG_SERVE_HELLO:
                raise wire.FrameError(
                    f"expected SERVE_HELLO, got type {frame.msg_type}")
            role, name, capacity = wire.decode_serve_hello(frame.payload)
        except (ConnectionError, OSError, wire.ShutdownError) as exc:
            logger.info("serving handshake failed: %s", exc)
            try:
                sock.close()
            except OSError:
                pass
            return
        if role == wire.SERVE_ROLE_WORKER:
            self._run_worker(_Worker(sock, name, capacity))
        else:
            self._run_client(_Peer(sock, name))

    # -------------------------------------------------------- replication
    def _snapshot_payload(self) -> bytes:
        with self.lock:
            results = [
                wire.encode_serve_result(rid, st, toks, err, lat)
                for rid, (st, toks, err, lat) in self.results.items()]
            pending = [p.payload for p in self.pending.values()]
            return wire.encode_serve_snapshot(self.fence_epoch, results,
                                              pending)

    def _run_repl_sink(self, s: _Peer) -> None:
        """One standby's replication stream: snapshot, then journal pushes
        from the request paths. The reader side only watches for EOF."""
        if not s.send(self.secret, MSG_SNAPSHOT, self._next_seq(),
                      self._snapshot_payload(), fence=self.fence_epoch):
            s.close()
            return
        with self.lock:
            self._repl_sinks.append(s)
        logger.info("serving standby attached for replication")
        try:
            while not self._stop.is_set() and s.alive:
                wire.recv_frame(s.sock, self.secret, self._stop,
                                guard=self.guard)
        except (ConnectionError, OSError, wire.ShutdownError):
            pass
        finally:
            s.close()
            with self.lock:
                if s in self._repl_sinks:
                    self._repl_sinks.remove(s)

    def _journal(self, kind: int, blob: bytes) -> None:
        with self.lock:
            sinks = list(self._repl_sinks)
        if not sinks:
            return
        payload = wire.encode_serve_journal(kind, blob)
        for s in sinks:
            if not s.send(self.secret, MSG_JOURNAL, self._next_seq(),
                          payload, fence=self.fence_epoch):
                with self.lock:
                    if s in self._repl_sinks:
                        self._repl_sinks.remove(s)

    def seed_state(self, results: List[bytes],
                   pending: List[bytes]) -> None:
        """Adopt replicated state (a promoted standby calls this before
        :meth:`start`): finished results answer duplicates from the LRU,
        open submits re-enter the dispatch queue. Deadline budgets restart
        at promotion — strictly later than the original cutoff, never
        earlier."""
        with self.lock:
            for blob in results:
                rid, st, toks, err, lat = wire.decode_serve_result(blob)
                self.results[rid] = (st, toks, err, lat)
            for blob in pending:
                (rid, _, _, _, deadline,
                 priority) = wire.decode_serve_submit_ex(blob)
                if rid in self.results or rid in self.pending:
                    continue
                self.pending[rid] = _Pending(rid, blob, None,
                                             deadline=deadline,
                                             priority=priority)
                self.backlog.append(rid)

    # ------------------------------------------------------------ workers
    def _run_worker(self, w: _Worker) -> None:
        with self.lock:
            old = self.workers.get(w.name)
            if old is not None:
                # a replacement claimed the name: settle the old socket's
                # estate NOW so its reader thread (which may fire later)
                # cannot mistake the newcomer's dispatches for orphans
                old.close()
                self._orphan_locked(old)
            self.workers[w.name] = w
        logger.info("serving worker %r joined (capacity %d)", w.name,
                    w.capacity)
        self._drain_backlog()
        try:
            while not self._stop.is_set() and w.alive:
                frame = wire.recv_frame(w.sock, self.secret, self._stop,
                                        guard=self.guard)
                w.last_seen = time.monotonic()
                if frame.msg_type == wire.MSG_SERVE_RESULT:
                    self._on_result(w, frame.payload)
                elif frame.msg_type == MSG_METRICS:
                    rank, ts, snap = wire.decode_metrics_report(
                        frame.payload)
                    w.metrics_rank = rank
                    # a frame from a live connection proves the rank is
                    # back — lift any dead-rank ledger entry first
                    readmit_report(rank)
                    store_report(rank, snap, ts)
                elif frame.msg_type == MSG_HEARTBEAT:
                    pass  # last_seen bump above is the whole point
        except (ConnectionError, OSError, wire.ShutdownError) as exc:
            if not self._stop.is_set():
                logger.warning("serving worker %r lost: %s", w.name, exc)
        finally:
            self._drop_worker(w)

    def _drop_worker(self, w: _Worker) -> None:
        w.close()
        if w.metrics_rank is not None:
            drop_report(w.metrics_rank)
        with self.lock:
            if self.workers.get(w.name) is not w:
                # a replacement already took the name and _run_worker
                # settled this socket's estate at takeover; every pending
                # bound to the name now belongs to the newcomer
                return
            del self.workers[w.name]
            n = self._orphan_locked(w)
        for _ in range(n):
            instruments.serving_requests().labels(status="readmitted").inc()
        if n:
            logger.warning(
                "re-admitting %d in-flight request(s) from dead worker %r",
                n, w.name)
        self._drain_backlog()

    def _orphan_locked(self, w: _Worker) -> int:
        """Re-own every pending bound to ``w`` (caller holds the lock):
        hedged dispatches collapse onto their surviving leg, the rest go
        back to the head of the line. Returns the readmitted count."""
        orphans = []
        for p in self.pending.values():
            if p.hedge_worker == w.name:
                # the surviving primary dispatch still owns it
                p.hedge_worker = None
                continue
            if p.worker != w.name:
                continue
            if p.hedge_worker is not None:
                # promote the hedge to primary instead of readmitting
                p.worker, p.hedge_worker = p.hedge_worker, None
                continue
            orphans.append(p)
        for p in orphans:
            p.worker = None
            p.dispatched_t = None
            self.backlog.appendleft(p.request_id)
        self.readmitted += len(orphans)
        return len(orphans)

    def _liveness_loop(self) -> None:
        while not self._stop.wait(min(1.0, self.heartbeat_grace / 3)):
            now = time.monotonic()
            with self.lock:
                stale = [w for w in self.workers.values()
                         if now - w.last_seen > self.heartbeat_grace]
                # heartbeat-latency feed of the circuit breaker: a replica
                # late past half the grace window stops taking new load
                # before the hard liveness verdict lands
                for w in self.workers.values():
                    if (now - w.last_seen > self.heartbeat_grace / 2
                            and not w.breaker_open(now) and w not in stale):
                        w.breaker_until = now + self.heartbeat_grace / 2
                        logger.warning(
                            "serving worker %r heartbeat late (%.1fs) — "
                            "circuit breaker open", w.name,
                            now - w.last_seen)
                expired = [p.request_id for p in self.pending.values()
                           if p.deadline_t is not None
                           and now >= p.deadline_t]
            for w in stale:
                logger.warning(
                    "serving worker %r silent for %.1fs — declaring dead",
                    w.name, now - w.last_seen)
                w.close()  # the reader thread unblocks and drops it
            for rid in expired:
                self._cancel_request(rid, "deadline exceeded", "deadline")

    # ------------------------------------------------------------ clients
    def _run_client(self, c: _Peer) -> None:
        logger.info("serving client %r connected", c.name)
        try:
            while not self._stop.is_set() and c.alive:
                frame = wire.recv_frame(c.sock, self.secret, self._stop,
                                        guard=self.guard)
                if frame.msg_type == wire.MSG_SERVE_SUBMIT:
                    self._on_submit(c, frame.payload)
                elif frame.msg_type == wire.MSG_SERVE_CANCEL:
                    rid, reason = wire.decode_serve_cancel(frame.payload)
                    self._cancel_request(rid, reason or "client cancel",
                                         "client")
        except (ConnectionError, OSError, wire.ShutdownError):
            pass
        finally:
            c.close()
            with self.lock:
                # keep pending requests running; results for a vanished
                # client stay in the dedupe cache for its reconnect. The
                # worker-side TTL sweep reaps them if nobody ever returns.
                for p in self.pending.values():
                    if p.client is c:
                        p.client = None

    def _shed_point(self) -> float:
        return self.shed_frac * self.max_backlog

    def _record_shed(self, klass: str, occupancy: int) -> None:
        now = time.monotonic()
        if now - self._last_shed_event < 1.0:
            return  # one blackbox event per burst-second is plenty
        self._last_shed_event = now
        _blackbox.record(
            _blackbox.K_ANOMALY, "serving_shed",
            "shedding class=%s resource=queue backlog=%d/%d"
            % (klass, occupancy, self.max_backlog), rank=self.rank)

    def _on_submit(self, c: _Peer, payload: bytes) -> None:
        (request_id, prompt, max_new, eos, deadline,
         priority) = wire.decode_serve_submit_ex(payload)
        if self.fenced:
            return  # deposed; the connection is being torn down anyway
        with self.lock:
            done = self.results.get(request_id)
            if done is not None:  # duplicate of a finished request
                status, tokens, error, latency = done
                c.send(self.secret, wire.MSG_SERVE_RESULT, self._next_seq(),
                       wire.encode_serve_result(request_id, status, tokens,
                                                error, latency),
                       fence=self.fence_epoch)
                return
            p = self.pending.get(request_id)
            if p is not None:     # duplicate of an in-flight request —
                p.client = c      # re-own it (client reconnected)
                return
            # true queue depth: requests waiting for worker capacity —
            # dispatched in-flight work is already bounded by replica
            # capacity and must not eat into the admission budget
            occupancy = len(self.backlog)
            if occupancy >= self.max_backlog:
                instruments.serving_requests().labels(
                    status="rejected").inc()
                c.send(self.secret, wire.MSG_SERVE_RESULT, self._next_seq(),
                       wire.encode_serve_result(
                           request_id, wire.SERVE_REJECTED, [],
                           "frontend backlog full; retry with backoff"),
                       fence=self.fence_epoch)
                return
            if (self.shed_frac > 0
                    and priority >= wire.SERVE_PRIO_BEST_EFFORT):
                shed_point = self._shed_point()
                if occupancy >= shed_point:
                    # hard shed: terminal, never dispatched — the client
                    # must NOT retry into the same overload
                    self.shed += 1
                    instruments.serving_shed().labels(
                        **{"class": "best_effort"}).inc()
                    self._record_shed("best_effort", occupancy)
                    c.send(self.secret, wire.MSG_SERVE_RESULT, self._next_seq(),
                           wire.encode_serve_result(
                               request_id, wire.SERVE_SHED, [],
                               "shed: best-effort load over %.0f%% of "
                               "backlog" % (self.shed_frac * 100)),
                           fence=self.fence_epoch)
                    return
                if (occupancy >= BROWNOUT_FRACTION * shed_point
                        and max_new > 1):
                    # brownout: serve a shorter generation instead of
                    # nothing — degraded beats shed beats saturated
                    max_new = max(1, max_new // 2)
                    payload = wire.encode_serve_submit(
                        request_id, prompt, max_new, eos, deadline,
                        priority)
                    instruments.serving_shed().labels(
                        **{"class": "brownout"}).inc()
                    self._record_shed("brownout", occupancy)
            p = _Pending(request_id, payload, c, deadline=deadline,
                         priority=priority)
            self.pending[request_id] = p
            self.backlog.append(request_id)
            instruments.serving_requests().labels(status="submitted").inc()
        self._journal(wire.SERVE_J_SUBMIT, payload)
        self._drain_backlog()

    # ------------------------------------------------------- cancellation
    def _cancel_request(self, rid: str, reason: str, label: str) -> bool:
        """Terminally cancel one open request: tombstone the result LRU
        (so replays dedupe), answer the owning client, propagate
        ``MSG_SERVE_CANCEL`` to every replica working on it."""
        with self.lock:
            p = self.pending.pop(rid, None)
            if p is None:
                return False  # already terminal — cancels race results
            self.results[rid] = (wire.SERVE_CANCELLED, [], reason, 0.0)
            while len(self.results) > RESULT_CACHE:
                self.results.popitem(last=False)
            self.cancelled += 1
            workers = [self.workers.get(n)
                       for n in (p.worker, p.hedge_worker) if n]
            for w in workers:
                if w is not None and w.inflight > 0:
                    w.inflight -= 1
            client = p.client
        instruments.serving_cancels().labels(reason=label).inc()
        instruments.serving_requests().labels(status="cancelled").inc()
        cancel_payload = wire.encode_serve_cancel(rid, reason)
        for w in workers:
            if w is not None:
                w.send(self.secret, wire.MSG_SERVE_CANCEL,
                       self._next_seq(), cancel_payload,
                       fence=self.fence_epoch)
        if client is not None:
            client.send(self.secret, wire.MSG_SERVE_RESULT,
                        self._next_seq(),
                        wire.encode_serve_result(rid, wire.SERVE_CANCELLED,
                                                 [], reason),
                        fence=self.fence_epoch)
        self._journal(wire.SERVE_J_CANCEL, cancel_payload)
        self._drain_backlog()
        return True

    # ------------------------------------------------------------ hedging
    def _hedge_delay(self) -> float:
        if self.hedge_delay_override is not None:
            return self.hedge_delay_override
        ring = sorted(self._lat_ring)
        if len(ring) < 8:
            return max(self.hedge_floor, self.hedge_mult * 0.25)
        p95 = ring[min(len(ring) - 1, int(0.95 * len(ring)))]
        return max(self.hedge_floor, self.hedge_mult * p95)

    def _hedge_loop(self) -> None:
        while not self._stop.wait(0.05):
            delay = self._hedge_delay()
            now = time.monotonic()
            with self.lock:
                laggards = [
                    p.request_id for p in self.pending.values()
                    if p.worker is not None and p.hedge_worker is None
                    and p.dispatched_t is not None
                    and now - p.dispatched_t >= delay]
            for rid in laggards:
                self._launch_hedge(rid)

    def _launch_hedge(self, rid: str) -> None:
        now = time.monotonic()
        with self.lock:
            p = self.pending.get(rid)
            if p is None or p.worker is None or p.hedge_worker is not None:
                return
            cands = [w for w in self.workers.values()
                     if w.alive and not w.draining and w.name != p.worker
                     and w.inflight < w.capacity
                     and not w.breaker_open(now)]
            if not cands:
                return
            w = min(cands, key=lambda x: x.inflight / x.capacity)
            p.hedge_worker = w.name
            w.inflight += 1
            self.hedged += 1
        instruments.serving_hedges().labels(outcome="launched").inc()
        logger.info("hedging request %s to %r (delay %.3fs past p95)",
                    rid, w.name, self._hedge_delay())
        w.send(self.secret, wire.MSG_SERVE_SUBMIT, self._next_seq(),
               p.payload, fence=self.fence_epoch)

    # ---------------------------------------------------------- dispatch
    def _drain_backlog(self) -> None:
        """Assign queued requests to the least-loaded live workers."""
        while True:
            now = time.monotonic()
            with self.lock:
                if not self.backlog or self.fenced:
                    return
                live = [w for w in self.workers.values()
                        if w.alive and not w.draining
                        and w.inflight < w.capacity]
                # breaker-open replicas are skipped — unless EVERY live
                # replica is open, where degraded dispatch beats none
                candidates = ([w for w in live if not w.breaker_open(now)]
                              or live)
                if not candidates:
                    instruments.serving_queue_depth().set(len(self.backlog))
                    return
                w = min(candidates, key=lambda x: x.inflight / x.capacity)
                # high-priority requests overtake queued best-effort ones
                # (FIFO within a class): the overload guarantee is that
                # the high class only ever waits on its own kind
                rid = None
                for i, cand in enumerate(self.backlog):
                    q = self.pending.get(cand)
                    if q is not None and q.priority == wire.SERVE_PRIO_HIGH:
                        rid = cand
                        del self.backlog[i]
                        break
                if rid is None:
                    rid = self.backlog.popleft()
                p = self.pending.get(rid)
                if p is None:
                    continue
                p.worker = w.name
                p.dispatched_t = now
                w.inflight += 1
                instruments.serving_queue_depth().set(len(self.backlog))
            if not w.send(self.secret, wire.MSG_SERVE_SUBMIT,
                          self._next_seq(), p.payload,
                          fence=self.fence_epoch):
                # send failed: the reader thread will reap the worker and
                # re-admit; nothing to do here
                logger.warning("dispatch to worker %r failed", w.name)

    def _on_result(self, w: _Worker, payload: bytes) -> None:
        request_id, status, tokens, error, latency = \
            wire.decode_serve_result(payload)
        now = time.monotonic()
        hedge_outcome = None
        loser: Optional[_Worker] = None
        with self.lock:
            p = self.pending.get(request_id)
            if p is None:
                # duplicate (worker resend), post-cancel echo, or the
                # hedging loser landing after the winner — already done,
                # and its inflight slot was already released
                return
            if w.inflight > 0:
                w.inflight -= 1
            if status == wire.SERVE_REJECTED:
                if p.worker == w.name and p.hedge_worker is not None:
                    # primary bounced but the hedge still runs it
                    p.worker, p.hedge_worker = p.hedge_worker, None
                    return
                if p.hedge_worker == w.name:
                    p.hedge_worker = None  # hedge bounced; primary runs it
                    return
                # worker-side backpressure: the request goes back in line
                # rather than bouncing to the client
                p.worker = None
                p.dispatched_t = None
                self.backlog.append(request_id)
                self.readmitted += 1
            else:
                self.pending.pop(request_id)
                if p.hedge_worker is not None and p.worker is not None:
                    won = w.name == p.hedge_worker
                    hedge_outcome = "won" if won else "lost"
                    loser = self.workers.get(
                        p.worker if won else p.hedge_worker)
                    if loser is not None and loser.inflight > 0:
                        loser.inflight -= 1
                self.results[request_id] = (status, tokens, error, latency)
                while len(self.results) > RESULT_CACHE:
                    self.results.popitem(last=False)
                self.completed += 1
                client = p.client
                w.record_outcome(status != wire.SERVE_FAILED, now,
                                 self.breaker_hold)
        if status == wire.SERVE_REJECTED:
            instruments.serving_requests().labels(status="readmitted").inc()
            self._drain_backlog()
            return
        if hedge_outcome is not None:
            instruments.serving_hedges().labels(
                outcome=hedge_outcome).inc()
            if loser is not None:
                loser.send(self.secret, wire.MSG_SERVE_CANCEL,
                           self._next_seq(),
                           wire.encode_serve_cancel(
                               request_id, "hedge: first winner answered"),
                           fence=self.fence_epoch)
        total = now - p.submitted_t
        if status == wire.SERVE_OK:
            self._lat_ring.append(total)
        instruments.serving_request_latency().labels(stage="frontend") \
            .observe(total)
        result_payload = wire.encode_serve_result(request_id, status,
                                                  tokens, error, total)
        if client is not None:
            client.send(self.secret, wire.MSG_SERVE_RESULT,
                        self._next_seq(), result_payload,
                        fence=self.fence_epoch)
        self._journal(wire.SERVE_J_RESULT, result_payload)
        self._drain_backlog()

    # -------------------------------------------------------------- drain
    def drain_worker(self, name: str,
                     reason: str = "rolling restart") -> bool:
        """Quiesce one replica: no new dispatch from here, a
        ``MSG_SERVE_DRAIN`` there (it finishes in-flight work and hands
        queued work back as ``SERVE_REJECTED`` for re-dispatch)."""
        with self.lock:
            w = self.workers.get(name)
            if w is None:
                return False
            w.draining = True
        logger.info("draining serving worker %r (%s)", name, reason)
        w.send(self.secret, wire.MSG_SERVE_DRAIN, self._next_seq(),
               wire.encode_serve_drain(reason), fence=self.fence_epoch)
        return True

    def wait_worker_drained(self, name: str, timeout: float = 60.0) -> bool:
        """True once the draining replica has zero in-flight requests —
        the rolling-restart signal that it is safe to kill."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self.lock:
                w = self.workers.get(name)
                if w is None or w.inflight == 0:
                    return True
            time.sleep(0.02)
        return False

    # ------------------------------------------------------------- status
    def stats(self) -> dict:
        now = time.monotonic()
        with self.lock:
            return {
                "workers": sorted(self.workers),
                "pending": len(self.pending),
                "backlog": len(self.backlog),
                "completed": self.completed,
                "readmitted": self.readmitted,
                "cancelled": self.cancelled,
                "shed": self.shed,
                "hedged": self.hedged,
                "fence_epoch": self.fence_epoch,
                "fenced": self.fenced,
                "draining": sorted(w.name for w in self.workers.values()
                                   if w.draining),
                "breaker_open": sorted(w.name
                                       for w in self.workers.values()
                                       if w.breaker_open(now)),
            }

    def wait_for_workers(self, n: int, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self.lock:
                if len(self.workers) >= n:
                    return
            time.sleep(0.05)
        raise TimeoutError(f"fewer than {n} serving workers joined")


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m horovod_tpu.serving.server`` — the frontend process the
    chaos drills SIGKILL. Publishes its address to the rendezvous KV
    (``serve.addr.{gen}``) when one is configured, and flushes the
    blackbox periodically so a SIGKILL still leaves a ledger behind."""
    ap = argparse.ArgumentParser(description="horovod_tpu serving frontend")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--gen", type=int, default=0)
    ap.add_argument("--max-backlog", type=int, default=1024)
    ap.add_argument("--heartbeat-grace", type=float, default=None)
    ap.add_argument("--flush-every", type=float, default=0.5,
                    help="blackbox flush interval (seconds)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s frontend %(message)s")
    _blackbox.maybe_activate()
    _blackbox.set_identity(args.rank, 2)
    fe = ServingFrontend(host=args.host, port=args.port, rank=args.rank,
                         gen=args.gen, max_backlog=args.max_backlog,
                         heartbeat_grace=args.heartbeat_grace)
    fe.start()
    if os.environ.get("HVD_KV_ADDR"):
        _publish_key(f"serve.addr.{args.gen}",
                     "%s:%d" % fe.addr[:2], fe.secret)
    print("SERVING_FRONTEND %s:%d" % fe.addr[:2], flush=True)
    try:
        while True:
            time.sleep(args.flush_every)
            # periodic flight-recorder flush: a SIGKILLed frontend loses
            # at most one interval of lease/frame events
            _blackbox.dump("serving frontend periodic flush", force=True)
    except KeyboardInterrupt:
        fe.stop()
        _blackbox.dump("serving frontend exit", force=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
