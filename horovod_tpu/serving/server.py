"""Serving frontend: request routing over the hardened control plane.

The frontend is the serving pod's coordinator-analog: one TCP listener
speaking the ``runtime/wire.py`` framing (CRC32 + optional HMAC, bounded
frames) to two kinds of peers that both introduce themselves with
``MSG_SERVE_HELLO`` — *workers* (model replicas running a
:class:`~.engine.ServingEngine`, ``serving/worker.py``) and *clients*
(``serving/client.py``). Clients submit ``MSG_SERVE_SUBMIT`` frames; the
dispatcher routes each to the least-loaded live worker and relays the
worker's ``MSG_SERVE_RESULT`` back to whichever client owns the request.

Fault tolerance is the PR-2/PR-4 recipe applied to requests instead of
gradients:

* **Liveness** — workers heartbeat (``MSG_HEARTBEAT``) every
  ``HOROVOD_HEARTBEAT_INTERVAL``; a worker silent past the grace window
  (or whose socket drops) is declared dead.
* **Elastic re-admission** — a dead worker's in-flight requests do NOT
  error: they re-enter the dispatch queue and land on surviving replicas
  (counted by ``hvd_serving_requests_total{status="readmitted"}``). A
  rejoining worker just HELLOs again and starts taking load.
* **Exactly-once for clients** — request ids are client-chosen; the
  frontend keeps an LRU of finished results and answers duplicate submits
  from it, so a client that reconnects and blindly resubmits everything
  unresolved (the ``client.py`` recovery move) never double-generates.
* **Observability** — worker ``MSG_METRICS`` reports merge into the
  frontend's ``/metrics`` endpoint via the PR-3 dead-rank ledger
  (``store_report``/``drop_report``), so pod-level serving dashboards
  survive replica churn.
"""

from __future__ import annotations

import collections
import logging
import os
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..metrics import (drop_report, instruments, maybe_start_server,
                       readmit_report, store_report)
from ..runtime import wire
from ..runtime.coordinator import MSG_HEARTBEAT, MSG_METRICS

logger = logging.getLogger("horovod_tpu")

#: completed results kept for duplicate-submit answers
RESULT_CACHE = 4096


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    return float(raw) if raw else default


class _Peer:
    """One connected socket (worker or client) with a write lock — results
    and relays are sent from multiple threads."""

    def __init__(self, sock: socket.socket, name: str):
        self.sock = sock
        self.name = name
        self.send_lock = threading.Lock()
        self.alive = True
        self.last_seen = time.monotonic()

    def send(self, secret: str, msg_type: int, seq: int,
             payload: bytes) -> bool:
        try:
            with self.send_lock:
                wire.send_frame(self.sock, secret, msg_type, seq, -1,
                                payload)
            return True
        except OSError:
            self.alive = False
            return False

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


class _Worker(_Peer):
    def __init__(self, sock: socket.socket, name: str, capacity: int):
        super().__init__(sock, name)
        self.capacity = max(1, capacity)
        self.inflight = 0  # guarded by the frontend lock
        self.metrics_rank: Optional[int] = None


class _Pending:
    """One request the frontend has accepted but not answered."""

    __slots__ = ("request_id", "payload", "client", "worker", "submitted_t")

    def __init__(self, request_id: str, payload: bytes,
                 client: Optional[_Peer]):
        self.request_id = request_id
        self.payload = payload           # the SUBMIT payload, relay-ready
        self.client = client
        self.worker: Optional[str] = None
        self.submitted_t = time.monotonic()


class ServingFrontend:
    """Accepts workers and clients; routes requests; survives worker loss.

    ``max_backlog`` bounds requests waiting for worker capacity — beyond
    it, submits answer ``SERVE_REJECTED`` (clients back off and retry).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 secret: Optional[str] = None, max_backlog: int = 1024,
                 heartbeat_grace: Optional[float] = None):
        self.secret = (secret if secret is not None
                       else os.environ.get("HVD_SECRET", ""))
        hb = _env_float("HOROVOD_HEARTBEAT_INTERVAL", 5.0)
        self.heartbeat_grace = (heartbeat_grace if heartbeat_grace
                                is not None else 3.0 * hb)
        self.max_backlog = int(max_backlog)
        self._stop = threading.Event()
        self.lock = threading.RLock()
        self.workers: Dict[str, _Worker] = {}
        self.pending: Dict[str, _Pending] = {}
        self.backlog: collections.deque = collections.deque()  # request ids
        self.results: "collections.OrderedDict[str, Tuple[int, List[int], str, float]]" = \
            collections.OrderedDict()
        self.readmitted = 0
        self.completed = 0
        self._seq = 0
        self._threads: List[threading.Thread] = []
        self.listener = socket.create_server((host, port))
        self.listener.settimeout(0.2)
        self.addr = self.listener.getsockname()

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "ServingFrontend":
        for fn, name in ((self._accept_loop, "hvd-serve-accept"),
                         (self._liveness_loop, "hvd-serve-liveness")):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        maybe_start_server()
        logger.info("serving frontend listening on %s:%d", *self.addr[:2])
        return self

    def stop(self) -> None:
        self._stop.set()
        with self.lock:
            peers = list(self.workers.values())
        for p in peers:
            p.close()
        try:
            self.listener.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=5)

    def _next_seq(self) -> int:
        with self.lock:
            self._seq += 1
            return self._seq

    # ------------------------------------------------------------- accept
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self.listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sock.settimeout(1.0)
            threading.Thread(target=self._handshake, args=(sock,),
                             name="hvd-serve-peer", daemon=True).start()

    def _handshake(self, sock: socket.socket) -> None:
        try:
            frame = wire.recv_frame(sock, self.secret, self._stop)
            if frame.msg_type != wire.MSG_SERVE_HELLO:
                raise wire.FrameError(
                    f"expected SERVE_HELLO, got type {frame.msg_type}")
            role, name, capacity = wire.decode_serve_hello(frame.payload)
        except (ConnectionError, OSError) as exc:
            logger.info("serving handshake failed: %s", exc)
            try:
                sock.close()
            except OSError:
                pass
            return
        if role == wire.SERVE_ROLE_WORKER:
            self._run_worker(_Worker(sock, name, capacity))
        else:
            self._run_client(_Peer(sock, name))

    # ------------------------------------------------------------ workers
    def _run_worker(self, w: _Worker) -> None:
        with self.lock:
            old = self.workers.get(w.name)
            if old is not None:
                old.close()
            self.workers[w.name] = w
        logger.info("serving worker %r joined (capacity %d)", w.name,
                    w.capacity)
        self._drain_backlog()
        try:
            while not self._stop.is_set() and w.alive:
                frame = wire.recv_frame(w.sock, self.secret, self._stop)
                w.last_seen = time.monotonic()
                if frame.msg_type == wire.MSG_SERVE_RESULT:
                    self._on_result(w, frame.payload)
                elif frame.msg_type == MSG_METRICS:
                    rank, ts, snap = wire.decode_metrics_report(
                        frame.payload)
                    w.metrics_rank = rank
                    # a frame from a live connection proves the rank is
                    # back — lift any dead-rank ledger entry first
                    readmit_report(rank)
                    store_report(rank, snap, ts)
                elif frame.msg_type == MSG_HEARTBEAT:
                    pass  # last_seen bump above is the whole point
        except (ConnectionError, OSError) as exc:
            if not self._stop.is_set():
                logger.warning("serving worker %r lost: %s", w.name, exc)
        finally:
            self._drop_worker(w)

    def _drop_worker(self, w: _Worker) -> None:
        w.close()
        if w.metrics_rank is not None:
            drop_report(w.metrics_rank)
        with self.lock:
            if self.workers.get(w.name) is w:
                del self.workers[w.name]
            orphans = [p for p in self.pending.values()
                       if p.worker == w.name]
            for p in orphans:
                p.worker = None
                self.backlog.appendleft(p.request_id)
            self.readmitted += len(orphans)
        for _ in orphans:
            instruments.serving_requests().labels(status="readmitted").inc()
        if orphans:
            logger.warning(
                "re-admitting %d in-flight request(s) from dead worker %r",
                len(orphans), w.name)
        self._drain_backlog()

    def _liveness_loop(self) -> None:
        while not self._stop.wait(min(1.0, self.heartbeat_grace / 3)):
            now = time.monotonic()
            with self.lock:
                stale = [w for w in self.workers.values()
                         if now - w.last_seen > self.heartbeat_grace]
            for w in stale:
                logger.warning(
                    "serving worker %r silent for %.1fs — declaring dead",
                    w.name, now - w.last_seen)
                w.close()  # the reader thread unblocks and drops it

    # ------------------------------------------------------------ clients
    def _run_client(self, c: _Peer) -> None:
        logger.info("serving client %r connected", c.name)
        try:
            while not self._stop.is_set() and c.alive:
                frame = wire.recv_frame(c.sock, self.secret, self._stop)
                if frame.msg_type == wire.MSG_SERVE_SUBMIT:
                    self._on_submit(c, frame.payload)
        except (ConnectionError, OSError):
            pass
        finally:
            c.close()
            with self.lock:
                # keep pending requests running; results for a vanished
                # client stay in the dedupe cache for its reconnect
                for p in self.pending.values():
                    if p.client is c:
                        p.client = None

    def _on_submit(self, c: _Peer, payload: bytes) -> None:
        request_id, _, _, _ = wire.decode_serve_submit(payload)
        with self.lock:
            done = self.results.get(request_id)
            if done is not None:  # duplicate of a finished request
                status, tokens, error, latency = done
                c.send(self.secret, wire.MSG_SERVE_RESULT, self._seq,
                       wire.encode_serve_result(request_id, status, tokens,
                                                error, latency))
                return
            p = self.pending.get(request_id)
            if p is not None:     # duplicate of an in-flight request —
                p.client = c      # re-own it (client reconnected)
                return
            if len(self.pending) >= self.max_backlog:
                instruments.serving_requests().labels(
                    status="rejected").inc()
                c.send(self.secret, wire.MSG_SERVE_RESULT, self._seq,
                       wire.encode_serve_result(
                           request_id, wire.SERVE_REJECTED, [],
                           "frontend backlog full; retry with backoff"))
                return
            p = _Pending(request_id, payload, c)
            self.pending[request_id] = p
            self.backlog.append(request_id)
            instruments.serving_requests().labels(status="submitted").inc()
        self._drain_backlog()

    # ---------------------------------------------------------- dispatch
    def _drain_backlog(self) -> None:
        """Assign queued requests to the least-loaded live workers."""
        while True:
            with self.lock:
                if not self.backlog:
                    return
                candidates = [w for w in self.workers.values()
                              if w.alive and w.inflight < w.capacity]
                if not candidates:
                    instruments.serving_queue_depth().set(len(self.backlog))
                    return
                w = min(candidates, key=lambda x: x.inflight / x.capacity)
                rid = self.backlog.popleft()
                p = self.pending.get(rid)
                if p is None:
                    continue
                p.worker = w.name
                w.inflight += 1
                instruments.serving_queue_depth().set(len(self.backlog))
            if not w.send(self.secret, wire.MSG_SERVE_SUBMIT,
                          self._next_seq(), p.payload):
                # send failed: the reader thread will reap the worker and
                # re-admit; nothing to do here
                logger.warning("dispatch to worker %r failed", w.name)

    def _on_result(self, w: _Worker, payload: bytes) -> None:
        request_id, status, tokens, error, latency = \
            wire.decode_serve_result(payload)
        with self.lock:
            p = self.pending.pop(request_id, None)
            if p is None:
                return  # duplicate result (worker resend) — already done
            if w.inflight > 0:
                w.inflight -= 1
            if status == wire.SERVE_REJECTED:
                # worker-side backpressure: the request goes back in line
                # rather than bouncing to the client
                p.worker = None
                self.pending[request_id] = p
                self.backlog.append(request_id)
                self.readmitted += 1
            else:
                self.results[request_id] = (status, tokens, error, latency)
                while len(self.results) > RESULT_CACHE:
                    self.results.popitem(last=False)
                self.completed += 1
                client = p.client
        if status == wire.SERVE_REJECTED:
            instruments.serving_requests().labels(status="readmitted").inc()
            self._drain_backlog()
            return
        total = time.monotonic() - p.submitted_t
        instruments.serving_request_latency().labels(stage="frontend") \
            .observe(total)
        if client is not None:
            client.send(self.secret, wire.MSG_SERVE_RESULT,
                        self._next_seq(),
                        wire.encode_serve_result(request_id, status, tokens,
                                                 error, total))
        self._drain_backlog()

    # ------------------------------------------------------------- status
    def stats(self) -> dict:
        with self.lock:
            return {
                "workers": sorted(self.workers),
                "pending": len(self.pending),
                "backlog": len(self.backlog),
                "completed": self.completed,
                "readmitted": self.readmitted,
            }

    def wait_for_workers(self, n: int, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self.lock:
                if len(self.workers) >= n:
                    return
            time.sleep(0.05)
        raise TimeoutError(f"fewer than {n} serving workers joined")
